"""Per-op numerics (nn + contrib) vs NumPy references.

Models the reference's ``tests/python/unittest/test_operator.py``
[unverified]: forward parity against NumPy implementations, including
regression cases found in review (topk mask, reverse reshape, adaptive
pooling, roi pooling max, out= under autograd).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def assert_close(a, b, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(
        a.asnumpy() if isinstance(a, mx.NDArray) else a,
        b.asnumpy() if isinstance(b, mx.NDArray) else b,
        rtol=rtol, atol=atol,
    )


class TestNNOps:
    def test_fully_connected(self):
        x = np.random.rand(4, 6).astype(np.float32)
        w = np.random.rand(3, 6).astype(np.float32)
        b = np.random.rand(3).astype(np.float32)
        out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b), num_hidden=3)
        assert_close(out, x @ w.T + b, rtol=1e-3)

    def test_fully_connected_no_flatten_4d(self):
        x = np.random.rand(2, 5, 6).astype(np.float32)
        w = np.random.rand(3, 6).astype(np.float32)
        out = nd.FullyConnected(nd.array(x), nd.array(w), None, num_hidden=3,
                                no_bias=True, flatten=False)
        assert out.shape == (2, 5, 3)
        assert_close(out, x @ w.T, rtol=1e-3)

    def test_convolution_matches_explicit(self):
        # 1x1 conv == channel mixing matmul
        x = np.random.rand(2, 3, 5, 5).astype(np.float32)
        w = np.random.rand(4, 3, 1, 1).astype(np.float32)
        out = nd.Convolution(nd.array(x), nd.array(w), None, kernel=(1, 1),
                             num_filter=4, no_bias=True)
        expect = np.einsum("nchw,oc->nohw", x, w[:, :, 0, 0])
        assert_close(out, expect, rtol=1e-3)

    def test_convolution_padding_stride(self):
        x = np.random.rand(1, 1, 6, 6).astype(np.float32)
        w = np.ones((1, 1, 3, 3), np.float32)
        out = nd.Convolution(nd.array(x), nd.array(w), None, kernel=(3, 3),
                             stride=(2, 2), pad=(1, 1), num_filter=1, no_bias=True)
        assert out.shape == (1, 1, 3, 3)
        # output (1,1): window starts at 1*stride - pad = 1 -> rows/cols 1:4
        assert_close(out[0, 0, 1, 1], x[0, 0, 1:4, 1:4].sum(), rtol=1e-3)

    def test_pooling_max_avg(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        mx_max = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="max")
        assert_close(mx_max, np.array([[[[5, 7], [13, 15]]]], np.float32))
        mx_avg = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="avg")
        assert_close(mx_avg, np.array([[[[2.5, 4.5], [10.5, 12.5]]]], np.float32))

    def test_global_pooling(self):
        x = np.random.rand(2, 3, 4, 4).astype(np.float32)
        out = nd.Pooling(nd.array(x), global_pool=True, pool_type="avg")
        assert_close(out, x.mean(axis=(2, 3), keepdims=True), rtol=1e-4)

    def test_batch_norm_training_stats(self):
        x = np.random.rand(4, 3, 2, 2).astype(np.float32)
        g = np.ones(3, np.float32)
        b = np.zeros(3, np.float32)
        mm, mv = np.zeros(3, np.float32), np.ones(3, np.float32)
        out, mean, var = nd.BatchNorm(nd.array(x), nd.array(g), nd.array(b),
                                      nd.array(mm), nd.array(mv),
                                      fix_gamma=False, training=True, eps=1e-5)
        assert_close(mean, x.mean(axis=(0, 2, 3)), rtol=1e-4)
        norm = (x - x.mean((0, 2, 3), keepdims=True).reshape(1, 3, 1, 1)) / np.sqrt(
            x.var((0, 2, 3)).reshape(1, 3, 1, 1) + 1e-5)
        assert_close(out, norm, rtol=1e-3, atol=1e-4)

    def test_batch_norm_inference_uses_moving(self):
        x = np.random.rand(4, 3).astype(np.float32)
        mm = np.array([0.1, 0.2, 0.3], np.float32)
        mv = np.array([1.0, 2.0, 3.0], np.float32)
        out, _, _ = nd.BatchNorm(nd.array(x), nd.ones((3,)), nd.zeros((3,)),
                                 nd.array(mm), nd.array(mv), training=False,
                                 fix_gamma=True, eps=1e-5, axis=1)
        assert_close(out, (x - mm) / np.sqrt(mv + 1e-5), rtol=1e-3)

    def test_dropout_train_vs_predict(self):
        x = nd.ones((1000,))
        with autograd.record(train_mode=True):
            y = nd.Dropout(x, p=0.5)
        kept = (y.asnumpy() != 0).mean()
        assert 0.4 < kept < 0.6
        assert_close(y.asnumpy()[y.asnumpy() != 0], 2.0)
        y2 = nd.Dropout(x, p=0.5)  # predict mode: identity
        assert_close(y2, np.ones(1000, np.float32))

    def test_rnn_lstm_shapes(self):
        T, N, I, H = 5, 2, 3, 4
        x = nd.random.normal(0, 1, (T, N, I))
        nparams = 4 * H * (I + H) + 8 * H
        params = nd.random.normal(0, 0.1, (nparams,))
        h0 = nd.zeros((1, N, H))
        c0 = nd.zeros((1, N, H))
        out, hT, cT = nd.RNN(x, params, h0, c0, state_size=H, num_layers=1,
                             mode="lstm", state_outputs=True)
        assert out.shape == (T, N, H)
        assert hT.shape == (1, N, H)
        assert cT.shape == (1, N, H)

    def test_rnn_gru_bidirectional(self):
        T, N, I, H = 3, 2, 3, 4
        x = nd.random.normal(0, 1, (T, N, I))
        size_per_dir = 3 * H * (I + H) + 6 * H
        params = nd.random.normal(0, 0.1, (2 * size_per_dir,))
        h0 = nd.zeros((2, N, H))
        out, hT = nd.RNN(x, params, h0, state_size=H, num_layers=1,
                         bidirectional=True, mode="gru")
        assert out.shape == (T, N, 2 * H)

    def test_layer_norm_forward(self):
        x = np.random.rand(2, 5).astype(np.float32)
        g = np.random.rand(5).astype(np.float32)
        b = np.random.rand(5).astype(np.float32)
        out = nd.LayerNorm(nd.array(x), nd.array(g), nd.array(b), eps=1e-5)
        m, v = x.mean(-1, keepdims=True), x.var(-1, keepdims=True)
        assert_close(out, (x - m) / np.sqrt(v + 1e-5) * g + b, rtol=1e-3)


class TestContribAttention:
    def test_selfatt_qk_parity(self):
        L, B, H, C = 4, 2, 3, 5
        qkv = np.random.rand(L, B, H * 3 * C).astype(np.float32)
        out = nd.interleaved_matmul_selfatt_qk(nd.array(qkv), heads=H)
        assert out.shape == (B * H, L, L)
        x = qkv.reshape(L, B, H, 3, C)
        q, k = x[..., 0, :], x[..., 1, :]
        expect = np.einsum("lbhc,mbhc->bhlm", q, k).reshape(B * H, L, L)
        assert_close(out, expect, rtol=1e-3)

    def test_selfatt_full_attention_equivalence(self):
        """qk -> softmax -> valatt == straightforward attention."""
        L, B, H, C = 6, 2, 2, 4
        qkv = np.random.rand(L, B, H * 3 * C).astype(np.float32)
        scores = nd.interleaved_matmul_selfatt_qk(nd.array(qkv), heads=H)
        att = nd.softmax(nd.div_sqrt_dim(scores), axis=-1)
        out = nd.interleaved_matmul_selfatt_valatt(nd.array(qkv), att, heads=H)
        x = qkv.reshape(L, B, H, 3, C)
        q, k, v = x[..., 0, :], x[..., 1, :], x[..., 2, :]
        s = np.einsum("lbhc,mbhc->bhlm", q, k) / np.sqrt(L)  # div_sqrt_dim on last dim L
        e = np.exp(s - s.max(-1, keepdims=True))
        a = e / e.sum(-1, keepdims=True)
        expect = np.einsum("bhlm,mbhc->lbhc", a, v).reshape(L, B, H * C)
        assert_close(out, expect, rtol=1e-3, atol=1e-4)

    def test_encdec_qk(self):
        Lq, Lk, B, H, C = 3, 5, 2, 2, 4
        q = np.random.rand(Lq, B, H * C).astype(np.float32)
        kv = np.random.rand(Lk, B, H * 2 * C).astype(np.float32)
        out = nd.interleaved_matmul_encdec_qk(nd.array(q), nd.array(kv), heads=H)
        assert out.shape == (B * H, Lq, Lk)


class TestContribBoxOps:
    def test_box_iou_identity(self):
        boxes = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
        iou = nd.box_iou(nd.array(boxes), nd.array(boxes))
        assert_close(np.diag(iou.asnumpy()), np.ones(2), rtol=1e-5)
        assert abs(iou.asnumpy()[0, 1] - 1.0 / 7.0) < 1e-5

    def test_box_nms_suppresses(self):
        # [cls_id, score, x1, y1, x2, y2]
        dets = np.array([
            [0, 0.9, 0, 0, 2, 2],
            [0, 0.8, 0.1, 0.1, 2.1, 2.1],  # heavy overlap with first -> suppressed
            [0, 0.7, 5, 5, 7, 7],
        ], np.float32)
        out = nd.box_nms(nd.array(dets), overlap_thresh=0.5, coord_start=2,
                         score_index=1, id_index=0).asnumpy()
        assert out[0, 1] == pytest.approx(0.9)
        assert out[1, 1] == -1.0
        assert out[2, 1] == pytest.approx(0.7)

    def test_box_decode_roundtrip(self):
        anchors = np.array([[[0.0, 0.0, 2.0, 2.0]]], np.float32)
        zero = np.zeros((1, 1, 4), np.float32)
        out = nd.box_decode(nd.array(zero), nd.array(anchors))
        assert_close(out, anchors, rtol=1e-5)


class TestRegressions:
    """Cases from code review."""

    def test_topk_mask_per_row(self):
        x = nd.array([[1.0, 3.0, 2.0], [9.0, 7.0, 8.0]])
        mask = nd.topk(x, k=1, ret_typ="mask").asnumpy()
        np.testing.assert_array_equal(mask, [[0, 1, 0], [1, 0, 0]])

    def test_reshape_reverse(self):
        x = nd.zeros((10, 5, 4))
        out = nd.Reshape(x, shape=(-1, 0), reverse=True)
        assert out.shape == (50, 4)

    def test_adaptive_avg_pool(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        out = nd.AdaptiveAvgPooling2D(nd.array(x), output_size=2)
        assert_close(out, np.array([[[[2.5, 4.5], [10.5, 12.5]]]], np.float32))
        out1 = nd.AdaptiveAvgPooling2D(nd.array(x), output_size=1)
        assert_close(out1, x.mean().reshape(1, 1, 1, 1))

    def test_roi_pooling_is_max(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        rois = np.array([[0, 0, 0, 3, 3]], np.float32)
        out = nd.ROIPooling(nd.array(x), nd.array(rois), pooled_size=(1, 1))
        assert float(out.asscalar()) == 15.0  # exact max over the window

    def test_out_kwarg_keeps_tape(self):
        a = nd.array([1.0, -2.0])
        a.attach_grad()
        buf = nd.zeros((2,))
        with autograd.record():
            r = nd.abs(a, out=buf)
            loss = (r * 2).sum()
        loss.backward()
        assert_close(a.grad, np.array([2.0, -2.0]))

    def test_inplace_under_record_raises(self):
        x = nd.array([1.0])
        x.attach_grad()
        with autograd.record():
            y = x * x
            with pytest.raises(mx.MXNetError):
                y += 1.0
        # leaf mutation outside record is fine
        x += 1.0

    def test_roi_align_average(self):
        x = np.ones((1, 1, 4, 4), np.float32)
        rois = np.array([[0, 0, 0, 3, 3]], np.float32)
        out = nd.ROIAlign(nd.array(x), nd.array(rois), pooled_size=(2, 2),
                          spatial_scale=1.0)
        assert out.shape == (1, 1, 2, 2)
        assert_close(out, np.ones((1, 1, 2, 2)), rtol=1e-4)

    def test_roi_align_batched_matches_flat(self):
        # the (B, K, 4) batched fast path == the flat (R, 5) reference form
        rng = np.random.RandomState(0)
        B, K, C, H, W = 3, 5, 4, 8, 8
        feats = rng.randn(B, C, H, W).astype(np.float32)
        xy1 = rng.rand(B, K, 2).astype(np.float32) * 3
        wh = rng.rand(B, K, 2).astype(np.float32) * 4 + 1
        rois_xy = np.concatenate([xy1, xy1 + wh], -1)
        bidx = np.broadcast_to(
            np.arange(B, dtype=np.float32)[:, None, None], (B, K, 1)
        )
        flat = np.concatenate([bidx, rois_xy], -1).reshape(-1, 5)
        out_flat = nd.ROIAlign(nd.array(feats), nd.array(flat),
                               pooled_size=(2, 2), spatial_scale=1.0,
                               sample_ratio=2)
        out_batched = nd.ROIAlign(nd.array(feats), nd.array(rois_xy),
                                  pooled_size=(2, 2), spatial_scale=1.0,
                                  sample_ratio=2)
        assert out_batched.shape == (B, K, C, 2, 2)
        assert_close(out_batched.asnumpy().reshape(B * K, C, 2, 2),
                     out_flat.asnumpy(), rtol=1e-5)


def test_softmax_output_int_label_vjp():
    # integer labels must yield a float0 cotangent, not a TypeError
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    rng = np.random.RandomState(3)
    data = mx.nd.array(rng.randn(4, 5).astype("float32"))
    label = mx.nd.array(rng.randint(0, 5, (4,)), dtype="int32")
    data.attach_grad()
    with autograd.record():
        out = mx.nd.SoftmaxOutput(data, label, grad_scale=2.0)
    out.backward()
    prob = np.exp(data.asnumpy()) / np.exp(data.asnumpy()).sum(-1, keepdims=True)
    onehot = np.eye(5, dtype="float32")[label.asnumpy().astype(int)]
    np.testing.assert_allclose(data.grad.asnumpy(), 2.0 * (prob - onehot),
                               rtol=1e-5, atol=1e-6)


def test_softmax_output_ignore_label():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    rng = np.random.RandomState(4)
    data = mx.nd.array(rng.randn(4, 5).astype("float32"))
    label = mx.nd.array(np.array([0, 1, -1, 2]), dtype="int32")
    data.attach_grad()
    with autograd.record():
        out = mx.nd.SoftmaxOutput(data, label, use_ignore=True, ignore_label=-1)
    out.backward()
    g = data.grad.asnumpy()
    assert np.allclose(g[2], 0.0)
    assert not np.allclose(g[0], 0.0)


class TestDeformableConvolution:
    def test_zero_offset_matches_standard_conv(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        w = rng.randn(5, 3, 3, 3).astype(np.float32)
        off = np.zeros((2, 2 * 9, 8, 8), np.float32)
        out_d = nd.DeformableConvolution(
            nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
            pad=(1, 1), num_filter=5, no_bias=True)
        out_c = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                               pad=(1, 1), num_filter=5, no_bias=True)
        assert_close(out_d, out_c.asnumpy(), rtol=1e-4)

    def test_integer_shift_offset(self):
        # constant (dy=0, dx=1) offset == convolving the left-shifted image
        rng = np.random.RandomState(1)
        x = rng.randn(1, 2, 6, 6).astype(np.float32)
        w = rng.randn(4, 2, 3, 3).astype(np.float32)
        off = np.zeros((1, 18, 6, 6), np.float32)
        off[:, 1::2] = 1.0  # dx for every tap
        out_d = nd.DeformableConvolution(
            nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
            pad=(1, 1), num_filter=4, no_bias=True)
        x_shift = np.zeros_like(x)
        x_shift[..., :-1] = x[..., 1:]  # shift left, zero-pad right edge
        out_c = nd.Convolution(nd.array(x_shift), nd.array(w), kernel=(3, 3),
                               pad=(1, 1), num_filter=4, no_bias=True)
        # interior columns match exactly; both boundaries differ (the
        # deformed sample stays in-bounds where the shifted image hits
        # conv zero-padding), so compare away from them
        assert_close(out_d.asnumpy()[..., 1:-2], out_c.asnumpy()[..., 1:-2],
                     rtol=1e-4)

    def test_gradients_flow_to_offsets(self):
        rng = np.random.RandomState(2)
        x = nd.array(rng.randn(1, 2, 5, 5).astype(np.float32))
        w = nd.array(rng.randn(3, 2, 3, 3).astype(np.float32))
        off = nd.array((rng.rand(1, 18, 5, 5) * 0.3).astype(np.float32))
        for v in (x, w, off):
            v.attach_grad()
        with autograd.record():
            out = nd.DeformableConvolution(x, off, w, kernel=(3, 3),
                                           pad=(1, 1), num_filter=3,
                                           no_bias=True)
            loss = (out * out).sum()
        loss.backward()
        for v, name in ((x, "data"), (w, "weight"), (off, "offset")):
            g = v.grad.asnumpy()
            assert np.isfinite(g).all(), name
            assert np.abs(g).sum() > 0, f"no gradient reached {name}"

    def test_stride_and_deformable_groups(self):
        rng = np.random.RandomState(3)
        x = rng.randn(1, 4, 9, 9).astype(np.float32)
        w = rng.randn(2, 4, 3, 3).astype(np.float32)
        off = np.zeros((1, 2 * 2 * 9, 5, 5), np.float32)  # G=2, (Ho, Wo)
        out = nd.DeformableConvolution(
            nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
            stride=(2, 2), pad=(1, 1), num_filter=2,
            num_deformable_group=2, no_bias=True)
        ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                             stride=(2, 2), pad=(1, 1), num_filter=2,
                             no_bias=True)
        assert out.shape == (1, 2, 5, 5)
        assert_close(out, ref.asnumpy(), rtol=1e-4)
        # offset map at input resolution must be rejected (stride
        # misalignment would otherwise be silent)
        bad = np.zeros((1, 2 * 2 * 9, 9, 9), np.float32)
        with pytest.raises(ValueError, match="OUTPUT spatial"):
            nd.DeformableConvolution(
                nd.array(x), nd.array(bad), nd.array(w), kernel=(3, 3),
                stride=(2, 2), pad=(1, 1), num_filter=2,
                num_deformable_group=2, no_bias=True)


class TestLegacyLossHeads:
    """Round-4 tail: regression/SVM/MakeLoss heads (reference
    regression_output.cc, svm_output.cc, make_loss.cc [unverified]) —
    forward is the prediction, backward injects the loss gradient."""

    def test_linear_regression_output(self):
        rng = np.random.RandomState(0)
        d = nd.array(rng.rand(4, 3).astype(np.float32))
        lab = nd.array(rng.rand(4, 3).astype(np.float32))
        d.attach_grad()
        with mx.autograd.record():
            out = mx.nd.LinearRegressionOutput(d, lab)
        np.testing.assert_allclose(out.asnumpy(), d.asnumpy())
        out.backward()
        np.testing.assert_allclose(
            d.grad.asnumpy(), (d.asnumpy() - lab.asnumpy()) / 3, rtol=1e-5)

    def test_logistic_regression_output(self):
        rng = np.random.RandomState(1)
        d = nd.array(rng.randn(4, 1).astype(np.float32))
        lab = nd.array(rng.randint(0, 2, (4, 1)).astype(np.float32))
        d.attach_grad()
        with mx.autograd.record():
            out = mx.nd.LogisticRegressionOutput(d, lab)
        sig = 1 / (1 + np.exp(-d.asnumpy()))
        np.testing.assert_allclose(out.asnumpy(), sig, rtol=1e-5)
        out.backward()
        np.testing.assert_allclose(d.grad.asnumpy(), sig - lab.asnumpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_mae_regression_output(self):
        d = nd.array(np.asarray([[2.0, -1.0]], np.float32))
        lab = nd.array(np.asarray([[0.0, 0.0]], np.float32))
        d.attach_grad()
        with mx.autograd.record():
            out = mx.nd.MAERegressionOutput(d, lab)
        out.backward()
        np.testing.assert_allclose(d.grad.asnumpy(), [[0.5, -0.5]])

    def test_make_loss(self):
        d = nd.array(np.asarray([1.0, 2.0], np.float32))
        d.attach_grad()
        with mx.autograd.record():
            out = mx.nd.MakeLoss(d, grad_scale=2.0)
        np.testing.assert_allclose(out.asnumpy(), d.asnumpy())
        out.backward()
        np.testing.assert_allclose(d.grad.asnumpy(), [2.0, 2.0])

    def test_svm_output(self):
        d = nd.array(np.asarray([[2.0, 1.0, 0.0]], np.float32))
        lab = nd.array(np.asarray([0.0], np.float32))
        d.attach_grad()
        with mx.autograd.record():
            out = mx.nd.SVMOutput(d, lab, margin=1.0, use_linear=True)
        np.testing.assert_allclose(out.asnumpy(), d.asnumpy())
        out.backward()
        g = d.grad.asnumpy()
        # class 1 violates (1 - 2 + 1 = 0 not > 0)? boundary: not viol;
        # class 2: 0 - 2 + 1 = -1 < 0 not viol -> but class1 at margin
        # boundary (>0 strict) -> no violations -> zero grad
        np.testing.assert_allclose(g, np.zeros((1, 3)))
        d2 = nd.array(np.asarray([[0.5, 1.0, 0.0]], np.float32))
        d2.attach_grad()
        with mx.autograd.record():
            out2 = mx.nd.SVMOutput(d2, lab, margin=1.0, use_linear=True)
        out2.backward()
        g2 = d2.grad.asnumpy()
        assert g2[0, 1] > 0 and g2[0, 0] < 0  # label pushed up, violator down

    def test_cumsum_batch_take_ravel(self):
        x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
        np.testing.assert_allclose(
            mx.nd.cumsum(x, axis=1).asnumpy(),
            np.cumsum(np.arange(6).reshape(2, 3), axis=1))
        idx = nd.array(np.asarray([2, 0], np.float32))
        np.testing.assert_allclose(
            mx.nd.batch_take(x, idx).asnumpy(), [2.0, 3.0])
        flat = mx.nd.unravel_index(nd.array(np.asarray([5], np.float32)),
                                   shape=(2, 3)).asnumpy()
        np.testing.assert_array_equal(flat.ravel(), [1, 2])
        r = mx.nd.ravel_multi_index(
            nd.array(np.asarray([[1], [2]], np.float32)), shape=(2, 3))
        np.testing.assert_array_equal(r.asnumpy(), [5])


class TestRegistryRandomOps:
    """Registry forms of the samplers (reference sample_op.cc /
    multisample_op.cc): _random_* from scalars, sample_* per-element."""

    def test_random_ops_shapes_and_stats(self):
        mx.random.seed(5)
        u = mx.nd.random_uniform(low=2.0, high=4.0, shape=(2000,)).asnumpy()
        assert u.shape == (2000,) and 2.0 <= u.min() and u.max() <= 4.0
        n = mx.nd.random_normal(loc=1.0, scale=0.1, shape=(2000,)).asnumpy()
        assert abs(n.mean() - 1.0) < 0.02
        r = mx.nd.random_randint(low=0, high=7, shape=(500,)).asnumpy()
        assert r.min() >= 0 and r.max() < 7 and r.dtype == np.int32
        p = mx.nd.random_poisson(lam=4.0, shape=(2000,)).asnumpy()
        assert abs(p.mean() - 4.0) < 0.3
        e = mx.nd.random_exponential(lam=2.0, shape=(4000,)).asnumpy()
        assert abs(e.mean() - 0.5) < 0.05
        g = mx.nd.random_gamma(alpha=3.0, beta=2.0, shape=(4000,)).asnumpy()
        assert abs(g.mean() - 6.0) < 0.4

    def test_sample_ops_per_element(self):
        mx.random.seed(6)
        lows = nd.array(np.asarray([0.0, 10.0], np.float32))
        highs = nd.array(np.asarray([1.0, 11.0], np.float32))
        s = mx.nd.sample_uniform(lows, highs, shape=(500,)).asnumpy()
        assert s.shape == (2, 500)
        assert s[0].max() <= 1.0 and 10.0 <= s[1].min() <= s[1].max() <= 11.0
        mus = nd.array(np.asarray([0.0, 100.0], np.float32))
        sig = nd.array(np.asarray([1.0, 1.0], np.float32))
        sn = mx.nd.sample_normal(mus, sig, shape=(800,)).asnumpy()
        assert abs(sn[0].mean()) < 0.15 and abs(sn[1].mean() - 100.0) < 0.15

    def test_random_ops_draw_fresh(self):
        mx.random.seed(7)
        a = mx.nd.random_uniform(shape=(16,)).asnumpy()
        b = mx.nd.random_uniform(shape=(16,)).asnumpy()
        assert not np.array_equal(a, b)  # deny-listed from jit freezing


def test_scalar_op_family():
    """Reference elemwise_binary_scalar_op names: distinct registry ops
    (they appear verbatim in reference-exported symbol JSON)."""
    x = nd.array(np.asarray([[1.0, -2.0], [4.0, 0.5]], np.float32))
    cases = {
        "_plus_scalar": x.asnumpy() + 2.0,
        "_rminus_scalar": 2.0 - x.asnumpy(),
        "_mul_scalar": x.asnumpy() * 2.0,
        "_rdiv_scalar": 2.0 / x.asnumpy(),
        "_power_scalar": x.asnumpy() ** 2.0,
        "_maximum_scalar": np.maximum(x.asnumpy(), 2.0),
        "_lesser_scalar": (x.asnumpy() < 2.0).astype(np.float32),
    }
    for name, want in cases.items():
        got = getattr(mx.nd, name)(x, scalar=2.0).asnumpy()
        np.testing.assert_allclose(got, want, rtol=1e-6, err_msg=name)
    # gradient flows through the arithmetic ones
    x.attach_grad()
    with mx.autograd.record():
        out = mx.nd._mul_scalar(x, scalar=3.0)
    out.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.full((2, 2), 3.0))
    # and the names round-trip through symbol JSON (reference graphs)
    from mxnet_tpu import sym
    a = sym.var("a")
    s = sym._mul_scalar(a, scalar=4.0)
    s2 = sym.load_json(s.tojson())
    r = s2.eval(a=nd.array(np.ones(3, np.float32)))[0]
    np.testing.assert_allclose(r.asnumpy(), [4.0, 4.0, 4.0])


def test_creation_and_legacy_tail_ops():
    """_zeros/_ones/_full/_arange appear in reference symbol JSON;
    legacy aliases + Crop (crop.cc)."""
    assert mx.nd._zeros(shape=(2, 3)).asnumpy().sum() == 0
    np.testing.assert_allclose(mx.nd._full(shape=(2,), value=7).asnumpy(),
                               [7.0, 7.0])
    np.testing.assert_allclose(
        mx.nd._arange(start=0, stop=3, repeat=2).asnumpy(),
        [0, 0, 1, 1, 2, 2])
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose(mx.nd.zeros_like(x).asnumpy(),
                               np.zeros((2, 3)))
    np.testing.assert_allclose(mx.nd.ones_like(x).asnumpy(),
                               np.ones((2, 3)))
    np.testing.assert_allclose(mx.nd.reverse(x, axis=1).asnumpy(),
                               np.arange(6, dtype=np.float32
                                         ).reshape(2, 3)[:, ::-1])
    np.testing.assert_allclose(mx.nd.degrees(nd.array(
        np.asarray([np.pi], np.float32))).asnumpy(), [180.0], rtol=1e-5)
    a = nd.array(np.asarray([1.0, 0.0], np.float32))
    b = nd.array(np.asarray([1.0, 1.0], np.float32))
    np.testing.assert_allclose(mx.nd.logical_and(a, b).asnumpy(), [1, 0])
    s = nd.array(np.random.rand(2, 4, 3).astype(np.float32))
    np.testing.assert_allclose(mx.nd.argmax_channel(s).asnumpy(),
                               s.asnumpy().argmax(1))
    # Crop: offset and like-input forms
    img = nd.array(np.arange(2 * 1 * 6 * 6, dtype=np.float32
                             ).reshape(2, 1, 6, 6))
    c1 = mx.nd.Crop(img, offset=(1, 2), h_w=(3, 3)).asnumpy()
    np.testing.assert_allclose(c1, img.asnumpy()[:, :, 1:4, 2:5])
    ref = nd.array(np.zeros((2, 1, 4, 4), np.float32))
    c2 = mx.nd.Crop(img, ref, num_args=2, center_crop=True).asnumpy()
    np.testing.assert_allclose(c2, img.asnumpy()[:, :, 1:5, 1:5])
    # symbol JSON round trip of a creation op (reference graphs embed them)
    from mxnet_tpu import sym
    z = sym._arange(start=0, stop=4)
    out = sym.load_json((z + sym.var("a")).tojson()).eval(
        a=nd.array(np.ones(4, np.float32)))[0]
    np.testing.assert_allclose(out.asnumpy(), [1, 2, 3, 4])


def test_ctc_loss():
    """CTCLoss over the optax forward algorithm (reference warp-ctc
    contract: (T, N, C) activations, per-sample NLL)."""
    rng = np.random.RandomState(0)
    T, N, C, L = 10, 2, 5, 3
    data = nd.array(rng.randn(T, N, C).astype(np.float32))
    label = nd.array(np.asarray([[1, 2, 3], [2, 4, 0]], np.float32))
    out = mx.nd.CTCLoss(data, label).asnumpy()
    assert out.shape == (N,)
    assert (out > 0).all() and np.isfinite(out).all()
    # a sequence that matches its only label perfectly should have a
    # much smaller loss than a contradicting one
    strong = np.full((6, 1, 3), -10.0, np.float32)
    strong[:, 0, 1] = 10.0  # class 1 at every step
    l_match = mx.nd.CTCLoss(nd.array(strong),
                            nd.array(np.asarray([[1]], np.float32))
                            ).asnumpy()[0]
    l_wrong = mx.nd.CTCLoss(nd.array(strong),
                            nd.array(np.asarray([[2]], np.float32))
                            ).asnumpy()[0]
    assert l_match < 1.0 < l_wrong
    # gradients flow (training usability)
    x = nd.array(rng.randn(T, N, C).astype(np.float32))
    x.attach_grad()
    with mx.autograd.record():
        loss = mx.nd.CTCLoss(x, label).sum()
    loss.backward()
    assert np.isfinite(x.grad.asnumpy()).all()
    assert np.abs(x.grad.asnumpy()).sum() > 0
    # explicit lengths path
    out2 = mx.nd.CTCLoss(data, label,
                         nd.array(np.asarray([10, 8], np.float32)),
                         nd.array(np.asarray([3, 2], np.float32)),
                         use_data_lengths=True,
                         use_label_lengths=True).asnumpy()
    assert out2.shape == (N,) and np.isfinite(out2).all()


def test_ctc_loss_blank_last_padding():
    """Review fix: blank_label='last' uses -1 padding (reference
    convention) — padded slots must not flow in as class ids."""
    strong = np.full((6, 1, 4), -10.0, np.float32)
    strong[:, 0, 1] = 10.0
    lab = nd.array(np.asarray([[1, -1, -1]], np.float32))
    l_pad = mx.nd.CTCLoss(nd.array(strong), lab,
                          blank_label="last").asnumpy()[0]
    l_len = mx.nd.CTCLoss(nd.array(strong),
                          nd.array(np.asarray([[1, 0, 0]], np.float32)),
                          None, nd.array(np.asarray([1], np.float32)),
                          use_label_lengths=True,
                          blank_label="last").asnumpy()[0]
    np.testing.assert_allclose(l_pad, l_len, rtol=1e-4, atol=1e-5)
    assert l_pad < 1.0
