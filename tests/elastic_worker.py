"""Worker body for the elastic-restart test: a 2-process global-mesh
training job where rank 1 dies mid-run on the first attempt; the
relaunched attempt resumes from the latest COMMITTED sharded checkpoint
and finishes. Exercises SURVEY §5 failure recovery end-to-end:
crash -> launcher teardown -> relaunch -> checkpoint restore -> resume.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if "MXNET_TPU_PROC_ID" in os.environ and __name__ == "__main__":
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=4")
    os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")


def main():
    from jax.sharding import Mesh

    from mxnet_tpu.parallel import init_process_group

    coord = os.environ["MXNET_TPU_COORDINATOR"]
    nproc = int(os.environ["MXNET_TPU_NUM_PROCS"])
    pid = int(os.environ["MXNET_TPU_PROC_ID"])
    attempt = int(os.environ.get("MXNET_TPU_RESTART_COUNT", "0"))
    init_process_group(coord, nproc, pid)

    import mxnet_tpu as mx
    from mxnet_tpu import checkpoint as ck, nd
    from tests.test_trainstep_checkpoint import (_make_step, TP_RULES,
                                                 X, Y, _params)

    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    step = _make_step(mesh, TP_RULES, seed=11)

    ckdir = os.environ["ELASTIC_CKPT"]
    start = 0
    if ck.latest_step(ckdir) is not None:
        ck.load_checkpoint(ckdir, train_step=step)
        start = step._t
        print(f"worker {pid} attempt {attempt}: resumed from step {start}")
    if attempt >= 1:
        # the crash happened after step 3 committed; resume must see it
        assert start >= 3, f"resume lost progress: start={start}"

    for t in range(start + 1, 7):
        step(nd.array(X), nd.array(Y))
        ck.save_checkpoint(ckdir, t, train_step=step)
        if attempt == 0 and t == 3 and pid == 1:
            time.sleep(2)  # let rank 0 finish committing step 3
            print("worker 1: simulating mid-training crash")
            os._exit(13)

    if pid == 0:
        np.savez(os.environ["ELASTIC_OUT"], **_params(step))
    print(f"worker {pid} attempt {attempt}: finished at step {step._t}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
