"""Parity tests for the blocked vocab-projection + cross-entropy.

Checks value and gradients against the naive materialized-logits pipeline
(reference semantics: FullyConnected -> log_softmax -> pick)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.ops.fused_loss import linear_cross_entropy


def _naive(x, w, labels, ignore_label=None):
    logits = jnp.dot(
        x.reshape(-1, x.shape[-1]), w.T, preferred_element_type=jnp.float32
    )
    lp = jax.nn.log_softmax(logits, axis=-1)
    lf = labels.reshape(-1)
    loss = -jnp.take_along_axis(lp, lf[:, None], axis=-1)[:, 0]
    if ignore_label is not None:
        loss = jnp.where(lf == ignore_label, 0.0, loss)
    return loss.reshape(labels.shape)


@pytest.mark.parametrize("n,h,v,block", [
    (17, 8, 50, 16),      # vocab not divisible by block
    (32, 16, 64, 64),     # single block
    (8, 4, 200, 32),      # many blocks
])
def test_value_parity_f32(n, h, v, block):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, h), jnp.float32)
    w = jnp.asarray(rng.randn(v, h), jnp.float32)
    labels = jnp.asarray(rng.randint(0, v, (n,)), jnp.int32)
    got = linear_cross_entropy(x, w, labels, mode="blocked", block_size=block)
    want = _naive(x, w, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5,
                               atol=1e-5)


def test_grad_parity_f32():
    rng = np.random.RandomState(1)
    n, h, v = 24, 12, 90
    x = jnp.asarray(rng.randn(n, h), jnp.float32)
    w = jnp.asarray(rng.randn(v, h), jnp.float32)
    labels = jnp.asarray(rng.randint(0, v, (n,)), jnp.int32)
    gsc = jnp.asarray(rng.rand(n), jnp.float32)  # non-uniform upstream grads

    def fused(x, w):
        return jnp.sum(linear_cross_entropy(x, w, labels, mode="blocked", block_size=32) * gsc)

    def naive(x, w):
        return jnp.sum(_naive(x, w, labels) * gsc)

    gx1, gw1 = jax.grad(fused, argnums=(0, 1))(x, w)
    gx2, gw2 = jax.grad(naive, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2), rtol=2e-4,
                               atol=2e-4)


def test_ignore_label():
    rng = np.random.RandomState(2)
    n, h, v = 16, 8, 40
    x = jnp.asarray(rng.randn(n, h), jnp.float32)
    w = jnp.asarray(rng.randn(v, h), jnp.float32)
    labels = jnp.asarray(rng.randint(0, v, (n,)), jnp.int32)
    labels = labels.at[::4].set(0)
    got = linear_cross_entropy(x, w, labels, mode="blocked", block_size=16, ignore_label=0)
    want = _naive(x, w, labels, ignore_label=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5,
                               atol=1e-5)
    # grads of ignored rows must be exactly zero
    def fused(x):
        return jnp.sum(linear_cross_entropy(x, w, labels, mode="blocked", block_size=16,
                                            ignore_label=0))
    gx = jax.grad(fused)(x)
    assert np.allclose(np.asarray(gx)[::4], 0.0)


def test_bf16_inputs_leading_shape():
    rng = np.random.RandomState(3)
    b, s, h, v = 4, 6, 16, 120
    x = jnp.asarray(rng.randn(b, s, h), jnp.bfloat16)
    w = jnp.asarray(rng.randn(v, h), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, v, (b, s)), jnp.int32)
    got = linear_cross_entropy(x, w, labels, mode="blocked", block_size=64)
    assert got.shape == (b, s)
    assert got.dtype == jnp.float32
    want = _naive(x.astype(jnp.float32), w.astype(jnp.float32), labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=5e-2,
                               atol=5e-2)

    def f(x, w):
        return jnp.mean(linear_cross_entropy(x, w, labels, mode="blocked", block_size=64))
    gx, gw = jax.grad(f, argnums=(0, 1))(x, w)
    assert gx.dtype == jnp.bfloat16 and gw.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(gx, dtype=np.float32)).all()


def test_jit_and_vs_big_block():
    # one-block path == multi-block path, and both jit cleanly
    rng = np.random.RandomState(4)
    n, h, v = 10, 8, 70
    x = jnp.asarray(rng.randn(n, h), jnp.float32)
    w = jnp.asarray(rng.randn(v, h), jnp.float32)
    labels = jnp.asarray(rng.randint(0, v, (n,)), jnp.int32)
    f1 = jax.jit(lambda x: linear_cross_entropy(x, w, labels, mode="blocked", block_size=16))
    f2 = jax.jit(lambda x: linear_cross_entropy(x, w, labels, mode="blocked", block_size=4096))
    np.testing.assert_allclose(np.asarray(f1(x)), np.asarray(f2(x)),
                               rtol=1e-5, atol=1e-5)


def test_mode_auto_and_dense_parity():
    """Round-4 auto-select: dense under the byte budget, blocked above;
    both match the reference computation."""
    import os

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(0, 1, (12, 8)).astype(np.float32))
    w = jnp.asarray(rng.normal(0, 0.1, (40, 8)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 40, (12,)).astype(np.int32))
    logits = np.asarray(x) @ np.asarray(w).T
    ref = (np.log(np.exp(logits - logits.max(1, keepdims=True)).sum(1))
           + logits.max(1) - logits[np.arange(12), np.asarray(labels)])
    for mode in ("dense", "blocked", "auto"):
        got = np.asarray(linear_cross_entropy(x, w, labels, mode=mode))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5,
                                   err_msg=mode)
    # auto flips to blocked when the budget is tiny
    os.environ["MXTPU_CE_DENSE_MAX_BYTES"] = "1"
    try:
        got = np.asarray(linear_cross_entropy(x, w, labels, mode="auto"))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    finally:
        del os.environ["MXTPU_CE_DENSE_MAX_BYTES"]
