"""Forward bulk queue + bulked backward semantics (round-5; reference
analogue: engine bulked segments, ``MXNET_GLUON_EXEC_BULK_SIZE``,
``src/imperative/imperative_utils.h`` [unverified]).

The invariants that must hold for laziness to be invisible:
value reads flush; shape/dtype peek WITHOUT flushing; operands are
captured by value at enqueue (later mutation cannot retroactively change
a queued op); the bulked backward is numerically identical to per-op
replay; every kill switch restores the old path.
"""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, imperative, nd
from mxnet_tpu.ndarray.ndarray import _Pending


def _pending(a):
    return type(a._chunk.data) is _Pending


def test_shape_peek_does_not_flush():
    x = nd.array(np.ones((4, 5), np.float32))
    y = x * 2.0 + 1.0
    assert _pending(y)
    assert y.shape == (4, 5) and y.dtype == np.float32
    assert _pending(y), "shape/dtype peek must not force the queue"
    np.testing.assert_allclose(y.asnumpy(), np.full((4, 5), 3.0))
    assert not _pending(y)


def test_capture_by_value_mutation_after_enqueue():
    """w is mutated in place AFTER an op consuming it was queued: the
    queued op must see the value at call time, not the mutated one."""
    w = nd.array(np.ones((3,), np.float32))
    y = w * 10.0  # queued against w == 1
    w += 5.0      # in-place rebind (w's read does NOT flush y's queue...
    # ...necessarily; either way y must be 10, not 60)
    np.testing.assert_allclose(y.asnumpy(), [10.0, 10.0, 10.0])
    np.testing.assert_allclose(w.asnumpy(), [6.0, 6.0, 6.0])


def test_rebind_of_pending_not_clobbered_by_flush():
    x = nd.array(np.ones((2,), np.float32))
    y = x + 1.0           # pending
    y._rebind((x * 0.0).data)  # user replaces y's value before flush
    imperative.flush_bulk()
    np.testing.assert_allclose(y.asnumpy(), [0.0, 0.0])


def test_segment_contains_multiple_ops():
    imperative.flush_bulk()
    before = len(imperative._SEG_CACHE)
    x = nd.array(np.random.rand(4, 4).astype(np.float32))
    y = ((x * 2.0) + 1.0).tanh() - 0.5
    y.asnumpy()
    grew = len(imperative._SEG_CACHE) - before
    assert grew >= 1  # the chain compiled as segment(s), not per-op


def test_bulk_parity_with_disabled():
    rng = np.random.RandomState(0)
    xs = rng.rand(8, 6).astype(np.float32)

    def run():
        x = nd.array(xs)
        y = nd.dot(x, x.T)
        z = (y.tanh() * 0.5 + y).sum(axis=1)
        return z.asnumpy()

    on = run()
    os.environ["MXTPU_BULK_FWD"] = "0"
    try:
        off = run()
    finally:
        os.environ.pop("MXTPU_BULK_FWD")
    np.testing.assert_allclose(on, off, rtol=1e-6, atol=1e-7)


def test_backward_bulk_parity():
    rng = np.random.RandomState(1)
    xs = rng.rand(5, 4).astype(np.float32)

    def run():
        w = nd.array(xs)
        w.attach_grad()
        with autograd.record():
            y = (w * w).tanh()
            loss = (y * 3.0).sum()
        loss.backward()
        return w.grad.asnumpy()

    g_bulk = run()
    os.environ["MXTPU_BULK_BWD"] = "0"
    try:
        g_plain = run()
    finally:
        os.environ.pop("MXTPU_BULK_BWD")
    np.testing.assert_allclose(g_bulk, g_plain, rtol=1e-6, atol=1e-7)


def test_grad_add_accumulates_through_bulk():
    w = nd.array(np.ones((3,), np.float32))
    w.attach_grad(grad_req="add")
    for _ in range(2):
        with autograd.record():
            loss = (w * w).sum()
        loss.backward()
    np.testing.assert_allclose(w.grad.asnumpy(), [4.0, 4.0, 4.0])


def test_denied_op_interleaves_correctly():
    """A deny-listed (RNG) op in the middle of a chain: earlier queued
    ops must flush before it consumes their values."""
    mx.random.seed(7)
    x = nd.array(np.full((64, 64), 2.0, np.float32))
    y = x * 3.0  # queued
    with autograd.train_mode():
        d = nd.Dropout(y, p=0.5)  # denied: consumes y.data -> flush
    out = d.asnumpy()
    kept = out[out != 0]
    np.testing.assert_allclose(kept, np.full_like(kept, 12.0))  # 6 / (1-p)


def test_head_grads_respected_in_bulk_backward():
    w = nd.array(np.ones((4,), np.float32))
    w.attach_grad()
    with autograd.record():
        y = w * 2.0
        z = y + 1.0
    z.backward(nd.array(np.asarray([1.0, 2.0, 3.0, 4.0], np.float32)))
    np.testing.assert_allclose(w.grad.asnumpy(), [2.0, 4.0, 6.0, 8.0])


def test_retain_graph_allows_second_backward():
    w = nd.array(np.ones((2,), np.float32))
    w.attach_grad()
    with autograd.record():
        y = (w * 3.0).tanh()
        loss = y.sum()
    loss.backward(retain_graph=True)
    g1 = w.grad.asnumpy().copy()
    loss.backward()
    np.testing.assert_allclose(w.grad.asnumpy(), g1)


def test_queue_caps_at_bulk_size():
    imperative.flush_bulk()
    x = nd.array(np.ones((2, 2), np.float32))
    y = x
    for _ in range(imperative._bulk_size() + 3):
        y = y + 1.0
    # the queue must have auto-flushed at the cap: at most (cap - 1)
    # entries remain pending
    assert len(imperative._queue().entries) < imperative._bulk_size()
    y.asnumpy()


def test_pending_never_escapes_to_user_numpy():
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = x * 2.0
    arr = np.asarray(y)  # __array__ path
    np.testing.assert_allclose(arr, xs := np.arange(6).reshape(2, 3) * 2.0)
    assert float((y + 0.0).asscalar() if False else y.sum().asscalar()) == \
        float(xs.sum())


def test_donating_update_flushes_queue_first():
    """Regression (round-5 suite): a forward output left pending while a
    donating optimizer update consumes the same weight buffer — the
    queue must flush before donation deletes its captured operand."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    net = nn.Dense(4)
    net.initialize()
    x = nd.array(np.random.rand(2, 3).astype(np.float32))
    out = net(x)  # enqueued, never read
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.1})
    with autograd.record():
        loss = (net(x) * 1.0).sum()
    loss.backward()
    trainer.step(2)  # per-param path donates weight buffers
    out.asnumpy()  # must not read deleted memory


def test_weak_scalar_promotion_through_queue():
    """Advisor round-5 review: a weak-typed scalar operand must keep its
    promotion semantics through the queue — bf16 * scalar stays bf16,
    and the peeked dtype agrees with the delivered one."""
    x = nd.array(np.ones((3,), np.float32)).astype("bfloat16")
    s = nd.array(2.0)  # weak f32 scalar array
    y = x * s
    peek = y.dtype
    got = y.asnumpy()
    assert str(got.dtype) == "bfloat16", got.dtype
    assert str(peek) == str(got.dtype), (peek, got.dtype)


def test_runtime_bulk_size_change_respected():
    """MXNET_GLUON_EXEC_BULK_SIZE is re-read per call (base.get_env
    contract), so flipping it at runtime takes effect."""
    imperative.flush_bulk()
    os.environ["MXNET_GLUON_EXEC_BULK_SIZE"] = "0"
    try:
        x = nd.array(np.ones((2,), np.float32))
        y = x + 1.0
        from mxnet_tpu.ndarray.ndarray import _Pending as _P
        assert type(y._chunk.data) is not _P  # executed immediately
    finally:
        os.environ.pop("MXNET_GLUON_EXEC_BULK_SIZE")


def test_backward_releases_primal_buffers():
    """After a non-retained backward, nodes must not keep primal operand
    buffers (xs) alive through the loss reference."""
    w = nd.array(np.ones((4,), np.float32))
    w.attach_grad()
    with autograd.record():
        loss = (w * 3.0).sum()
    loss.backward()
    node = loss._ag.node
    assert node.freed and node.xs is None and node.bwd_fn is None
