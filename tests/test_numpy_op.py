"""mx.np fidelity vs NumPy (VERDICT weak #7: the reference ships
``tests/python/unittest/test_numpy_op.py`` with thousands of semantic
checks [unverified]; this covers the load-bearing subset — results,
dtype promotion, reductions, indexing, linalg/fft/random sub-namespaces,
out=, and autograd integration)."""

import os

import numpy as onp
import pytest

# the tunneled axon TPU backend lacks complex/FFT support and, worse, the
# UNIMPLEMENTED fault wedges the backend for every subsequent op in the
# process — keep FFT coverage on the CPU platform run
_skip_no_complex = pytest.mark.skipif(
    os.environ.get("MXTPU_TEST_PLATFORM", "cpu") != "cpu",
    reason="tunneled TPU backend: complex dtypes unimplemented (and the "
           "fault poisons the session)",
)

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu import np as mnp
from mxnet_tpu.ndarray.ndarray import NDArray


def _r(*shape, seed=0):
    return onp.random.RandomState(seed).rand(*shape).astype(onp.float32)


def _check(m_out, n_out, rtol=1e-5, atol=1e-6):
    onp.testing.assert_allclose(
        m_out.asnumpy() if isinstance(m_out, NDArray) else onp.asarray(m_out),
        n_out, rtol=rtol, atol=atol,
    )


UNARY = ["exp", "log", "sqrt", "abs", "sin", "cos", "tanh", "floor", "ceil",
         "sign", "square", "negative"]
BINARY = ["add", "subtract", "multiply", "divide", "power", "maximum",
          "minimum", "hypot", "arctan2"]
REDUCE = ["sum", "mean", "max", "min", "prod", "std", "var", "argmax",
          "argmin"]


class TestElementwise:
    @pytest.mark.parametrize("name", UNARY)
    def test_unary(self, name):
        x = _r(3, 4) + 0.5
        # loosen only for TPU transcendental approximations (~7e-5 on
        # log/tanh); CPU keeps the tight bound
        if os.environ.get("MXTPU_TEST_PLATFORM", "cpu") != "cpu":
            tol = dict(rtol=1e-4, atol=1e-4)
        else:
            tol = dict(rtol=1e-5, atol=1e-6)
        _check(getattr(mnp, name)(mnp.array(x)), getattr(onp, name)(x),
               **tol)

    @pytest.mark.parametrize("name", BINARY)
    def test_binary(self, name):
        a, b = _r(3, 4) + 0.5, _r(3, 4, seed=1) + 0.5
        _check(getattr(mnp, name)(mnp.array(a), mnp.array(b)),
               getattr(onp, name)(a, b), rtol=1e-5)

    def test_broadcasting(self):
        a, b = _r(3, 1), _r(1, 4)
        _check(mnp.array(a) + mnp.array(b), a + b)
        _check(mnp.array(a) * 2.0, a * 2.0)

    def test_python_scalar_promotion(self):
        x = mnp.array(_r(2, 2))
        assert (x + 1).dtype == onp.float32  # scalar must not upcast f32


class TestReductions:
    @pytest.mark.parametrize("name", REDUCE)
    def test_full_reduce(self, name):
        x = _r(4, 5)
        _check(getattr(mnp, name)(mnp.array(x)), getattr(onp, name)(x),
               rtol=1e-5)

    @pytest.mark.parametrize("name", ["sum", "mean", "max", "argmax"])
    def test_axis_keepdims(self, name):
        x = _r(4, 5)
        kw = {} if name == "argmax" else {"keepdims": True}
        _check(getattr(mnp, name)(mnp.array(x), axis=1, **kw),
               getattr(onp, name)(x, axis=1, **kw), rtol=1e-5)

    def test_argmax_dtype_is_integer(self):
        x = mnp.array(_r(3, 4))
        assert onp.issubdtype(mnp.argmax(x).asnumpy().dtype, onp.integer)


class TestShapes:
    def test_reshape_transpose_stack_concat(self):
        x = _r(2, 6)
        _check(mnp.reshape(mnp.array(x), (3, 4)), x.reshape(3, 4))
        _check(mnp.transpose(mnp.array(x)), x.T)
        _check(mnp.stack([mnp.array(x), mnp.array(x)]), onp.stack([x, x]))
        _check(mnp.concatenate([mnp.array(x), mnp.array(x)], axis=1),
               onp.concatenate([x, x], axis=1))

    def test_split_returns_list(self):
        x = _r(6, 2)
        parts = mnp.split(mnp.array(x), 3)
        ref = onp.split(x, 3)
        assert len(parts) == 3
        for p, r in zip(parts, ref):
            _check(p, r)

    def test_where_and_clip(self):
        x = _r(3, 4) - 0.5
        _check(mnp.where(mnp.array(x) > 0, mnp.array(x), mnp.zeros((3, 4))),
               onp.where(x > 0, x, onp.zeros((3, 4), onp.float32)))
        _check(mnp.clip(mnp.array(x), 0.0, 0.3), onp.clip(x, 0.0, 0.3))


class TestCreation:
    def test_creation_defaults_f32(self):
        # MXNet numpy defaults to float32 (unlike numpy's float64)
        for arr in (mnp.zeros((2, 3)), mnp.ones((2, 3)),
                    mnp.full((2,), 7.0)):
            assert arr.dtype == onp.float32
        _check(mnp.arange(5), onp.arange(5, dtype=onp.float32))
        _check(mnp.linspace(0, 1, 5), onp.linspace(0, 1, 5,
                                                   dtype=onp.float32))
        _check(mnp.eye(3), onp.eye(3, dtype=onp.float32))


class TestLinalgFftRandom:
    def test_linalg(self):
        a = _r(3, 3) + onp.eye(3, dtype=onp.float32) * 3
        _check(mnp.linalg.norm(mnp.array(a)), onp.linalg.norm(a), rtol=1e-5)
        _check(mnp.linalg.inv(mnp.array(a)), onp.linalg.inv(a), rtol=1e-3,
               atol=1e-4)
        _check(mnp.dot(mnp.array(a), mnp.array(a)), onp.dot(a, a), rtol=1e-4)

    @_skip_no_complex
    def test_fft_roundtrip(self):
        x = _r(8)
        out = mnp.fft.ifft(mnp.fft.fft(mnp.array(x)))
        onp.testing.assert_allclose(out.asnumpy().real, x, rtol=1e-4,
                                    atol=1e-5)

    def test_random_shapes_and_determinism(self):
        mx.random.seed(3)
        a = mnp.random.uniform(0, 1, (3, 4))
        mx.random.seed(3)
        b = mnp.random.uniform(0, 1, (3, 4))
        assert a.shape == (3, 4)
        _check(a, b.asnumpy())  # same seed, same stream
        n = mnp.random.normal(0, 1, (500,))
        assert abs(float(n.asnumpy().mean())) < 0.2


class TestAutogradIntegration:
    def test_np_ops_record_on_tape(self):
        x = mx.nd.array(_r(3))
        x.attach_grad()
        with autograd.record():
            y = mnp.sum(mnp.exp(x) * 2)
        y.backward()
        onp.testing.assert_allclose(
            x.grad.asnumpy(), 2 * onp.exp(_r(3)), rtol=1e-5
        )

    def test_mixed_nd_np(self):
        x = mx.nd.ones((2, 2))
        out = mnp.add(x, mnp.ones((2, 2)))
        _check(out, onp.full((2, 2), 2.0, onp.float32))


class TestPassthroughStatics:
    def test_positional_axis_under_record(self):
        """Positional axis ints must stay static — not vjp-traced."""
        x = mx.nd.array(_r(2, 3))
        y = mx.nd.array(_r(2, 3, seed=1))
        x.attach_grad()
        with autograd.record():
            out = mnp.concatenate((x, y), 1)
            s = mnp.stack([out, out], 0)
            s.sum().backward()
        onp.testing.assert_allclose(x.grad.asnumpy(),
                                    onp.full((2, 3), 2.0), rtol=1e-6)

    def test_scalar_operand_still_works(self):
        x = mnp.array(_r(2, 2))
        _check(mnp.add(x, 2.0), _r(2, 2) + 2.0)
        _check(mnp.power(x, 2), _r(2, 2) ** 2)
