"""tools/check_no_sync_in_step.py as a tier-1 unit test: the jitted hot
paths — TrainStep's pre-placed fast path (__call__ + _dispatch), the
inference engine's decode path (InferStep.__call__/_dispatch/decode_n)
and the serving batcher's dispatch — must stay free of blocking host
syncs, or the async overlap / O(1)-per-token decode silently degrades."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import check_no_sync_in_step  # noqa: E402


def test_fast_path_is_sync_free():
    violations = check_no_sync_in_step.find_violations()
    assert not violations, "\n".join(
        f"step.py:{ln}: {msg}" for ln, msg in violations)


def test_all_hot_paths_are_sync_free():
    """Train, inference, and serving hot paths together (TARGETS)."""
    violations = check_no_sync_in_step.find_all_violations()
    assert not violations, "\n".join(
        f"{path}:{ln}: {msg}" for path, ln, msg in violations)


def test_targets_cover_inference_engine():
    """The lint must keep covering the decode hot path named in the
    serving contract — a rename that silently drops coverage fails."""
    covered = {(os.path.basename(p), cls): set(funcs)
               for p, cls, funcs in check_no_sync_in_step.TARGETS}
    assert "decode_n" in covered[("infer.py", "InferStep")]
    assert "_dispatch" in covered[("batcher.py", "DynamicBatcher")]


def test_targets_cover_continuous_batching():
    """ISSUE 8: the iteration-level scheduling hot path — the paged
    decode/prefill dispatches and the ContinuousBatcher scheduler loop —
    must stay under the lint."""
    covered = {(os.path.basename(p), cls): set(funcs)
               for p, cls, funcs in check_no_sync_in_step.TARGETS}
    assert "decode_iter" in covered[("infer.py", "InferStep")]
    assert "prefill_paged" in covered[("infer.py", "InferStep")]
    cont = covered[("batcher.py", "ContinuousBatcher")]
    assert "_dispatch" in cont
    assert "_step_once" in cont  # the scheduler loop body


def test_lint_catches_a_violation(tmp_path):
    """The lint itself must actually detect a blocking call (guards
    against the checker rotting into a no-op when step.py is refactored)."""
    bad = tmp_path / "step_bad.py"
    bad.write_text(
        "class TrainStep:\n"
        "    def __call__(self, x):\n"
        "        return float(self._dispatch(x))\n"
        "    def _dispatch(self, x):\n"
        "        return x.asnumpy()\n"
    )
    violations = check_no_sync_in_step.find_violations(str(bad))
    assert len(violations) == 2
    assert any("float" in m for _, m in violations)
    assert any("asnumpy" in m for _, m in violations)


def test_lint_catches_decode_violation(tmp_path):
    """Same self-test for the inference target shape (custom class +
    method list)."""
    bad = tmp_path / "infer_bad.py"
    bad.write_text(
        "class InferStep:\n"
        "    def decode_n(self, src):\n"
        "        import jax\n"
        "        out = self._fn(src)\n"
        "        jax.block_until_ready(out)\n"
        "        return out\n"
    )
    violations = check_no_sync_in_step.find_violations(
        str(bad), "InferStep", ("decode_n",))
    assert len(violations) == 1
    assert "block_until_ready" in violations[0][1]


def test_lint_catches_decode_iter_violation(tmp_path):
    """A host read smuggled into the paged iteration dispatch (the
    continuous-batching hot path) must be flagged — per-token host syncs
    there serialize every scheduler iteration against the device."""
    bad = tmp_path / "infer_bad_paged.py"
    bad.write_text(
        "class InferStep:\n"
        "    def decode_iter(self, state, tables, tokens):\n"
        "        buf, state = self._fn(state, tables, tokens)\n"
        "        return buf.asnumpy(), state\n"
        "    def prefill_paged(self, state, src):\n"
        "        tok0, state = self._fn(state, src)\n"
        "        return int(tok0[0]), state\n"
    )
    violations = check_no_sync_in_step.find_violations(
        str(bad), "InferStep", ("decode_iter", "prefill_paged"))
    assert len(violations) == 2
    assert any("asnumpy" in m for _, m in violations)
    assert any("int" in m for _, m in violations)


def test_lint_catches_scheduler_loop_violation(tmp_path):
    """The ContinuousBatcher scheduler loop body must keep its syncs in
    the designated collect/admit phases — an inline sleep or device read
    in _step_once/_dispatch is a violation."""
    bad = tmp_path / "batcher_bad.py"
    bad.write_text(
        "import time\n"
        "class ContinuousBatcher:\n"
        "    def _step_once(self):\n"
        "        time.sleep(0.01)\n"
        "        return True\n"
        "    def _dispatch(self, live):\n"
        "        out = self._engine.decode_iter(live)\n"
        "        return out[0].tolist()\n"
    )
    violations = check_no_sync_in_step.find_violations(
        str(bad), "ContinuousBatcher", ("_step_once", "_dispatch"))
    assert len(violations) == 2
    assert any("sleep" in m for _, m in violations)
    assert any("tolist" in m for _, m in violations)
