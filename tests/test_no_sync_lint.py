"""tools/check_no_sync_in_step.py as a tier-1 unit test: the TrainStep
pre-placed fast path (__call__ + _dispatch) must stay free of blocking
host syncs, or the async device-feed overlap silently degrades."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import check_no_sync_in_step  # noqa: E402


def test_fast_path_is_sync_free():
    violations = check_no_sync_in_step.find_violations()
    assert not violations, "\n".join(
        f"step.py:{ln}: {msg}" for ln, msg in violations)


def test_lint_catches_a_violation(tmp_path):
    """The lint itself must actually detect a blocking call (guards
    against the checker rotting into a no-op when step.py is refactored)."""
    bad = tmp_path / "step_bad.py"
    bad.write_text(
        "class TrainStep:\n"
        "    def __call__(self, x):\n"
        "        return float(self._dispatch(x))\n"
        "    def _dispatch(self, x):\n"
        "        return x.asnumpy()\n"
    )
    violations = check_no_sync_in_step.find_violations(str(bad))
    assert len(violations) == 2
    assert any("float" in m for _, m in violations)
    assert any("asnumpy" in m for _, m in violations)
