"""2-bit gradient compression (reference:
``src/kvstore/gradient_compression.cc`` + ``tests/python/unittest/
test_kvstore.py`` compression cases [unverified])."""

import numpy as np
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.kvstore.compression import (
    GradientCompression, pack_2bit, quantize_2bit, unpack_2bit,
)


class TestQuantize:
    def test_threshold_semantics(self):
        g = jnp.asarray([-2.0, -0.5, -0.1, 0.0, 0.3, 0.5, 3.0])
        q, r = quantize_2bit(g, 0.5)
        np.testing.assert_allclose(
            np.asarray(q), [-0.5, -0.5, 0, 0, 0, 0.5, 0.5]
        )
        np.testing.assert_allclose(np.asarray(q + r), np.asarray(g), rtol=1e-6)

    def test_pack_unpack_roundtrip(self):
        rng = np.random.RandomState(0)
        g = jnp.asarray(rng.randn(37).astype(np.float32))  # non-multiple of 4
        q, _ = quantize_2bit(g, 0.7)
        packed, n = pack_2bit(q, 0.7)
        assert packed.dtype == jnp.uint8 and packed.shape[0] == (37 + 3) // 4
        out = unpack_2bit(packed, n, 0.7)
        np.testing.assert_allclose(np.asarray(out), np.asarray(q), rtol=1e-6)

    def test_error_feedback_accumulates(self):
        gc = GradientCompression({"type": "2bit", "threshold": 1.0})
        # constant small gradient 0.4 < threshold: quantizes to 0 at first,
        # residual builds until it crosses the threshold
        sent = [np.asarray(gc.compress("k", jnp.full((4,), 0.4)))
                for _ in range(5)]
        total = sum(s.sum() for s in sent)
        # after 5 pushes of 0.4 (=2.0 total per element), ~2.0/1.0 quanta
        # per element should have flowed (error feedback conserves mass)
        np.testing.assert_allclose(total, 4 * 2.0, atol=4 * 0.5)
        assert sent[0].sum() == 0.0  # first push below threshold


class TestKVStoreCompression:
    def test_push_applies_compression(self):
        kv = mx.kv.create("local")
        kv.init("w", nd.zeros((6,)))
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        kv.push("w", nd.array(np.array([2.0, -2.0, 0.1, 0, 0.6, -0.3],
                                       np.float32)))
        out = nd.zeros((6,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(
            out.asnumpy(), [0.5, -0.5, 0.0, 0.0, 0.5, 0.0], rtol=1e-6
        )

    def test_multi_device_residuals_independent(self):
        kv = mx.kv.create("device")
        kv.init("0", nd.zeros((2,)))
        kv.set_gradient_compression({"type": "2bit", "threshold": 1.0})
        # replica 0 pushes 0.6, replica 1 pushes 0.6 -> both below threshold
        kv.push("0", [nd.array(np.array([0.6, 0.6], np.float32)),
                      nd.array(np.array([0.6, 0.6], np.float32))])
        out = nd.zeros((2,))
        kv.pull("0", out=out)
        np.testing.assert_allclose(out.asnumpy(), [0.0, 0.0])
        # second push: residual 0.6 + 0.6 = 1.2 >= 1.0 on each replica
        kv.push("0", [nd.array(np.array([0.6, 0.6], np.float32)),
                      nd.array(np.array([0.6, 0.6], np.float32))])
        kv.pull("0", out=out)
        np.testing.assert_allclose(out.asnumpy(), [2.0, 2.0])  # 1.0 x 2 replicas

    def test_unsupported_type_raises(self):
        kv = mx.kv.create("local")
        try:
            kv.set_gradient_compression({"type": "1bit"})
            assert False
        except mx.base.MXNetError:
            pass


def test_trainer_forwards_compression_params():
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.kvstore.compression import GradientCompression

    net = nn.Dense(2)
    net.initialize()
    net(nd.ones((2, 3)))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore="device",
                       compression_params={"type": "2bit", "threshold": 0.5})
    with autograd.record():
        loss = (net(nd.ones((2, 3))) ** 2).sum()
    loss.backward()
    tr.step(2)
    assert isinstance(tr._kvstore._compression, GradientCompression)


def test_wire_byte_pack_sum_exactness():
    """Round-4 wire path: sum of per-worker unpacked codes must equal the
    sum of per-worker quantized grads exactly (codes are {-t,0,+t})."""
    t = 0.5
    rng = np.random.default_rng(0)
    grads = [rng.normal(0, 1, (13,)).astype(np.float32) for _ in range(4)]
    total_q = np.zeros(13, np.float32)
    total_wire = np.zeros(13, np.float32)
    for g in grads:
        q, _ = quantize_2bit(jnp.asarray(g), t)
        total_q += np.asarray(q)
        packed, n = pack_2bit(q, t)
        total_wire += np.asarray(unpack_2bit(packed, n, t))
    np.testing.assert_array_equal(total_wire, total_q)


def test_compression_order_dynamics_harmless():
    """Round-3 verdict weak #5: per-replica compress-then-sum (local
    path) vs the reference's aggregate-then-compress (dist path, round-4
    wire implementation). Both run error feedback, so both converge on a
    toy least-squares problem; this measures the deviation and pins it
    harmless (both reach the same loss floor)."""
    rng = np.random.default_rng(1)
    dim, workers, steps, lr, t = 8, 4, 300, 0.05, 0.5
    target = rng.normal(0, 1, dim).astype(np.float32)

    def worker_grad(w, k):
        # worker k sees a noisy quadratic: grad = (w - target) + noise_k
        noise = rng.normal(0, 0.3, dim).astype(np.float32)
        return (w - target) / workers + noise / workers

    def run(order):
        w = np.zeros(dim, np.float32)
        resid = [np.zeros(dim, np.float32) for _ in range(workers + 1)]
        for _ in range(steps):
            gs = [worker_grad(w, k) for k in range(workers)]
            if order == "compress_then_sum":
                agg = np.zeros(dim, np.float32)
                for k, g in enumerate(gs):
                    q, r = quantize_2bit(jnp.asarray(g + resid[k]), t)
                    resid[k] = np.asarray(r)
                    agg += np.asarray(q)
            else:  # aggregate_then_compress (reference worker order)
                s = np.sum(gs, axis=0)
                q, r = quantize_2bit(jnp.asarray(s + resid[-1]), t)
                resid[-1] = np.asarray(r)
                agg = np.asarray(q)
            w = w - lr * agg
        return float(np.mean((w - target) ** 2))

    rng = np.random.default_rng(1)
    l1 = run("compress_then_sum")
    rng = np.random.default_rng(1)
    l2 = run("aggregate_then_compress")
    # both orders must converge to a small loss floor (error feedback
    # guarantees this); neither should diverge or stall
    assert l1 < 0.2, f"compress-then-sum stalled at {l1}"
    assert l2 < 0.2, f"aggregate-then-compress stalled at {l2}"
