"""2-bit gradient compression (reference:
``src/kvstore/gradient_compression.cc`` + ``tests/python/unittest/
test_kvstore.py`` compression cases [unverified])."""

import numpy as np
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.kvstore.compression import (
    GradientCompression, pack_2bit, quantize_2bit, unpack_2bit,
)


class TestQuantize:
    def test_threshold_semantics(self):
        g = jnp.asarray([-2.0, -0.5, -0.1, 0.0, 0.3, 0.5, 3.0])
        q, r = quantize_2bit(g, 0.5)
        np.testing.assert_allclose(
            np.asarray(q), [-0.5, -0.5, 0, 0, 0, 0.5, 0.5]
        )
        np.testing.assert_allclose(np.asarray(q + r), np.asarray(g), rtol=1e-6)

    def test_pack_unpack_roundtrip(self):
        rng = np.random.RandomState(0)
        g = jnp.asarray(rng.randn(37).astype(np.float32))  # non-multiple of 4
        q, _ = quantize_2bit(g, 0.7)
        packed, n = pack_2bit(q, 0.7)
        assert packed.dtype == jnp.uint8 and packed.shape[0] == (37 + 3) // 4
        out = unpack_2bit(packed, n, 0.7)
        np.testing.assert_allclose(np.asarray(out), np.asarray(q), rtol=1e-6)

    def test_error_feedback_accumulates(self):
        gc = GradientCompression({"type": "2bit", "threshold": 1.0})
        # constant small gradient 0.4 < threshold: quantizes to 0 at first,
        # residual builds until it crosses the threshold
        sent = [np.asarray(gc.compress("k", jnp.full((4,), 0.4)))
                for _ in range(5)]
        total = sum(s.sum() for s in sent)
        # after 5 pushes of 0.4 (=2.0 total per element), ~2.0/1.0 quanta
        # per element should have flowed (error feedback conserves mass)
        np.testing.assert_allclose(total, 4 * 2.0, atol=4 * 0.5)
        assert sent[0].sum() == 0.0  # first push below threshold


class TestKVStoreCompression:
    def test_push_applies_compression(self):
        kv = mx.kv.create("local")
        kv.init("w", nd.zeros((6,)))
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        kv.push("w", nd.array(np.array([2.0, -2.0, 0.1, 0, 0.6, -0.3],
                                       np.float32)))
        out = nd.zeros((6,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(
            out.asnumpy(), [0.5, -0.5, 0.0, 0.0, 0.5, 0.0], rtol=1e-6
        )

    def test_multi_device_residuals_independent(self):
        kv = mx.kv.create("device")
        kv.init("0", nd.zeros((2,)))
        kv.set_gradient_compression({"type": "2bit", "threshold": 1.0})
        # replica 0 pushes 0.6, replica 1 pushes 0.6 -> both below threshold
        kv.push("0", [nd.array(np.array([0.6, 0.6], np.float32)),
                      nd.array(np.array([0.6, 0.6], np.float32))])
        out = nd.zeros((2,))
        kv.pull("0", out=out)
        np.testing.assert_allclose(out.asnumpy(), [0.0, 0.0])
        # second push: residual 0.6 + 0.6 = 1.2 >= 1.0 on each replica
        kv.push("0", [nd.array(np.array([0.6, 0.6], np.float32)),
                      nd.array(np.array([0.6, 0.6], np.float32))])
        kv.pull("0", out=out)
        np.testing.assert_allclose(out.asnumpy(), [2.0, 2.0])  # 1.0 x 2 replicas

    def test_unsupported_type_raises(self):
        kv = mx.kv.create("local")
        try:
            kv.set_gradient_compression({"type": "1bit"})
            assert False
        except mx.base.MXNetError:
            pass


def test_trainer_forwards_compression_params():
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.kvstore.compression import GradientCompression

    net = nn.Dense(2)
    net.initialize()
    net(nd.ones((2, 3)))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore="device",
                       compression_params={"type": "2bit", "threshold": 0.5})
    with autograd.record():
        loss = (net(nd.ones((2, 3))) ** 2).sum()
    loss.backward()
    tr.step(2)
    assert isinstance(tr._kvstore._compression, GradientCompression)
