"""Faster R-CNN: Proposal/RPN op, second-stage sampler, and the two-stage
model (reference: ``src/operator/contrib/proposal.cc`` + the rcnn
``proposal_target`` pattern / GluonCV faster_rcnn [unverified])."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon.model_zoo.faster_rcnn import faster_rcnn_tiny


class TestProposalOp:
    def test_shapes_and_batch_index(self):
        rng = np.random.RandomState(0)
        B, A, H, W = 2, 6, 8, 8  # A = len(scales) * len(ratios)
        cls_prob = nd.array(rng.rand(B, 2 * A, H, W).astype(np.float32))
        bbox_pred = nd.array(
            (rng.rand(B, 4 * A, H, W) * 0.1).astype(np.float32)
        )
        im_info = nd.array(np.array([[64, 64, 1.0]] * B, np.float32))
        rois = nd.Proposal(cls_prob, bbox_pred, im_info,
                           rpn_pre_nms_top_n=64, rpn_post_nms_top_n=16,
                           scales=(2, 4), ratios=(0.5, 1, 2),
                           feature_stride=8)
        assert rois.shape == (B, 16, 5)
        r = rois.asnumpy()
        assert np.all(r[0, :, 0] == 0) and np.all(r[1, :, 0] == 1)
        # rois clipped to the image
        assert r[..., 1:].min() >= 0 and r[..., 1:].max() <= 63.0

    def test_top_proposal_tracks_hot_anchor(self):
        # plant a single hot fg score; the top roi must decode that anchor
        B, A, H, W = 1, 1, 4, 4
        cls_prob = np.zeros((B, 2, H, W), np.float32)
        cls_prob[0, 1, 2, 3] = 5.0  # fg map, position (y=2, x=3)
        bbox_pred = np.zeros((B, 4, H, W), np.float32)
        im_info = nd.array(np.array([[64, 64, 1.0]], np.float32))
        rois, scores = nd.Proposal(
            nd.array(cls_prob), nd.array(bbox_pred), im_info,
            rpn_pre_nms_top_n=16, rpn_post_nms_top_n=4,
            scales=(2,), ratios=(1,), feature_stride=16,
            output_score=True,
        )
        r = rois.asnumpy()[0, 0]
        # anchor center (3.5*16, 2.5*16) = (56, 40), side 32 -> clipped
        np.testing.assert_allclose(r[1:], [40.0, 24.0, 63.0, 56.0],
                                   atol=1e-4)
        assert scores.asnumpy()[0, 0, 0] == pytest.approx(5.0)

    def test_min_size_filter(self):
        # deltas that shrink boxes below min_size must be score-masked
        B, A, H, W = 1, 1, 2, 2
        cls_prob = np.zeros((B, 2, H, W), np.float32)
        cls_prob[0, 1] = 1.0
        bbox_pred = np.zeros((B, 4, H, W), np.float32)
        bbox_pred[0, 2:] = -6.0  # log-shrink w,h to ~nothing
        im_info = nd.array(np.array([[32, 32, 1.0]], np.float32))
        _, scores = nd.Proposal(
            nd.array(cls_prob), nd.array(bbox_pred), im_info,
            rpn_pre_nms_top_n=4, rpn_post_nms_top_n=4,
            scales=(2,), ratios=(1,), feature_stride=16,
            rpn_min_size=8, output_score=True,
        )
        assert np.all(scores.asnumpy() <= 0)


class TestRCNNTargetSampler:
    def test_fg_bg_split_and_encoding(self):
        rois = np.array([[
            [8, 8, 24, 24],      # IoU 1 with gt 0 -> fg
            [9, 9, 25, 25],      # high IoU -> fg
            [40, 40, 56, 56],    # far -> bg
            [0, 0, 4, 4],        # far -> bg
        ]], np.float32)
        gt = np.array([[[1, 8, 8, 24, 24], [-1, 0, 0, 0, 0]]], np.float32)
        s_rois, cls_t, box_t, box_m = nd.rcnn_target_sampler(
            nd.array(rois), nd.array(gt), num_sample=4, pos_ratio=0.5,
        )
        cls_t = cls_t.asnumpy()[0]
        assert cls_t[0] == 2  # gt class 1 -> target 2
        assert set(cls_t[2:]) == {0}
        bm = box_m.asnumpy()[0]
        assert bm[0].sum() == 4 and bm[2].sum() == 0
        # exact-match roi encodes to ~zero deltas
        np.testing.assert_allclose(box_t.asnumpy()[0, 0], 0.0, atol=1e-5)

    def test_padding_gt_ignored(self):
        rois = np.array([[[0, 0, 10, 10]]], np.float32).repeat(4, axis=1)
        gt = np.full((1, 2, 5), -1, np.float32)  # all padding
        _, cls_t, _, box_m = nd.rcnn_target_sampler(
            nd.array(rois), nd.array(gt), num_sample=4)
        assert np.all(cls_t.asnumpy() == 0)
        assert np.all(box_m.asnumpy() == 0)


class TestFasterRCNNModel:
    def _data(self, rng, B=4, S=64):
        """Images with a bright planted square; gt = its box, class 0."""
        x = rng.rand(B, 3, S, S).astype(np.float32) * 0.1
        gt = np.full((B, 2, 5), -1, np.float32)
        for b in range(B):
            cx, cy = rng.randint(16, S - 16, 2)
            half = 10
            x[b, :, cy - half:cy + half, cx - half:cx + half] += 0.9
            gt[b, 0] = [0, cx - half, cy - half, cx + half, cy + half]
        return x, gt

    def test_train_step_decreases_losses(self):
        rng = np.random.RandomState(0)
        net = faster_rcnn_tiny(num_classes=1, rpn_pre_nms_top_n=128,
                               rpn_post_nms_top_n=32, num_sample=16)
        net.initialize(mx.initializer.Xavier())
        x_np, gt_np = self._data(rng)
        x, gt = nd.array(x_np), nd.array(gt_np)
        ce = gluon.loss.SoftmaxCrossEntropyLoss()
        huber = gluon.loss.HuberLoss()
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 2e-3})
        feat_hw = (x.shape[2] // net._stride, x.shape[3] // net._stride)
        losses = []
        for i in range(30):
            with autograd.record():
                (cls, box, cls_t, box_t, box_m, rpn_cls, rpn_box,
                 rois) = net(x, gt)
                logits, deltas = net.rpn_per_anchor(rpn_cls, rpn_box)
                bt, bm, ct = net.rpn_dense_targets(
                    gt, (x.shape[2], x.shape[3]), feat_hw)
                # dense loss, fg up-weighted: every anchor stays
                # constrained (mined subsets leave un-sampled anchors
                # free to drift high and poison the proposal ranking)
                w = 1.0 + 19.0 * (ct > 0)
                rpn_cls_loss = ce(logits.reshape(-1, 2), ct.reshape(-1),
                                  w.reshape(-1, 1))
                # box losses normalized by the FOREGROUND fraction
                # (reference: smooth-l1 summed over fg / num_fg) — a plain
                # mean over all anchor slots dilutes the gradient ~100x
                # and the box heads never converge in a short schedule
                rpn_box_loss = huber(deltas * bm, bt * bm).mean() \
                    / (bm.mean() + 1e-6)
                rcnn_cls_loss = ce(
                    cls.reshape(-1, cls.shape[-1]), cls_t.reshape(-1))
                rcnn_box_loss = huber(box * box_m, box_t).mean() \
                    / (box_m.mean() + 1e-6)
                L = (rpn_cls_loss.mean() + rpn_box_loss
                     + rcnn_cls_loss.mean() + rcnn_box_loss)
            L.backward()
            trainer.step(x.shape[0])
            losses.append(float(L.asscalar()))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.7, losses[::6]

    def test_detect_finds_planted_object(self):
        rng = np.random.RandomState(1)
        net = faster_rcnn_tiny(num_classes=1, rpn_pre_nms_top_n=128,
                               rpn_post_nms_top_n=32, num_sample=16)
        net.initialize(mx.initializer.Xavier())
        x_np, gt_np = self._data(rng, B=8)
        x, gt = nd.array(x_np), nd.array(gt_np)
        ce = gluon.loss.SoftmaxCrossEntropyLoss()
        huber = gluon.loss.HuberLoss()
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 3e-3})
        feat_hw = (x.shape[2] // net._stride, x.shape[3] // net._stride)
        for i in range(60):
            with autograd.record():
                (cls, box, cls_t, box_t, box_m, rpn_cls, rpn_box,
                 rois) = net(x, gt)
                logits, deltas = net.rpn_per_anchor(rpn_cls, rpn_box)
                bt, bm, ct = net.rpn_dense_targets(
                    gt, (x.shape[2], x.shape[3]), feat_hw)
                w = 1.0 + 19.0 * (ct > 0)
                L = (ce(logits.reshape(-1, 2), ct.reshape(-1),
                        w.reshape(-1, 1)).mean()
                     + huber(deltas * bm, bt * bm).mean()
                     / (bm.mean() + 1e-6)
                     + ce(cls.reshape(-1, cls.shape[-1]),
                          cls_t.reshape(-1)).mean()
                     + huber(box * box_m, box_t).mean()
                     / (box_m.mean() + 1e-6))
            L.backward()
            trainer.step(x.shape[0])
        dets = net.detect(x, threshold=0.1).asnumpy()
        # for most images the best detection should overlap the planted box
        hits = 0
        for b in range(x.shape[0]):
            rows = dets[b]
            rows = rows[rows[:, 1] > 0]
            if len(rows) == 0:
                continue
            best = rows[np.argmax(rows[:, 1])]
            gtb = gt_np[b, 0, 1:]
            ix1, iy1 = np.maximum(best[2:4], gtb[:2])
            ix2, iy2 = np.minimum(best[4:6], gtb[2:])
            inter = max(0, ix2 - ix1) * max(0, iy2 - iy1)
            union = ((best[4] - best[2]) * (best[5] - best[3])
                     + (gtb[2] - gtb[0]) * (gtb[3] - gtb[1]) - inter)
            if inter / max(union, 1e-6) > 0.3:
                hits += 1
        assert hits >= x.shape[0] // 2, f"only {hits} hits: {dets[:, 0]}"
