"""Executor (reference: ``src/executor/graph_executor.cc`` +
``python/mxnet/executor.py`` [unverified]).

``simple_bind``'s whole pipeline — InferShape, PlanMemory, AttachOpExecs,
pointwise fusion — is one ``jax.jit`` here: the graph evaluates as a single
XLA executable; backward is its vjp. Buffer sharing/liveness is XLA's
problem (it does the reference's PlanMemory job during buffer assignment).

Auxiliary states (BatchNorm moving_mean/moving_var) follow reference
semantics: allocated by simple_bind from their ``__init__`` hints, fed to
the forward, excluded from gradients, and — in ``is_train`` mode — updated
by the forward pass itself via the momentum moving average (the nnvm
FMutateInputs role)."""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["Executor"]

_INITS = {"zeros": jnp.zeros, "ones": jnp.ones}


class Executor:
    def __init__(self, symbol, ctx=None, shapes=None, grad_req="write",
                 args=None, args_grad=None, aux_states=None):
        self._symbol = symbol
        self._ctx = ctx
        self._grad_req = grad_req
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        var_attrs = symbol._var_attrs()
        self.arg_dict: Dict[str, NDArray] = {}
        self.grad_dict: Dict[str, NDArray] = {}
        self.aux_dict: Dict[str, NDArray] = {}

        inferred = None
        if shapes:
            needed = [n for n in self._arg_names + self._aux_names
                      if n not in shapes]
            if needed:
                # fill parameter/aux shapes from data shapes (nnvm
                # InferShape role — Symbol._infer_all_shapes)
                inferred = symbol._infer_all_shapes(
                    {k: tuple(v) for k, v in shapes.items()}
                )
            else:
                inferred = {k: tuple(v) for k, v in shapes.items()}

        if args is not None:
            if isinstance(args, dict):
                self.arg_dict = dict(args)
            else:
                self.arg_dict = dict(zip(self._arg_names, args))
        elif inferred is not None:
            for name in self._arg_names:
                if name in inferred:
                    self.arg_dict[name] = NDArray(
                        jnp.zeros(inferred[name], jnp.float32)
                    )
                else:
                    raise MXNetError(
                        f"simple_bind needs a shape for argument {name}"
                    )

        if aux_states is not None:
            if isinstance(aux_states, dict):
                self.aux_dict = dict(aux_states)
            else:
                self.aux_dict = dict(zip(self._aux_names, aux_states))
        elif inferred is not None:
            for name in self._aux_names:
                if name not in inferred:
                    raise MXNetError(
                        f"simple_bind cannot infer aux state {name}"
                    )
                init = _INITS[
                    var_attrs.get(name, {}).get("__init__", "zeros")
                ]
                self.aux_dict[name] = NDArray(
                    init(inferred[name], jnp.float32)
                )

        if args_grad is not None:
            if isinstance(args_grad, dict):
                self.grad_dict = dict(args_grad)
            else:
                self.grad_dict = dict(zip(self._arg_names, args_grad))
        elif grad_req != "null":
            self.grad_dict = {
                n: NDArray(jnp.zeros_like(a.data))
                for n, a in self.arg_dict.items()
            }
        self.outputs: List[NDArray] = []

        # BatchNorm nodes: (node, moving_mean name, moving_var name,
        # momentum) for the forward-side aux update in train mode
        self._bn_nodes = []
        aux_set = set(self._aux_names)
        for node in symbol.get_internals()._inputs:
            if node._op == "BatchNorm" and len(node._inputs) >= 5:
                mm, mv = node._inputs[3], node._inputs[4]
                # only AUX-marked moving stats get the forward-side update;
                # explicit argument-style moving_mean/var (the 5-positional
                # construction) stay plain arguments the user manages
                if (mm._is_var() and mv._is_var()
                        and mm._name in aux_set and mv._name in aux_set):
                    self._bn_nodes.append(
                        (node, mm._name, mv._name,
                         float(node._attrs.get("momentum", 0.9)))
                    )

        self._fwd = jax.jit(lambda v: self._run(v, False))
        self._fwd_train = jax.jit(lambda v: self._run(v, True))
        self._vjp_fn = None

    def _run(self, values, training):
        from .symbol.symbol import train_mode_scope

        cache: Dict[int, object] = {}
        with train_mode_scope(training):
            out = self._symbol._eval(dict(values), cache)
        outs = out if isinstance(out, tuple) else (out,)
        # a multi-output op as the bound head (e.g. BatchNorm's internal
        # (out, mean, var)) exposes only its declared output count —
        # otherwise backward() would feed ones-cotangents into the extras
        if self._symbol._op is not None and self._symbol._out_index is None:
            outs = outs[: self._symbol._num_outputs]
        # batch stats of every BatchNorm node (outputs 1, 2) for the aux
        # moving update; nodes are in the cache after evaluation
        stats = tuple(
            (cache[id(node)][1], cache[id(node)][2])
            for node, _, _, _ in self._bn_nodes
            if id(node) in cache
        )
        return outs, stats

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._aux_names]

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._rebind(
                    v.data if isinstance(v, NDArray) else jnp.asarray(v)
                )
            else:
                self.arg_dict[k] = v if isinstance(v, NDArray) else NDArray(
                    jnp.asarray(v)
                )
        values = {n: a.data for n, a in self.arg_dict.items()}
        values.update({n: a.data for n, a in self.aux_dict.items()})
        if is_train and self._grad_req != "null":
            # batch stats ride along as vjp aux (not differentiated)
            outs, self._vjp_fn, stats = jax.vjp(
                lambda v: self._run(v, True), values, has_aux=True
            )
        elif is_train:
            outs, stats = self._fwd_train(values)
            self._vjp_fn = None
        else:
            outs, stats = self._fwd(values)
            self._vjp_fn = None
        if is_train and stats:
            # reference aux update: moving = m*moving + (1-m)*batch
            for (node, mm, mv, momentum), (bmean, bvar) in zip(
                self._bn_nodes, stats
            ):
                self.aux_dict[mm]._rebind(
                    momentum * self.aux_dict[mm].data + (1 - momentum) * bmean
                )
                self.aux_dict[mv]._rebind(
                    momentum * self.aux_dict[mv].data + (1 - momentum) * bvar
                )
        self.outputs = [NDArray(o) for o in outs]
        return self.outputs

    def backward(self, out_grads=None):
        if self._vjp_fn is None:
            raise MXNetError("call forward(is_train=True) before backward()")
        if out_grads is None:
            cts = tuple(jnp.ones_like(o.data) for o in self.outputs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = tuple(
                g.data if isinstance(g, NDArray) else jnp.asarray(g)
                for g in out_grads
            )
        (grads,) = self._vjp_fn(cts)
        for name, g in grads.items():
            if name not in self.grad_dict or self.grad_dict[name] is None:
                continue  # aux states and null-grad args take no gradient
            if self._grad_req == "add":
                self.grad_dict[name]._rebind(self.grad_dict[name].data + g)
            elif self._grad_req == "write":
                self.grad_dict[name]._rebind(g)
        return [self.grad_dict.get(n) for n in self._arg_names]

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._rebind(
                    arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
                )
            elif not allow_extra_params:
                raise MXNetError(f"extra parameter {name}")
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    self.aux_dict[name]._rebind(
                        arr.data if isinstance(arr, NDArray)
                        else jnp.asarray(arr)
                    )
                elif not allow_extra_params:
                    raise MXNetError(f"extra aux state {name}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        shapes = {n: tuple(a.shape) for n, a in self.arg_dict.items()}
        shapes.update(kwargs)
        return Executor(self._symbol, self._ctx, shapes, self._grad_req)
