"""Executor (reference: ``src/executor/graph_executor.cc`` +
``python/mxnet/executor.py`` [unverified]).

``simple_bind``'s whole pipeline — InferShape, PlanMemory, AttachOpExecs,
pointwise fusion — is one ``jax.jit`` here: the graph evaluates as a single
XLA executable; backward is its vjp. Buffer sharing/liveness is XLA's
problem (it does the reference's PlanMemory job during buffer assignment)."""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx=None, shapes=None, grad_req="write",
                 args=None, args_grad=None):
        self._symbol = symbol
        self._ctx = ctx
        self._grad_req = grad_req
        self._arg_names = symbol.list_arguments()
        self.arg_dict: Dict[str, NDArray] = {}
        self.grad_dict: Dict[str, NDArray] = {}
        self.aux_dict: Dict[str, NDArray] = {}
        if args is not None:
            if isinstance(args, dict):
                self.arg_dict = dict(args)
            else:
                self.arg_dict = dict(zip(self._arg_names, args))
        elif shapes:
            missing = [n for n in self._arg_names if n not in shapes]
            if missing:
                # infer parameter shapes from the data shapes (the nnvm
                # InferShape role — see Symbol._infer_all_shapes)
                shapes = symbol._infer_all_shapes(
                    {k: tuple(v) for k, v in shapes.items()}
                )
            for name in self._arg_names:
                if name in shapes:
                    self.arg_dict[name] = NDArray(
                        jnp.zeros(shapes[name], jnp.float32)
                    )
                else:
                    raise MXNetError(
                        f"simple_bind needs a shape for argument {name}"
                    )
        if args_grad is not None:
            if isinstance(args_grad, dict):
                self.grad_dict = dict(args_grad)
            else:
                self.grad_dict = dict(zip(self._arg_names, args_grad))
        elif grad_req != "null":
            self.grad_dict = {
                n: NDArray(jnp.zeros_like(a.data))
                for n, a in self.arg_dict.items()
            }
        self.outputs: List[NDArray] = []
        self._fwd = jax.jit(self._run)
        self._vjp_fn = None

    def _run(self, values):
        out = self._symbol._eval(dict(values), {})
        return out if isinstance(out, tuple) else (out,)

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._arg_names]

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._rebind(
                    v.data if isinstance(v, NDArray) else jnp.asarray(v)
                )
            else:
                self.arg_dict[k] = v if isinstance(v, NDArray) else NDArray(
                    jnp.asarray(v)
                )
        values = {n: a.data for n, a in self.arg_dict.items()}
        if is_train and self._grad_req != "null":
            outs, self._vjp_fn = jax.vjp(self._run, values)
        else:
            outs = self._fwd(values)
            self._vjp_fn = None
        self.outputs = [NDArray(o) for o in outs]
        return self.outputs

    def backward(self, out_grads=None):
        if self._vjp_fn is None:
            raise MXNetError("call forward(is_train=True) before backward()")
        if out_grads is None:
            cts = tuple(jnp.ones_like(o.data) for o in self.outputs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = tuple(
                g.data if isinstance(g, NDArray) else jnp.asarray(g)
                for g in out_grads
            )
        (grads,) = self._vjp_fn(cts)
        for name, g in grads.items():
            if name not in self.grad_dict or self.grad_dict[name] is None:
                continue
            if self._grad_req == "add":
                self.grad_dict[name]._rebind(self.grad_dict[name].data + g)
            elif self._grad_req == "write":
                self.grad_dict[name]._rebind(g)
        return [self.grad_dict.get(n) for n in self._arg_names]

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._rebind(
                    arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
                )
            elif not allow_extra_params:
                raise MXNetError(f"extra parameter {name}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        shapes = {n: tuple(a.shape) for n, a in self.arg_dict.items()}
        shapes.update(kwargs)
        return Executor(self._symbol, self._ctx, shapes, self._grad_req)
