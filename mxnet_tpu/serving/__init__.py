"""Serving front-end: dynamic batching, hot weight swap, multi-replica
routing with failover — the self-healing serving plane.

The inference engine (``parallel.infer.InferStep``) turns one *batch* of
prompts into tokens at O(1)/token; this package turns *concurrent
requests* into those batches and keeps doing so across weight updates
and replica failures:

- ``DynamicBatcher`` admits requests into fixed ``(batch, bucket)``
  slots — pad-to-bucket prompts, timeout-or-full dispatch, per-request
  future resolution, per-request deadlines — so the engine only ever
  sees the warmed shape menu and the steady-state loop never compiles
  (Yu et al., Orca, OSDI 2022: between decode dispatches is the safe
  point for everything below).
- ``CheckpointWatcher`` hot-swaps newly committed checkpoints into live
  engines between dispatches (double-buffered device params,
  version-tagged responses, zero dropped requests).
- ``Router`` fronts N replicas behind one ``submit()``: health scoring
  from the watchdog heartbeat + per-replica backlog, eviction with
  transparent resubmission (bounded retries, exponential backoff,
  per-request deadlines), respawn via a replica factory.
- ``faults`` plants deterministic failure points in all of the above
  (``MXTPU_FAULT_*``), so the failure paths are testable in tier-1.

Env knobs: ``MXTPU_BATCHER_SLOTS`` (batch slots per dispatch, default 8),
``MXTPU_BATCHER_TIMEOUT_MS`` (admission window, default 10),
``MXTPU_DECODE_MAX_LEN`` (engine cache capacity — see ``parallel.infer``),
``MXTPU_SWAP_POLL_S`` (checkpoint poll period), ``MXTPU_RETRY_MAX``
(router resubmissions per request), ``MXTPU_RESTART_BACKOFF_S`` (restart
backoff base, shared with ``tools/launch.py``), ``MXTPU_FAULT_*``
(fault-injection specs — see ``serving.faults``).
"""

from . import faults
from .batcher import DeadlineExceeded, DynamicBatcher, GenerationResult, \
    batcher_slots, batcher_timeout_ms
from .router import Replica, ReplicaUnavailable, Router, restart_backoff_s, \
    retry_max
from .watcher import CheckpointWatcher, swap_poll_s

__all__ = ["DynamicBatcher", "GenerationResult", "DeadlineExceeded",
           "Router", "Replica", "ReplicaUnavailable", "CheckpointWatcher",
           "faults", "batcher_slots", "batcher_timeout_ms", "swap_poll_s",
           "retry_max", "restart_backoff_s"]
