"""Serving front-end: request-level dynamic batching over ``InferStep``.

The inference engine (``parallel.infer.InferStep``) turns one *batch* of
prompts into tokens at O(1)/token; this package turns *concurrent
requests* into those batches (Yu et al., Orca, OSDI 2022 — here the
iteration granularity is one generation call, with per-request detach at
EOS trim time): ``DynamicBatcher`` admits requests into fixed
``(batch, bucket)`` slots — pad-to-bucket prompts, timeout-or-full
dispatch, per-request future resolution — so the engine only ever sees
the warmed shape menu and the steady-state loop never compiles.

Env knobs: ``MXTPU_BATCHER_SLOTS`` (batch slots per dispatch, default 8),
``MXTPU_BATCHER_TIMEOUT_MS`` (admission window, default 10),
``MXTPU_DECODE_MAX_LEN`` (engine cache capacity — see
``parallel.infer``).
"""

from .batcher import DynamicBatcher, GenerationResult, batcher_slots, \
    batcher_timeout_ms

__all__ = ["DynamicBatcher", "GenerationResult", "batcher_slots",
           "batcher_timeout_ms"]
