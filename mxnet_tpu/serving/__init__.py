"""Serving front-end: dynamic batching, hot weight swap, multi-replica
routing with failover — the self-healing serving plane.

The inference engine (``parallel.infer.InferStep``) turns one *batch* of
prompts into tokens at O(1)/token; this package turns *concurrent
requests* into those batches and keeps doing so across weight updates
and replica failures:

- ``ContinuousBatcher`` (the ``MXTPU_BATCHER=continuous`` default) runs
  Orca-style ITERATION-LEVEL scheduling over a paged KV cache
  (``serving.pages`` + the paged attention mode): between decode
  iterations it retires EOS/deadline rows, frees their pages, and
  admits queued requests into the vacated slots via a jitted
  prefill-into-pages dispatch — occupancy is dynamic, shapes are
  static, tokens stream per iteration, and admission control rejects
  with ``Backpressure`` when the pool can't absorb more work.
- ``DynamicBatcher`` (``MXTPU_BATCHER=fixed``) admits requests into
  fixed ``(batch, bucket)`` slots — pad-to-bucket prompts,
  timeout-or-full dispatch, per-request future resolution, per-request
  deadlines — the strict one-weight-version-per-request fallback (Yu
  et al., Orca, OSDI 2022: between decode dispatches is the safe point
  for everything below).
- ``CheckpointWatcher`` hot-swaps newly committed checkpoints into live
  engines between dispatches (double-buffered device params,
  version-tagged responses, zero dropped requests).
- ``Router`` fronts N replicas behind one ``submit()``: health scoring
  from the watchdog heartbeat + per-replica backlog, eviction with
  transparent resubmission (bounded retries, exponential backoff,
  per-request deadlines), respawn via a replica factory, and
  load-shedding admission (``Backpressure`` at submit, ``serve/shed_*``
  accounting) once EVERY replica is degraded.
- ``transport``/``worker``/``remote`` cross the process boundary:
  replicas run as real worker processes (``python -m
  mxnet_tpu.serving.worker``) behind a length-prefixed socket RPC
  (submit/stream/health/stage/swap/drain verbs, no pickle);
  ``RemoteReplica`` gives the router process-level failover (SIGKILL'd
  worker → dead socket/stale heartbeat → eviction + transparent
  resubmission → factory respawns a REAL process) and the
  ``CheckpointWatcher`` drives the same stage-all-then-flip-all hot
  swap over the control channel so every process flips coherently.
- ``disagg`` splits the fleet into prefill and decode roles
  (``MXTPU_ROLE``): prefill workers run the admission prefill and ship
  the filled KV page frames over the ``kv_push`` transport verb (or the
  ``MXTPU_KV_SPILL_DIR`` filesystem spill) to decode workers whose
  batcher ADOPTS them without re-prefilling — bit-identical greedy
  tokens, with any handoff failure degrading to a local re-prefill
  (zero lost requests). The router is SLO-aware: predicted-wait
  placement (worker-reported rolling p50 × backlog, rotating
  tie-break), request classes (``interactive``/``batch``) with
  per-class deadline defaults (``MXTPU_SLO_*_MS``) and batch-first
  shedding, and ``tools.launch.FleetScaler`` elasticity
  (``MXTPU_SCALE_*``).
- ``prefix`` caches computed KV across requests: a radix trie per
  exact prompt maps page-aligned target-token blocks to refcounted KV
  pages; retiring slots donate their chains, admission adopts matched
  prefixes read-only (copy-on-write on the partial tail page) and
  replays only the uncached suffix through a teacher-forced program
  that is bit-identical to the token-at-a-time decode. The router
  prefers replicas advertising the request's prompt digest
  (prefix-affinity placement, ``MXTPU_PREFIX_AFFINITY``).
- ``faults`` plants deterministic failure points in all of the above
  (``MXTPU_FAULT_*``), so the failure paths are testable in tier-1.
- ``tracing`` is the fleet-scope observability plane: distributed
  request tracing (a ``request_id`` minted at ``Router.submit`` rides
  every RPC frame; each process appends parent-linked spans to its own
  events JSONL; ``tools/fleet_trace.py`` merges them into one
  clock-aligned Chrome trace), a telemetry scrape/aggregation loop
  (``FleetTelemetry`` polls each worker's ``telemetry`` verb on
  ``MXTPU_SCRAPE_S``), and per-request SLO attribution
  (``GenerationResult.phases`` — queue/handoff/prefill/decode/retry
  breakdown summing to the observed end-to-end latency).

Env knobs: ``MXTPU_BATCHER`` (scheduler kind, default ``continuous``),
``MXTPU_PAGE_SIZE``/``MXTPU_PAGES`` (KV pool geometry),
``MXTPU_ITER_TOKENS`` (decode tokens per scheduler iteration),
``MXTPU_ADMIT_*`` (backpressure thresholds — see ``serving.pages``),
``MXTPU_BATCHER_SLOTS`` (batch slots per dispatch, default 8),
``MXTPU_BATCHER_TIMEOUT_MS`` (admission window, default 10),
``MXTPU_DECODE_MAX_LEN`` (engine cache capacity — see ``parallel.infer``),
``MXTPU_SWAP_POLL_S`` (checkpoint poll period), ``MXTPU_RETRY_MAX``
(router resubmissions per request), ``MXTPU_RESTART_BACKOFF_S`` (restart
backoff base, shared with ``tools/launch.py``), ``MXTPU_SERVE_PORT`` /
``MXTPU_RPC_TIMEOUT_S`` / ``MXTPU_RPC_CONNECT_S`` (worker transport),
``MXTPU_WORKER_DRAIN_S`` (SIGTERM drain budget), ``MXTPU_SHED_*``
(router load-shedding thresholds), ``MXTPU_PREFIX_CACHE`` /
``MXTPU_PREFIX_MAX_PAGES`` / ``MXTPU_PREFIX_MAX_ROOTS`` /
``MXTPU_PREFIX_AFFINITY`` / ``MXTPU_PREFIX_DIGEST_MAX`` (prefix cache +
affinity — see ``serving.prefix``), ``MXTPU_FAULT_*`` (fault-injection
specs — see ``serving.faults``), ``MXTPU_TRACE`` / ``MXTPU_TRACE_DIR`` /
``MXTPU_SCRAPE_S`` (fleet tracing + telemetry scraping — see
``serving.tracing``).
"""

from . import disagg
from . import faults
from . import pages
from . import prefix
from . import tracing
from .batcher import Backpressure, ContinuousBatcher, DeadlineExceeded, \
    DynamicBatcher, GenerationResult, batcher_kind, batcher_slots, \
    batcher_timeout_ms, iter_tokens_default, make_batcher
from .disagg import HandoffStash, PrefillEngine, kv_spill_dir, \
    worker_role
from .pages import PagePool
from .prefix import PrefixCache, prefix_affinity_enabled, \
    prefix_cache_enabled, prefix_digest_max, prefix_max_pages, \
    prefix_max_roots, prompt_digest
from .router import REQUEST_CLASSES, Replica, ReplicaUnavailable, \
    Router, restart_backoff_s, retry_max, shed_max_queue, \
    shed_queue_depth, shed_wait_ms, slo_batch_ms, slo_interactive_ms
from .remote import RemoteEngineHandle, RemoteReplica
from .tracing import FleetTelemetry, aggregate_snapshots, \
    estimate_offset, replay_scrapes, scrape_interval_s, trace_enabled
from .transport import RpcClient, RpcServer, TransportError, \
    rpc_connect_s, rpc_timeout_s, serve_port
from .watcher import CheckpointWatcher, swap_poll_s, version_for

__all__ = ["DynamicBatcher", "ContinuousBatcher", "GenerationResult",
           "DeadlineExceeded", "Backpressure", "PagePool", "pages",
           "Router", "Replica", "ReplicaUnavailable", "CheckpointWatcher",
           "RemoteReplica", "RemoteEngineHandle", "RpcClient", "RpcServer",
           "TransportError", "faults", "batcher_slots",
           "batcher_timeout_ms", "batcher_kind", "iter_tokens_default",
           "make_batcher", "swap_poll_s", "version_for", "retry_max",
           "restart_backoff_s", "shed_queue_depth", "shed_wait_ms",
           "shed_max_queue", "rpc_timeout_s", "rpc_connect_s",
           "serve_port", "disagg", "PrefillEngine", "HandoffStash",
           "worker_role", "kv_spill_dir", "REQUEST_CLASSES",
           "slo_interactive_ms", "slo_batch_ms", "prefix", "PrefixCache",
           "prompt_digest", "prefix_cache_enabled", "prefix_max_pages",
           "prefix_max_roots", "prefix_affinity_enabled",
           "prefix_digest_max", "tracing", "FleetTelemetry",
           "aggregate_snapshots", "estimate_offset", "replay_scrapes",
           "scrape_interval_s", "trace_enabled"]
