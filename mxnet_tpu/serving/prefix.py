"""Prefix cache: radix trie over page-aligned token blocks + refcounted
page adoption (vLLM's shared-page observation, SGLang's RadixAttention
trie, adapted to an encoder-decoder engine).

Why roots are EXACT prompts here. In a decoder-only engine any shared
token prefix shares KV. This engine is encoder-decoder: the prompt runs
through a BIDIRECTIONAL encoder, so a shared *prompt prefix* does NOT
determine the cross-attention memory (later prompt tokens change every
position's encoding) — source-side prefix reuse would be unsound. What
IS causally invariant is the decode side: the target sequence
([BOS] + re-sent history + emitted tokens) attends causally, so its KV
pages are determined by (exact prompt, target tokens so far). The trie
therefore maps an **exact prompt** to a root holding the host-side
cross-attention frames (a root hit skips the encoder entirely — the
dominant prefill cost) and, under each root, a radix tree of
page-aligned **target-token blocks** mapping to physical page ids in the
``PagePool`` (multi-turn requests that re-send their history adopt those
pages instead of re-prefilling them).

Sharing protocol (see ``PagePool``): every cached page carries one cache
reference; adopters map it read-only via ``adopt_ref``. Pages are
append-only logs, and adopted FULL blocks sit entirely below the
adopter's first write position, so they are never written. A partially
matched block is never adopted in place — the batcher copy-on-writes it
into a fresh page (one admission-group-batched device scatter,
``ContinuousBatcher._apply_prefix_hits``) and the adopter appends
there. Page
content beyond the matched length is garbage that the causal mask
(q_offset) provably never reads.

Eviction: nodes are LRU-stamped on every match/insert touch.
``evict(need)`` releases least-recently-used leaf pages whose only
remaining reference is the cache's (releasing those actually frees
memory); the batcher calls it under the admission free-page watermark
and before resorting to preemption. ``MXTPU_PREFIX_MAX_PAGES`` caps the
trie's page footprint and ``MXTPU_PREFIX_MAX_ROOTS`` its root count
(whole LRU roots evict when over).

All public methods take the cache lock and do pure bookkeeping — no
device dispatch, no blocking call ever runs under it (lock-order pass).

Env knobs: ``MXTPU_PREFIX_CACHE`` (default on), ``MXTPU_PREFIX_MAX_PAGES``
(0 = unbounded), ``MXTPU_PREFIX_MAX_ROOTS``, ``MXTPU_PREFIX_AFFINITY``
(router prefix-affinity placement), ``MXTPU_PREFIX_DIGEST_MAX`` (digest
entries a health response carries).
"""

from __future__ import annotations

import os
import threading
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..base import MXNetError

__all__ = ["PrefixCache", "PrefixHit", "prompt_digest",
           "prefix_cache_enabled", "prefix_max_pages", "prefix_max_roots",
           "prefix_affinity_enabled", "prefix_digest_max"]

_FALSY = ("0", "false", "off", "no")


def prefix_cache_enabled(default: bool = True) -> bool:
    """``MXTPU_PREFIX_CACHE``: prefix caching on/off (default on)."""
    v = os.environ.get("MXTPU_PREFIX_CACHE", "").strip().lower()
    if not v:
        return default
    return v not in _FALSY


def prefix_max_pages(default: int = 0) -> int:
    """``MXTPU_PREFIX_MAX_PAGES``: cap on pages the trie may hold
    references to (0 = unbounded; the free-page watermark still evicts
    under memory pressure either way)."""
    v = os.environ.get("MXTPU_PREFIX_MAX_PAGES", "").strip()
    try:
        return max(int(v), 0) if v else default
    except ValueError:
        return default


def prefix_max_roots(default: int = 64) -> int:
    """``MXTPU_PREFIX_MAX_ROOTS``: distinct prompts the trie caches
    cross-attention frames for; LRU roots evict whole over the cap."""
    v = os.environ.get("MXTPU_PREFIX_MAX_ROOTS", "").strip()
    try:
        return max(int(v), 1) if v else default
    except ValueError:
        return default


def prefix_affinity_enabled(default: bool = True) -> bool:
    """``MXTPU_PREFIX_AFFINITY``: router prefers replicas whose health
    digest already holds the request's prompt (default on)."""
    v = os.environ.get("MXTPU_PREFIX_AFFINITY", "").strip().lower()
    if not v:
        return default
    return v not in _FALSY


def prefix_digest_max(default: int = 32) -> int:
    """``MXTPU_PREFIX_DIGEST_MAX``: max root digests a health response
    advertises (most recently used first)."""
    v = os.environ.get("MXTPU_PREFIX_DIGEST_MAX", "").strip()
    try:
        return max(int(v), 1) if v else default
    except ValueError:
        return default


def prompt_digest(prompt_ids) -> int:
    """Stable cross-process digest of a prompt (crc32 over the int32
    token bytes — Python ``hash()`` is salted per process and useless
    on the wire)."""
    return zlib.crc32(np.asarray(prompt_ids, np.int32).tobytes()) & 0xFFFFFFFF


class _Node:
    """One cached page: the target-token block it holds and its children
    (keyed by their block tuples). Only full (page_size) blocks may have
    children — a partial tail is by construction a leaf."""

    __slots__ = ("tokens", "page", "children", "touch")

    def __init__(self, tokens: Tuple[int, ...], page: int, touch: int):
        self.tokens = tokens
        self.page = int(page)
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.touch = touch


class _Root:
    """One exact prompt: host-side cross-attention frames (the encoder
    output this prompt maps to) + the target-block radix tree."""

    __slots__ = ("key", "digest", "mem_vl", "ck", "cv", "children",
                 "touch")

    def __init__(self, key: Tuple[int, ...], mem_vl: int, ck, cv,
                 touch: int):
        self.key = key
        self.digest = prompt_digest(key)
        self.mem_vl = int(mem_vl)
        self.ck = ck  # per-layer (mem_vl, H, D) host arrays, read-only
        self.cv = cv
        self.children: Dict[Tuple[int, ...], _Node] = {}
        self.touch = touch


class PrefixHit:
    """Match result: how much of the target prefix is served from cache.

    ``matched`` target positions [0, matched) are covered: ``full_pages``
    (adopt read-only, in depth order) plus optionally ``cow`` =
    ``(src_page, used)`` — copy ``src_page`` and treat its first ``used``
    entries as valid. Cross frames (``ck``/``cv``/``mem_vl``) replace the
    encoder pass entirely.
    """

    __slots__ = ("matched", "full_pages", "cow", "mem_vl", "ck", "cv",
                 "digest")

    def __init__(self, matched, full_pages, cow, mem_vl, ck, cv, digest):
        self.matched = matched
        self.full_pages = full_pages
        self.cow = cow
        self.mem_vl = mem_vl
        self.ck = ck
        self.cv = cv
        self.digest = digest


class PrefixCache:
    """Radix-trie prefix cache over one ``PagePool``.

    The cache and the pool share a refcount ledger: every node's page
    carries one ``cache_acquire`` reference for exactly as long as the
    node exists (``PagePool.check_invariants(cache_pages=cache.pages())``
    proves exactness). All mutation happens on the batcher's scheduler
    thread or health/stat readers — every public method locks.
    """

    def __init__(self, pool, page_size: int,
                 max_pages: Optional[int] = None,
                 max_roots: Optional[int] = None,
                 enabled: Optional[bool] = None):
        self._pool = pool
        self.page_size = int(page_size)
        self.max_pages = prefix_max_pages() if max_pages is None \
            else int(max_pages)
        self.max_roots = prefix_max_roots() if max_roots is None \
            else int(max_roots)
        self.enabled = prefix_cache_enabled() if enabled is None \
            else bool(enabled)
        self._lock = threading.Lock()
        self._roots: Dict[Tuple[int, ...], _Root] = {}
        self._clock = 0
        self._pages = 0  # nodes (== cached pages) currently held
        self.stats = {"hits": 0, "misses": 0, "tokens_saved": 0,
                      "inserts": 0, "evicted_pages": 0, "evicted_roots": 0,
                      "flushes": 0}

    # ------------------------------------------------------------- queries
    def __len__(self) -> int:
        with self._lock:
            return len(self._roots)

    @property
    def total_pages(self) -> int:
        with self._lock:
            return self._pages

    def pages(self) -> set:
        """Every page id the trie currently references (invariant
        checks; O(nodes))."""
        with self._lock:
            out: set = set()
            for root in self._roots.values():
                stack = list(root.children.values())
                while stack:
                    n = stack.pop()
                    out.add(n.page)
                    stack.extend(n.children.values())
            return out

    def digests(self, limit: Optional[int] = None) -> List[int]:
        """Root digests, most recently touched first — the compact
        prefix advertisement the health verb carries."""
        limit = prefix_digest_max() if limit is None else int(limit)
        with self._lock:
            roots = sorted(self._roots.values(), key=lambda r: -r.touch)
            return [r.digest for r in roots[:limit]]

    def has_root(self, prompt_ids) -> bool:
        """True when this exact prompt already has a trie root — lets
        the batcher skip the device readback of cross frames at
        insert time."""
        key = tuple(int(t) for t in np.asarray(prompt_ids).reshape(-1))
        with self._lock:
            return key in self._roots

    def hit_rate(self) -> float:
        with self._lock:
            n = self.stats["hits"] + self.stats["misses"]
            return self.stats["hits"] / n if n else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
            out["roots"] = len(self._roots)
            out["pages"] = self._pages
            n = out["hits"] + out["misses"]
            out["hit_rate"] = out["hits"] / n if n else 0.0
            return out

    # ------------------------------------------------------------ matching
    def match(self, prompt_ids, target_ids) -> Optional[PrefixHit]:
        """Longest cached cover of ``target_ids`` (the decode-side
        [BOS] + re-sent history) under the exact-prompt root. At most
        ``len(target_ids) - 1`` positions match — the final position's
        forward pass must run to produce the first-token logits. Returns
        None (and counts a miss) when the prompt has no root."""
        if not self.enabled:
            return None
        key = tuple(int(t) for t in np.asarray(prompt_ids).reshape(-1))
        target = tuple(int(t) for t in np.asarray(target_ids).reshape(-1))
        ps = self.page_size
        with self._lock:
            root = self._roots.get(key)
            if root is None:
                self.stats["misses"] += 1
                return None
            self._clock += 1
            root.touch = self._clock
            node: object = root
            depth = 0
            full_pages: List[int] = []
            cow = None
            while True:
                limit = len(target) - 1 - depth * ps
                if limit <= 0:
                    break
                best, best_lcp = None, 0
                for tokens, child in node.children.items():
                    want = target[depth * ps: depth * ps + len(tokens)]
                    lcp = 0
                    for a, b in zip(tokens, want):
                        if a != b:
                            break
                        lcp += 1
                    if lcp > best_lcp:
                        best, best_lcp = child, lcp
                if best is None or best_lcp == 0:
                    break
                if best_lcp == len(best.tokens) == ps and ps <= limit:
                    best.touch = self._clock
                    full_pages.append(best.page)
                    node = best
                    depth += 1
                    continue
                used = min(best_lcp, limit)
                if used > 0:
                    best.touch = self._clock
                    cow = (best.page, used)
                break
            matched = depth * ps + (cow[1] if cow else 0)
            self.stats["hits"] += 1
            # savings: the skipped encoder pass (prompt tokens) plus the
            # target positions adopted instead of re-prefilled
            self.stats["tokens_saved"] += len(key) + matched
            return PrefixHit(matched, tuple(full_pages), cow, root.mem_vl,
                             root.ck, root.cv, root.digest)

    # ----------------------------------------------------------- insertion
    def insert(self, prompt_ids, target_ids, pages, mem_vl=None,
               ck=None, cv=None) -> int:
        """Register a slot's computed prefix: ``target_ids`` are the
        cached decode-side tokens (positions [0, len)), ``pages`` the
        slot's pages in depth order. Creates the root from the cross
        frames (``ck``/``cv``/``mem_vl``) when this prompt is new —
        without frames an unknown prompt is skipped (nothing to serve a
        future encoder-skip from). Existing blocks are deduplicated;
        new ones take a cache reference on their page. Returns how many
        pages were newly cached."""
        if not self.enabled:
            return 0
        key = tuple(int(t) for t in np.asarray(prompt_ids).reshape(-1))
        target = tuple(int(t) for t in np.asarray(target_ids).reshape(-1))
        pages = [int(p) for p in pages]
        ps = self.page_size
        with self._lock:
            self._clock += 1
            root = self._roots.get(key)
            if root is None:
                if ck is None or cv is None or mem_vl is None:
                    return 0
                root = _Root(key, mem_vl, ck, cv, self._clock)
                self._roots[key] = root
                self._evict_roots_locked()
            root.touch = self._clock
            node: object = root
            added = 0
            depth = 0
            while (depth + 1) * ps <= len(target) and depth < len(pages):
                blk = target[depth * ps:(depth + 1) * ps]
                child = node.children.get(blk) \
                    or self._extend_locked(node, blk, pages[depth])
                if child is None:
                    child = _Node(blk, pages[depth], self._clock)
                    self._pool.cache_acquire((pages[depth],))
                    node.children[blk] = child
                    self._pages += 1
                    added += 1
                child.touch = self._clock
                node = child
                depth += 1
            tail = target[depth * ps:]
            if tail and depth < len(pages):
                child = node.children.get(tail) \
                    or self._extend_locked(node, tail, pages[depth])
                if child is None:
                    self._pool.cache_acquire((pages[depth],))
                    node.children[tail] = _Node(tail, pages[depth],
                                                self._clock)
                    self._pages += 1
                    added += 1
            if added:
                self.stats["inserts"] += added
            if self.max_pages and self._pages > self.max_pages:
                self._evict_lru_locked(self._pages - self.max_pages,
                                       require_sole_ref=False)
            return added

    @staticmethod
    def _extend_locked(node, blk, page):
        """The slot that donated a partial tail kept filling that same
        page (no COW — it owned it), so a longer block over the SAME
        page supersedes the shorter node: re-key it in place rather
        than double-acquiring its page."""
        for key, child in node.children.items():
            if child.page == int(page) and len(key) < len(blk) \
                    and blk[:len(key)] == key:
                del node.children[key]
                child.tokens = blk
                node.children[blk] = child
                return child
        return None

    # ------------------------------------------------------------ eviction
    def evict(self, need_pages: int) -> int:
        """Free up to ``need_pages`` pool pages by releasing LRU leaf
        nodes whose page the cache alone still references (releasing
        those actually returns memory). Returns pages freed."""
        with self._lock:
            return self._evict_lru_locked(need_pages, require_sole_ref=True)

    def _leaves_locked(self):
        """[(touch, parent, key, node)] for every leaf node."""
        out = []
        for root in self._roots.values():
            stack = [(root, k, n) for k, n in root.children.items()]
            while stack:
                parent, key, n = stack.pop()
                if n.children:
                    stack.extend((n, k, c) for k, c in n.children.items())
                else:
                    out.append((n.touch, parent, key, n))
        return out

    def _evict_lru_locked(self, need: int, require_sole_ref: bool) -> int:
        freed = 0
        dropped = 0
        while dropped < need or (not require_sole_ref
                                 and self._pages_over_cap_locked()):
            leaves = self._leaves_locked()
            if require_sole_ref:
                leaves = [e for e in leaves
                          if self._pool.ref(e[3].page) == 1]
            if not leaves:
                break
            leaves.sort(key=lambda e: e[0])
            _, parent, key, node = leaves[0]
            del parent.children[key]
            self._pages -= 1
            freed += self._pool.cache_release((node.page,))
            self.stats["evicted_pages"] += 1
            dropped += 1
        return freed

    def _pages_over_cap_locked(self) -> bool:
        return bool(self.max_pages) and self._pages > self.max_pages

    def _evict_roots_locked(self):
        while len(self._roots) > self.max_roots:
            key = min(self._roots, key=lambda k: self._roots[k].touch)
            self._drop_root_locked(key)
            self.stats["evicted_roots"] += 1

    def _drop_root_locked(self, key):
        root = self._roots.pop(key)
        stack = list(root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            self._pool.cache_release((n.page,))
            self._pages -= 1

    def flush(self) -> int:
        """Drop everything (weights swapped or state poisoned): every
        cache reference is released; pages still mapped by live slots
        stay alive under their own references. Returns roots dropped."""
        with self._lock:
            n = len(self._roots)
            for key in list(self._roots):
                self._drop_root_locked(key)
            self.stats["flushes"] += 1
            return n

    def check_invariants(self):
        """Trie-side audit: the page ledger matches the tree and no node
        holds the trash page or a duplicate reference."""
        with self._lock:
            seen: set = set()
            count = 0
            for root in self._roots.values():
                stack = list(root.children.values())
                while stack:
                    n = stack.pop()
                    if n.page in seen:
                        raise MXNetError(
                            f"trie references page {n.page} twice")
                    if n.page == 0:
                        raise MXNetError("trie references the trash page")
                    if len(n.tokens) < self.page_size and n.children:
                        raise MXNetError(
                            "partial-tail trie node has children")
                    seen.add(n.page)
                    count += 1
                    stack.extend(n.children.values())
            if count != self._pages:
                raise MXNetError(
                    f"trie page ledger {self._pages} != {count} nodes")
