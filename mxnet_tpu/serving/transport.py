"""Length-prefixed socket RPC for the cross-process serving plane.

The PR-7 self-healing plane was in-process: a "replica crash" was a
Python thread dying. This module is the boundary that makes it real —
the router talks to worker *processes* (``serving.worker``) over a tiny
explicit-schema RPC, so isolation, failover and swap coordination are
exercised across the boundary that matters in deployments.

Wire format: every frame is a 4-byte big-endian length prefix followed
by one UTF-8 JSON object — **no pickle of arbitrary objects**, ever.
Requests carry ``{"id": n, "verb": ..., ...payload}``; responses carry
``{"id": n, "ok": bool, "done": bool, ...}``. A verb may answer with
several frames: ``submit`` streams ``{"stream": [tokens...]}`` chunks
(one per decode iteration under the worker's ``ContinuousBatcher``)
before its final ``{"done": true, "tokens": [...]}`` frame.

Verbs (the control channel of the cross-process plane):

========== ===========================================================
``submit``  enqueue one prompt into the worker's batcher; token chunks
            stream back, the final frame carries the full trimmed
            token list + ``weights_version``/``queue_wait_ms``.
``health``  liveness/load snapshot: status (``serving``/``draining``),
            queue depth, in-flight slots, ``weights_version``, pid.
``stage``   phase 1 of the coordinated hot swap: the worker loads the
            named committed checkpoint host-side and stages it into
            its engine's standby buffer (``InferStep.stage_params``).
``swap``    phase 2: flip the staged buffer live under the given
            version tag — one reference assignment at a dispatch
            boundary.
``drain``   stop accepting new submits, finish in-flight requests,
            reply when the batcher is drained (the SIGTERM path).
``ping``    transport echo (connect probes, tests).
``prefill`` disaggregated serving (prefill-role workers): run one
            admission prefill and ship the filled KV frames to the
            decode worker named in ``push_to`` (or the spill dir).
``kv_push`` decode-role workers: receive one handoff's KV frames —
            a JSON header (``nbin`` = binary frame count) followed by
            that many LENGTH-PREFIXED BINARY frames (high bit of the
            length word set) carrying raw array bytes, no pickle.
========== ===========================================================

Binary frames ride the same 4-byte big-endian length prefix as JSON
frames with the TOP BIT set (``_BIN_FLAG``): a reader that expects JSON
and sees the flag fails loudly instead of parsing garbage. A sender
holds its send lock across the JSON header AND its binary frames, so a
handoff arrives contiguous on the stream.

Client calls take per-call timeouts (``MXTPU_RPC_TIMEOUT_S`` default)
and the initial connect retries under the router's ``backoff_delay``
(``MXTPU_RPC_CONNECT_S`` total budget) — a worker that is still booting
is a retriable condition, not an outage. A dead connection fails every
pending call with the client's ``dead_error`` (the router wires
``ReplicaUnavailable`` so in-flight requests fail over transparently).

Fault points (``serving.faults``): ``transport.send`` /
``transport.recv`` — raise-mode drops the connection at that end,
delay-mode injects latency, ``times=None`` on both simulates a
partition; tags are the client/server name so ``match=`` can cut one
replica's link.

Telemetry: ``transport/rpc_ms`` per-call latency histogram,
``transport/reconnects`` connect-retry counter, ``transport/errors``
dead-connection counter; ``transport.dead`` instants mark connection
loss.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..base import MXNetError
from .. import telemetry as _tel
from . import faults as _faults
from .batcher import Backpressure, DeadlineExceeded, GenerationResult
from .router import ReplicaUnavailable, backoff_delay

__all__ = ["RpcClient", "RpcServer", "TransportError", "rpc_timeout_s",
           "rpc_connect_s", "serve_port"]

_MAX_FRAME = 64 << 20  # 64 MiB: a token stream frame is tiny; a header
                       # this large means a corrupt/hostile peer
_BIN_FLAG = 0x80000000  # length-word top bit: raw binary frame (kv_push)

# remote error types mapped back onto the caller's exception classes so
# router semantics survive the wire (Backpressure retriable, deadline
# final); anything unknown degrades to MXNetError
_ERROR_TYPES = {
    "Backpressure": Backpressure,
    "DeadlineExceeded": DeadlineExceeded,
    "ReplicaUnavailable": ReplicaUnavailable,
}


class TransportError(MXNetError):
    """The RPC connection failed (dead socket, timeout, bad frame)."""


def rpc_timeout_s(default: float = 30.0) -> float:
    """``MXTPU_RPC_TIMEOUT_S``: default per-call RPC timeout (control
    verbs; ``submit`` streams have no overall cap — deadlines do that)."""
    v = os.environ.get("MXTPU_RPC_TIMEOUT_S", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


def rpc_connect_s(default: float = 60.0) -> float:
    """``MXTPU_RPC_CONNECT_S``: total budget for the initial connect
    retry loop (a spawning worker needs import+build+warmup time)."""
    v = os.environ.get("MXTPU_RPC_CONNECT_S", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


def serve_port(default: int = 0) -> int:
    """``MXTPU_SERVE_PORT``: base port for serving workers (0 = bind an
    ephemeral port and announce it in ``worker.json``). Under
    ``tools/launch.py`` each worker offsets by its ``MXNET_TPU_PROC_ID``."""
    v = os.environ.get("MXTPU_SERVE_PORT", "").strip()
    try:
        return int(v) if v else default
    except ValueError:
        return default


def parse_address(address) -> Tuple[str, int]:
    if isinstance(address, (tuple, list)):
        return str(address[0]), int(address[1])
    host, _, port = str(address).rpartition(":")
    return (host or "127.0.0.1"), int(port)


# ------------------------------------------------------------------ frames
def _send_frame(sock, msg: dict, tag=None) -> None:
    """One frame out. The ``transport.send`` fault point sits before the
    write: raise-mode = the link drops, delay-mode = a slow link."""
    _faults.fire("transport.send", tag=tag)
    body = json.dumps(msg).encode("utf-8")
    sock.sendall(struct.pack(">I", len(body)) + body)


def _recvall(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportError("connection closed mid-frame"
                                 if buf else "connection closed")
        buf += chunk
    return buf


def _send_bin(sock, buf: bytes, tag=None) -> None:
    """One raw binary frame out (kv_push payload): the length word
    carries ``_BIN_FLAG`` so a JSON reader cannot mistake it."""
    _faults.fire("transport.send", tag=tag)
    if len(buf) > _MAX_FRAME:
        raise TransportError(
            f"binary frame of {len(buf)} bytes exceeds the "
            f"{_MAX_FRAME}-byte cap")
    sock.sendall(struct.pack(">I", len(buf) | _BIN_FLAG) + bytes(buf))


def _recv_frame(sock, tag=None) -> dict:
    """One frame in; raises :class:`TransportError` on EOF / bad data.
    The ``transport.recv`` fault point models the receiving end of a
    drop/partition."""
    _faults.fire("transport.recv", tag=tag)
    (n,) = struct.unpack(">I", _recvall(sock, 4))
    if n & _BIN_FLAG:
        raise TransportError(
            "binary frame where a JSON frame was expected (kv_push "
            "header/stream desync)")
    if n > _MAX_FRAME:
        raise TransportError(f"frame of {n} bytes exceeds the "
                             f"{_MAX_FRAME}-byte cap (corrupt stream?)")
    msg = json.loads(_recvall(sock, n).decode("utf-8"))
    if not isinstance(msg, dict):
        raise TransportError("frame is not a JSON object")
    return msg


def _recv_bin(sock, tag=None) -> bytes:
    """One binary frame in (the ``nbin`` frames following a kv_push
    header); the flag bit must be set."""
    _faults.fire("transport.recv", tag=tag)
    (n,) = struct.unpack(">I", _recvall(sock, 4))
    if not n & _BIN_FLAG:
        raise TransportError(
            "JSON frame where a kv_push binary frame was expected")
    n &= ~_BIN_FLAG
    if n > _MAX_FRAME:
        raise TransportError(f"binary frame of {n} bytes exceeds the "
                             f"{_MAX_FRAME}-byte cap (corrupt stream?)")
    return _recvall(sock, n)


def _remote_error(err: Optional[dict]) -> BaseException:
    err = err or {}
    cls = _ERROR_TYPES.get(err.get("type"), MXNetError)
    return cls(f"remote: {err.get('message', 'unknown error')}")


class _Call:
    """Client-side record of one in-flight RPC id."""

    __slots__ = ("queue", "future")

    def __init__(self, queue=None, future=None):
        self.queue = queue    # control verbs: a one-slot Queue
        self.future = future  # submit: a GenerationResult


class RpcClient:
    """One connection to a serving worker.

    A background reader thread routes response frames to their calls by
    id, so concurrent ``call()``/``submit()`` from many threads share
    the one socket. Thread-safety: ``_lock`` guards the call table and
    the dead flag; ``_send_lock`` serializes frame writes; the two are
    never nested.
    """

    def __init__(self, address, timeout_s: Optional[float] = None,
                 name: Optional[str] = None,
                 dead_error=TransportError):
        self.address = parse_address(address)
        self.timeout_s = timeout_s if timeout_s is not None \
            else rpc_timeout_s()
        self.name = name if name is not None else f"{self.address[1]}"
        self._dead_error = dead_error
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._calls: Dict[int, _Call] = {}
        self._next_id = 0
        self._sock = None
        self._dead: Optional[BaseException] = None
        self._reader = None

    # ----------------------------------------------------------- lifecycle
    def connect(self, budget_s: Optional[float] = None,
                backoff_base_s: float = 0.05) -> "RpcClient":
        """Connect, retrying under capped exponential backoff until
        ``budget_s`` (``MXTPU_RPC_CONNECT_S``) runs out — the peer may
        still be importing jax and warming up its engine."""
        budget = budget_s if budget_s is not None else rpc_connect_s()
        deadline = time.monotonic() + budget
        attempt = 0
        while True:
            try:
                sock = socket.create_connection(self.address, timeout=5.0)
                sock.settimeout(None)
                break
            except OSError as e:
                attempt += 1
                delay = backoff_delay(backoff_base_s, attempt - 1,
                                      cap=1.0)
                if time.monotonic() + delay > deadline:
                    raise TransportError(
                        f"could not connect to worker {self.name!r} at "
                        f"{self.address} within {budget:.1f}s: {e}") \
                        from e
                _tel.registry().counter("transport/reconnects").inc()
                time.sleep(delay)
        with self._lock:
            self._sock = sock
            self._dead = None
        self._reader = threading.Thread(
            target=self._read_loop, name=f"mxtpu-rpc-{self.name}",
            daemon=True)
        self._reader.start()
        return self

    def close(self):
        self._shutdown(TransportError(
            f"client for worker {self.name!r} closed"))

    @property
    def dead(self) -> Optional[BaseException]:
        """The error that killed the connection, or None while live."""
        return self._dead

    # ----------------------------------------------------------- requests
    def _register(self, call: _Call) -> int:
        with self._lock:
            if self._dead is not None:
                raise TransportError(
                    f"connection to worker {self.name!r} is dead: "
                    f"{self._dead}")
            self._next_id += 1
            self._calls[self._next_id] = call
            return self._next_id

    def _drop(self, call_id: int):
        with self._lock:
            self._calls.pop(call_id, None)

    def _send(self, msg: dict, bin_frames=None):
        try:
            with self._send_lock:
                _send_frame(self._sock, msg, tag=self.name)
                for buf in bin_frames or ():
                    _send_bin(self._sock, buf, tag=self.name)
        except BaseException as e:
            # a failed write means the link is gone: kill the connection
            # so the reader's pending calls fail over too
            self._shutdown(e)
            raise TransportError(
                f"send to worker {self.name!r} failed: {e}") from e

    def call(self, verb: str, payload: Optional[dict] = None,
             timeout_s: Optional[float] = None, bin_frames=None):
        """One request/response RPC; returns the final frame's payload
        dict. Raises :class:`TransportError` on timeout or a dead link,
        or the mapped remote error class on ``ok: false``.

        ``bin_frames``: raw buffers appended after the JSON header as
        length-prefixed BINARY frames under one send-lock hold (the
        ``kv_push`` payload path); ``nbin`` is stamped on the header so
        the server reader consumes exactly that many."""
        import queue as _queue

        timeout = timeout_s if timeout_s is not None else self.timeout_s
        q = _queue.Queue(maxsize=4)
        call_id = self._register(_Call(queue=q))
        msg = {"id": call_id, "verb": str(verb)}
        msg.update(payload or {})
        if bin_frames:
            msg["nbin"] = len(bin_frames)
        t0 = time.perf_counter()
        try:
            self._send(msg, bin_frames)
            try:
                resp = q.get(timeout=timeout)
            except _queue.Empty:
                raise TransportError(
                    f"rpc {verb!r} to worker {self.name!r} timed out "
                    f"after {timeout:.1f}s") from None
        finally:
            self._drop(call_id)
        _tel.registry().histogram("transport/rpc_ms").observe(
            (time.perf_counter() - t0) * 1e3)
        if isinstance(resp, BaseException):
            raise resp
        if not resp.get("ok", False):
            raise _remote_error(resp.get("error"))
        return resp

    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               extra: Optional[dict] = None,
               future: Optional[GenerationResult] = None
               ) -> GenerationResult:
        """Enqueue one prompt on the remote batcher. Returns a local
        ``GenerationResult`` future fed by the response stream; a dead
        connection fails it with the client's ``dead_error`` (the
        router's signal to resubmit elsewhere). ``extra`` merges more
        header fields (e.g. the disagg path's ``handoff`` id /
        ``klass``); ``future`` reuses a caller-made result object so a
        handoff thread can hand the SAME future to the router before the
        wire submit happens."""
        import numpy as _np

        prompt = _np.asarray(prompt_ids, dtype=_np.int64).reshape(-1)
        fut = future if future is not None else GenerationResult()
        try:
            call_id = self._register(_Call(future=fut))
        except TransportError as e:
            fut._fail(self._dead_error(str(e)))
            return fut
        msg = {"id": call_id, "verb": "submit",
               "prompt": prompt.tolist()}
        if max_new_tokens is not None:
            msg["max_new_tokens"] = int(max_new_tokens)
        if deadline_ms is not None:
            msg["deadline_ms"] = float(deadline_ms)
        if extra:
            msg.update(extra)
        try:
            self._send(msg)
        except TransportError as e:
            self._drop(call_id)
            if not fut.done():
                fut._fail(self._dead_error(str(e)))
        return fut

    # -------------------------------------------------------- reader thread
    def _read_loop(self):
        sock = self._sock
        try:
            while True:
                self._route(_recv_frame(sock, tag=self.name))
        except BaseException as e:  # noqa: BLE001 - any read error = dead link
            self._shutdown(e)

    def _route(self, msg: dict):
        call_id = msg.get("id")
        done = msg.get("done", True)
        with self._lock:
            call = self._calls.get(call_id)
            if call is not None and done:
                self._calls.pop(call_id, None)
        if call is None:
            return  # zombie response after timeout/cancel: discarded
        if call.queue is not None:
            call.queue.put(msg)
            return
        fut = call.future
        stream = msg.get("stream")
        if stream:
            fut._stream_tokens([int(t) for t in stream])
        if not done:
            return
        if msg.get("ok", False):
            fut.weights_version = msg.get("weights_version")
            fut.replica = msg.get("replica", self.name)
            fut.queue_wait_ms = msg.get("queue_wait_ms")
            if msg.get("request_id") is not None:
                fut.request_id = msg.get("request_id")
            phases = msg.get("phases")
            if phases:
                # merge, don't overwrite: the router side may have
                # stamped its own phases (disagg handoff wall) before
                # the worker's breakdown arrived
                base = dict(fut.phases or {})
                base.update(phases)
                fut.phases = base
            if not fut.done():
                fut._resolve([int(t) for t in msg.get("tokens", ())])
        elif not fut.done():
            fut._fail(_remote_error(msg.get("error")))

    def _shutdown(self, err: BaseException):
        """Mark the connection dead and fail every pending call — no
        future may ever be left unresolvable behind a dead socket."""
        with self._lock:
            already = self._dead is not None
            if not already:
                self._dead = err
            pending = list(self._calls.values())
            self._calls.clear()
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if already and not pending:
            return
        if not already:
            _tel.registry().counter("transport/errors").inc()
            _tel.instant("transport.dead",
                         {"worker": self.name, "error": repr(err)})
        wrapped = self._dead_error(
            f"connection to worker {self.name!r} died: {err}")
        for call in pending:
            if call.queue is not None:
                call.queue.put(wrapped)
            elif not call.future.done():
                call.future._fail(wrapped)


# ------------------------------------------------------------------ server
class _Conn:
    """One accepted connection: its socket plus a send lock so handler
    threads (streamers) and the reader interleave whole frames."""

    __slots__ = ("sock", "peer", "_send_lock")

    def __init__(self, sock, peer):
        self.sock = sock
        self.peer = peer
        self._send_lock = threading.Lock()

    def send(self, msg: dict, tag=None) -> bool:
        """Best-effort frame write; False when the peer is gone (a
        streamer must simply stop, not crash the worker)."""
        try:
            with self._send_lock:
                _send_frame(self.sock, msg, tag=tag)
            return True
        except BaseException:  # noqa: BLE001 - peer gone / injected drop
            try:
                self.sock.close()
            except OSError:
                pass
            return False

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class RpcServer:
    """Frame server over a handlers table: ``verb -> fn(payload,
    respond)``.

    Each connection gets a reader thread; quick verbs respond inline,
    streaming verbs capture ``respond`` and reply from their own
    threads. ``respond(done=..., ok=..., **fields)`` may be called any
    number of times with ``done=False`` and exactly once with
    ``done=True``.
    """

    def __init__(self, handlers: Dict[str, Callable],
                 host: str = "127.0.0.1", port: int = 0,
                 name: Optional[str] = None):
        self._handlers = dict(handlers)
        self.name = name
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(16)
        self.host, self.port = self._listener.getsockname()[:2]
        self._lock = threading.Lock()
        self._conns: list = []
        self._threads: list = []
        self._stop = threading.Event()
        self._accept_thread = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "RpcServer":
        if self._accept_thread is not None:
            return self
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="mxtpu-rpc-accept", daemon=True)
        self._accept_thread.start()
        return self

    def stop(self, timeout: float = 5.0):
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
            self._conns = []
            threads = list(self._threads)
            self._threads = []
        for conn in conns:
            conn.close()
        t, self._accept_thread = self._accept_thread, None
        if t is not None:
            t.join(timeout=timeout)
        for t in threads:
            t.join(timeout=timeout)

    # ------------------------------------------------------------- threads
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                sock, peer = self._listener.accept()
            except OSError:
                return  # listener closed (stop)
            conn = _Conn(sock, peer)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="mxtpu-rpc-conn", daemon=True)
            with self._lock:
                self._conns.append(conn)
                self._threads.append(t)
            t.start()

    def _serve_conn(self, conn: _Conn):
        try:
            while not self._stop.is_set():
                msg = _recv_frame(conn.sock, tag=self.name)
                nbin = int(msg.get("nbin", 0) or 0)
                if nbin:
                    # a kv_push header: its binary frames follow
                    # contiguously (the sender held its send lock)
                    msg["_bin"] = [_recv_bin(conn.sock, tag=self.name)
                                   for _ in range(nbin)]
                self._dispatch(conn, msg)
        except BaseException:  # noqa: BLE001 - peer gone / injected drop
            pass
        finally:
            conn.close()
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _dispatch(self, conn: _Conn, msg: dict):
        call_id = msg.get("id")
        verb = msg.get("verb")
        tag = self.name

        def respond(done: bool = True, ok: bool = True, **fields):
            out = {"id": call_id, "ok": ok, "done": done}
            out.update(fields)
            return conn.send(out, tag=tag)

        handler = self._handlers.get(verb)
        if handler is None:
            respond(ok=False, error={
                "type": "TransportError",
                "message": f"unknown verb {verb!r} (schema: "
                           f"{sorted(self._handlers)})"})
            return
        try:
            handler(msg, respond)
        except BaseException as e:  # noqa: BLE001 - fail the call, not the conn
            respond(ok=False, error={"type": type(e).__name__,
                                     "message": str(e)})
