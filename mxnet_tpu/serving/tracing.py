"""Fleet-scope request tracing + the telemetry scrape/aggregation plane.

The serving fleet is multi-process (PR 12) and disaggregated (PR 14),
but until now every process kept a private telemetry registry and spans
never crossed a socket — a request handed off prefill→decode, retried
after a SIGKILL, or shed left no artifact explaining where its latency
went. This module is the missing spine, in three parts:

- **Request tracing** (Dapper-style): ``Router.submit`` mints a
  ``request_id``; the id rides RPC payloads as a ``trace`` dict
  (submit/``prefill``/``kv_push``/``stage``/``swap`` verbs), worker
  handlers adopt it into a thread-local scope
  (``request_scope``/``current_request_id``), and every serving layer
  emits ``trace.*`` spans/instants tagged with it. Spans whose
  endpoints cross threads (enqueue→retire) use explicit-start emission
  (``span(name, start_us)`` → one Chrome complete event).
- **Clock alignment**: every process stamps events on its own trace
  clock (``telemetry.clock_us``, µs since module import). The ``ping``/
  ``health``/``telemetry`` verbs reply with the worker's ``clock_us``;
  the router brackets each probe with its own clock and records
  ``trace.clock_offset`` instants (midpoint estimator, min-RTT sample
  wins — ``estimate_offset``). ``tools/fleet_trace.py`` shifts every
  worker stream onto the router timeline and emits ONE Chrome trace for
  the fleet.
- **Scrape/aggregation** (the Prometheus model): ``FleetTelemetry``
  polls the ``telemetry`` RPC verb on ``MXTPU_SCRAPE_S`` intervals,
  sums counters / merges histogram summaries
  (``telemetry.metrics.merge_summaries``) into a fleet aggregate with
  per-replica breakdowns, and appends each raw scrape to a JSONL stream
  (``fleet_telemetry.jsonl``). Aggregation is a pure function of the
  recorded snapshots (``aggregate_snapshots``), so replaying the file
  (``replay_scrapes``) re-derives identical aggregates by construction
  — the sampling substrate ROADMAP item 6's fleet simulator draws from.

Env knobs: ``MXTPU_TRACE=1`` turns span emission on (the ``force()``
override exists for benches measuring tracing overhead);
``MXTPU_TRACE_DIR`` routes each process's telemetry into its own
subdirectory (``<dir>/<name>_<pid>/events.jsonl``) so the merge tool
can find every stream; ``MXTPU_SCRAPE_S`` > 0 starts the router's
scrape loop. Zero-overhead contract: with tracing off, every emission
helper is one env/flag check.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Optional

from .. import telemetry as _tel
from ..telemetry.metrics import merge_summaries

__all__ = ["trace_enabled", "force", "new_request_id", "request_scope",
           "current_request_id", "context", "span", "instant",
           "clock_us", "maybe_enable_process", "estimate_offset",
           "note_clock_sample", "scrape_interval_s", "FleetTelemetry",
           "aggregate_snapshots", "replay_scrapes"]

_FORCE: Optional[bool] = None
_TLS = threading.local()


def trace_enabled() -> bool:
    """Tracing gate: the ``force()`` override when set, else
    ``MXTPU_TRACE``. Read live (a dict get) so tests and benches can
    flip it without re-importing."""
    f = _FORCE
    if f is not None:
        return f
    return os.environ.get("MXTPU_TRACE", "0").lower() not in (
        "0", "", "false", "no")


def force(on: Optional[bool]):
    """Programmatic override of ``MXTPU_TRACE``: True/False pin tracing
    on/off (benches measure overhead by flipping this around identical
    load), None restores env control."""
    global _FORCE
    _FORCE = on


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


def current_request_id() -> Optional[str]:
    """The request id adopted by the current thread (None outside a
    ``request_scope``) — lets deep layers (``faults.fire``) attribute
    events without threading the id through every signature."""
    return getattr(_TLS, "rid", None)


class request_scope:
    """Thread-local request context: worker verb handlers enter it with
    the RPC payload's ``trace.request_id`` so everything they touch
    (spans, fault instants) is attributable. Re-entrant; restores the
    previous id on exit. A None id is a no-op scope."""

    __slots__ = ("rid", "_prev")

    def __init__(self, request_id: Optional[str]):
        self.rid = request_id

    def __enter__(self):
        self._prev = getattr(_TLS, "rid", None)
        if self.rid is not None:
            _TLS.rid = self.rid
        return self.rid

    def __exit__(self, *exc):
        _TLS.rid = self._prev
        return False


def context(request_id: Optional[str] = None) -> Optional[dict]:
    """The ``trace`` dict a client attaches to an RPC payload; None when
    there is nothing to propagate (keeps untraced frames byte-identical
    to the pre-tracing wire format)."""
    rid = request_id if request_id is not None else current_request_id()
    return {"request_id": rid} if rid is not None else None


def clock_us() -> float:
    return _tel.clock_us()


def span(name: str, start_us: float, args: Optional[dict] = None,
         request_id: Optional[str] = None, end_us: Optional[float] = None):
    """Emit one complete span from an explicit start timestamp to now
    (or ``end_us``), tagged with the in-scope request id. One flag check
    when tracing is off."""
    if not trace_enabled():
        return
    a = dict(args) if args else {}
    rid = request_id if request_id is not None else current_request_id()
    if rid is not None:
        a.setdefault("request_id", rid)
    end = clock_us() if end_us is None else end_us
    _tel.complete(name, start_us, end - start_us, a)


def instant(name: str, args: Optional[dict] = None,
            request_id: Optional[str] = None):
    if not trace_enabled():
        return
    a = dict(args) if args else {}
    rid = request_id if request_id is not None else current_request_id()
    if rid is not None:
        a.setdefault("request_id", rid)
    _tel.instant(name, a)


def maybe_enable_process(name: Optional[str] = None) -> Optional[str]:
    """Fleet trace capture: when ``MXTPU_TRACE_DIR`` is set (and tracing
    on), enable telemetry into this process's own subdirectory —
    ``<dir>/<name>_<pid>`` — so every fleet process writes a separate
    ``events.jsonl`` that ``tools/fleet_trace.py`` can discover and
    merge. Idempotent; a no-op when telemetry is already enabled (the
    caller picked a directory) or the env is absent."""
    root = os.environ.get("MXTPU_TRACE_DIR")
    if not root or not trace_enabled():
        return None
    if _tel.enabled():
        return None
    d = os.path.join(root, f"{name or 'proc'}_{os.getpid()}")
    _tel.enable(d)
    return d


# ------------------------------------------------------- clock alignment
def estimate_offset(samples):
    """Best clock-offset estimate from ping-style probe samples.

    Each sample is ``(t_send_us, t_recv_us, peer_clock_us)`` — the
    caller's clock bracketing one RPC whose reply carried the peer's
    clock. The midpoint estimator assumes symmetric network delay, so
    its error is bounded by RTT/2 — the MINIMUM-RTT sample is the best
    estimate (NTP's selection rule). Returns ``(offset_us, rtt_us)``
    with ``peer_ts + offset ≈ caller_ts``, or None for no samples."""
    best = None
    for t_send, t_recv, peer in samples:
        rtt = t_recv - t_send
        if best is None or rtt < best[1]:
            best = ((t_send + t_recv) / 2.0 - peer, rtt)
    return best


def note_clock_sample(replica: str, peer_pid, t_send_us: float,
                      t_recv_us: float, peer_clock_us: float):
    """Record one clock probe as a ``trace.clock_offset`` instant in
    THIS process's event stream — the merge tool reads these (min-RTT
    per peer pid) to shift worker timelines onto the router's."""
    if not trace_enabled() or peer_clock_us is None:
        return
    off = (t_send_us + t_recv_us) / 2.0 - peer_clock_us
    instant("trace.clock_offset", {
        "replica": replica,
        "peer_pid": peer_pid,
        "offset_us": off,
        "rtt_us": t_recv_us - t_send_us,
    })


# ------------------------------------------------- scrape / aggregation
def scrape_interval_s() -> float:
    """``MXTPU_SCRAPE_S``: seconds between fleet telemetry scrapes;
    0 (default) disables the scrape loop."""
    try:
        return float(os.environ.get("MXTPU_SCRAPE_S", "0") or 0.0)
    except ValueError:
        return 0.0


def aggregate_snapshots(snapshots: dict) -> dict:
    """Merge per-process registry snapshots (``{name: snapshot}``) into
    one fleet view: counters sum, histogram summaries merge
    (``merge_summaries``), gauges stay per-replica (summing last-write
    gauges across processes is meaningless). Pure and deterministic —
    the same function serves the live aggregate and the recorded-stream
    replay, which is what makes the JSONL replayable by construction."""
    counters: dict = {}
    hists: dict = {}
    per_replica: dict = {}
    for name in sorted(snapshots):
        snap = snapshots[name] or {}
        per_replica[name] = snap
        for k, v in (snap.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, v in (snap.get("histograms") or {}).items():
            hists.setdefault(k, []).append(v)
    return {
        "replicas": sorted(snapshots),
        "counters": counters,
        "histograms": {k: merge_summaries(v)
                       for k, v in sorted(hists.items())},
        "per_replica": per_replica,
    }


def replay_scrapes(path: str):
    """Re-derive the aggregate stream from a recorded
    ``fleet_telemetry.jsonl``: one ``{"t", "aggregate"}`` entry per
    recorded scrape, skipping torn lines (append-only stream). Feeding
    the recorded raw snapshots through the same ``aggregate_snapshots``
    is the replay guarantee ROADMAP-6's simulator samples from."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            out.append({
                "t": rec.get("t"),
                "aggregate": aggregate_snapshots(
                    rec.get("snapshots") or {}),
            })
    return out


class FleetTelemetry:
    """Router-side scrape/aggregation plane.

    Polls each remote replica's ``telemetry`` RPC verb (a registry
    snapshot + the worker's trace clock), folds in the local (router)
    registry, appends the raw scrape to ``fleet_telemetry.jsonl``, and
    keeps the latest snapshots for ``aggregate()``. Each scrape doubles
    as a clock probe (``note_clock_sample``). Scrape RPCs run OUTSIDE
    the lock — the lock only guards the latest-snapshot swap."""

    def __init__(self, replicas, interval_s: Optional[float] = None,
                 directory: Optional[str] = None, local_name: str = "router",
                 rpc_timeout_s: float = 5.0):
        # a sequence, or a zero-arg callable returning the CURRENT
        # sequence — the router passes its snapshot method so replicas
        # added by respawn/scale-up join the scrape without re-wiring
        self._replicas = replicas
        self.interval_s = float(interval_s if interval_s is not None
                                else scrape_interval_s())
        if directory is None:
            jp = _tel.jsonl_path()
            directory = os.path.dirname(jp) if jp else _tel.default_dir()
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, "fleet_telemetry.jsonl")
        self.local_name = local_name
        self.rpc_timeout_s = float(rpc_timeout_s)
        self._lock = threading.Lock()
        self._last: dict = {}
        self._stop = threading.Event()
        self._thread = None

    # --------------------------------------------------------- control
    def start(self):
        if self._thread is not None or self.interval_s <= 0:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mxtpu-fleet-telemetry", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.rpc_timeout_s + self.interval_s + 1.0)

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 - scraping must not kill serving
                _tel.registry().counter("fleet/scrape_errors").inc()

    def _replica_list(self) -> list:
        reps = self._replicas
        return list(reps()) if callable(reps) else list(reps)

    # ---------------------------------------------------------- scrape
    def scrape_once(self) -> dict:
        """One scrape pass: remote snapshots via the ``telemetry`` verb
        (failures counted, never fatal), local registry under
        ``local_name``, record + publish. Returns the snapshot map."""
        reg = _tel.registry()
        snaps = {}
        for rep in self._replica_list():
            client = getattr(rep, "client", None)
            if client is None:
                continue
            t0 = clock_us()
            try:
                msg = client.call("telemetry", {},
                                  timeout_s=self.rpc_timeout_s)
            except Exception:  # noqa: BLE001 - dead replica: scrape on
                reg.counter("fleet/scrape_errors").inc()
                continue
            t1 = clock_us()
            snaps[rep.name] = msg.get("snapshot") or {}
            note_clock_sample(rep.name, msg.get("pid"), t0, t1,
                              msg.get("clock_us"))
        snaps[self.local_name] = reg.snapshot()
        reg.counter("fleet/scrapes").inc()
        reg.gauge("fleet/replicas").set(len(snaps) - 1)
        line = json.dumps({"t": time.time(), "snapshots": snaps},
                          default=str)
        with self._lock:
            self._last = snaps
        try:
            with open(self.path, "a") as f:
                f.write(line + "\n")
        except OSError:
            pass
        return snaps

    def aggregate(self) -> dict:
        """Fleet aggregate of the latest scrape (see
        ``aggregate_snapshots``)."""
        with self._lock:
            snaps = dict(self._last)
        return aggregate_snapshots(snaps)
