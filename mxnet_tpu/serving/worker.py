"""Serving worker process: one engine + batcher behind the RPC transport.

The process half of the cross-process serving plane
(``serving.transport``): ``python -m mxnet_tpu.serving.worker --dir D``
builds a net, wraps it in an ``InferStep`` + the process-default batcher
(``serving.make_batcher`` — ``ContinuousBatcher`` unless
``MXTPU_BATCHER=fixed``), writes the PR-1 watchdog heartbeat into
``--dir``, announces itself in ``worker.json`` (name/host/port/pid —
written AFTER warmup, so its existence is the readiness signal), and
serves the transport verbs until told to stop:

- **SIGTERM** (or the ``drain`` verb) drains gracefully: new submits
  are rejected with ``ReplicaUnavailable`` (the router replays them
  elsewhere for free), in-flight requests finish and stream their final
  frames, then the process exits 0.
- **SIGKILL** is the crash case the plane exists for: the heartbeat
  goes stale, the router's socket dies, the replica is evicted and its
  in-flight requests transparently resubmit (see
  ``serving.remote.RemoteReplica``).

``--ckpt-dir`` makes a (re)spawned worker adopt the newest committed
checkpoint at boot — a worker respawned after a coordinated hot swap
rejoins at the fleet's CURRENT ``weights_version``, not at its net
factory's initial weights (same version-tag derivation as
``CheckpointWatcher``, so tags stay coherent across the fleet).

Nets come from ``--model transformer`` (a built-in model-zoo
transformer, seeded deterministically — two processes with the same
spec build bit-identical params) or ``--net-factory module:callable``
(any importable zero-config factory). Under ``tools/launch.py`` the
worker picks its identity up from ``MXNET_TPU_PROC_ID``: name defaults
to ``worker-<id>``, the port offsets from ``MXTPU_SERVE_PORT``, and the
heartbeat/announce files land in ``<dir>/worker-<id>`` — so
``python tools/launch.py -n 4 -- python -m mxnet_tpu.serving.worker
--dir /tmp/fleet`` brings up a 4-worker fleet in one line.

Disaggregated serving (``--role`` / ``MXTPU_ROLE``, ``serving.disagg``):
a ``prefill``-role worker serves the ``prefill`` verb — one admission
prefill per request, KV frames shipped to the decode worker named in
``push_to`` over ``kv_push`` (or spilled to ``MXTPU_KV_SPILL_DIR``) —
and REFUSES decode submits; a ``decode``-role worker stashes pushed
frames (``HandoffStash``) until the router's ``submit`` with the same
handoff id claims them, adopting the KV without re-prefilling (missing
or unusable frames re-prefill from the prompt: ``disagg/re_prefills``,
zero lost requests). The default ``both`` co-schedules as before. The
health verb reports the role plus the rolling queue-wait/TTFT p50s the
SLO-aware router places by.

Fault point: ``worker.exit`` (``MXTPU_FAULT_WORKER_EXIT``) hard-kills
the process from the inside (``os._exit``) — sudden process death on a
deterministic schedule, for the chaos bench. ``transport.kv_push``
fires in the prefill worker's push path (raise = the handoff fails and
the decode side re-prefills; delay = a slow push).

Env knobs: ``MXTPU_SERVE_PORT`` (base port, 0 = ephemeral),
``MXTPU_WORKER_DRAIN_S`` (SIGTERM drain budget, default 30),
``MXTPU_RPC_TIMEOUT_S``/``MXTPU_RPC_CONNECT_S`` (transport),
``MXTPU_ROLE``/``MXTPU_KV_SPILL_DIR`` (disaggregation).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Optional

from ..base import MXNetError
from .. import telemetry as _tel
from . import disagg as _disagg
from . import faults as _faults
from . import prefix as _prefix
from . import tracing as _tracing
from .transport import RpcClient, RpcServer, serve_port

__all__ = ["ServingWorker", "WorkerHandle", "spawn_worker", "main",
           "worker_drain_s"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def worker_drain_s(default: float = 30.0) -> float:
    """``MXTPU_WORKER_DRAIN_S``: how long a SIGTERM'd worker may spend
    draining in-flight requests before it stops waiting and exits."""
    v = os.environ.get("MXTPU_WORKER_DRAIN_S", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


def _proc_id() -> Optional[int]:
    """Rank under ``tools/launch.py`` (``MXNET_TPU_PROC_ID``), else None."""
    v = os.environ.get("MXNET_TPU_PROC_ID", "").strip()
    try:
        return int(v) if v else None
    except ValueError:
        return None


# ------------------------------------------------------------- net factory
def make_transformer_net(vocab: int = 61, units: int = 16, layers: int = 1,
                         heads: int = 2, seed: int = 0,
                         max_length: int = 64,
                         prefix: str = "serve_net_"):
    """Built-in deterministic factory: the model-zoo transformer at a
    CPU-testable size. Two processes calling this with the same spec get
    bit-identical params — the cross-process analogue of the trainer and
    server building the net from the same code."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel

    np.random.seed(seed)
    mx.random.seed(seed)
    net = TransformerModel(src_vocab=vocab, tgt_vocab=vocab, units=units,
                           hidden_size=units * 2, num_layers=layers,
                           num_heads=heads, max_length=max_length,
                           dropout=0.0, prefix=prefix)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    return net


def _net_from_factory(spec: str):
    """``module:callable`` — import and call a zero-arg net factory."""
    mod_name, _, fn_name = spec.partition(":")
    if not mod_name or not fn_name:
        raise MXNetError(
            f"--net-factory wants 'module:callable', got {spec!r}")
    import importlib

    return getattr(importlib.import_module(mod_name), fn_name)()


# ------------------------------------------------------------------ worker
class ServingWorker:
    """One worker process's serving state: engine, batcher, watchdog
    heartbeat, RPC handlers, drain lifecycle."""

    def __init__(self, net, directory: str, name: str,
                 port: int = 0, max_len: int = 24,
                 bucket_keys=(8,), slots: int = 2, max_new: int = 4,
                 batcher_kind: Optional[str] = None,
                 warmup: bool = True, heartbeat_s: float = 0.5,
                 ckpt_dir: Optional[str] = None,
                 drain_s: Optional[float] = None,
                 role: Optional[str] = None,
                 max_prefix: int = 0):
        from ..parallel import InferStep
        from ..telemetry.watchdog import Watchdog
        from . import make_batcher
        from .batcher import DynamicBatcher

        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.name = name
        self.drain_s = drain_s if drain_s is not None else worker_drain_s()
        self.role = role if role else _disagg.worker_role()
        if self.role not in _disagg.ROLES:
            raise MXNetError(f"unknown worker role {self.role!r} "
                             f"(one of {_disagg.ROLES})")
        self._lock = threading.Lock()   # guards _staged/_streamers
        self._staged = None             # (arrays staged, pending version)
        self._streamers: list = []
        self._stop = threading.Event()
        self._draining = False
        self.exit_code = 0
        # disaggregated serving state: arrival stash for pushed KV
        # (decode side) and cached worker-to-worker clients (prefill
        # side); _peer_lock guards the cache, never held across a
        # connect or a call
        self._handoffs = _disagg.HandoffStash()
        self._peers: dict = {}
        self._peer_lock = threading.Lock()

        self.engine = InferStep(net, max_len=max_len)
        if ckpt_dir:
            self._adopt_checkpoint(ckpt_dir)
        self.watchdog = Watchdog(directory, interval=heartbeat_s)
        # a dedicated prefill worker never decodes: skip the batcher's
        # decode-program warmup and warm the prefill engine instead
        bat_warmup = warmup and self.role != "prefill"
        if batcher_kind == "fixed":
            self.batcher = DynamicBatcher(
                self.engine, bucket_keys=tuple(bucket_keys), slots=slots,
                max_new_tokens=max_new, warmup=bat_warmup, name=name,
                watchdog=self.watchdog)
        else:
            self.batcher = make_batcher(
                self.engine, tuple(bucket_keys), slots=slots,
                max_new_tokens=max_new, warmup=bat_warmup, name=name,
                watchdog=self.watchdog,
                max_prefix_tokens=int(max_prefix))
        self.prefiller = None
        if self.role == "prefill":
            self.prefiller = _disagg.PrefillEngine(
                self.engine, tuple(bucket_keys), warmup=warmup)
        self.watchdog.start()
        self.server = RpcServer({
            "ping": self._handle_ping,
            "health": self._handle_health,
            "submit": self._handle_submit,
            "prefill": self._handle_prefill,
            "kv_push": self._handle_kv_push,
            "stage": self._handle_stage,
            "swap": self._handle_swap,
            "drain": self._handle_drain,
            "telemetry": self._handle_telemetry,
        }, port=port, name=name)

    def _adopt_checkpoint(self, ckpt_dir: str):
        """Boot-time version adoption: a worker (re)spawned after the
        fleet hot-swapped must serve the swapped weights, tagged with
        the SAME version string the watcher handed everyone else."""
        from .. import checkpoint_sharded as _cs
        from .watcher import version_for

        found = _cs.latest_committed(ckpt_dir)
        if found is None:
            return
        path, token = found
        self.engine.swap_params(arrays=_cs.load_sharded(path),
                                version=version_for(path, token))

    # ----------------------------------------------------------- lifecycle
    def announce(self):
        """Publish ``worker.json`` (atomic rename): existence = ready."""
        info = {"name": self.name, "host": self.server.host,
                "port": self.server.port, "pid": os.getpid(),
                "heartbeat": self.watchdog.heartbeat_path,
                "dir": self.directory, "role": self.role}
        path = os.path.join(self.directory, "worker.json")
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(info, f)
        os.replace(tmp, path)
        return info

    def serve_forever(self) -> int:
        """Main-thread loop: idle heartbeat + the ``worker.exit`` fault
        point, until SIGTERM / the drain verb sets the stop event. Then
        drain and tear down. Returns the process exit code."""
        self.server.start()
        self.announce()
        while not self._stop.wait(0.05):
            if _faults.check("worker.exit", tag=self.name) is not None:
                os._exit(29)  # sudden process death, by request
            if self.batcher._drained():
                # idle is progress, not a stall: keep the heartbeat
                # honest while no work exists (a wedged dispatch still
                # goes stale — notify_step only runs when drained)
                self.watchdog.notify_step()
        self.shutdown()
        return self.exit_code

    def request_stop(self):
        self._draining = True
        self._stop.set()

    def shutdown(self):
        """Graceful teardown: drain the batcher (in-flight requests
        finish and stream their final frames), then close transport and
        heartbeat."""
        self._draining = True
        try:
            self.batcher.stop(drain=True, timeout=self.drain_s)
        except Exception:  # noqa: BLE001 - teardown must complete
            pass
        with self._lock:
            streamers = list(self._streamers)
        for t in streamers:
            t.join(timeout=5.0)
        with self._peer_lock:
            peers, self._peers = list(self._peers.values()), {}
        for client in peers:
            client.close()
        self.server.stop()
        self.watchdog.stop()

    # ------------------------------------------------------------ handlers
    def _handle_ping(self, msg, respond):
        # clock_us lets the caller estimate this process's event-clock
        # offset from one round trip (serving.tracing.estimate_offset)
        respond(pong=True, name=self.name, pid=os.getpid(),
                clock_us=_tel.clock_us())

    def _handle_health(self, msg, respond):
        bat = self.batcher
        busy = 0
        slots = getattr(bat, "_slots", None)
        if slots is not None:
            busy = sum(1 for s in slots if s is not None)
        adopted = re_prefilled = None
        stats_lock = getattr(bat, "_stats_lock", None)
        if stats_lock is not None:
            with stats_lock:
                adopted = bat.stats.get("adopted")
                re_prefilled = bat.stats.get("re_prefills")
        digests = prefix_stats = None
        fn = getattr(bat, "prefix_digests", None)
        if fn is not None:
            # the affinity signal: which prompts this worker's prefix
            # cache holds, as compact digests (bounded by the env knob —
            # the health frame must stay small)
            digests = list(fn(_prefix.prefix_digest_max()))
            prefix_stats = bat.prefix_stats()
        respond(healthy=bool(bat.healthy and not self._draining),
                status="draining" if self._draining else "serving",
                queue_depth=bat._queue.qsize() + busy,
                weights_version=self.engine.weights_version,
                role=self.role,
                queue_wait_p50_ms=bat.rolling_wait_ms(),
                ttft_p50_ms=bat.rolling_ttft_ms(),
                disagg_adopted=adopted,
                disagg_re_prefills=re_prefilled,
                prefix_digests=digests,
                prefix_stats=prefix_stats,
                name=self.name, pid=os.getpid(),
                clock_us=_tel.clock_us())

    def _handle_telemetry(self, msg, respond):
        """Scrape verb: one frame with the full registry snapshot plus
        this process's event clock, so the router-side aggregation plane
        (``serving.tracing.FleetTelemetry``) gets counters, histogram
        summaries, and a clock sample from a single round trip."""
        respond(snapshot=_tel.registry().snapshot(),
                clock_us=_tel.clock_us(),
                name=self.name, pid=os.getpid())

    def _handle_submit(self, msg, respond):
        import numpy as np

        if self._draining or not self.batcher.healthy:
            respond(ok=False, error={
                "type": "ReplicaUnavailable",
                "message": f"worker {self.name!r} is draining"})
            return
        if self.role == "prefill":
            respond(ok=False, error={
                "type": "ReplicaUnavailable",
                "message": f"worker {self.name!r} is prefill-role: it "
                           "does not serve decode submits"})
            return
        prompt = np.asarray(msg.get("prompt", ()), np.int32).reshape(-1)
        frames = None
        handoff = msg.get("handoff")
        if handoff:
            frames = self._handoffs.pop(str(handoff))
            if frames is None:
                spill = _disagg.kv_spill_dir()
                if spill:
                    frames = _disagg.load_spilled(spill, str(handoff))
            if frames is None:
                # the push never landed (dead prefill worker, dropped
                # link, torn spill): prefill locally from the prompt
                _tel.registry().counter("disagg/re_prefills").inc()
        fut = self.batcher.submit(
            prompt, msg.get("max_new_tokens"),
            deadline_ms=msg.get("deadline_ms"), frames=frames,
            prefix_ids=msg.get("prefix_ids"),
            request_id=(msg.get("trace") or {}).get("request_id"))
        try:
            t = threading.Thread(target=self._stream_result,
                                 args=(fut, respond),
                                 name="mxtpu-worker-stream", daemon=True)
            with self._lock:
                self._streamers.append(t)
                if len(self._streamers) > 64:
                    self._streamers = [s for s in self._streamers
                                       if s.is_alive()]
            t.start()
        except Exception as e:  # noqa: BLE001 - fail the row, answer the peer
            # without this, a thread-spawn failure leaves a future whose
            # tokens nobody will ever stream and the caller camped on
            # its deadline: fail it, then let _dispatch answer ok=False.
            if not fut.done():
                fut._fail(e)
            raise

    def _stream_result(self, fut, respond):
        """Relay one request's token stream, then its final frame — runs
        on its own thread so the connection's reader never blocks on a
        decode."""
        try:
            for chunk in fut.tokens_iter():
                if not respond(done=False, stream=chunk):
                    break  # peer gone: the batcher still finishes the row
            tokens = fut.result(timeout=0)
        except BaseException as e:  # noqa: BLE001 - relay the failure
            respond(ok=False, error={"type": type(e).__name__,
                                     "message": str(e)})
            return
        respond(tokens=tokens, weights_version=fut.weights_version,
                replica=self.name, queue_wait_ms=fut.queue_wait_ms,
                phases=fut.phases, request_id=fut.request_id)

    # ------------------------------------------------ disaggregated verbs
    def _peer(self, address) -> RpcClient:
        """Cached worker-to-worker RPC client (prefill -> decode
        ``kv_push``). A dead cached link is replaced; connects happen
        OUTSIDE the cache lock."""
        with self._peer_lock:
            client = self._peers.get(address)
        if client is not None and client.dead is None:
            return client
        fresh = RpcClient(address,
                          name=f"{self.name}->{address}").connect(
                              budget_s=5.0)
        with self._peer_lock:
            held = self._peers.get(address)
            if held is not None and held is not client \
                    and held.dead is None:
                chosen = held  # another handler won the connect race
            else:
                self._peers[address] = fresh
                chosen = fresh
        if chosen is not fresh:
            fresh.close()
        return chosen

    def _handle_prefill(self, msg, respond):
        """Prefill-role verb: run ONE admission prefill and ship the
        filled KV frames to the decode worker named in ``push_to`` (or
        the ``MXTPU_KV_SPILL_DIR`` spill). The frames reproduce exactly
        what the decode worker's own ``prefill_paged`` would have
        written, so adopted decode is bit-identical.

        The work runs on its OWN thread: all of a router's prefill
        verbs arrive over one connection, and the transport dispatches
        a connection's verbs inline on its reader thread — served
        inline they would serialize (and the ``PrefillEngine``'s
        request batching could never engage)."""
        if self.prefiller is None:
            raise MXNetError(
                f"worker {self.name!r} has role {self.role!r}: no "
                "prefill engine (spawn it with --role prefill)")
        if self._draining:
            respond(ok=False, error={
                "type": "ReplicaUnavailable",
                "message": f"worker {self.name!r} is draining"})
            return
        handoff = str(msg.get("handoff") or "")
        if not handoff:
            raise MXNetError("prefill verb needs a 'handoff' id")
        t = threading.Thread(target=self._run_prefill,
                             args=(msg, handoff, respond),
                             name="mxtpu-worker-prefill", daemon=True)
        with self._lock:
            self._streamers.append(t)
            if len(self._streamers) > 64:
                self._streamers = [s for s in self._streamers
                                   if s.is_alive()]
        t.start()

    def _run_prefill(self, msg, handoff, respond):
        """Prefill-thread body: prefill (batched with concurrent
        callers), push, respond — exceptions relay as error frames (the
        transport's inline catch does not cover this thread)."""
        try:
            with _tracing.request_scope(
                    (msg.get("trace") or {}).get("request_id")):
                self._prefill_and_push(msg, handoff, respond)
        except BaseException as e:  # noqa: BLE001 - relay the failure
            respond(ok=False, error={"type": type(e).__name__,
                                     "message": str(e)})

    def _prefill_and_push(self, msg, handoff, respond):
        tp0 = _tracing.clock_us()
        frames = self.prefiller.prefill(msg.get("prompt", ()))
        _tracing.span("trace.prefill", tp0,
                      {"replica": self.name, "handoff": handoff})
        nbytes = _disagg.frame_bytes(frames)
        t0 = time.perf_counter()
        tk0 = _tracing.clock_us()
        # fault point: the push itself drops (raise) or crawls (delay) —
        # the decode side then re-prefills from the prompt
        _faults.fire("transport.kv_push",
                     tag=str(msg.get("push_to") or handoff))
        spill = _disagg.kv_spill_dir()
        if spill:
            _disagg.spill_frames(spill, handoff, frames)
        else:
            push_to = msg.get("push_to")
            if not push_to:
                raise MXNetError("prefill verb needs 'push_to' when "
                                 "MXTPU_KV_SPILL_DIR is unset")
            meta, bufs = _disagg.pack_frames(frames)
            self._peer(str(push_to)).call(
                "kv_push", {"handoff": handoff, "meta": meta},
                bin_frames=bufs)
        reg = _tel.registry()
        reg.histogram("disagg/kv_push_ms").observe(
            (time.perf_counter() - t0) * 1e3)
        reg.counter("disagg/kv_bytes").inc(nbytes)
        _tracing.span("trace.kv_push", tk0,
                      {"replica": self.name, "handoff": handoff,
                       "kv_bytes": nbytes, "spilled": bool(spill)})
        respond(pushed=True, handoff=handoff, kv_bytes=nbytes,
                spilled=bool(spill))

    def _handle_kv_push(self, msg, respond):
        """Decode-role verb: stash one handoff's KV frames (JSON meta +
        the binary frames the transport read after the header) until the
        matching ``submit`` claims them."""
        handoff = str(msg.get("handoff") or "")
        if not handoff:
            raise MXNetError("kv_push needs a 'handoff' id")
        frames = _disagg.unpack_frames(msg.get("meta") or {},
                                       msg.get("_bin") or [])
        self._handoffs.put(handoff, frames)
        respond(received=True, handoff=handoff)

    def _handle_stage(self, msg, respond):
        """Swap phase 1: load the committed checkpoint host-side and
        stage it into the engine's standby buffer. The live set is
        untouched — serving continues on the old weights."""
        from .. import checkpoint_sharded as _cs

        path = msg.get("path")
        if not path:
            raise MXNetError("stage verb needs a checkpoint 'path'")
        with _tracing.request_scope(
                (msg.get("trace") or {}).get("request_id")):
            t0 = _tracing.clock_us()
            _faults.fire("ckpt.load", tag=path)
            staged = self.engine.stage_params(_cs.load_sharded(path))
            with self._lock:
                self._staged = staged
            _tracing.span("trace.stage", t0,
                          {"replica": self.name, "path": path})
        respond(staged=True, path=path)

    def _handle_swap(self, msg, respond):
        """Swap phase 2: flip the staged buffer live — one reference
        assignment, taken by the next dispatch."""
        with self._lock:
            staged, self._staged = self._staged, None
        if staged is None:
            raise MXNetError(
                "swap verb with nothing staged (stage must precede swap)")
        with _tracing.request_scope(
                (msg.get("trace") or {}).get("request_id")):
            t0 = _tracing.clock_us()
            version = self.engine.swap_params(staged=staged,
                                              version=msg.get("version"))
            _tracing.span("trace.swap", t0,
                          {"replica": self.name, "version": version})
        respond(version=version)

    def _handle_drain(self, msg, respond):
        """Stop accepting, wait for the queue+slots to empty (in-flight
        streams finish meanwhile), then acknowledge and schedule exit."""
        self._draining = True
        deadline = time.monotonic() + self.drain_s
        while not self.batcher._drained() and time.monotonic() < deadline:
            time.sleep(0.01)
        respond(drained=self.batcher._drained())
        self._stop.set()


# ------------------------------------------------------------- spawn helper
class WorkerHandle:
    """Parent-side handle for one spawned worker process."""

    def __init__(self, proc, directory: str, name: str):
        self.proc = proc
        self.directory = directory
        self.name = name

    @property
    def pid(self) -> int:
        return self.proc.pid

    def info(self) -> Optional[dict]:
        """Parsed ``worker.json``, or None while the worker boots."""
        try:
            with open(os.path.join(self.directory, "worker.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def wait_ready(self, timeout: float = 120.0) -> dict:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = self.info()
            if info is not None:
                return info
            if self.proc.poll() is not None:
                raise MXNetError(
                    f"worker {self.name!r} exited rc={self.proc.returncode} "
                    f"before announcing (see {self.log_path})")
            time.sleep(0.05)
        raise MXNetError(f"worker {self.name!r} not ready in {timeout}s")

    @property
    def address(self) -> str:
        info = self.wait_ready()
        return f"{info['host']}:{info['port']}"

    @property
    def heartbeat_path(self) -> str:
        return os.path.join(self.directory, "heartbeat.json")

    @property
    def log_path(self) -> str:
        return os.path.join(self.directory, "worker.log")

    def alive(self) -> bool:
        return self.proc.poll() is None

    def terminate(self):
        """SIGTERM: the worker drains in-flight requests and exits 0."""
        self.proc.terminate()

    def kill(self):
        """SIGKILL: sudden death — the failure the plane must absorb."""
        self.proc.kill()

    def wait(self, timeout: Optional[float] = None) -> int:
        return self.proc.wait(timeout=timeout)


def spawn_worker(directory: str, name: Optional[str] = None,
                 port: int = 0, model: Optional[dict] = None,
                 net_factory: Optional[str] = None,
                 max_len: int = 24, bucket_keys=(8,), slots: int = 2,
                 max_new: int = 4, ckpt_dir: Optional[str] = None,
                 batcher: Optional[str] = None, warmup: bool = True,
                 heartbeat_s: float = 0.1,
                 extra_env: Optional[dict] = None,
                 python: Optional[str] = None,
                 role: Optional[str] = None,
                 max_prefix: int = 0) -> WorkerHandle:
    """Spawn one serving worker process (``-m mxnet_tpu.serving.worker``)
    with stdout/stderr captured to ``<directory>/worker.log``. Readiness
    is ``handle.wait_ready()`` (the worker announces after warmup)."""
    import subprocess

    os.makedirs(directory, exist_ok=True)
    name = name or os.path.basename(os.path.normpath(directory))
    cmd = [python or sys.executable, "-m", "mxnet_tpu.serving.worker",
           "--dir", directory, "--name", name, "--port", str(port),
           "--max-len", str(max_len),
           "--bucket-keys", ",".join(str(k) for k in bucket_keys),
           "--slots", str(slots), "--max-new", str(max_new),
           "--heartbeat-s", str(heartbeat_s)]
    if net_factory:
        cmd += ["--net-factory", net_factory]
    else:
        for k, v in (model or {}).items():
            cmd += [f"--{k.replace('_', '-')}", str(v)]
    if ckpt_dir:
        cmd += ["--ckpt-dir", ckpt_dir]
    if batcher:
        cmd += ["--batcher", batcher]
    if role:
        cmd += ["--role", role]
    if max_prefix:
        cmd += ["--max-prefix", str(max_prefix)]
    if not warmup:
        cmd += ["--no-warmup"]
    env = dict(os.environ)
    env.update(extra_env or {})
    # resolve `-m mxnet_tpu...` via cwd, NOT PYTHONPATH — a PYTHONPATH
    # entry breaks registration of the axon TPU jax plugin in the child
    log = open(os.path.join(directory, "worker.log"), "ab")
    try:
        proc = subprocess.Popen(cmd, env=env, cwd=_REPO_ROOT,
                                stdout=log, stderr=log)
    finally:
        log.close()
    return WorkerHandle(proc, directory, name)


# --------------------------------------------------------------- entrypoint
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--dir", required=True,
                    help="worker state dir: heartbeat.json, worker.json, "
                    "worker.log (per-proc subdir under tools/launch.py)")
    ap.add_argument("--name", default=None)
    ap.add_argument("--port", type=int, default=None,
                    help="listen port (default MXTPU_SERVE_PORT [+rank]; "
                    "0 = ephemeral, announced in worker.json)")
    ap.add_argument("--net-factory", default=None,
                    help="module:callable returning an initialized net")
    ap.add_argument("--model", default="transformer",
                    choices=["transformer"])
    ap.add_argument("--vocab", type=int, default=61)
    ap.add_argument("--units", type=int, default=16)
    ap.add_argument("--layers", type=int, default=1)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-length", type=int, default=64)
    ap.add_argument("--prefix", default="serve_net_")
    ap.add_argument("--max-len", type=int, default=24,
                    help="engine KV capacity (InferStep max_len)")
    ap.add_argument("--bucket-keys", default="8",
                    help="comma-separated prompt bucket menu")
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--max-new", type=int, default=4)
    ap.add_argument("--batcher", default=None,
                    choices=["continuous", "fixed"],
                    help="override MXTPU_BATCHER for this worker")
    ap.add_argument("--role", default=None,
                    choices=["both", "prefill", "decode"],
                    help="disaggregated-fleet role (default MXTPU_ROLE "
                    "or 'both')")
    ap.add_argument("--max-prefix", type=int, default=0,
                    help="max forced-history tokens per request (> 0 "
                    "sizes the suffix-replay menu and enables the "
                    "prefix cache per MXTPU_PREFIX_CACHE)")
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument("--heartbeat-s", type=float, default=0.5)
    ap.add_argument("--ckpt-dir", default=None,
                    help="adopt the newest committed checkpoint at boot")
    ap.add_argument("--drain-s", type=float, default=None)
    args = ap.parse_args(argv)

    rank = _proc_id()
    name = args.name or (f"worker-{rank}" if rank is not None
                         else f"worker-{os.getpid()}")
    directory = args.dir
    if rank is not None and args.name is None:
        directory = os.path.join(directory, name)
    port = args.port if args.port is not None else serve_port()
    if port and rank:
        port += rank
    # per-process trace sink (MXTPU_TRACE + MXTPU_TRACE_DIR): each
    # worker writes its own events.jsonl; tools/fleet_trace.py merges
    # them onto the router's timeline afterwards
    _tracing.maybe_enable_process(name)

    if args.net_factory:
        net = _net_from_factory(args.net_factory)
    else:
        net = make_transformer_net(
            vocab=args.vocab, units=args.units, layers=args.layers,
            heads=args.heads, seed=args.seed, max_length=args.max_length,
            prefix=args.prefix)
    worker = ServingWorker(
        net, directory, name, port=port, max_len=args.max_len,
        bucket_keys=tuple(int(k) for k in args.bucket_keys.split(",")),
        slots=args.slots, max_new=args.max_new,
        batcher_kind=args.batcher, warmup=not args.no_warmup,
        heartbeat_s=args.heartbeat_s, ckpt_dir=args.ckpt_dir,
        drain_s=args.drain_s, role=args.role,
        max_prefix=args.max_prefix)

    def _sigterm(signum, frame):
        worker.request_stop()

    signal.signal(signal.SIGTERM, _sigterm)
    signal.signal(signal.SIGINT, _sigterm)
    return worker.serve_forever()


if __name__ == "__main__":
    sys.exit(main())
