"""Background checkpoint watcher: train→serve hot weight swap.

Replaces the manual, stop-the-world ``InferStep.sync_params()`` handoff:
a ``CheckpointWatcher`` polls a checkpoint directory (the trainer keeps
``save_checkpoint``-ing into it; commit is the ``checkpoint_sharded``
DONE-marker protocol, so a half-written save is invisible), and when a
NEW committed checkpoint appears it

1. loads the arrays (host-side, off the serving threads),
2. **stages** them into each engine's standby buffer
   (``InferStep.stage_params`` — cast to the live dtype, placed under the
   live sharding, so the flip cannot change a dispatch signature), and
3. **flips** every engine's live buffer (``swap_params``) — one reference
   assignment between decode dispatches.

The engine set may mix IN-PROCESS engines (``InferStep``) and REMOTE
worker processes (``serving.remote.RemoteEngineHandle``): the same
two-phase protocol runs over the control channel — phase 1 sends each
worker a ``stage`` verb (the worker loads the committed checkpoint
host-side and stages standby; arrays never cross the socket), phase 2
sends ``swap`` with ONE version tag derived once by the watcher
(:func:`version_for`) — so every process flips at a dispatch boundary
and version tags stay monotonic and coherent across the fleet. Staging
is all-or-nothing: any stage failure (including a remote one) aborts
the poll before ANY engine flips, counts ``serve/swap_failures``, and
everyone keeps serving the old weights.

In a DISAGGREGATED fleet (``serving.disagg``) the swap barrier covers
BOTH roles: ``Router.engines`` includes prefill-role replicas, so phase
1 stages every prefill AND decode worker before phase 2 flips any — a
handoff can never pair a new-version prefill with an old-version decode
(or vice versa) across the flip, because nobody flips until everyone
staged and each worker flips at its own dispatch boundary under the one
version tag.

In-flight dispatches hold their own param snapshot and finish on the old
version; responses are tagged with the ``weights_version`` their dispatch
actually served. A torn or unloadable checkpoint counts
``serve/swap_failures`` and the engines keep serving the old weights —
the next poll retries.

Env knobs: ``MXTPU_SWAP_POLL_S`` (poll period, default 2.0).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Optional

from .. import checkpoint_sharded as _cs
from .. import telemetry as _tel
from . import faults as _faults
from . import tracing as _tracing

__all__ = ["CheckpointWatcher", "swap_poll_s", "version_for"]


def version_for(path: str, token: str) -> str:
    """Canonical version tag for a committed checkpoint — shared by the
    watcher's flip and ``serving.worker --ckpt-dir`` boot adoption, so a
    respawned process rejoins under the fleet's exact current tag."""
    return os.path.basename(os.path.normpath(path)) + \
        ":" + token.rsplit("@", 1)[-1]


def swap_poll_s(default: float = 2.0) -> float:
    """``MXTPU_SWAP_POLL_S``: checkpoint-directory poll period."""
    v = os.environ.get("MXTPU_SWAP_POLL_S", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


class CheckpointWatcher:
    """Poll ``directory`` for committed checkpoints and hot-swap them
    into live engines.

    Parameters
    ----------
    engines : one ``InferStep`` or a sequence (e.g. ``Router.engines`` —
        every replica swaps to the same version). A zero-arg callable is
        also accepted and re-evaluated per poll, so respawned replicas
        join the swap set automatically.
    directory : checkpoint root — either itself a sharded checkpoint or
        a directory of ``step_N``-style checkpoint subdirectories; the
        newest committed one wins (``checkpoint_sharded.latest_committed``).
    poll_s : poll period (``MXTPU_SWAP_POLL_S`` default).
    on_swap : callback ``(version, path)`` after a successful flip.
    """

    def __init__(self, engines, directory: str,
                 poll_s: Optional[float] = None,
                 on_swap: Optional[Callable[[str, str], None]] = None,
                 start: bool = True):
        # NB: an InferStep is itself callable (its jitted forward), so
        # "factory" means callable-but-not-an-engine
        if hasattr(engines, "stage_params") or \
                hasattr(engines, "stage_checkpoint"):
            fixed = [engines]
            self._engines_fn = lambda: fixed
        elif callable(engines):
            self._engines_fn = engines
        else:
            fixed = list(engines)
            self._engines_fn = lambda: fixed
        self.directory = directory
        self.poll_s = float(poll_s) if poll_s is not None else swap_poll_s()
        self.on_swap = on_swap
        self._seen: Optional[str] = None
        self.last_error: Optional[BaseException] = None
        # poll_once is both the background thread's body and a public
        # API (tests/manual swaps drive it directly): the lock keeps two
        # concurrent polls from double-staging one checkpoint and makes
        # the _seen/last_error writes coherent (mxlint lock-order pass)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        if start:
            self.start()

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mxtpu-ckpt-watcher", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=timeout)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def _run(self):
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - poll_once already accounts;
                pass           # a watcher crash must never take serving down

    # ----------------------------------------------------------------- poll
    @property
    def current_version(self) -> Optional[str]:
        return self._seen

    def poll_once(self) -> Optional[str]:
        """One poll: find the newest committed checkpoint; if it is new,
        load + stage + flip every engine. Returns the new version tag, or
        None (nothing new, or the swap failed and the old weights keep
        serving). Serialized: a caller-driven poll and the background
        thread never stage the same checkpoint twice."""
        with self._lock:
            # one trace id per swap CYCLE: every stage/swap verb the
            # barrier fans out carries it, so the merged fleet trace
            # shows the whole two-phase flip as one operation
            rid = _tracing.new_request_id() \
                if _tracing.trace_enabled() else None
            with _tracing.request_scope(rid):
                return self._poll_once_locked()

    def _poll_once_locked(self) -> Optional[str]:
        found = _cs.latest_committed(self.directory)
        if found is None:
            return None
        path, token = found
        if token == self._seen:
            return None
        reg = _tel.registry()
        engines = list(self._engines_fn())
        local = [e for e in engines if hasattr(e, "stage_params")]
        remote = [e for e in engines if hasattr(e, "stage_checkpoint")]
        try:
            # fault point: a checkpoint that commits but cannot be read
            # back (torn file, lost shard) mid-swap
            _faults.fire("ckpt.load", tag=path)
            # phase 1 — stage EVERYTHING before flipping ANYTHING:
            # either every replica (in-process or worker process) moves
            # to the new version or none does. Workers load the
            # committed checkpoint themselves (the `stage` verb) so
            # arrays never cross the socket.
            staged = []
            if local:
                arrays = _cs.load_sharded(path)
                staged = [eng.stage_params(arrays) for eng in local]
            for eng in remote:
                eng.stage_checkpoint(path)
        except Exception as e:  # noqa: BLE001 - keep serving old weights
            self.last_error = e
            reg.counter("serve/swap_failures").inc()
            _tel.instant("serve.swap_failure",
                         {"path": path, "error": repr(e)})
            return None
        # phase 2 — flip ALL under one coherent tag, each at its own
        # dispatch boundary. A remote flip can only fail if the worker
        # died between the phases; it is then evicted/respawned and
        # rejoins at this same version via --ckpt-dir boot adoption.
        version = version_for(path, token)
        for eng, vals in zip(local, staged):
            eng.swap_params(staged=vals, version=version)
        flip_failures = 0
        for eng in remote:
            try:
                eng.swap_staged(version)
            except Exception as e:  # noqa: BLE001 - worker died mid-flip
                flip_failures += 1
                self.last_error = e
                reg.counter("serve/swap_failures").inc()
                _tel.instant("serve.swap_failure",
                             {"path": path, "error": repr(e),
                              "phase": "flip"})
        if flip_failures and not local and \
                flip_failures == len(remote):
            return None  # nobody flipped: the next poll retries
        self._seen = token
        self.last_error = None
        reg.counter("serve/swaps").inc()
        _tel.set_info(weights_version=version)
        _tel.instant("serve.swap", {"path": path, "version": version})
        if self.on_swap is not None:
            try:
                self.on_swap(version, path)
            except Exception:  # noqa: BLE001 - user callback
                pass
        return version
