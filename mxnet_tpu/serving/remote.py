"""Remote replicas: worker processes behind the ``Replica`` protocol.

``RemoteReplica`` plugs a ``serving.worker`` process into the existing
``Router`` unchanged — same health/load/submit surface, but the failure
modes are now real: a SIGKILL'd worker is a dead socket plus a stale
heartbeat file, eviction is process-level failover, and a respawn
factory spawns an actual fresh process.

- **submit** rides ``transport.RpcClient.submit``: the inner future is
  local, fed by the worker's token stream; a dead connection fails it
  with ``ReplicaUnavailable`` so the router replays it elsewhere
  without charging the retry budget.
- **health** is a cached RPC probe (``health`` verb, refreshed at most
  every ``probe_ttl_s`` — the router's submit path may ask under its
  lock and must not block on the wire) combined with the worker's
  heartbeat FILE: a wedged worker whose socket still answers is caught
  by heartbeat staleness, a dead one by the dead socket. The probe also
  feeds ``load()`` (remote queue depth + occupied slots) and the
  ``serve/worker_heartbeat_lag_ms`` gauge.
- **engine** is a ``RemoteEngineHandle`` speaking the two-phase swap
  protocol (``stage_checkpoint``/``swap_staged``) — the
  ``CheckpointWatcher`` drives it over the control channel so every
  process flips at a dispatch boundary under one coherent version tag.
- A replica built from a just-spawned ``WorkerHandle``
  (``RemoteReplica.spawning``) connects on a background thread:
  ``starting`` stays True (the router neither places on it nor evicts
  it) until the worker announces and the socket opens.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

from .. import telemetry as _tel
from ..telemetry.watchdog import read_heartbeat
from . import tracing as _tracing
from .batcher import GenerationResult
from .router import Replica, ReplicaUnavailable
from .transport import RpcClient, TransportError

__all__ = ["RemoteReplica", "RemoteEngineHandle"]


class RemoteEngineHandle:
    """``CheckpointWatcher``-facing proxy for one worker's engine: the
    worker loads checkpoints host-side (arrays never cross the socket),
    this handle only carries the control verbs."""

    def __init__(self, client: RpcClient, name: str):
        self._client = client
        self.name = name
        self.weights_version: Optional[str] = None

    def stage_checkpoint(self, path: str) -> None:
        """Phase 1: the worker loads ``path`` and stages it standby."""
        payload = {"path": path}
        ctx = _tracing.context()
        if ctx is not None:
            payload["trace"] = ctx
        self._client.call("stage", payload)

    def swap_staged(self, version: str) -> str:
        """Phase 2: flip the staged buffer live under ``version``."""
        payload = {"version": version}
        ctx = _tracing.context()
        if ctx is not None:
            payload["trace"] = ctx
        out = self._client.call("swap", payload)
        self.weights_version = out.get("version", version)
        return self.weights_version


class _RemoteBatcher:
    """The slice of the batcher surface the ``Router`` touches, mapped
    onto the transport. ``cancel_pending`` fails the LOCAL inner futures
    (a remote queue cannot be reached once the worker is gone — its
    zombie completions are discarded by the router)."""

    def __init__(self, client: RpcClient, name: str,
                 engine: RemoteEngineHandle):
        self._client = client
        self.name = name
        self._engine = engine

    @property
    def healthy(self) -> bool:
        return self._client.dead is None

    def submit(self, prompt_ids, max_new_tokens=None,
               deadline_ms=None, prefix_ids=None,
               request_id=None) -> GenerationResult:
        extra = {}
        if prefix_ids is not None and len(prefix_ids) > 0:
            extra["prefix_ids"] = [int(t) for t in prefix_ids]
        if request_id is not None:
            # trace context rides the submit frame: the worker adopts
            # the id so its spans/phases link back to this request
            extra["trace"] = {"request_id": request_id}
        fut = self._client.submit(prompt_ids, max_new_tokens,
                                  deadline_ms=deadline_ms,
                                  extra=extra or None)
        if request_id is not None:
            fut.request_id = request_id
        return fut

    def cancel_pending(self, error=None) -> int:
        err = error if error is not None else ReplicaUnavailable(
            f"remote replica {self.name} cancelled")
        self._client._shutdown(err)
        return 0

    def stop(self, drain: bool = True, timeout: float = 30.0):
        if drain and self._client.dead is None:
            try:
                self._client.call("drain", timeout_s=timeout)
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        self._client.close()


class RemoteReplica(Replica):
    """One worker process behind the router.

    Parameters
    ----------
    name : replica tag (fault ``match``, telemetry, routing).
    address : ``host:port`` of a READY worker; or None with ``worker``.
    worker : a ``serving.worker.WorkerHandle`` still booting — the
        replica resolves its address and connects on a background
        thread (``starting`` until then).
    heartbeat_path / heartbeat_stale_s : the worker's watchdog
        ``heartbeat.json`` (defaults to the handle's); staleness or a
        ``stalled``/``hard_hang`` status fails health even while the
        socket answers.
    probe_ttl_s : max age of the cached health probe (the router's
        monitor refreshes it every ``health_interval_s`` anyway).
    """

    def __init__(self, name: str, address=None, worker=None,
                 heartbeat_path: Optional[str] = None,
                 heartbeat_stale_s: float = 10.0,
                 rpc_timeout_s: Optional[float] = None,
                 probe_ttl_s: float = 0.05,
                 connect_budget_s: Optional[float] = None,
                 role: str = "both"):
        if address is None and worker is None:
            raise ValueError("RemoteReplica needs address= or worker=")
        self.worker = worker
        if heartbeat_path is None and worker is not None:
            heartbeat_path = worker.heartbeat_path
        self.probe_ttl_s = float(probe_ttl_s)
        self._connect_budget_s = connect_budget_s
        self._rpc_timeout_s = rpc_timeout_s
        self._probe = None      # cached (healthy, reason)
        self._probe_at = 0.0
        self._probe_info: dict = {}
        self._client = RpcClient(address if address is not None
                                 else ("127.0.0.1", 0),
                                 timeout_s=rpc_timeout_s, name=name,
                                 dead_error=ReplicaUnavailable)
        self._engine_handle = RemoteEngineHandle(self._client, name)
        self._starting = True
        self._start_error: Optional[BaseException] = None
        super().__init__(name, _RemoteBatcher(self._client, name,
                                              self._engine_handle),
                         heartbeat_path=heartbeat_path,
                         heartbeat_stale_s=heartbeat_stale_s, role=role)
        if address is not None and worker is None:
            self._connect_now()
        else:
            threading.Thread(target=self._connect_bg,
                             name=f"mxtpu-replica-connect-{name}",
                             daemon=True).start()

    # ---------------------------------------------------------- connection
    def _connect_now(self):
        self._client.connect(budget_s=self._connect_budget_s)
        self._starting = False

    def _connect_bg(self):
        """Resolve a booting worker's address and connect — off the
        router's threads, so a slow spawn never stalls placement or
        resubmission for the healthy replicas."""
        try:
            info = self.worker.wait_ready(
                timeout=self._connect_budget_s or 120.0)
            self._client.address = (info["host"], info["port"])
            self._connect_now()
        except BaseException as e:  # noqa: BLE001 - health() surfaces it
            self._start_error = e
            self._starting = False

    @property
    def starting(self) -> bool:
        """True while the worker is still booting/connecting: unhealthy
        for placement, but the router must not evict it yet."""
        return self._starting

    @property
    def client(self) -> RpcClient:
        return self._client

    # -------------------------------------------------------------- health
    def health(self) -> tuple:
        if self.evicted:
            return False, "evicted"
        if self._starting:
            return False, "starting (worker booting)"
        if self._start_error is not None:
            return False, f"spawn failed: {self._start_error}"
        now = time.monotonic()
        if self._probe is not None and \
                now - self._probe_at < self.probe_ttl_s:
            return self._probe
        result = self._probe_once()
        self._probe = result
        self._probe_at = now
        return result

    def _probe_once(self) -> tuple:
        dead = self._client.dead
        if dead is not None:
            return False, f"transport down: {dead}"
        try:
            info = self._client.call("health",
                                     timeout_s=self._rpc_timeout_s)
        except Exception as e:  # noqa: BLE001 - a failed probe IS the answer
            return False, f"health rpc failed: {e}"
        self._probe_info = info
        self._engine_handle.weights_version = info.get("weights_version")
        if not info.get("healthy", False):
            return False, f"worker reports {info.get('status', '?')}"
        if self.heartbeat_path is not None:
            hb = read_heartbeat(self.heartbeat_path)
            if hb is not None:
                if hb.get("status") in ("stalled", "hard_hang"):
                    return False, f"heartbeat status {hb['status']}"
                age = time.time() - float(hb.get("time", 0.0))
                _tel.registry().gauge(
                    "serve/worker_heartbeat_lag_ms").set(age * 1e3)
                if age > self.heartbeat_stale_s:
                    return False, f"heartbeat stale ({age:.1f}s)"
        return True, "ok"

    def sample_clock(self) -> None:
        """One ping round trip → a ``trace.clock_offset`` instant in
        THIS process's event log (``tracing.note_clock_sample``): the
        worker replies with its event clock, and the send/receive
        bracket bounds the offset to within the RTT —
        ``tools/fleet_trace.py`` keeps the min-RTT sample per peer.
        No-op when tracing is off or the transport is down."""
        if not _tracing.trace_enabled() or self._client.dead is not None:
            return
        try:
            t0 = _tracing.clock_us()
            msg = self._client.call("ping", {},
                                    timeout_s=self._rpc_timeout_s or 5.0)
            t1 = _tracing.clock_us()
        except Exception:  # noqa: BLE001 - sampling is best-effort
            return
        if msg.get("clock_us") is None:
            return  # worker predates the clock_us reply
        _tracing.note_clock_sample(self.name, msg.get("pid"), t0, t1,
                                   msg["clock_us"])

    def load(self) -> int:
        """Router-tracked in-flight plus the worker's last-reported
        backlog (queued + occupied slots, from the health probe)."""
        return self.inflight + int(self._probe_info.get("queue_depth", 0))

    def queue_wait_p50_ms(self) -> Optional[float]:
        """The worker-reported rolling queue-wait p50 (health verb) —
        the SLO placement signal."""
        return self._probe_info.get("queue_wait_p50_ms")

    @property
    def weights_version(self) -> Optional[str]:
        return self._probe_info.get("weights_version")

    def prefix_digests(self) -> tuple:
        """Worker-reported prefix-cache digests (health verb) — the
        prefix-affinity placement signal; empty until the first probe
        answers or when the worker's cache is disabled."""
        return tuple(self._probe_info.get("prefix_digests") or ())

    # ------------------------------------------------ disaggregated serving
    @property
    def role(self) -> str:  # type: ignore[override]
        """Worker-reported role (health verb / ``MXTPU_ROLE``); the
        constructor's role until the first probe answers."""
        return self._probe_info.get("role", self._role)

    @role.setter
    def role(self, value: str):
        self._role = value

    def submit_disagg(self, prefill_rep, prompt_ids, max_new_tokens=None,
                      deadline_ms: Optional[float] = None,
                      klass: str = "interactive",
                      request_id: Optional[str] = None
                      ) -> GenerationResult:
        """Disaggregated submit: ask ``prefill_rep`` (a prefill-role
        replica) to run the admission prefill and push the KV frames to
        THIS worker, then submit here with the handoff id — the decode
        batcher adopts the frames without re-prefilling.

        Returns the future immediately; the prefill RPC + submit run on
        a handoff thread (the router's lock is never held across the
        wire). ANY handoff failure — prefill worker dead, push dropped,
        frames unusable — degrades to a plain submit whose prompt the
        decode worker prefills locally (``disagg/re_prefills``): the
        request is never lost to the handoff."""
        fut = GenerationResult()
        fut.request_id = request_id
        deadline_at = None if deadline_ms is None \
            else time.perf_counter() + float(deadline_ms) / 1e3
        try:
            threading.Thread(
                target=self._disagg_handoff,
                args=(prefill_rep, prompt_ids, max_new_tokens,
                      deadline_at, klass, fut, request_id),
                name=f"mxtpu-disagg-{self.name}", daemon=True).start()
        except Exception as e:  # noqa: BLE001 - no thread, no handoff
            if not fut.done():
                fut._fail(e)
            raise
        return fut

    def _disagg_handoff(self, prefill_rep, prompt_ids, max_new,
                        deadline_at, klass, fut, request_id=None):
        """Handoff thread body: prefill RPC (bounded by the remaining
        deadline), then the wire submit feeding the SAME future the
        router already holds. The prefill wall lands as the request's
        ``handoff_ms`` phase (stamped BEFORE the wire submit, so the
        worker's phase breakdown merges on top, never over it)."""
        handoff = uuid.uuid4().hex
        extra = {"klass": klass}
        if request_id is not None:
            extra["trace"] = {"request_id": request_id}
        budget = None
        if deadline_at is not None:
            budget = max(0.05, deadline_at - time.perf_counter())
        t0 = time.perf_counter()
        th0 = _tracing.clock_us()
        try:
            host, port = self._client.address
            payload = {"prompt": [int(t) for t in prompt_ids],
                       "push_to": f"{host}:{port}", "handoff": handoff}
            if request_id is not None:
                payload["trace"] = {"request_id": request_id}
            prefill_rep.client.call("prefill", payload, timeout_s=budget)
            extra["handoff"] = handoff
            _tracing.span("trace.handoff", th0,
                          {"prefill": prefill_rep.name,
                           "decode": self.name, "handoff": handoff},
                          request_id=request_id)
        except Exception as e:  # noqa: BLE001 - fall back to local prefill
            _tel.registry().counter("disagg/re_prefills").inc()
            _tel.instant("disagg.push_failed",
                         {"handoff": handoff, "replica": self.name,
                          "request_id": request_id, "error": repr(e)})
        fut.phases = {"handoff_ms": (time.perf_counter() - t0) * 1e3}
        remaining_ms = None
        if deadline_at is not None:
            remaining_ms = (deadline_at - time.perf_counter()) * 1e3
            if remaining_ms <= 0 and not fut.done():
                fut._fail(self._dead_error_instance(
                    "deadline passed during the KV handoff"))
                return
        try:
            self._client.submit(prompt_ids, max_new,
                                deadline_ms=remaining_ms, extra=extra,
                                future=fut)
        except BaseException as e:  # noqa: BLE001 - last holder of fut
            # this thread is the only code that will ever touch `fut`
            # again: if the wire submit itself dies (dead socket, frame
            # encode error), failing the future here is the difference
            # between an immediate caller error and a silent hang until
            # the caller's deadline.
            if not fut.done():
                fut._fail(e)

    def _dead_error_instance(self, msg: str):
        from .batcher import DeadlineExceeded

        return DeadlineExceeded(msg)

    # ------------------------------------------------------------- factory
    @classmethod
    def spawning(cls, worker, name: Optional[str] = None, **kwargs):
        """Wrap a just-spawned ``WorkerHandle`` without blocking on its
        boot — the respawn-factory shape."""
        return cls(name or worker.name, worker=worker, **kwargs)
