"""Fault-injection harness: deterministic failure points for the serving
plane.

A resilience feature that is never exercised is a liability: the failover
and hot-swap paths must be drivable through their FAILURE branches in
tier-1, on demand, without flaky sleeps or real crashes. This module
plants named failure points in the serving hot paths; each point is inert
(one dict lookup) until armed, either programmatically (``inject()`` in
tests) or by environment spec (``MXTPU_FAULT_*`` — the chaos-harness
contract, usable against a real serving process).

Failure points wired in this package:

==================== ====================================================
``batcher.dispatch``  raises inside ``DynamicBatcher._dispatch`` — the
                      engine call fails, futures get the error, the
                      dispatcher thread survives.
``batcher.thread``    raises at the top of the dispatcher loop, OUTSIDE
                      the dispatch try — the thread dies, simulating a
                      crashed replica (``healthy`` flips false).
``batcher.hang``      sleeps ``delay`` seconds inside the dispatch — a
                      wedged engine (watchdog heartbeat goes stale).
``watchdog.heartbeat`` suppresses heartbeat writes — a stale heartbeat
                      with the process otherwise alive.
``ckpt.load``         raises inside ``CheckpointWatcher``'s load (and the
                      worker's ``stage`` verb) — a torn / unreadable
                      checkpoint mid-swap.
``transport.send``    fires before a frame write (client or server side
                      of the cross-process RPC): raise-mode drops the
                      connection, delay-mode is a slow link; armed
                      ``times=None`` on send AND recv = a partition.
``transport.recv``    the receive half of the same — fires before a
                      frame read; tags are the client/server name.
``worker.exit``       hard-kills a serving worker process from inside
                      its main loop (``os._exit``) — sudden process
                      death on a deterministic schedule.
``transport.kv_push`` fires in a prefill-role worker's KV-handoff push
                      path (socket or spill): raise-mode drops the
                      handoff (the decode side re-prefills from the
                      prompt, ``disagg/re_prefills``), delay-mode is a
                      slow push; tags are the ``push_to`` address.
``router.place``      fires inside the router's placement decision:
                      raise-mode makes that pass place nothing (the
                      monitor retries), delay-mode is a slow placement;
                      tags are the request class.
==================== ====================================================

Env spec grammar (one var per point, ``.`` becomes ``_``)::

    MXTPU_FAULT_BATCHER_THREAD="times=1;after=2;match=replica-1"
    MXTPU_FAULT_BATCHER_HANG="delay=30"
    MXTPU_FAULT_WATCHDOG_HEARTBEAT="on"

``times`` caps how often the fault fires (default 1; ``on``/``1`` alone
means unlimited), ``after`` skips the first N matching hits, ``delay``
makes the point sleep instead of raise, ``match`` restricts the fault to
call sites whose tag (replica/batcher name, directory) contains the
substring. Hits and firings are counted per spec — deterministic
("the 3rd dispatch fails once") rather than probabilistic.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..base import MXNetError

__all__ = ["FaultInjected", "inject", "clear", "check", "fire",
           "specs"]


class FaultInjected(MXNetError):
    """Raised by an armed raise-mode failure point."""


class _Spec:
    __slots__ = ("point", "times", "after", "delay", "match", "hits",
                 "fired", "source")

    def __init__(self, point, times=1, after=0, delay=0.0, match=None,
                 source="inject"):
        self.point = point
        self.times = times  # None = unlimited
        self.after = int(after)
        self.delay = float(delay)
        self.match = match
        self.hits = 0
        self.fired = 0
        self.source = source

    def matches(self, tag) -> bool:
        if self.match is None:
            return True
        return tag is not None and self.match in str(tag)

    def try_fire(self) -> bool:
        """Count one matching hit; True iff the fault fires on it."""
        self.hits += 1
        if self.hits <= self.after:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        self.fired += 1
        return True

    def describe(self) -> dict:
        return {"point": self.point, "times": self.times,
                "after": self.after, "delay": self.delay,
                "match": self.match, "hits": self.hits,
                "fired": self.fired, "source": self.source}


_LOCK = threading.Lock()
_SPECS: dict = {}  # point -> list[_Spec]
_ENV_SCANNED: set = set()  # points whose MXTPU_FAULT_* var was parsed


def _env_var(point: str) -> str:
    return "MXTPU_FAULT_" + point.upper().replace(".", "_")


def _parse_env_spec(point: str, raw: str) -> Optional[_Spec]:
    raw = raw.strip()
    if raw.lower() in ("", "0", "off", "false"):
        return None
    kw = {"times": None, "after": 0, "delay": 0.0, "match": None}
    if raw.lower() not in ("1", "on", "true"):
        for part in raw.split(";"):
            part = part.strip()
            if not part or part.lower() in ("1", "on", "true"):
                continue
            if "=" not in part:
                raise MXNetError(
                    f"bad fault spec {_env_var(point)}={raw!r}: "
                    f"expected key=value, got {part!r}")
            k, v = part.split("=", 1)
            k, v = k.strip(), v.strip()
            if k in ("times", "after"):
                kw[k] = int(v)
            elif k == "delay":
                kw[k] = float(v)
            elif k == "match":
                kw[k] = v
            else:
                raise MXNetError(
                    f"bad fault spec {_env_var(point)}={raw!r}: "
                    f"unknown key {k!r} (times/after/delay/match)")
    return _Spec(point, source="env", **kw)


def inject(point: str, times: Optional[int] = 1, after: int = 0,
           delay: float = 0.0, match: Optional[str] = None) -> None:
    """Arm ``point`` programmatically (tests / chaos drivers).

    ``times=None`` fires on every matching hit; ``delay`` turns the point
    into a sleep instead of a raise; ``match`` restricts it to tags
    containing the substring."""
    with _LOCK:
        _SPECS.setdefault(point, []).append(
            _Spec(point, times=times, after=after, delay=delay,
                  match=match))


def clear(point: Optional[str] = None) -> None:
    """Disarm one point, or everything (including the env-spec cache, so
    a monkeypatched ``MXTPU_FAULT_*`` is re-read)."""
    with _LOCK:
        if point is None:
            _SPECS.clear()
            _ENV_SCANNED.clear()
        else:
            _SPECS.pop(point, None)
            _ENV_SCANNED.discard(point)


def specs() -> list:
    """Snapshot of every armed spec (hit/fire counters included)."""
    with _LOCK:
        return [s.describe() for ss in _SPECS.values() for s in ss]


def check(point: str, tag=None) -> Optional[dict]:
    """Consume one firing of ``point`` if armed and matching.

    Returns the firing spec's description (``delay`` tells the caller to
    stall instead of fail) or None. Used directly by suppress-style call
    sites (the watchdog skips a heartbeat write when this returns
    non-None); raise/sleep sites go through :func:`fire`."""
    with _LOCK:
        if point not in _ENV_SCANNED:
            _ENV_SCANNED.add(point)
            raw = os.environ.get(_env_var(point))
            if raw is not None:
                spec = _parse_env_spec(point, raw)
                if spec is not None:
                    _SPECS.setdefault(point, []).append(spec)
        for spec in _SPECS.get(point, ()):
            if spec.matches(tag) and spec.try_fire():
                fired = spec.describe()
                break
        else:
            return None
    # counter outside the lock: telemetry must not serialize hot paths
    try:
        from .. import telemetry as _tel
        from . import tracing as _tracing  # lazy: no import cycle

        _tel.registry().counter("serve/faults_injected").inc()
        _tel.instant("serve.fault",
                     {"point": point, "tag": tag, "spec": fired,
                      "request_id": _tracing.current_request_id()})
    except Exception:  # noqa: BLE001 - accounting must not mask the fault
        pass
    return fired


def fire(point: str, tag=None) -> None:
    """Trip ``point`` if armed: sleep ``delay`` seconds when the spec is
    delay-mode, else raise :class:`FaultInjected`. No-op when unarmed —
    this is the one-liner planted in hot paths."""
    spec = check(point, tag)
    if spec is None:
        return
    if spec["delay"] > 0:
        time.sleep(spec["delay"])
        return
    raise FaultInjected(
        f"injected fault at {point!r}"
        + (f" (tag={tag})" if tag is not None else ""))
