"""Multi-replica serving router: health-scored placement, watchdog-driven
failover, bounded transparent retries.

One ``submit()`` front-end over N engine+batcher replicas. The router
owns the request lifecycle end to end:

- **Placement (SLO-aware)**: each request goes to the healthy
  decode-serving replica with the lowest PREDICTED WAIT — the replica's
  rolling queue-wait p50 (worker-reported over the health verb, or the
  local batcher's window) times its backlog + 1 — rather than the
  instantaneous backlog count alone; replicas with no wait signal yet
  degenerate to backlog ordering. Ties break round-robin via a rotating
  cursor, so equal-score replicas share load instead of the first one
  absorbing everything.
- **Request classes**: ``submit(..., klass="interactive"|"batch")``
  tags each request; a request without an explicit ``deadline_ms``
  picks up its class default (``MXTPU_SLO_INTERACTIVE_MS`` /
  ``MXTPU_SLO_BATCH_MS``), and under a degraded fleet BATCH traffic
  sheds at HALF the ``MXTPU_SHED_MAX_QUEUE`` backlog bound — batch
  sheds before interactive by construction.
- **Disaggregation**: when the fleet contains prefill-role replicas
  (``serving.disagg.worker_role``), placement picks a decode replica
  AND a prefill replica: the prefill worker runs the admission prefill
  and ships the KV frames to the decode worker (``kv_push`` /
  ``MXTPU_KV_SPILL_DIR`` spill), whose batcher adopts them without
  re-prefilling. Any handoff failure degrades to the decode worker
  re-prefilling from the prompt (``disagg/re_prefills``) — requests
  are never lost to a handoff.
- **Health**: a replica is healthy while (a) its batcher's dispatcher
  thread is alive (``DynamicBatcher.healthy``), (b) its watchdog
  heartbeat — the PR-1 ``heartbeat.json``, written atomically — is fresh
  and not flagged ``stalled``/``hard_hang``, and (c) it has not been
  evicted. The health loop re-scores every ``health_interval_s``.
- **Failover**: an unhealthy replica is evicted — its queued-but-
  undispatched requests are cancelled out of its batcher and every
  router request assigned to it is transparently resubmitted to a
  healthy replica, with bounded retries (``MXTPU_RETRY_MAX``),
  exponential backoff with jitter, and per-request deadlines
  (``DeadlineExceeded`` rather than a late dispatch).
- **Replacement**: with a ``replica_factory``, evictions trigger
  respawn attempts under the same capped exponential backoff
  (``MXTPU_RESTART_BACKOFF_S``) that ``tools/launch.py`` uses for
  whole-job elastic restarts. A factory-returned replica may report
  ``starting`` (a worker process booting): it is skipped for placement
  but not evicted until it either comes up or fails.
- **Load shedding**: when EVERY replica is degraded — unhealthy,
  backlogged past ``MXTPU_SHED_QUEUE_DEPTH``, or the router's rolling
  completed-request queue-wait p50 past ``MXTPU_SHED_WAIT_MS`` — new
  submits are shed at admission with ``Backpressure`` instead of
  queueing behind work that cannot finish in time: a request whose
  deadline is infeasible under the current p50 wait is shed
  immediately (``serve/shed_deadline``), and once the router backlog
  reaches ``MXTPU_SHED_MAX_QUEUE`` everything is
  (``serve/shed_queue_full``) — queue growth is bounded by
  construction, rather than by deadlines expiring inside the queue.

Telemetry (``serve/`` family): ``requests``/``completed`` counters,
``failovers`` (evictions), ``retries`` (resubmissions), ``dropped``
(failed after retries exhausted), ``deadline_exceeded``,
``shed_deadline``/``shed_queue_full`` (admission sheds),
``replica_restarts``, ``replicas_healthy`` +
``shed_degraded_replicas`` gauges.
"""

from __future__ import annotations

import collections
import os
import random
import threading
import time
from typing import Callable, Optional, Sequence

from ..base import MXNetError
from .. import telemetry as _tel
from ..telemetry.watchdog import read_heartbeat
from . import faults as _faults
from . import prefix as _prefix
from . import tracing as _tracing
from .batcher import Backpressure, DeadlineExceeded, DynamicBatcher, \
    GenerationResult, _evus

__all__ = ["Router", "Replica", "ReplicaUnavailable", "retry_max",
           "restart_backoff_s", "shed_queue_depth", "shed_wait_ms",
           "shed_max_queue", "slo_interactive_ms", "slo_batch_ms",
           "REQUEST_CLASSES"]

REQUEST_CLASSES = ("interactive", "batch")


class ReplicaUnavailable(MXNetError):
    """The replica holding a request was evicted before dispatching it —
    a retriable condition (the router resubmits elsewhere)."""


def retry_max(default: int = 2) -> int:
    """``MXTPU_RETRY_MAX``: resubmissions per request after its first
    placement (0 = fail on the first replica error)."""
    v = os.environ.get("MXTPU_RETRY_MAX", "").strip()
    try:
        return int(v) if v else default
    except ValueError:
        return default


def restart_backoff_s(default: float = 1.0) -> float:
    """``MXTPU_RESTART_BACKOFF_S``: base of the capped exponential
    backoff between restart attempts — shared contract with
    ``tools/launch.py``'s elastic relaunch."""
    v = os.environ.get("MXTPU_RESTART_BACKOFF_S", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


def shed_queue_depth(default: int = 16) -> int:
    """``MXTPU_SHED_QUEUE_DEPTH``: a replica whose load (router-assigned
    in-flight + its own backlog) reaches this counts as DEGRADED for the
    all-replicas-degraded shedding gate."""
    v = os.environ.get("MXTPU_SHED_QUEUE_DEPTH", "").strip()
    try:
        return int(v) if v else default
    except ValueError:
        return default


def shed_wait_ms(default: float = 0.0) -> float:
    """``MXTPU_SHED_WAIT_MS``: rolling completed-request queue-wait p50
    beyond which the fleet counts as degraded (0/unset disables the
    wait-based gate; queue depth and health still apply)."""
    v = os.environ.get("MXTPU_SHED_WAIT_MS", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


def shed_max_queue(default: int = 128) -> int:
    """``MXTPU_SHED_MAX_QUEUE``: hard bound on the router's in-flight
    backlog while all replicas are degraded — admission beyond it sheds
    with ``Backpressure`` (bounded queue growth by construction)."""
    v = os.environ.get("MXTPU_SHED_MAX_QUEUE", "").strip()
    try:
        return int(v) if v else default
    except ValueError:
        return default


def disagg_min_prompt(default: int = 16) -> int:
    """``MXTPU_DISAGG_MIN_PROMPT``: prompts SHORTER than this prefill in
    place on the decode worker even when prefill-role replicas exist —
    a short prompt's prefill costs less than the handoff's extra hop,
    and keeping long-prompt prefills (and only those) off the decode
    workers is the whole point of the split. 0/1 = hand off
    everything."""
    v = os.environ.get("MXTPU_DISAGG_MIN_PROMPT", "").strip()
    try:
        return max(int(v), 1) if v else default
    except ValueError:
        return default


def slo_interactive_ms(default: float = 0.0) -> float:
    """``MXTPU_SLO_INTERACTIVE_MS``: default deadline for
    ``klass="interactive"`` requests submitted without an explicit
    ``deadline_ms`` (0/unset = no class default; the router-wide
    ``deadline_ms`` still applies)."""
    v = os.environ.get("MXTPU_SLO_INTERACTIVE_MS", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


def slo_batch_ms(default: float = 0.0) -> float:
    """``MXTPU_SLO_BATCH_MS``: default deadline for ``klass="batch"``
    requests submitted without an explicit ``deadline_ms`` (0/unset =
    no class default)."""
    v = os.environ.get("MXTPU_SLO_BATCH_MS", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


def backoff_delay(base: float, attempt: int, cap: float = 30.0,
                  jitter: float = 0.25) -> float:
    """Capped exponential backoff with multiplicative jitter: attempt 0
    waits ~base, each further attempt doubles, never exceeding ``cap``
    (pre-jitter). Jitter decorrelates replicas/restarts that failed at
    the same instant."""
    d = min(float(base) * (2.0 ** max(int(attempt), 0)), float(cap))
    return d * (1.0 + float(jitter) * random.random())


class Replica:
    """One engine+batcher unit behind the router.

    ``heartbeat_path`` points at a watchdog ``heartbeat.json`` (wire the
    same ``Watchdog`` into the batcher via ``DynamicBatcher(...,
    watchdog=...)`` so dispatches feed it). No path = liveness from the
    dispatcher thread alone."""

    def __init__(self, name: str, batcher: DynamicBatcher,
                 heartbeat_path: Optional[str] = None,
                 heartbeat_stale_s: float = 10.0, role: str = "both"):
        self.name = str(name)
        self.batcher = batcher
        if batcher.name is None:
            batcher.name = self.name
        self.heartbeat_path = heartbeat_path
        self.heartbeat_stale_s = float(heartbeat_stale_s)
        self.evicted = False
        # disaggregated fleet role (serving.disagg.worker_role):
        # "prefill" replicas never receive decode placements; they serve
        # as KV-handoff sources and still join the coordinated hot swap
        self.role = str(role)
        # deliberate scale-down (Router.retire_replica): excluded from
        # placement, its eventual eviction schedules NO respawn
        self.retired = False
        self.inflight = 0  # router-assigned, guarded by the router lock

    @property
    def engine(self):
        return self.batcher._engine

    def health(self) -> tuple:
        """(healthy, reason). Never raises — a health check that crashes
        is itself an outage."""
        if self.evicted:
            return False, "evicted"
        if not self.batcher.healthy:
            return False, "dispatcher thread down"
        if self.heartbeat_path is not None:
            hb = read_heartbeat(self.heartbeat_path)
            if hb is not None:
                if hb.get("status") in ("stalled", "hard_hang"):
                    return False, f"heartbeat status {hb['status']}"
                age = time.time() - float(hb.get("time", 0.0))
                if age > self.heartbeat_stale_s:
                    return False, f"heartbeat stale ({age:.1f}s)"
            # missing/torn file = unknown, not unhealthy: the watchdog
            # may simply not have written yet
        return True, "ok"

    @property
    def healthy(self) -> bool:
        return self.health()[0]

    @property
    def starting(self) -> bool:
        """True while the replica is still coming up (a spawning worker
        process): unhealthy for placement, exempt from eviction. In-
        process replicas are ready at construction."""
        return False

    @property
    def serves_decode(self) -> bool:
        """Whether decode placements may land here (everything but a
        dedicated prefill worker)."""
        return self.role != "prefill"

    @property
    def serves_prefill(self) -> bool:
        """Whether this replica is a KV-handoff source — only DEDICATED
        prefill workers; a ``both`` replica co-schedules instead."""
        return self.role == "prefill"

    def load(self) -> int:
        """Backlog: requests the router has in flight here plus the
        batcher's queued backlog (infer/ telemetry's queue_wait is this
        backlog measured in time)."""
        return self.inflight + self.batcher._queue.qsize()

    def queue_wait_p50_ms(self) -> Optional[float]:
        """Rolling queue-wait p50 this replica reports (the local
        batcher's window; remote replicas report it over the health
        verb). None until enough samples exist."""
        fn = getattr(self.batcher, "rolling_wait_ms", None)
        return fn() if fn is not None else None

    def predicted_wait_ms(self) -> float:
        """SLO placement score: rolling queue-wait p50 × (backlog + 1).
        With no wait signal yet the p50 factor is 1 ms, so scoring
        degenerates to backlog ordering on a fresh fleet."""
        p50 = self.queue_wait_p50_ms()
        return (p50 if p50 else 1.0) * (self.load() + 1)

    def prefix_digests(self) -> tuple:
        """Compact digest of the prompts this replica's prefix cache
        holds (``serving.prefix.prompt_digest`` per trie root) — what
        prefix-affinity placement matches against. Empty when the local
        batcher has no cache (remote replicas report theirs over the
        health verb)."""
        fn = getattr(self.batcher, "prefix_digests", None)
        if fn is None:
            return ()
        try:
            return tuple(fn(_prefix.prefix_digest_max()))
        except Exception:  # noqa: BLE001 - affinity is advisory only
            return ()


class _Routed:
    """Router-side record of one request across (re)submissions."""

    __slots__ = ("prompt", "max_new", "deadline", "outer", "replica",
                 "inner", "attempts", "next_try_at", "created", "klass",
                 "prefix", "digest", "request_id", "assigned_at")

    def __init__(self, prompt, max_new, deadline, outer,
                 klass="interactive", prefix=None, digest=None,
                 request_id=None):
        self.prompt = prompt
        self.max_new = max_new
        self.deadline = deadline  # absolute perf_counter instant or None
        self.outer = outer
        self.replica = None
        self.inner = None
        self.attempts = 0  # placements so far
        self.next_try_at = 0.0
        self.created = time.perf_counter()
        self.klass = klass  # SLO class: "interactive" | "batch"
        self.prefix = prefix  # forced history for prefix-cache replay
        self.digest = digest  # prompt digest for affinity placement
        self.request_id = request_id  # fleet-wide trace id
        self.assigned_at = None  # perf_counter of the LAST placement


class Router:
    """Self-healing serving front-end over N replicas.

    Parameters
    ----------
    replicas : sequence of ``Replica``.
    max_retries : resubmissions per request after its first placement
        (``MXTPU_RETRY_MAX`` default).
    retry_backoff_s : base backoff between a request's placements.
    deadline_ms : default per-request deadline (None = unbounded).
    health_interval_s : replica re-scoring period.
    replica_factory : zero-arg callable returning a fresh ``Replica``;
        evictions schedule respawns under capped exponential backoff.
    no_replica_timeout_s : how long a request may wait for ANY healthy
        replica (e.g. during respawn) before failing.
    """

    def __init__(self, replicas: Sequence[Replica],
                 max_retries: Optional[int] = None,
                 retry_backoff_s: float = 0.05,
                 deadline_ms: Optional[float] = None,
                 health_interval_s: float = 0.05,
                 replica_factory: Optional[Callable[[], Replica]] = None,
                 respawn_backoff_s: Optional[float] = None,
                 no_replica_timeout_s: float = 5.0,
                 shed_queue_depth: Optional[int] = None,
                 shed_wait_ms: Optional[float] = None,
                 shed_max_queue: Optional[int] = None,
                 disagg_min_prompt: Optional[int] = None,
                 telemetry_scrape_s: Optional[float] = None,
                 start: bool = True):
        from . import router as _self  # module fns shadowed by kwargs

        self._replicas = list(replicas)
        if not self._replicas:
            raise MXNetError("Router needs at least one replica")
        self.max_retries = max_retries if max_retries is not None \
            else retry_max()
        self.retry_backoff_s = float(retry_backoff_s)
        self.default_deadline_ms = deadline_ms
        self.health_interval_s = float(health_interval_s)
        self._factory = replica_factory
        self._respawn_base = respawn_backoff_s if respawn_backoff_s \
            is not None else restart_backoff_s()
        self.no_replica_timeout_s = float(no_replica_timeout_s)
        self.shed_queue_depth = shed_queue_depth \
            if shed_queue_depth is not None else _self.shed_queue_depth()
        self.shed_wait_ms = shed_wait_ms \
            if shed_wait_ms is not None else _self.shed_wait_ms()
        self.shed_max_queue = shed_max_queue \
            if shed_max_queue is not None else _self.shed_max_queue()
        self.disagg_min_prompt = disagg_min_prompt \
            if disagg_min_prompt is not None \
            else _self.disagg_min_prompt()
        self._recent_waits = collections.deque(maxlen=64)
        self._lock = threading.Lock()
        self._rr = 0  # rotating tie-break cursor, guarded by the lock
        self._inflight: list = []
        self._respawn_at = None  # next respawn attempt instant
        self._respawn_attempt = 0
        # fleet observability: periodic clock probes per remote replica
        # (tools/fleet_trace.py alignment) and the telemetry scrape
        # plane (MXTPU_SCRAPE_S / telemetry_scrape_s)
        self._clock_sample_at: dict = {}
        scrape_s = telemetry_scrape_s if telemetry_scrape_s is not None \
            else _tracing.scrape_interval_s()
        self._fleet_telemetry = None
        if scrape_s > 0:
            self._fleet_telemetry = _tracing.FleetTelemetry(
                self._replica_snapshot, interval_s=scrape_s)
        self._stop = threading.Event()
        self._thread = None
        if start:
            self.start()

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mxtpu-router", daemon=True)
        self._thread.start()
        if self._fleet_telemetry is not None:
            self._fleet_telemetry.start()

    def stop(self, stop_replicas: bool = True, timeout: float = 30.0):
        if self._fleet_telemetry is not None:
            self._fleet_telemetry.stop()
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=timeout)
        with self._lock:
            pending = list(self._inflight)
            self._inflight.clear()
        for r in pending:
            if not r.outer.done():
                r.outer._fail(RuntimeError("router stopped"))
        if stop_replicas:
            for rep in self._replica_snapshot():
                try:
                    rep.batcher.stop(drain=False, timeout=1.0)
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    @property
    def replicas(self) -> list:
        return self._replica_snapshot()

    @property
    def engines(self) -> list:
        """Live engines (for ``CheckpointWatcher`` wiring: one watcher
        hot-swaps every replica)."""
        return [rep.engine for rep in self._replica_snapshot()
                if not rep.evicted]

    @property
    def fleet_telemetry(self):
        """The scrape/aggregation plane (``tracing.FleetTelemetry``),
        or None when ``MXTPU_SCRAPE_S``/``telemetry_scrape_s`` left it
        disabled."""
        return self._fleet_telemetry

    # ------------------------------------------------------------- requests
    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               klass: str = "interactive",
               prefix_ids=None) -> GenerationResult:
        """Route one prompt to a healthy replica. The returned future
        resolves even across replica failures (transparent resubmission)
        — it fails only on retry exhaustion, deadline expiry, or total
        replica loss.

        ``klass`` is the SLO class (``interactive`` default, or
        ``batch``): without an explicit ``deadline_ms`` the class
        default (``MXTPU_SLO_INTERACTIVE_MS``/``MXTPU_SLO_BATCH_MS``)
        applies, per-class TTFT is recorded
        (``disagg/ttft_interactive_ms``/``disagg/ttft_batch_ms``), and
        under a degraded fleet batch traffic sheds first.

        ``prefix_ids`` is the already-generated conversation history to
        teacher-force before decoding (multi-turn). Placement then
        PREFERS replicas advertising this prompt's digest in their
        prefix cache (``MXTPU_PREFIX_AFFINITY``) so the cached KV is
        actually reused, falling back to predicted-wait placement when
        no replica holds it; prefix requests always route to the decode
        replica directly (the forced history makes a KV handoff moot)."""
        if klass not in REQUEST_CLASSES:
            raise MXNetError(
                f"unknown request class {klass!r} "
                f"(one of {REQUEST_CLASSES})")
        outer = GenerationResult()
        # minted unconditionally (a uuid4 slice): SLO attribution and
        # shed/failover/deadline event tagging must work even when span
        # emission (MXTPU_TRACE) is off
        outer.request_id = rid = _tracing.new_request_id()
        dl_ms = deadline_ms
        if dl_ms is None:
            slo = slo_batch_ms() if klass == "batch" \
                else slo_interactive_ms()
            dl_ms = slo if slo > 0 else self.default_deadline_ms
        deadline = None if dl_ms is None \
            else time.perf_counter() + float(dl_ms) / 1e3
        prefix = digest = None
        if prefix_ids is not None and len(prefix_ids) > 0:
            prefix = [int(t) for t in prefix_ids]
            digest = _prefix.prompt_digest(prompt_ids)
        r = _Routed(prompt_ids, max_new_tokens, deadline, outer,
                    klass=klass, prefix=prefix, digest=digest,
                    request_id=rid)
        _tel.registry().counter("serve/requests").inc()
        try:
            with self._lock:
                shed = self._shed_reason_locked(r)
                if shed is not None:
                    kind, parts = shed
                elif not self._assign_locked(r) \
                        and not self._may_recover_locked():
                    outer._fail(RuntimeError(
                        "no healthy replicas and no replica_factory — "
                        "request cannot be placed"))
                    return outer
                else:
                    self._inflight.append(r)
                    return outer
        except Exception as e:  # noqa: BLE001 - r may already be placed
            # _assign_locked can raise AFTER handing r to a replica: the
            # replica's relay thread then holds `outer` and would feed a
            # future whose submit-side caller never saw — fail it so
            # every holder observes the same error instead of a hang.
            if not outer.done():
                outer._fail(e)
            raise
        msg = "; ".join(parts)  # formatted OUTSIDE the router lock
        reg = _tel.registry()
        reg.counter(f"serve/shed_{kind}").inc()
        _tel.instant("serve.shed", {"kind": kind, "reason": msg,
                                    "request_id": rid, "klass": klass})
        outer._fail(Backpressure(f"router shed the request: {msg}"))
        return outer

    # ------------------------------------------------------------- shedding
    def _degraded_locked(self) -> Optional[list]:
        """Per-replica degradation reasons when EVERY replica is
        degraded — not healthy, or backlogged past ``shed_queue_depth``
        — plus the fleet-wide rolling-wait gate; None while any replica
        is in good shape (admission stays open). Returns reason PARTS
        (callers format outside the lock)."""
        reasons = []
        for rep in self._replicas:
            if rep.evicted or rep.retired or not rep.serves_decode:
                # prefill-only replicas cannot absorb decode work and a
                # retiring replica is on its way out: neither keeps
                # admission open
                continue
            if rep.starting or not rep.healthy:
                reasons.append(f"{rep.name}: unhealthy")
            elif rep.load() >= self.shed_queue_depth:
                reasons.append(f"{rep.name}: backlog {rep.load()} >= "
                               f"{self.shed_queue_depth}")
            else:
                return None  # a replica in good shape: no shedding
        if reasons:
            return reasons
        if self.shed_wait_ms > 0:
            waits = sorted(self._recent_waits)
            if len(waits) >= 8:
                p50 = waits[len(waits) // 2]
                if p50 > self.shed_wait_ms:
                    return [f"queue wait p50 {p50:.0f} ms > "
                            f"{self.shed_wait_ms:.0f} ms"]
        return None

    def _shed_reason_locked(self, r: _Routed) -> Optional[tuple]:
        """Admission-time shed decision for one request; None admits.
        Runs under the router lock (submit holds it); returns
        ``(kind, message parts)`` — no string assembly here."""
        degraded = self._degraded_locked()
        if degraded is None:
            return None
        backlog = len(self._inflight)
        # batch traffic sheds FIRST: under a degraded fleet its backlog
        # bound is half the interactive one, so the queue that remains
        # is spent on the latency-sensitive class
        limit = self.shed_max_queue if r.klass != "batch" \
            else max(1, self.shed_max_queue // 2)
        if backlog >= limit:
            return ("queue_full", [
                f"router backlog hit {backlog} >= {limit} "
                f"({r.klass} bound, MXTPU_SHED_MAX_QUEUE="
                f"{self.shed_max_queue}) with all replicas degraded"]
                + degraded)
        if r.deadline is not None:
            budget_ms = (r.deadline - time.perf_counter()) * 1e3
            waits = sorted(self._recent_waits)
            p50 = waits[len(waits) // 2] if len(waits) >= 8 else 0.0
            if budget_ms <= 0 or p50 > budget_ms:
                return ("deadline", [
                    f"deadline budget {budget_ms:.0f} ms is infeasible "
                    f"at queue-wait p50 {p50:.0f} ms with all replicas "
                    "degraded"] + degraded)
        return None

    def _may_recover_locked(self) -> bool:
        """Whether waiting could produce a healthy replica: a respawn
        factory exists, or some replica is merely degraded (not
        evicted) and may come back fresh. Runs under the router lock
        (submit holds it)."""
        return self._factory is not None or any(
            not rep.evicted for rep in self._replicas)

    def _replica_snapshot(self) -> list:
        """Coherent copy of the replica list for lock-free iteration:
        ``_respawn`` appends from the monitor thread while callers read
        ``replicas``/``engines`` — iterating the live list unlocked is
        the torn-read shape the mxlint lock-order pass flags."""
        with self._lock:
            return list(self._replicas)

    def _pick_locked(self, candidates: list):
        """Lowest predicted wait (rolling p50 × backlog) wins; exact
        ties rotate through a cursor so equal-score replicas share load
        instead of the first in replica order absorbing everything."""
        scored = [(rep.predicted_wait_ms(), rep) for rep in candidates]
        best = min(s for s, _ in scored)
        ties = [rep for s, rep in scored if s == best]
        rep = ties[self._rr % len(ties)]
        self._rr += 1
        return rep

    def _pick_prefill_locked(self):
        """A healthy dedicated prefill-role replica for the KV handoff,
        or None (the decode replica then prefills locally)."""
        pre = [rep for rep in self._replicas
               if not rep.evicted and not rep.retired
               and rep.serves_prefill and rep.healthy]
        return self._pick_locked(pre) if pre else None

    def _assign_locked(self, r: _Routed) -> bool:
        """Place ``r`` on the decode-serving healthy replica with the
        lowest predicted wait; False when none is available (the monitor
        retries until ``no_replica_timeout_s``). With prefill-role
        replicas in the fleet the placement is DISAGGREGATED: the
        chosen prefill worker computes and ships the KV, the decode
        replica adopts it (``RemoteReplica.submit_disagg``). A request
        carrying a prompt digest (multi-turn ``prefix_ids``) first
        narrows the candidates to replicas ADVERTISING that digest —
        prefix affinity — and only falls back to the whole fleet when
        no replica holds the cached prefix."""
        now = time.perf_counter()
        candidates = [rep for rep in self._replicas
                      if rep.healthy and rep.serves_decode
                      and not rep.retired]
        if not candidates:
            r.inner = None
            r.next_try_at = now + self.health_interval_s
            return False
        try:
            # fault point: a placement decision that fails/stalls (raise
            # = this pass places nothing and the monitor retries; delay
            # = a slow placement)
            _faults.fire("router.place", tag=r.klass)
        except _faults.FaultInjected:
            r.inner = None
            r.next_try_at = now + self.health_interval_s
            return False
        pool = candidates
        if r.digest is not None and _prefix.prefix_affinity_enabled():
            affine = [rep for rep in candidates
                      if r.digest in rep.prefix_digests()]
            if affine:
                pool = affine
                _tel.registry().counter("serve/prefix_affinity").inc()
        rep = self._pick_locked(pool)
        remaining_ms = None
        if r.deadline is not None:
            remaining_ms = (r.deadline - time.perf_counter()) * 1e3
            if remaining_ms <= 0:
                return True  # monitor fails it on the next tick
        r.replica = rep
        r.attempts += 1
        r.assigned_at = now
        rep.inflight += 1
        # hand off only prefill-HEAVY prompts: a short prompt's local
        # prefill is cheaper than the handoff's extra RPC hop, and the
        # split's whole point is keeping the long prefills off the
        # decode workers
        pre = None
        if r.prefix is None and hasattr(rep, "submit_disagg") \
                and len(r.prompt) >= self.disagg_min_prompt:
            pre = self._pick_prefill_locked()
        if pre is not None:
            r.inner = rep.submit_disagg(pre, r.prompt, r.max_new,
                                        deadline_ms=remaining_ms,
                                        klass=r.klass,
                                        request_id=r.request_id)
        elif r.prefix is not None:
            r.inner = rep.batcher.submit(r.prompt, r.max_new,
                                         deadline_ms=remaining_ms,
                                         prefix_ids=r.prefix,
                                         request_id=r.request_id)
        else:
            r.inner = rep.batcher.submit(r.prompt, r.max_new,
                                         deadline_ms=remaining_ms,
                                         request_id=r.request_id)
        return True

    # ----------------------------------------------------------- elasticity
    def add_replica(self, rep: Replica) -> Replica:
        """Register a replica mid-flight (fleet elasticity scale-up —
        ``tools.launch.FleetScaler`` spawns a worker, wraps it in a
        ``RemoteReplica`` and hands it here)."""
        with self._lock:
            self._replicas.append(rep)
        _tel.instant("serve.scale", {"action": "add", "replica": rep.name})
        return rep

    def retire_replica(self, rep: Replica) -> Replica:
        """Deliberate scale-down: exclude ``rep`` from placement and
        from the shed gate, let its in-flight work finish on the worker
        (the caller SIGTERMs it — the existing graceful drain), and when
        its health finally fails the eviction schedules NO respawn."""
        rep.retired = True
        _tel.instant("serve.scale", {"action": "retire",
                                     "replica": rep.name})
        return rep

    # -------------------------------------------------------------- monitor
    def _run(self):
        last_health = 0.0
        while not self._stop.wait(0.005):
            now = time.perf_counter()
            if now - last_health >= self.health_interval_s:
                last_health = now
                self._health_pass(now)
            self._request_pass(now)

    def _health_pass(self, now):
        reps = self._replica_snapshot()
        for rep in reps:
            if rep.evicted:
                continue
            ok, reason = rep.health()
            if not ok and not rep.starting:
                # a replica still booting (factory respawn: a worker
                # process importing + warming) is skipped for placement
                # but not evicted — its spawn failure is what evicts it
                self._evict(rep, reason)
        healthy = sum(1 for rep in reps if rep.healthy)
        degraded = sum(1 for rep in reps if not rep.evicted
                       and (rep.starting or not rep.healthy
                            or rep.load() >= self.shed_queue_depth))
        reg = _tel.registry()
        reg.gauge("serve/replicas_healthy").set(healthy)
        reg.gauge("serve/shed_degraded_replicas").set(degraded)
        if _tracing.trace_enabled():
            # throttled clock sampling piggybacks on the health cadence:
            # one ping RTT per remote replica per second keeps the
            # cross-process offset estimate fresh for trace merging
            for rep in reps:
                if rep.evicted or not hasattr(rep, "sample_clock"):
                    continue
                if now >= self._clock_sample_at.get(rep.name, 0.0):
                    self._clock_sample_at[rep.name] = now + 1.0
                    rep.sample_clock()
        if self._factory is not None and self._respawn_at is not None \
                and now >= self._respawn_at:
            self._respawn()

    def _evict(self, rep: Replica, reason: str):
        """Drain an unhealthy replica and mark every routed request on it
        for resubmission."""
        rep.evicted = True
        reg = _tel.registry()
        reg.counter("serve/failovers").inc()
        # cancel what sits undispatched in its queue: the inner futures
        # fail with ReplicaUnavailable and the request pass resubmits
        try:
            rep.batcher.cancel_pending(ReplicaUnavailable(
                f"replica {rep.name} evicted: {reason}"))
        except Exception:  # noqa: BLE001 - the queue may be torn mid-crash
            pass
        # a hung (not dead) dispatcher also holds requests it already
        # popped; their inner futures will never resolve — fail them over
        # too. A zombie completion later is ignored (outer settles once).
        affected = []
        with self._lock:
            for r in self._inflight:
                if r.replica is rep and r.inner is not None \
                        and not r.inner.done():
                    r.inner = None
                    r.replica = None
                    r.next_try_at = 0.0
                    if r.request_id is not None:
                        affected.append(r.request_id)
        # emitted outside the lock: the event write is I/O
        _tel.instant("serve.failover", {"replica": rep.name,
                                        "reason": reason,
                                        "requests": affected[:8],
                                        "n_requests": len(affected)})
        # stop the batcher without waiting on a possibly-hung thread
        try:
            rep.batcher.stop(drain=False, timeout=0.1)
        except Exception:  # noqa: BLE001
            pass
        # a deliberately retired replica (scale-down) leaves for good —
        # respawning it would defeat the scaler
        if self._factory is not None and self._respawn_at is None \
                and not rep.retired:
            self._respawn_at = time.perf_counter() + backoff_delay(
                self._respawn_base, self._respawn_attempt)

    def _respawn(self):
        try:
            rep = self._factory()
        except Exception as e:  # noqa: BLE001 - retry under backoff
            self._respawn_attempt += 1
            self._respawn_at = time.perf_counter() + backoff_delay(
                self._respawn_base, self._respawn_attempt)
            _tel.instant("serve.respawn_failed", {"error": repr(e)})
            return
        with self._lock:
            self._replicas.append(rep)
        self._respawn_attempt = 0
        self._respawn_at = None
        _tel.registry().counter("serve/replica_restarts").inc()
        _tel.instant("serve.replica_restart", {"replica": rep.name})

    def _request_pass(self, now):
        reg = _tel.registry()
        with self._lock:
            records = list(self._inflight)
        done = []
        for r in records:
            if r.outer.done():
                done.append(r)
                continue
            if r.inner is None:
                # waiting for a retry slot / a healthy replica
                if r.deadline is not None and now > r.deadline:
                    reg.counter("serve/deadline_exceeded").inc()
                    _tel.instant("serve.deadline",
                                 {"request_id": r.request_id,
                                  "replica": None, "klass": r.klass,
                                  "where": "unplaced"})
                    r.outer._fail(DeadlineExceeded(
                        "request deadline passed before it could be "
                        "(re)placed on a healthy replica"))
                    done.append(r)
                elif now - r.created > self.no_replica_timeout_s \
                        and not any(rep.healthy
                                    for rep in self._replica_snapshot()):
                    reg.counter("serve/dropped").inc()
                    r.outer._fail(RuntimeError(
                        f"no healthy replica within "
                        f"{self.no_replica_timeout_s:.1f}s"))
                    done.append(r)
                elif now >= r.next_try_at:
                    with self._lock:
                        self._assign_locked(r)
                continue
            if r.inner.done():
                wait = r.inner.queue_wait_ms
                with self._lock:
                    if r.replica is not None:
                        r.replica.inflight = max(0, r.replica.inflight - 1)
                    if r.inner.exception() is None and wait is not None:
                        # feeds the shed gate's rolling p50
                        self._recent_waits.append(wait)
                err = r.inner.exception()
                if err is None:
                    r.outer.weights_version = r.inner.weights_version
                    r.outer.replica = r.inner.replica
                    r.outer.queue_wait_ms = r.inner.queue_wait_ms
                    ft = getattr(r.inner, "first_token_at", None)
                    if ft is not None:
                        # per-class TTFT, measured from the router's
                        # admission instant (the SLO the classes exist
                        # for)
                        ttft = (ft - r.created) * 1e3
                        if r.klass == "batch":
                            reg.histogram(
                                "disagg/ttft_batch_ms").observe(ttft)
                        else:
                            reg.histogram(
                                "disagg/ttft_interactive_ms").observe(
                                    ttft)
                    # SLO attribution: the per-phase breakdown stamped
                    # by the worker (queue/prefill/decode), extended
                    # with router-side phases. ``other_ms`` is the
                    # residual and is deliberately UNCLAMPED so the
                    # ``*_ms`` phases sum to the observed end-to-end
                    # latency exactly, by construction.
                    tdone = time.perf_counter()
                    phases = dict(getattr(r.inner, "phases", None) or {})
                    if r.attempts > 1 and r.assigned_at is not None:
                        phases["retry_ms"] = \
                            (r.assigned_at - r.created) * 1e3
                    e2e_ms = (tdone - r.created) * 1e3
                    named = sum(v for k, v in phases.items()
                                if k.endswith("_ms")
                                and isinstance(v, (int, float)))
                    phases["other_ms"] = e2e_ms - named
                    r.outer.phases = phases
                    slo = slo_batch_ms() if r.klass == "batch" \
                        else slo_interactive_ms()
                    if slo > 0 and e2e_ms > slo:
                        reg.counter(
                            f"serve/slo_burn_{r.klass}").inc()
                    if _tracing.trace_enabled():
                        _tracing.span(
                            "trace.request", _evus(r.created),
                            {"replica": r.inner.replica,
                             "klass": r.klass,
                             "attempts": r.attempts,
                             "e2e_ms": e2e_ms},
                            request_id=r.request_id,
                            end_us=_evus(tdone))
                    r.outer._resolve(r.inner.result())
                    reg.counter("serve/completed").inc()
                    done.append(r)
                elif isinstance(err, DeadlineExceeded):
                    _tel.instant("serve.deadline",
                                 {"request_id": r.request_id,
                                  "replica": getattr(
                                      r.replica, "name", None),
                                  "klass": r.klass,
                                  "where": "batcher"})
                    r.outer._fail(err)  # counted at the batcher
                    done.append(r)
                else:
                    self._note_failure(r, err, now)
                    if r.outer.done():
                        done.append(r)
            elif r.deadline is not None and now > r.deadline:
                # dispatched but not resolving (e.g. hung engine): the
                # deadline settles the OUTER future; a zombie inner
                # completion is discarded
                reg.counter("serve/deadline_exceeded").inc()
                _tel.instant("serve.deadline",
                             {"request_id": r.request_id,
                              "replica": getattr(
                                  r.replica, "name", None),
                              "klass": r.klass,
                              "where": "dispatched"})
                r.outer._fail(DeadlineExceeded(
                    "request deadline passed while dispatched"))
                done.append(r)
        if done:
            with self._lock:
                self._inflight = [r for r in self._inflight
                                  if r not in done]

    def _note_failure(self, r: _Routed, err, now):
        """Inner attempt failed: resubmit under bounded backoff, or fail
        the outer future for good."""
        reg = _tel.registry()
        out_of_time = r.deadline is not None and now > r.deadline
        if r.attempts > self.max_retries and not isinstance(
                err, ReplicaUnavailable) or out_of_time:
            reg.counter("serve/dropped").inc()
            r.outer._fail(err if not out_of_time else DeadlineExceeded(
                f"deadline passed after {r.attempts} attempts "
                f"(last error: {err!r})"))
            return
        reg.counter("serve/retries").inc()
        rep_name = getattr(r.replica, "name", None)
        r.inner = None
        r.replica = None
        r.next_try_at = now + backoff_delay(
            self.retry_backoff_s, r.attempts - 1, cap=5.0)
        _tracing.instant("trace.retry",
                         {"replica": rep_name,
                          "attempt": r.attempts,
                          "error": type(err).__name__},
                         request_id=r.request_id)
