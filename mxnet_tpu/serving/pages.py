"""Host-side accounting for the paged KV cache: free list, page tables,
admission watermarks.

The device side (``gluon.nn.attention`` pools + the jitted
``InferStep.prefill_paged``/``decode_iter`` programs) only ever sees
fixed-shape arrays: ``(num_pages, page_size, H, D)`` pools and a
``(slots, pages_per_slot)`` int32 page table. THIS module owns what those
arrays mean — which pages are free, which slot owns which pages, and
whether admitting another request would starve the ones already decoding:

- **Page 0 is the trash page**, never allocated: inactive slots and
  finished rows scatter their writes there, and every unallocated table
  entry points at it, so the device programs need no masking branches and
  a stale table entry can never alias a live request's pages.
- ``alloc``/``release`` are LIFO over the free list — a retired request's
  pages are handed to the next admission, keeping the working set hot.
- ``ensure(slot, upto)`` grows a slot's allocation on demand, one page at
  a time, as its decode length crosses page boundaries — the whole point
  of paging: a request that stops at 3 tokens holds 1 page, not
  ``ceil(max_len / page_size)``.
- ``fragmentation(lengths)`` is INTERNAL fragmentation: the fraction of
  allocated page capacity not yet holding tokens (the only waste mode
  left once dense per-request slabs are gone).

Env knobs (read by ``ContinuousBatcher`` at construction):
``MXTPU_PAGE_SIZE`` (tokens per page, default 16), ``MXTPU_PAGES`` (pool
pages; default = full provisioning ``slots * pages_per_slot + 1`` so
backpressure/preemption only engage when the operator deliberately
undersizes the pool), ``MXTPU_ADMIT_FREE_PAGES`` (admission watermark:
keep at least this many pages free AFTER admitting, default 0),
``MXTPU_ADMIT_MAX_QUEUE`` (queue-depth rejection threshold, default
1024), ``MXTPU_ADMIT_MAX_WAIT_MS`` (reject when the rolling queue-wait
p50 breaches this, default off).
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["PagePool", "page_size_default", "num_pages_default",
           "admit_free_pages", "admit_max_queue", "admit_max_wait_ms"]

TRASH_PAGE = 0


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "").strip()
    try:
        return int(v) if v else default
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


def page_size_default(default: int = 16) -> int:
    """``MXTPU_PAGE_SIZE``: tokens per KV page."""
    return max(_env_int("MXTPU_PAGE_SIZE", default), 1)


def num_pages_default(slots: int, pages_per_slot: int) -> int:
    """``MXTPU_PAGES``: pool size in pages (excluding the trash page).
    Default fully provisions every slot — paging then saves nothing but
    costs nothing; undersize it (e.g. ``slots * pages_per_slot // 2``) to
    actually oversubscribe memory and let admission control earn its
    keep."""
    return max(_env_int("MXTPU_PAGES", slots * pages_per_slot), 1)


def admit_free_pages(default: int = 0) -> int:
    """``MXTPU_ADMIT_FREE_PAGES``: admission keeps at least this many
    pages free for the requests already decoding (free-page watermark)."""
    return max(_env_int("MXTPU_ADMIT_FREE_PAGES", default), 0)


def admit_max_queue(default: int = 1024) -> int:
    """``MXTPU_ADMIT_MAX_QUEUE``: submits beyond this queue depth are
    rejected with ``Backpressure``."""
    return max(_env_int("MXTPU_ADMIT_MAX_QUEUE", default), 1)


def admit_max_wait_ms(default: float = 0.0) -> float:
    """``MXTPU_ADMIT_MAX_WAIT_MS``: reject new submits while the rolling
    queue-wait p50 exceeds this (0 = disabled)."""
    return max(_env_float("MXTPU_ADMIT_MAX_WAIT_MS", default), 0.0)


class PagePool:
    """Free-list + page-table bookkeeping for one paged decode batch.

    Parameters
    ----------
    num_pages : allocatable pages (page 0, the trash page, is extra — the
        device pools are ``num_pages + 1`` rows).
    page_size : tokens per page.
    slots : decode-batch rows.
    pages_per_slot : page-table width P; a slot's logical capacity is
        ``P * page_size`` tokens.
    """

    def __init__(self, num_pages: int, page_size: int, slots: int,
                 pages_per_slot: int):
        if num_pages < 1:
            raise MXNetError("PagePool needs at least one allocatable page")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.slots = int(slots)
        self.pages_per_slot = int(pages_per_slot)
        # ids 1..num_pages; LIFO so freshly freed pages are reused first
        self._free: List[int] = list(range(self.num_pages, 0, -1))
        self._owned: List[List[int]] = [[] for _ in range(self.slots)]
        self.table = np.full((self.slots, self.pages_per_slot), TRASH_PAGE,
                             np.int32)

    # ------------------------------------------------------------- queries
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    def owned(self, slot: int) -> tuple:
        return tuple(self._owned[slot])

    def capacity(self, slot: int) -> int:
        """Tokens slot ``slot`` can hold with its current pages."""
        return len(self._owned[slot]) * self.page_size

    def fragmentation(self, lengths) -> float:
        """Internal fragmentation: allocated-but-empty token capacity as a
        fraction of allocated capacity (0.0 when nothing is allocated).
        ``lengths[slot]`` = tokens cached per slot (0 for empty slots)."""
        cap = self.pages_in_use * self.page_size
        if cap <= 0:
            return 0.0
        used = int(sum(int(x) for x in lengths))
        return max(0.0, 1.0 - used / cap)

    # ----------------------------------------------------------- lifecycle
    def alloc(self, slot: int, n: int = 1) -> bool:
        """Give ``slot`` ``n`` more pages; False (state unchanged) when
        the free list or the slot's table row can't cover it."""
        owned = self._owned[slot]
        if len(self._free) < n or len(owned) + n > self.pages_per_slot:
            return False
        for _ in range(n):
            p = self._free.pop()
            self.table[slot, len(owned)] = p
            owned.append(p)
        return True

    def ensure(self, slot: int, upto: int) -> bool:
        """Grow ``slot``'s allocation to hold ``upto`` tokens; False when
        the pool can't (the scheduler then preempts or backpressures)."""
        need = -(-int(upto) // self.page_size)  # ceil
        have = len(self._owned[slot])
        if need <= have:
            return True
        return self.alloc(slot, need - have)

    def release(self, slot: int) -> int:
        """Return every page ``slot`` owns to the free list and point its
        table row back at the trash page. Returns how many were freed."""
        owned = self._owned[slot]
        n = len(owned)
        while owned:
            self._free.append(owned.pop())
        self.table[slot, :] = TRASH_PAGE
        return n

    def reset(self):
        for s in range(self.slots):
            self.release(s)

    def check_invariants(self, live_slots=None):
        """Exactness audit (tests + debugging, not the hot path): free
        list + owned pages partition [1, num_pages] with no page owned by
        two slots, and the table mirrors ownership."""
        seen = {}
        for s, owned in enumerate(self._owned):
            for j, p in enumerate(owned):
                if p in seen:
                    raise MXNetError(
                        f"page {p} aliased by slots {seen[p]} and {s}")
                if p == TRASH_PAGE:
                    raise MXNetError(f"slot {s} owns the trash page")
                if int(self.table[s, j]) != p:
                    raise MXNetError(
                        f"table[{s},{j}]={self.table[s, j]} != owned {p}")
                seen[p] = s
        free = set(self._free)
        if len(free) != len(self._free):
            raise MXNetError("free list holds duplicate pages")
        universe = set(range(1, self.num_pages + 1))
        if free | set(seen) != universe or free & set(seen):
            raise MXNetError(
                f"free ({len(free)}) + owned ({len(seen)}) pages do not "
                f"partition the pool of {self.num_pages}")
        if live_slots is not None:
            for s in range(self.slots):
                if s not in live_slots and self._owned[s]:
                    raise MXNetError(f"retired slot {s} still owns pages")


def pages_for(tokens: int, page_size: int) -> int:
    """Pages needed for ``tokens`` cache entries."""
    return -(-int(tokens) // int(page_size))


__all__.append("pages_for")
__all__.append("TRASH_PAGE")
