"""Host-side accounting for the paged KV cache: free list, page tables,
admission watermarks.

The device side (``gluon.nn.attention`` pools + the jitted
``InferStep.prefill_paged``/``decode_iter`` programs) only ever sees
fixed-shape arrays: ``(num_pages, page_size, H, D)`` pools and a
``(slots, pages_per_slot)`` int32 page table. THIS module owns what those
arrays mean — which pages are free, which slot owns which pages, and
whether admitting another request would starve the ones already decoding:

- **Page 0 is the trash page**, never allocated: inactive slots and
  finished rows scatter their writes there, and every unallocated table
  entry points at it, so the device programs need no masking branches and
  a stale table entry can never alias a live request's pages.
- ``alloc``/``release`` are LIFO over the free list — a retired request's
  pages are handed to the next admission, keeping the working set hot.
- **Pages are reference-counted** (prefix caching): ``alloc`` starts a
  page at refcount 1, ``adopt_ref`` lets another slot map an existing
  page read-only (ref+1), ``cache_acquire``/``cache_release`` are the
  prefix trie's ref, and a page returns to the free list only when its
  refcount hits 0. A slot may WRITE a page only while it is the sole
  reference (ref == 1) — shared pages are append-only history that
  every reader replays identically, and divergence goes through a
  copy-on-write page instead (``ContinuousBatcher`` owns that protocol).
- ``ensure(slot, upto)`` grows a slot's allocation on demand, one page at
  a time, as its decode length crosses page boundaries — the whole point
  of paging: a request that stops at 3 tokens holds 1 page, not
  ``ceil(max_len / page_size)``.
- ``fragmentation(lengths)`` is INTERNAL fragmentation: the fraction of
  allocated page capacity not yet holding tokens (the only waste mode
  left once dense per-request slabs are gone).

Env knobs (read by ``ContinuousBatcher`` at construction):
``MXTPU_PAGE_SIZE`` (tokens per page, default 16), ``MXTPU_PAGES`` (pool
pages; default = full provisioning ``slots * pages_per_slot + 1`` so
backpressure/preemption only engage when the operator deliberately
undersizes the pool), ``MXTPU_ADMIT_FREE_PAGES`` (admission watermark:
keep at least this many pages free AFTER admitting, default 0),
``MXTPU_ADMIT_MAX_QUEUE`` (queue-depth rejection threshold, default
1024), ``MXTPU_ADMIT_MAX_WAIT_MS`` (reject when the rolling queue-wait
p50 breaches this, default off).
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["PagePool", "page_size_default", "num_pages_default",
           "admit_free_pages", "admit_max_queue", "admit_max_wait_ms"]

TRASH_PAGE = 0


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "").strip()
    try:
        return int(v) if v else default
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


def page_size_default(default: int = 16) -> int:
    """``MXTPU_PAGE_SIZE``: tokens per KV page."""
    return max(_env_int("MXTPU_PAGE_SIZE", default), 1)


def num_pages_default(slots: int, pages_per_slot: int) -> int:
    """``MXTPU_PAGES``: pool size in pages (excluding the trash page).
    Default fully provisions every slot — paging then saves nothing but
    costs nothing; undersize it (e.g. ``slots * pages_per_slot // 2``) to
    actually oversubscribe memory and let admission control earn its
    keep."""
    return max(_env_int("MXTPU_PAGES", slots * pages_per_slot), 1)


def admit_free_pages(default: int = 0) -> int:
    """``MXTPU_ADMIT_FREE_PAGES``: admission keeps at least this many
    pages free for the requests already decoding (free-page watermark)."""
    return max(_env_int("MXTPU_ADMIT_FREE_PAGES", default), 0)


def admit_max_queue(default: int = 1024) -> int:
    """``MXTPU_ADMIT_MAX_QUEUE``: submits beyond this queue depth are
    rejected with ``Backpressure``."""
    return max(_env_int("MXTPU_ADMIT_MAX_QUEUE", default), 1)


def admit_max_wait_ms(default: float = 0.0) -> float:
    """``MXTPU_ADMIT_MAX_WAIT_MS``: reject new submits while the rolling
    queue-wait p50 exceeds this (0 = disabled)."""
    return max(_env_float("MXTPU_ADMIT_MAX_WAIT_MS", default), 0.0)


class PagePool:
    """Free-list + page-table bookkeeping for one paged decode batch.

    Parameters
    ----------
    num_pages : allocatable pages (page 0, the trash page, is extra — the
        device pools are ``num_pages + 1`` rows).
    page_size : tokens per page.
    slots : decode-batch rows.
    pages_per_slot : page-table width P; a slot's logical capacity is
        ``P * page_size`` tokens.
    """

    def __init__(self, num_pages: int, page_size: int, slots: int,
                 pages_per_slot: int):
        if num_pages < 1:
            raise MXNetError("PagePool needs at least one allocatable page")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.slots = int(slots)
        self.pages_per_slot = int(pages_per_slot)
        # ids 1..num_pages; LIFO so freshly freed pages are reused first
        self._free: List[int] = list(range(self.num_pages, 0, -1))
        self._owned: List[List[int]] = [[] for _ in range(self.slots)]
        self.table = np.full((self.slots, self.pages_per_slot), TRASH_PAGE,
                             np.int32)
        # ref[p] = (#slots mapping p) + (1 if the prefix cache holds p);
        # a page is free iff ref == 0 — check_invariants proves exactness
        self._ref = np.zeros(self.num_pages + 1, np.int64)
        self._cached: set = set()

    # ------------------------------------------------------------- queries
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    def owned(self, slot: int) -> tuple:
        return tuple(self._owned[slot])

    def ref(self, page: int) -> int:
        """Current reference count of ``page`` (0 = free)."""
        return int(self._ref[page])

    def shared(self, page: int) -> bool:
        """True when more than one reference maps ``page`` — writes must
        go through copy-on-write."""
        return int(self._ref[page]) > 1

    @property
    def shared_pages(self) -> int:
        """How many pages currently carry more than one reference (the
        ``infer/pages_shared`` gauge)."""
        return int((self._ref[1:] > 1).sum())

    def cached_pages(self) -> frozenset:
        """Pages currently referenced by the prefix cache."""
        return frozenset(self._cached)

    def capacity(self, slot: int) -> int:
        """Tokens slot ``slot`` can hold with its current pages."""
        return len(self._owned[slot]) * self.page_size

    def fragmentation(self, lengths) -> float:
        """Internal fragmentation: allocated-but-empty token capacity as a
        fraction of allocated capacity (0.0 when nothing is allocated).
        ``lengths[slot]`` = tokens cached per slot (0 for empty slots)."""
        cap = self.pages_in_use * self.page_size
        if cap <= 0:
            return 0.0
        used = int(sum(int(x) for x in lengths))
        return max(0.0, 1.0 - used / cap)

    # ----------------------------------------------------------- lifecycle
    def alloc(self, slot: int, n: int = 1) -> bool:
        """Give ``slot`` ``n`` more fresh pages (refcount 1 each); False
        (state unchanged) when the free list or the slot's table row
        can't cover it."""
        owned = self._owned[slot]
        if len(self._free) < n or len(owned) + n > self.pages_per_slot:
            return False
        for _ in range(n):
            p = self._free.pop()
            self.table[slot, len(owned)] = p
            owned.append(p)
            self._ref[p] = 1
        return True

    def adopt_ref(self, slot: int, pages) -> bool:
        """Map already-live ``pages`` (in order) into ``slot``'s table
        read-only, bumping each refcount. False (state unchanged) when
        the slot's table row can't hold them; adopting a dead page or a
        page the slot already maps is a caller bug and raises."""
        pages = [int(p) for p in pages]
        owned = self._owned[slot]
        if len(owned) + len(pages) > self.pages_per_slot:
            return False
        for p in pages:
            if p == TRASH_PAGE or not 1 <= p <= self.num_pages:
                raise MXNetError(f"adopt_ref of invalid page {p}")
            if int(self._ref[p]) < 1:
                raise MXNetError(f"adopt_ref of free page {p}")
        if set(pages) & set(owned) or len(set(pages)) != len(pages):
            raise MXNetError(
                f"slot {slot} adopting a page it already maps: {pages}")
        for p in pages:
            self.table[slot, len(owned)] = p
            owned.append(p)
            self._ref[p] += 1
        return True

    def cache_acquire(self, pages):
        """The prefix cache takes one reference on each of ``pages``
        (they must be live — typically still mapped by the inserting
        slot). Double-acquire is a trie bug and raises."""
        for p in pages:
            p = int(p)
            if p == TRASH_PAGE or int(self._ref[p]) < 1:
                raise MXNetError(f"cache_acquire of free page {p}")
            if p in self._cached:
                raise MXNetError(f"cache_acquire of cached page {p}")
            self._cached.add(p)
            self._ref[p] += 1

    def cache_release(self, pages) -> int:
        """Drop the cache's reference on each of ``pages``; pages that
        hit refcount 0 return to the free list. Returns how many were
        actually freed."""
        freed = 0
        for p in pages:
            p = int(p)
            if p not in self._cached:
                raise MXNetError(f"cache_release of uncached page {p}")
            self._cached.discard(p)
            self._ref[p] -= 1
            if int(self._ref[p]) == 0:
                self._free.append(p)
                freed += 1
        return freed

    def ensure(self, slot: int, upto: int) -> bool:
        """Grow ``slot``'s allocation to hold ``upto`` tokens; False when
        the pool can't (the scheduler then preempts or backpressures)."""
        need = -(-int(upto) // self.page_size)  # ceil
        have = len(self._owned[slot])
        if need <= have:
            return True
        return self.alloc(slot, need - have)

    def release(self, slot: int) -> int:
        """Drop ``slot``'s reference on every page it maps and point its
        table row back at the trash page; pages that hit refcount 0
        return to the free list. Returns how many were actually freed."""
        owned = self._owned[slot]
        freed = 0
        while owned:
            p = owned.pop()
            self._ref[p] -= 1
            if int(self._ref[p]) == 0:
                self._free.append(p)
                freed += 1
        self.table[slot, :] = TRASH_PAGE
        return freed

    def reset(self):
        """Hard reinit: every slot and cache reference is dropped (the
        poison/rebuild path — callers also flush their prefix trie)."""
        self._free = list(range(self.num_pages, 0, -1))
        self._owned = [[] for _ in range(self.slots)]
        self.table[:, :] = TRASH_PAGE
        self._ref[:] = 0
        self._cached.clear()

    def check_invariants(self, live_slots=None, cache_pages=None):
        """Exactness audit (tests + debugging, not the hot path): every
        page's refcount equals its slot mappings plus its cache
        membership, the free list is exactly the refcount-0 pages, and
        the table mirrors ownership. ``cache_pages`` (the prefix trie's
        own page set) cross-checks the pool's cache-reference ledger."""
        owners = {}
        for s, owned in enumerate(self._owned):
            if len(set(owned)) != len(owned):
                raise MXNetError(f"slot {s} maps a page twice: {owned}")
            for j, p in enumerate(owned):
                if p == TRASH_PAGE:
                    raise MXNetError(f"slot {s} owns the trash page")
                if int(self.table[s, j]) != p:
                    raise MXNetError(
                        f"table[{s},{j}]={self.table[s, j]} != owned {p}")
                owners.setdefault(p, []).append(s)
        free = set(self._free)
        if len(free) != len(self._free):
            raise MXNetError("free list holds duplicate pages")
        for p in range(1, self.num_pages + 1):
            want = len(owners.get(p, ())) + (1 if p in self._cached else 0)
            if int(self._ref[p]) != want:
                raise MXNetError(
                    f"page {p} refcount {int(self._ref[p])} != "
                    f"{len(owners.get(p, ()))} slot owner(s) + "
                    f"{int(p in self._cached)} cache ref")
            if (p in free) != (want == 0):
                raise MXNetError(
                    f"page {p} ref {want} but free-list membership "
                    f"{p in free}")
        referenced = set(owners) | self._cached
        universe = set(range(1, self.num_pages + 1))
        if free | referenced != universe:
            raise MXNetError(
                f"free ({len(free)}) + referenced ({len(referenced)}) "
                f"pages do not cover the pool of {self.num_pages}")
        if cache_pages is not None and set(cache_pages) != self._cached:
            raise MXNetError(
                f"prefix-trie pages {sorted(set(cache_pages))} != pool "
                f"cache ledger {sorted(self._cached)}")
        if live_slots is not None:
            for s in range(self.slots):
                if s not in live_slots and self._owned[s]:
                    raise MXNetError(f"retired slot {s} still owns pages")


def pages_for(tokens: int, page_size: int) -> int:
    """Pages needed for ``tokens`` cache entries."""
    return -(-int(tokens) // int(page_size))


__all__.append("pages_for")
__all__.append("TRASH_PAGE")
