"""Dynamic batcher: concurrent generation requests -> fixed-shape batches.

Serving traffic arrives one prompt at a time; the TPU wants full batches
of warmed shapes. ``DynamicBatcher`` bridges them:

- **Admission**: ``submit()`` enqueues a request and returns a
  ``GenerationResult`` future. A background dispatcher collects up to
  ``slots`` requests, waiting at most ``timeout_ms`` after the first
  arrival — the classic timeout-or-full policy (latency bound under
  trickle load, full batches under pressure).
- **Fixed (batch, bucket) slots**: every dispatch pads prompts to the
  smallest bucket-menu boundary that fits the batch and pads the batch
  itself to exactly ``slots`` rows (empty rows carry ``valid_length=0``,
  fully masked out of attention) — the engine only ever sees
  ``len(bucket_keys)`` decode signatures, all warmed by
  ``InferStep.warmup``, so steady-state serving never compiles.
- **Per-request detach**: each request resolves independently — its
  tokens are trimmed at ITS EOS (and its own ``max_new_tokens``) the
  moment the batch's decode returns, and the slot is free for the next
  dispatch; a long request never holds another request's result hostage.

Telemetry (``infer/`` family): ``queue_wait_ms`` per request,
``batch_occupancy`` per dispatch, ``prefill_ms``/``decode_ms_per_token``
/``tokens_per_sec`` per dispatch, ``requests``/``tokens`` counters.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Optional, Sequence

import numpy as _np

from ..base import MXNetError
from .. import telemetry as _tel

__all__ = ["DynamicBatcher", "GenerationResult", "batcher_slots",
           "batcher_timeout_ms"]


def batcher_slots(default: int = 8) -> int:
    """``MXTPU_BATCHER_SLOTS``: batch rows per dispatch."""
    v = os.environ.get("MXTPU_BATCHER_SLOTS", "").strip()
    try:
        return int(v) if v else default
    except ValueError:
        return default


def batcher_timeout_ms(default: float = 10.0) -> float:
    """``MXTPU_BATCHER_TIMEOUT_MS``: admission window after the first
    request of a batch arrives."""
    v = os.environ.get("MXTPU_BATCHER_TIMEOUT_MS", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


class GenerationResult:
    """Future for one submitted request. ``result(timeout)`` blocks until
    the request's decode finished and returns the generated token list
    (trimmed at EOS); ``exception()`` surfaces a dispatch failure."""

    __slots__ = ("_event", "_tokens", "_error", "enqueued_at",
                 "queue_wait_ms")

    def __init__(self):
        self._event = threading.Event()
        self._tokens = None
        self._error = None
        self.enqueued_at = time.perf_counter()
        self.queue_wait_ms = None

    def _resolve(self, tokens):
        self._tokens = tokens
        self._event.set()

    def _fail(self, err):
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def exception(self):
        return self._error

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("generation result not ready")
        if self._error is not None:
            raise self._error
        return self._tokens


class _Request:
    __slots__ = ("prompt", "max_new", "future")

    def __init__(self, prompt, max_new, future):
        self.prompt = prompt
        self.max_new = max_new
        self.future = future


class DynamicBatcher:
    """Admit concurrent generation requests into fixed (batch, bucket)
    engine dispatches.

    Parameters
    ----------
    engine : ``parallel.infer.InferStep`` over a decode-capable net.
    bucket_keys : ascending prompt-length menu (the warmup contract —
        ``engine.warmup([(slots, k) for k in bucket_keys], max_new)``
        compiles every shape this batcher can emit).
    slots : batch rows per dispatch (``MXTPU_BATCHER_SLOTS``).
    timeout_ms : admission window (``MXTPU_BATCHER_TIMEOUT_MS``).
    max_new_tokens : decode length of every dispatch (per-request
        ``max_new_tokens`` may only be <= this; results are trimmed).
    sampling : dict of ``decode_n`` sampling kwargs (method/top_k/
        temperature/seed) shared by the batch.
    warmup : drive the engine's prefill+decode programs for the whole
        menu at construction (recommended for serving).
    """

    def __init__(self, engine, bucket_keys: Sequence[int],
                 slots: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 max_new_tokens: int = 32, sampling: Optional[dict] = None,
                 pad_id: Optional[int] = None, warmup: bool = False,
                 start: bool = True):
        if not getattr(engine, "supports_decode", False):
            raise MXNetError(
                "DynamicBatcher needs a decode-capable InferStep "
                "(net with prefill/decode_step)")
        self._engine = engine
        self.bucket_keys = sorted(int(k) for k in bucket_keys)
        if not self.bucket_keys:
            raise MXNetError("bucket_keys must be non-empty")
        self.slots = int(slots) if slots is not None else batcher_slots()
        self.timeout_s = (timeout_ms if timeout_ms is not None
                          else batcher_timeout_ms()) / 1e3
        self.max_new = int(max_new_tokens)
        self._sampling = dict(sampling or {})
        self._pad = int(pad_id) if pad_id is not None else engine._pad
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._stop = threading.Event()
        self._thread = None
        if warmup:
            engine.warmup([(self.slots, k) for k in self.bucket_keys],
                          max_new_tokens=self.max_new, **self._sampling)
        if start:
            self.start()

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mxtpu-batcher", daemon=True)
        self._thread.start()

    def stop(self, drain: bool = True, timeout: float = 30.0):
        """Stop the dispatcher; with ``drain`` (default) outstanding
        requests are dispatched first."""
        if drain:
            deadline = time.perf_counter() + timeout
            while not self._queue.empty() and \
                    time.perf_counter() < deadline:
                time.sleep(0.005)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------- requests
    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None
               ) -> GenerationResult:
        """Enqueue one prompt (1-D int sequence). Returns a future whose
        ``result()`` is the generated token list, trimmed at EOS and at
        the request's ``max_new_tokens`` (<= the batcher's)."""
        prompt = _np.asarray(prompt_ids, dtype=_np.int32).reshape(-1)
        if prompt.shape[0] > self.bucket_keys[-1]:
            raise MXNetError(
                f"prompt length {prompt.shape[0]} exceeds the largest "
                f"bucket key {self.bucket_keys[-1]}")
        max_new = self.max_new if max_new_tokens is None \
            else int(max_new_tokens)
        if max_new > self.max_new:
            raise MXNetError(
                f"request max_new_tokens {max_new} > batcher "
                f"max_new_tokens {self.max_new}")
        fut = GenerationResult()
        self._queue.put(_Request(prompt, max_new, fut))
        return fut

    # ------------------------------------------------------------ dispatcher
    def _run(self):
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            reqs = [first]
            deadline = time.perf_counter() + self.timeout_s
            while len(reqs) < self.slots:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    reqs.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            t0 = time.perf_counter()
            try:
                out = self._dispatch(reqs)
            except Exception as e:  # noqa: BLE001 - fail the futures, not the thread
                for r in reqs:
                    r.future._fail(e)
                continue
            self._resolve(reqs, out, t0)

    def _bucket_for(self, max_len):
        for k in self.bucket_keys:
            if max_len <= k:
                return k
        raise MXNetError(
            f"prompt length {max_len} > largest bucket key "
            f"{self.bucket_keys[-1]}")

    def _dispatch(self, reqs):
        """Assemble one fixed (slots, bucket) batch and fire the engine.
        Pure staging + dispatch — linted sync-free
        (``tools/check_no_sync_in_step.py``): the host reads happen in
        ``_resolve`` after the device work is in flight."""
        bucket = self._bucket_for(max(r.prompt.shape[0] for r in reqs))
        src = _np.full((self.slots, bucket), self._pad, _np.int32)
        vl = _np.zeros((self.slots,), _np.int32)
        for i, r in enumerate(reqs):
            n = r.prompt.shape[0]
            src[i, :n] = r.prompt
            vl[i] = n
        return self._engine.decode_n(
            src, vl, max_new_tokens=self.max_new, **self._sampling)

    def _resolve(self, reqs, out, t0):
        """Per-request detach: trim each row at its EOS / its own
        ``max_new_tokens`` and resolve its future. The host read here is
        the sync point of the whole pipeline."""
        tokens_nd, lengths_nd = out
        tokens = tokens_nd.asnumpy()
        lengths = lengths_nd.asnumpy()
        dispatch_ms = (time.perf_counter() - t0) * 1e3
        now = time.perf_counter()
        reg = _tel.registry()
        emitted = 0
        for i, r in enumerate(reqs):
            n = min(int(lengths[i]), r.max_new)
            r.future.queue_wait_ms = (now - r.future.enqueued_at) * 1e3 \
                - dispatch_ms
            reg.histogram("infer/queue_wait_ms").observe(
                max(r.future.queue_wait_ms, 0.0))
            emitted += n
            r.future._resolve(tokens[i, :n].tolist())
        reg.counter("infer/requests").inc(len(reqs))
        reg.counter("infer/tokens").inc(emitted)
        reg.gauge("infer/batch_occupancy").set(len(reqs) / self.slots)
        reg.histogram("infer/prefill_ms").observe(dispatch_ms)
        if emitted:
            reg.histogram("infer/decode_ms_per_token").observe(
                dispatch_ms / emitted)
            reg.gauge("infer/tokens_per_sec").set(
                emitted / (dispatch_ms / 1e3))
