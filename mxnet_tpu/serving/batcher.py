"""Serving batchers: concurrent generation requests -> fixed-shape
engine dispatches.

Two schedulers share one admission/lifecycle spine (``_BatcherBase``):

- ``ContinuousBatcher`` (default, ``MXTPU_BATCHER=continuous``) —
  Orca-style ITERATION-LEVEL scheduling (Yu et al., OSDI 2022) over a
  PAGED KV cache (Kwon et al., SOSP 2023). The decode batch is a static
  menu of ``slots``; each iteration dispatches one jitted
  ``InferStep.decode_iter`` burst, then — between dispatches — retires
  rows that hit EOS / their ``max_new_tokens`` / their deadline, frees
  their pages back to the pool, and admits queued requests into the
  vacated slots through a jitted prefill-into-pages dispatch. Slot count,
  page-table shape and pool shape never change, so occupancy is dynamic
  while the program menu stays exactly two entries per prompt bucket.
  Tokens stream per iteration (``GenerationResult.tokens_iter``), and
  admission control rejects with ``Backpressure`` when the queue or the
  free-page watermark says the pool can't absorb more work.
- ``DynamicBatcher`` (``MXTPU_BATCHER=fixed``) — the PR-5 fallback:
  timeout-or-full admission into whole-batch ``decode_n`` dispatches; a
  finished row idles its slot until the batch drains. Kept as the strict
  per-dispatch-coherent path (one weight version per request) and the
  baseline the open-loop bench measures against.

Telemetry (``infer/`` family): ``queue_wait_ms``/``ttft_ms`` per request,
``batch_occupancy``/``pages_in_use``/``page_fragmentation``/
``admitted_per_iter`` per iteration, ``prefill_ms``/
``decode_ms_per_token``/``tokens_per_sec`` per dispatch,
``requests``/``tokens``/``rejected_backpressure``/``preempted`` counters.
"""

from __future__ import annotations

import collections
import os
import queue
import threading
import time
from typing import Optional, Sequence

import numpy as _np

from ..base import MXNetError
from .. import telemetry as _tel
from . import faults as _faults
from . import pages as _pages
from . import prefix as _prefix
from . import tracing as _tracing


def _evus(t_pc: float) -> float:
    """Event-clock µs for a ``perf_counter`` instant (telemetry events
    share the ``perf_counter`` timebase, so the two clocks differ only
    by the process's event-log origin)."""
    return _tracing.clock_us() - (time.perf_counter() - t_pc) * 1e6

__all__ = ["DynamicBatcher", "ContinuousBatcher", "GenerationResult",
           "DeadlineExceeded", "Backpressure", "batcher_slots",
           "batcher_timeout_ms", "batcher_kind", "iter_tokens_default",
           "spec_k_default", "spec_draft_enabled", "make_batcher"]


class DeadlineExceeded(MXNetError):
    """A request's deadline passed while it was still queued (or before
    the router could place it) — it is FAILED, never dispatched late."""


class Backpressure(MXNetError):
    """Admission control rejected the request at submit: the queue or the
    free-page watermark breached its threshold (``MXTPU_ADMIT_*``).
    Retriable — the router resubmits to a less-loaded replica."""


def batcher_slots(default: int = 8) -> int:
    """``MXTPU_BATCHER_SLOTS``: batch rows per dispatch."""
    v = os.environ.get("MXTPU_BATCHER_SLOTS", "").strip()
    try:
        return int(v) if v else default
    except ValueError:
        return default


def batcher_timeout_ms(default: float = 10.0) -> float:
    """``MXTPU_BATCHER_TIMEOUT_MS``: admission window after the first
    request of a batch arrives."""
    v = os.environ.get("MXTPU_BATCHER_TIMEOUT_MS", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


def batcher_kind(default: str = "continuous") -> str:
    """``MXTPU_BATCHER``: which scheduler fronts the serving engine —
    ``continuous`` (iteration-level, paged KV; the default) or ``fixed``
    (the PR-5 whole-batch ``DynamicBatcher``). ``off``/``direct`` makes
    ``model.generate`` bypass batching entirely (raw ``decode_n``)."""
    v = os.environ.get("MXTPU_BATCHER", "").strip().lower()
    return v if v in ("continuous", "fixed", "off", "direct") else default


def iter_tokens_default(default: int = 4) -> int:
    """``MXTPU_ITER_TOKENS``: decode tokens per scheduler iteration
    (dispatch granularity). 1 = pure per-token Orca scheduling (finest
    retirement/streaming granularity); larger bursts amortize dispatch
    overhead at the cost of up to ``iter_tokens - 1`` wasted steps per
    retiring row."""
    v = os.environ.get("MXTPU_ITER_TOKENS", "").strip()
    try:
        return max(int(v), 1) if v else default
    except ValueError:
        return default


def spec_k_default(default: int = 0) -> int:
    """``MXTPU_SPEC_K``: draft tokens proposed per speculative-decoding
    round. 0 (the default) disables speculation; a positive k makes the
    scheduler draft k tokens per live slot and verify them in ONE target
    dispatch (greedy output stays bit-identical to non-speculative)."""
    v = os.environ.get("MXTPU_SPEC_K", "").strip()
    try:
        return max(int(v), 0) if v else default
    except ValueError:
        return default


def spec_draft_enabled(default: bool = True) -> bool:
    """``MXTPU_SPEC_DRAFT``: master enable for the speculative-decoding
    draft path — ``0``/``false``/``off`` force-disables speculation even
    when a draft model is attached and ``MXTPU_SPEC_K`` is positive (the
    operator kill switch)."""
    v = os.environ.get("MXTPU_SPEC_DRAFT", "").strip().lower()
    if not v:
        return default
    return v not in ("0", "false", "off")


def make_batcher(engine, bucket_keys, **kwargs):
    """Build the process-default batcher over ``engine``:
    ``ContinuousBatcher`` unless ``MXTPU_BATCHER=fixed`` (or the net
    lacks the paged protocol), then ``DynamicBatcher``. Kwargs the chosen
    class doesn't take are dropped."""
    if batcher_kind() != "fixed" and getattr(engine, "supports_paged",
                                             False):
        kwargs.pop("timeout_ms", None)
        return ContinuousBatcher(engine, bucket_keys, **kwargs)
    for k in ("page_size", "num_pages", "iter_tokens",
              "max_prefix_tokens", "prefix_cache", "spec_k",
              "spec_wide", "suffix_wide"):
        kwargs.pop(k, None)
    return DynamicBatcher(engine, bucket_keys, **kwargs)


class GenerationResult:
    """Future for one submitted request.

    ``result(timeout)`` blocks until the request finished and returns the
    full generated token list (trimmed at EOS); ``exception()`` surfaces
    a failure. ``tokens_iter(timeout)`` STREAMS instead: it yields token
    chunks as the scheduler emits them (per decode iteration under
    ``ContinuousBatcher``; one final chunk under ``DynamicBatcher``) and
    ends when the request resolves. ``weights_version`` tags the param
    set that served the request (hot weight swap; under continuous
    batching, the version of its final iteration) and ``replica`` which
    engine replica ran it (router). ``first_token_at`` is the
    ``perf_counter`` instant of the first streamed token (TTFT =
    ``first_token_at - enqueued_at``)."""

    __slots__ = ("_event", "_tokens", "_error", "enqueued_at",
                 "queue_wait_ms", "weights_version", "replica",
                 "_cond", "_stream", "first_token_at",
                 "request_id", "phases")

    def __init__(self):
        self._event = threading.Event()
        self._tokens = None
        self._error = None
        self.enqueued_at = time.perf_counter()
        self.queue_wait_ms = None
        self.weights_version = None
        self.replica = None
        self._cond = threading.Condition()
        self._stream = []
        self.first_token_at = None
        # fleet tracing/SLO attribution: the request id minted at the
        # router (or adopted from the RPC trace context) and the
        # per-phase latency breakdown — every ``*_ms`` entry names a
        # phase; the router adds ``other_ms`` so the sum equals the
        # observed end-to-end latency exactly
        self.request_id = None
        self.phases = None

    def _stream_tokens(self, tokens):
        """Append newly emitted tokens to the live stream (scheduler
        thread). First call stamps ``first_token_at`` (TTFT)."""
        if not tokens:
            return
        with self._cond:
            if self.first_token_at is None:
                self.first_token_at = time.perf_counter()
            self._stream.extend(tokens)
            self._cond.notify_all()

    def _stream_reset(self):
        """Preemption (pool exhaustion): the request restarts from its
        prompt, so the stream restarts too. ``result()`` is unaffected —
        only live ``tokens_iter`` consumers observe the re-emission."""
        with self._cond:
            self._stream = []
            self._cond.notify_all()

    def _resolve(self, tokens):
        with self._cond:
            self._tokens = tokens
            if not self._stream and tokens:
                if self.first_token_at is None:
                    self.first_token_at = time.perf_counter()
                self._stream = list(tokens)
            self._event.set()
            self._cond.notify_all()

    def _fail(self, err):
        with self._cond:
            self._error = err
            self._event.set()
            self._cond.notify_all()

    def done(self) -> bool:
        return self._event.is_set()

    def exception(self):
        return self._error

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("generation result not ready")
        if self._error is not None:
            raise self._error
        return self._tokens

    def tokens_iter(self, timeout: Optional[float] = None):
        """Yield generated-token chunks (lists) as they stream in; ends
        when the request resolves (raising its error if it failed).
        ``timeout`` bounds each wait for the NEXT chunk. After a pool
        preemption the stream restarts from the first token."""
        i = 0
        while True:
            with self._cond:
                if i > len(self._stream):
                    i = 0  # stream was reset by a preemption
                while len(self._stream) <= i and not self._event.is_set():
                    if not self._cond.wait(timeout):
                        raise TimeoutError("no token within timeout")
                chunk = list(self._stream[i:])
                done = self._event.is_set()
            if chunk:
                i += len(chunk)
                yield chunk
            if done and i >= len(self._stream):
                if self._error is not None:
                    raise self._error
                return


class _Request:
    __slots__ = ("prompt", "max_new", "future", "deadline", "frames",
                 "prefix")

    def __init__(self, prompt, max_new, future, deadline=None,
                 frames=None, prefix=None):
        self.prompt = prompt
        self.max_new = max_new
        self.future = future
        self.deadline = deadline  # absolute perf_counter instant or None
        # disaggregated serving: prefilled KV frames shipped by a
        # prefill-role worker (serving.disagg); None = prefill locally
        self.frames = frames
        # prefix caching: target-side conversation history the client
        # re-sends (multi-turn); forced verbatim before new tokens, and
        # the part already in the prefix trie is adopted instead of
        # recomputed. None/empty = fresh conversation.
        self.prefix = prefix


class _BatcherBase:
    """Shared admission/lifecycle spine for both schedulers: request
    validation, queueing, deadline expiry, dispatcher-thread health and
    teardown. Subclasses implement ``_run_loop`` (the scheduling policy)
    and dispatching."""

    def __init__(self, engine, bucket_keys: Sequence[int],
                 slots: Optional[int] = None,
                 max_new_tokens: int = 32, sampling: Optional[dict] = None,
                 pad_id: Optional[int] = None, start: bool = True,
                 name: Optional[str] = None, watchdog=None):
        if not getattr(engine, "supports_decode", False):
            raise MXNetError(
                f"{type(self).__name__} needs a decode-capable InferStep "
                "(net with prefill/decode_step)")
        self._engine = engine
        self.bucket_keys = sorted(int(k) for k in bucket_keys)
        if not self.bucket_keys:
            raise MXNetError("bucket_keys must be non-empty")
        self.slots = int(slots) if slots is not None else batcher_slots()
        self.max_new = int(max_new_tokens)
        # forced target-prefix budget; only ContinuousBatcher (paged
        # pool + prefix trie) raises this above zero
        self.max_prefix = 0
        self._sampling = dict(sampling or {})
        self._pad = int(pad_id) if pad_id is not None else engine._pad
        self.name = name
        self._watchdog = watchdog
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._init_rolling()
        self._stop = threading.Event()
        self._thread = None
        if start:
            self.start()

    # --------------------------------------------------- SLO telemetry
    def _init_rolling(self):
        """Rolling SLO windows (queue wait / TTFT) feeding the worker's
        health report and the router's predicted-wait placement; written
        by the scheduler thread, read by caller threads — every touch
        holds ``_roll_lock`` (and nothing blocking runs under it)."""
        self._roll_lock = threading.Lock()
        self._recent_waits = collections.deque(maxlen=64)
        self._recent_ttft = collections.deque(maxlen=64)

    def _note_wait(self, ms: float):
        with self._roll_lock:
            self._recent_waits.append(ms)

    def _note_ttft(self, ms: float):
        with self._roll_lock:
            self._recent_ttft.append(ms)

    def rolling_wait_ms(self, min_samples: int = 8) -> Optional[float]:
        """Rolling queue-wait p50 (ms) over recent completions, or None
        below ``min_samples`` — the worker-reported signal behind both
        admission control and SLO-aware router placement."""
        with self._roll_lock:
            waits = sorted(self._recent_waits)
        if len(waits) < min_samples:
            return None
        return waits[len(waits) // 2]

    def rolling_ttft_ms(self, min_samples: int = 4) -> Optional[float]:
        """Rolling time-to-first-token p50 (ms), or None below
        ``min_samples``."""
        with self._roll_lock:
            ttft = sorted(self._recent_ttft)
        if len(ttft) < min_samples:
            return None
        return ttft[len(ttft) // 2]

    def _label(self) -> str:
        return f"{type(self).__name__}" + (f" {self.name!r}"
                                           if self.name else "")

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mxtpu-batcher", daemon=True)
        self._thread.start()

    def stop(self, drain: bool = True, timeout: float = 30.0):
        """Stop the dispatcher; with ``drain`` (default) outstanding
        requests are dispatched first. Anything still queued when the
        thread is down is FAILED (a stopped batcher must never hold an
        unresolvable future)."""
        if drain and self.healthy:
            deadline = time.perf_counter() + timeout
            while not self._drained() and time.perf_counter() < deadline:
                time.sleep(0.005)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        self.cancel_pending()

    def _drained(self) -> bool:
        return self._queue.empty()

    @property
    def healthy(self) -> bool:
        """True while the dispatcher thread is alive and accepting — the
        router's per-replica liveness poll. Goes false on ``stop()`` and
        when the thread died (a crash outside the dispatch try)."""
        t = self._thread
        return t is not None and t.is_alive() and not self._stop.is_set()

    def cancel_pending(self, error: Optional[BaseException] = None) -> int:
        """Drain the queue, failing every undispatched request's future
        (default error: RuntimeError naming the batcher). The router uses
        this when evicting an unhealthy replica — the failed futures are
        its signal to resubmit those requests elsewhere. Returns how many
        requests were cancelled."""
        n = 0
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                return n
            r.future._fail(error if error is not None else RuntimeError(
                f"{self._label()} stopped with this request still queued"))
            n += 1

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------- requests
    def _admission_check(self, fut) -> bool:
        """Subclass hook: return False (after failing ``fut``) to reject
        the request at submit time (backpressure)."""
        return True

    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               frames: Optional[dict] = None,
               prefix_ids=None,
               request_id: Optional[str] = None) -> GenerationResult:
        """Enqueue one prompt (1-D int sequence). Returns a future whose
        ``result()`` is the generated token list, trimmed at EOS and at
        the request's ``max_new_tokens`` (<= the batcher's).

        ``deadline_ms`` bounds the request's total latency from NOW: a
        request still queued (or, under continuous batching, still
        decoding) when its deadline passes is failed with
        ``DeadlineExceeded`` instead of being served late.

        ``frames`` carries prefilled KV from a prefill-role worker
        (``serving.disagg``): ``ContinuousBatcher`` adopts them into its
        pool at admission instead of re-running the prefill; any
        adoption failure (and the ``DynamicBatcher`` fallback, which has
        no paged pool) re-prefills from the prompt — the request is
        served either way.

        ``prefix_ids`` is target-side conversation history (tokens the
        model already produced in earlier turns, re-sent by the client):
        ``ContinuousBatcher`` forces them verbatim before sampling new
        tokens and serves any part already in its prefix trie straight
        from cached KV pages. Only new tokens are returned. Requires a
        batcher built with ``max_prefix_tokens > 0``.

        ``request_id`` tags the future (and its spans/phase breakdown)
        with the fleet-wide trace id minted at the router; None is fine
        for direct callers — phases still stamp, spans are just
        unlinked.

        Submitting to a stopped (or crashed) batcher fails the future
        immediately with a RuntimeError — a request must never enqueue
        behind a dispatcher that will not run again."""
        prompt = _np.asarray(prompt_ids, dtype=_np.int32).reshape(-1)
        if prompt.shape[0] > self.bucket_keys[-1]:
            raise MXNetError(
                f"prompt length {prompt.shape[0]} exceeds the largest "
                f"bucket key {self.bucket_keys[-1]}")
        max_new = self.max_new if max_new_tokens is None \
            else int(max_new_tokens)
        if max_new > self.max_new:
            raise MXNetError(
                f"request max_new_tokens {max_new} > batcher "
                f"max_new_tokens {self.max_new}")
        prefix = None
        if prefix_ids is not None:
            prefix = _np.asarray(prefix_ids, dtype=_np.int32).reshape(-1)
            if prefix.shape[0] == 0:
                prefix = None
            elif prefix.shape[0] > self.max_prefix:
                raise MXNetError(
                    f"prefix length {prefix.shape[0]} > batcher "
                    f"max_prefix_tokens {self.max_prefix}")
        fut = GenerationResult()
        fut.request_id = request_id
        if not self.healthy:
            fut._fail(RuntimeError(
                f"{self._label()} is not accepting requests (stopped, or "
                "its dispatcher thread died) — the request would never "
                "resolve"))
            return fut
        if not self._admission_check(fut):
            return fut
        deadline = None if deadline_ms is None \
            else time.perf_counter() + float(deadline_ms) / 1e3
        self._queue.put(_Request(prompt, max_new, fut, deadline,
                                 frames=frames, prefix=prefix))
        return fut

    def _expire(self, reqs):
        """Fail (never dispatch) requests whose deadline passed while
        they were queued. Runs BEFORE batch assembly, so expired rows
        don't occupy slots and the occupancy/queue-wait telemetry of the
        dispatched batch is unaffected."""
        now = time.perf_counter()
        live = []
        for r in reqs:
            if r.deadline is not None and now > r.deadline:
                _tel.registry().counter("serve/deadline_exceeded").inc()
                r.future._fail(DeadlineExceeded(
                    f"request deadline passed after "
                    f"{(now - r.future.enqueued_at) * 1e3:.0f} ms in "
                    "queue — not dispatched"))
            else:
                live.append(r)
        return live

    def _bucket_for(self, max_len):
        for k in self.bucket_keys:
            if max_len <= k:
                return k
        raise MXNetError(
            f"prompt length {max_len} > largest bucket key "
            f"{self.bucket_keys[-1]}")

    # ------------------------------------------------------------ dispatcher
    def _run(self):
        try:
            self._run_loop()
        except BaseException as e:
            # the thread is dying (a crash outside the dispatch try, e.g.
            # the `batcher.thread` fault point): fail whatever is queued
            # so no future is left unresolvable, then let it die —
            # `healthy` flips false and the router (if any) takes over
            self._fail_inflight(RuntimeError(
                f"{self._label()} dispatcher thread died"))
            self.cancel_pending(RuntimeError(
                f"{self._label()} dispatcher thread died"))
            # injected deaths exit quietly (the crash is the test's
            # point); real crashes re-raise for the interpreter's
            # thread-exception hook
            if not isinstance(e, _faults.FaultInjected):
                raise

    def _fail_inflight(self, error):
        """Subclass hook: fail requests the scheduler already pulled off
        the queue (slots, partial batches) when the thread dies."""

    def _run_loop(self):  # pragma: no cover - abstract
        raise NotImplementedError


class DynamicBatcher(_BatcherBase):
    """Admit concurrent generation requests into fixed (batch, bucket)
    engine dispatches — the PR-5 whole-batch scheduler, kept as the
    ``MXTPU_BATCHER=fixed`` fallback and the strict one-weight-version-
    per-request path.

    Parameters
    ----------
    engine : ``parallel.infer.InferStep`` over a decode-capable net.
    bucket_keys : ascending prompt-length menu (the warmup contract —
        ``engine.warmup([(slots, k) for k in bucket_keys], max_new)``
        compiles every shape this batcher can emit).
    slots : batch rows per dispatch (``MXTPU_BATCHER_SLOTS``).
    timeout_ms : admission window (``MXTPU_BATCHER_TIMEOUT_MS``).
    max_new_tokens : decode length of every dispatch (per-request
        ``max_new_tokens`` may only be <= this; results are trimmed).
    sampling : dict of ``decode_n`` sampling kwargs (method/top_k/
        temperature/seed) shared by the batch.
    warmup : drive the engine's prefill+decode programs for the whole
        menu at construction (recommended for serving).
    name : tag for telemetry and fault matching (``serving.faults``);
        the router names each replica's batcher after the replica.
    watchdog : optional ``telemetry.Watchdog`` notified after every
        resolved dispatch — its ``heartbeat.json`` is the router's
        liveness signal for this replica (a hung dispatch stops the
        notifications and the heartbeat goes stale).
    """

    def __init__(self, engine, bucket_keys: Sequence[int],
                 slots: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 max_new_tokens: int = 32, sampling: Optional[dict] = None,
                 pad_id: Optional[int] = None, warmup: bool = False,
                 start: bool = True, name: Optional[str] = None,
                 watchdog=None):
        super().__init__(engine, bucket_keys, slots=slots,
                         max_new_tokens=max_new_tokens, sampling=sampling,
                         pad_id=pad_id, start=False, name=name,
                         watchdog=watchdog)
        self.timeout_s = (timeout_ms if timeout_ms is not None
                          else batcher_timeout_ms()) / 1e3
        if warmup:
            engine.warmup([(self.slots, k) for k in self.bucket_keys],
                          max_new_tokens=self.max_new, **self._sampling)
        if start:
            self.start()

    def _run_loop(self):
        while not self._stop.is_set():
            # fault point: an unhandled crash of the dispatcher thread
            # (NOT caught by the dispatch try below) — a dead replica
            _faults.fire("batcher.thread", tag=self.name)
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            reqs = [first]
            deadline = time.perf_counter() + self.timeout_s
            while len(reqs) < self.slots:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    reqs.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            reqs = self._expire(reqs)
            if not reqs:
                continue
            t0 = time.perf_counter()
            try:
                out = self._dispatch(reqs)
            except Exception as e:  # noqa: BLE001 - fail the futures, not the thread
                for r in reqs:
                    r.future._fail(e)
                continue
            self._resolve(reqs, out, t0)

    def _dispatch(self, reqs):
        """Assemble one fixed (slots, bucket) batch and fire the engine.
        Pure staging + dispatch — linted sync-free
        (``tools/check_no_sync_in_step.py``): the host reads happen in
        ``_resolve`` after the device work is in flight."""
        _faults.fire("batcher.hang", tag=self.name)
        _faults.fire("batcher.dispatch", tag=self.name)
        bucket = self._bucket_for(max(r.prompt.shape[0] for r in reqs))
        src = _np.full((self.slots, bucket), self._pad, _np.int32)
        vl = _np.zeros((self.slots,), _np.int32)
        for i, r in enumerate(reqs):
            n = r.prompt.shape[0]
            src[i, :n] = r.prompt
            vl[i] = n
        # the version THIS dispatch serves, captured with the dispatch:
        # responses are tagged with it even if a hot swap flips the
        # engine's live buffer before the results are read back
        version = getattr(self._engine, "weights_version", None)
        out = self._engine.decode_n(
            src, vl, max_new_tokens=self.max_new, **self._sampling)
        return out, version

    def _resolve(self, reqs, out, t0):
        """Per-request detach: trim each row at its EOS / its own
        ``max_new_tokens`` and resolve its future. The host read here is
        the sync point of the whole pipeline."""
        (tokens_nd, lengths_nd), version = out
        tokens = tokens_nd.asnumpy()
        lengths = lengths_nd.asnumpy()
        dispatch_ms = (time.perf_counter() - t0) * 1e3
        now = time.perf_counter()
        reg = _tel.registry()
        emitted = 0
        for i, r in enumerate(reqs):
            n = min(int(lengths[i]), r.max_new)
            r.future.queue_wait_ms = (now - r.future.enqueued_at) * 1e3 \
                - dispatch_ms
            reg.histogram("infer/queue_wait_ms").observe(
                max(r.future.queue_wait_ms, 0.0))
            self._note_wait(max(r.future.queue_wait_ms, 0.0))
            emitted += n
            r.future.weights_version = version
            r.future.replica = self.name
            r.future.phases = {
                "queue_ms": max(r.future.queue_wait_ms, 0.0),
                "decode_ms": dispatch_ms,
            }
            if _tracing.trace_enabled():
                _tracing.span("trace.queue", _evus(r.future.enqueued_at),
                              {"replica": self.name},
                              request_id=r.future.request_id,
                              end_us=_evus(t0))
                _tracing.span("trace.decode", _evus(t0),
                              {"replica": self.name, "tokens": n},
                              request_id=r.future.request_id,
                              end_us=_evus(now))
            r.future._resolve(tokens[i, :n].tolist())
            if r.future.first_token_at is not None:
                ttft = (r.future.first_token_at
                        - r.future.enqueued_at) * 1e3
                reg.histogram("infer/ttft_ms").observe(ttft)
                self._note_ttft(ttft)
        wd = self._watchdog
        if wd is not None:
            wd.notify_step(seconds=dispatch_ms / 1e3)
            wd.note_request(inflight=self._queue.qsize(),
                            request_id=reqs[-1].future.request_id,
                            completed=len(reqs))
        reg.counter("infer/requests").inc(len(reqs))
        reg.counter("infer/tokens").inc(emitted)
        reg.gauge("infer/batch_occupancy").set(len(reqs) / self.slots)
        reg.histogram("infer/prefill_ms").observe(dispatch_ms)
        if emitted:
            reg.histogram("infer/decode_ms_per_token").observe(
                dispatch_ms / emitted)
            reg.gauge("infer/tokens_per_sec").set(
                emitted / (dispatch_ms / 1e3))


class _Slot:
    """Host-side record of one OCCUPIED decode slot."""

    __slots__ = ("req", "carry", "length", "emitted", "finished",
                 "admitted_seq", "version", "active_at")

    def __init__(self, req, admitted_seq):
        self.req = req
        self.carry = None        # last sampled token, not yet KV-cached
        self.length = 0          # KV entries cached in this slot's pages
        self.emitted = []        # generated tokens streamed so far
        self.finished = False
        self.admitted_seq = admitted_seq
        self.version = None
        self.active_at = None    # perf_counter at activation (decode_ms)


class ContinuousBatcher(_BatcherBase):
    """Iteration-level scheduler over a paged KV cache — the tentpole.

    Between every decode iteration the scheduler retires finished rows
    (EOS, per-request ``max_new_tokens``, deadline), returns their pages
    to the pool, and admits queued requests into the vacated slots via a
    jitted prefill-into-pages dispatch — the decode batch stays full
    under load without a single retrace.

    Parameters
    ----------
    engine : paged-protocol ``InferStep`` (``supports_paged``).
    bucket_keys : ascending prompt-length menu; the LARGEST key is also
        the static cross-attention memory width every slot carries.
    slots : decode-batch rows (``MXTPU_BATCHER_SLOTS``).
    max_new_tokens : per-request generation cap (requests may ask less).
    page_size / num_pages : KV pool geometry (``MXTPU_PAGE_SIZE`` /
        ``MXTPU_PAGES``; default pool fully provisions every slot).
    iter_tokens : decode tokens per iteration (``MXTPU_ITER_TOKENS``);
        1 = pure Orca-style per-token scheduling.
    admit_free_pages / admit_max_queue / admit_max_wait_ms : backpressure
        thresholds (``MXTPU_ADMIT_*``): keep N pages free, bound the
        queue depth, reject while rolling queue-wait p50 breaches.
    max_prefix_tokens : forced target-prefix budget per request (re-sent
        multi-turn history, ``submit(prefix_ids=...)``); each slot is
        provisioned for ``1 + max_prefix_tokens + max_new_tokens``
        cached positions. 0 (default) rejects prefix requests.
    prefix_cache : enable the copy-on-write prefix trie over the page
        pool (``MXTPU_PREFIX_CACHE`` when None): retiring slots donate
        their page chains; admission adopts matched prefixes read-only
        and replays only the uncached suffix.
    spec_k : draft tokens per speculative round (``MXTPU_SPEC_K`` when
        None; 0 disables). Speculation engages only when the engine has
        an attached draft (``InferStep.attach_draft``), sampling is
        greedy, and ``MXTPU_SPEC_DRAFT`` isn't force-off — greedy output
        stays BIT-IDENTICAL to the non-speculative scheduler; only the
        tokens-per-dispatch ratio changes.
    spec_wide : verify drafts with the one-pass windowed target program
        (the shape the paged flash kernel accelerates) instead of the
        bit-exact sequential verifier.
    suffix_wide : replay prefix-cache suffixes through the one-pass
        q_offset-aware window program instead of the sequential stream.
    warmup : compile the admission-prefill program per bucket plus the
        decode-iteration program at construction (inert rows — the pools
        only ever see trash-page writes).
    sampling : ``method``/``top_k``/``temperature`` shared by every
        iteration. NOTE the key schedule is per-iteration, so sampled
        runs are reproducible per batcher, not vs ``decode_n``.
    """

    def __init__(self, engine, bucket_keys: Sequence[int],
                 slots: Optional[int] = None, max_new_tokens: int = 32,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 iter_tokens: Optional[int] = None,
                 sampling: Optional[dict] = None,
                 pad_id: Optional[int] = None,
                 admit_free_pages: Optional[int] = None,
                 admit_max_queue: Optional[int] = None,
                 admit_max_wait_ms: Optional[float] = None,
                 max_prefix_tokens: int = 0,
                 prefix_cache: Optional[bool] = None,
                 spec_k: Optional[int] = None, spec_wide: bool = False,
                 suffix_wide: bool = False,
                 warmup: bool = False, start: bool = True,
                 name: Optional[str] = None, watchdog=None):
        super().__init__(engine, bucket_keys, slots=slots,
                         max_new_tokens=max_new_tokens, sampling=sampling,
                         pad_id=pad_id, start=False, name=name,
                         watchdog=watchdog)
        if not getattr(engine, "supports_paged", False):
            raise MXNetError(
                "ContinuousBatcher needs a paged-protocol InferStep "
                "(net with prefill_paged/decode_step_paged); use "
                "DynamicBatcher (MXTPU_BATCHER=fixed) otherwise")
        self._sampling.pop("seed", None)  # per-iteration key schedule
        self.page_size = int(page_size) if page_size is not None \
            else _pages.page_size_default()
        self.max_prefix = int(max_prefix_tokens)
        # speculative decoding resolves BEFORE pool geometry: a spec
        # round writes up to k target entries past a row's emitted
        # length, and ACCEPTED entries must land in real pages (a
        # trash-page overflow would silently lose cached KV), so every
        # slot is provisioned k positions deeper
        self.spec_k = int(spec_k) if spec_k is not None \
            else spec_k_default()
        self._spec_on = (self.spec_k > 0
                         and getattr(engine, "has_draft", False)
                         and spec_draft_enabled()
                         and self._sampling.get("method",
                                                "greedy") == "greedy")
        self.spec_wide = bool(spec_wide)
        self.suffix_wide = bool(suffix_wide)
        self.pages_per_slot = _pages.pages_for(
            1 + self.max_prefix + self.max_new
            + (self.spec_k if self._spec_on else 0), self.page_size)
        self.num_pages = int(num_pages) if num_pages is not None \
            else _pages.num_pages_default(self.slots, self.pages_per_slot)
        if self.pages_per_slot > self.num_pages:
            raise MXNetError(
                f"one request needs {self.pages_per_slot} pages for "
                f"max_new_tokens={self.max_new} but the pool has only "
                f"{self.num_pages} (MXTPU_PAGES / MXTPU_PAGE_SIZE)")
        self.iter_tokens = int(iter_tokens) if iter_tokens is not None \
            else iter_tokens_default()
        self.mem_len = self.bucket_keys[-1]
        self._admit_free_pages = admit_free_pages \
            if admit_free_pages is not None else _pages.admit_free_pages()
        self._admit_max_queue = admit_max_queue \
            if admit_max_queue is not None else _pages.admit_max_queue()
        self._admit_max_wait_ms = admit_max_wait_ms \
            if admit_max_wait_ms is not None else _pages.admit_max_wait_ms()
        self.pool = _pages.PagePool(self.num_pages, self.page_size,
                                    self.slots, self.pages_per_slot)
        self._state = engine.init_paged_state(
            self.slots, self.num_pages, self.page_size, self.mem_len)
        # the draft model decodes against its OWN pools but the SAME
        # page table — one allocator, two KV caches
        self._dstate = engine.init_draft_state(
            self.slots, self.num_pages, self.page_size,
            self.mem_len) if self._spec_on else None
        from ..ops.pallas import paged_flash_attention as _pfa
        _tel.registry().gauge("infer/flash_kernel").set(
            1.0 if _pfa.flash_paged_enabled() else 0.0)
        # prefix trie over this pool: retired slots donate their page
        # chains (refcounted, read-only) and admission adopts matched
        # prefixes instead of recomputing them
        self.cache = _prefix.PrefixCache(
            self.pool, self.page_size, enabled=prefix_cache)
        self._cache_tag = getattr(engine, "weights_version", None)
        # compiled batched hit-adoption program (traced once by warmup)
        self._hits_fn = None
        # suffix-length bucket menu for the forced-prefix replay program
        # (same powers-of-2 discipline as the admission-row menu)
        self._suffix_menu = []
        if self.max_prefix > 0:
            s = 1
            while s < self.max_prefix:
                self._suffix_menu.append(s)
                s *= 2
            self._suffix_menu.append(self.max_prefix)
        self._slots = [None] * self.slots
        self._pending = collections.deque()
        self._seq = 0
        self._iter = 0
        # stats + the rolling-wait window are written by the scheduler
        # thread AND by submit-side admission control (caller threads);
        # every touch goes through this lock — an unsynchronized
        # sorted() over the deque while the scheduler appends raises
        # "deque mutated during iteration" (mxlint lock-order pass)
        self._stats_lock = threading.Lock()
        self.stats = {"iterations": 0, "occupancy_sum": 0.0,
                      "admitted": 0, "retired": 0, "preempted": 0,
                      "rejected": 0, "tokens": 0,
                      # disaggregated serving: KV handoffs adopted into
                      # this pool / handoffs that fell back to a local
                      # re-prefill (serving.disagg)
                      "adopted": 0, "re_prefills": 0,
                      # prefix caching: trie lookups that matched, KV
                      # tokens served from cache instead of recomputed,
                      # and copy-on-write page copies
                      "prefix_hits": 0, "prefix_lookups": 0,
                      "prefix_tokens_saved": 0, "cow_copies": 0}
        if warmup:
            self._warmup()
        if start:
            self.start()

    # --------------------------------------------------------------- warmup
    def _warmup(self):
        """Compile every program the scheduler can dispatch — one
        admission prefill per bucket + the decode-iteration program —
        with fully inert rows (no slot ids, trash pages only), then mark
        the guard steady."""
        import jax

        eng = self._engine
        reg = _tel.registry()
        before = eng.compile_guard.signatures
        rows_menu = []
        rows = 1
        while rows < self.slots:
            rows_menu.append(rows)
            rows *= 2
        rows_menu.append(self.slots)
        for bucket in self.bucket_keys:
            for rows in rows_menu:
                src = _np.zeros((rows, bucket), _np.int32)
                vl = _np.full((rows,), bucket, _np.int32)
                inert = _np.full((rows,), self.slots, _np.int32)  # OOB
                tok0, self._state = eng.prefill_paged(
                    self._state, src, vl, inert,
                    _np.zeros((rows,), _np.int32),
                    _np.zeros((rows,), bool), **self._sampling)
                jax.block_until_ready(tok0.data)
                if self._spec_on:
                    # draft admission shares every shape bucket with the
                    # target so cold admits never trace mid-serving
                    tokD, self._dstate = eng.draft.prefill_paged(
                        self._dstate, src, vl, inert,
                        _np.zeros((rows,), _np.int32),
                        _np.zeros((rows,), bool), **self._sampling)
                    jax.block_until_ready(tokD.data)
        zeros = _np.zeros((self.slots,), _np.int32)
        buf, self._state = eng.decode_iter(
            self._state, self.pool.table, zeros, zeros,
            _np.zeros((self.slots,), bool), steps=self.iter_tokens,
            **self._sampling)
        jax.block_until_ready(buf.data)
        if self._spec_on:
            # one inert speculative round compiles BOTH spec programs
            # (draft k-token proposal + target k+1 verification)
            inactive = _np.zeros((self.slots,), bool)
            pair = eng.spec_pair()
            dbuf, self._dstate = eng.spec_draft(
                self._dstate, self.pool.table, zeros, zeros, inactive,
                k=self.spec_k, pair=pair)
            vbuf, self._state = eng.spec_verify(
                self._state, self.pool.table, dbuf, zeros, zeros,
                inactive, pair=pair, wide=self.spec_wide)
            jax.block_until_ready(vbuf.data)
        # forced-prefix replay menu (rows x suffix-length buckets): the
        # teacher-forced suffix program serves both cache hits and cold
        # prefix replays, so it must be steady before the first one
        for srows in rows_menu:
            for s_len in self._suffix_menu:
                toks = _np.zeros((srows, s_len), _np.int32)
                ones = _np.ones((srows,), _np.int32)
                tokS, self._state = eng.prefill_suffix_paged(
                    self._state, toks, ones, ones,
                    _np.zeros((srows, self.pages_per_slot), _np.int32),
                    _np.full((srows,), self.slots, _np.int32),
                    _np.zeros((srows,), bool), wide=self.suffix_wide,
                    **self._sampling)
                jax.block_until_ready(tokS.data)
        # the batched hit-adoption program (inert here: TRASH->TRASH
        # COW self-copies, out-of-bounds cross rows — shapes are padded
        # to `slots`, so this one trace covers every admission group)
        if self.cache.enabled:
            self._apply_prefix_hits([])
        # warm the disaggregated-handoff adoption scatters too: the
        # first `.at[].set` per pool array otherwise compiles on the
        # scheduler thread mid-serving (a ~200 ms TTFT spike on the
        # first adopted request, measured on the CPU rig)
        if self.pool.alloc(0, 1):
            st = self._state
            fake = {"length": 1, "carry": 0, "emitted": [0], "mem_vl": 1,
                    "k": [_np.zeros((1,) + tuple(p.shape[2:]), _np.float32)
                          for p in st["k_pools"]],
                    "v": [_np.zeros((1,) + tuple(p.shape[2:]), _np.float32)
                          for p in st["v_pools"]],
                    "ck": [_np.zeros((1,) + tuple(c.shape[2:]),
                                     _np.float32)
                           for c in st["cross_k"]],
                    "cv": [_np.zeros((1,) + tuple(c.shape[2:]),
                                     _np.float32)
                           for c in st["cross_v"]]}
            self._adopt(0, fake)
            self.pool.release(0)
        reg.counter("compile/warmup_compiles").inc(
            eng.compile_guard.signatures - before)
        eng.compile_guard.mark_steady()

    # ---------------------------------------------------------- admission
    def _admission_check(self, fut) -> bool:
        """Reject-with-backpressure at submit: queue depth beyond
        ``MXTPU_ADMIT_MAX_QUEUE``, or rolling queue-wait p50 beyond
        ``MXTPU_ADMIT_MAX_WAIT_MS``, or free pages below the watermark
        with nothing about to retire — the caller (router) reroutes."""
        reason = None
        if self._queue.qsize() + len(self._pending) >= self._admit_max_queue:
            reason = (f"queue depth {self._queue.qsize()} >= "
                      f"{self._admit_max_queue} (MXTPU_ADMIT_MAX_QUEUE)")
        elif self._admit_max_wait_ms > 0:
            p50 = self.rolling_wait_ms()
            if p50 is not None and p50 > self._admit_max_wait_ms:
                reason = (f"queue wait p50 {p50:.0f} ms > "
                          f"{self._admit_max_wait_ms:.0f} ms "
                          "(MXTPU_ADMIT_MAX_WAIT_MS)")
        if reason is not None:
            with self._stats_lock:
                self.stats["rejected"] += 1
            _tel.registry().counter("infer/rejected_backpressure").inc()
            fut._fail(Backpressure(
                f"{self._label()} rejected the request: {reason}"))
            return False
        return True

    def _drained(self) -> bool:
        return self._queue.empty() and not self._pending and \
            not any(self._slots)

    def _fail_inflight(self, error):
        for i, s in enumerate(self._slots):
            if s is not None and not s.req.future.done():
                s.req.future._fail(error)
            self._slots[i] = None
        for r in self._pending:
            if not r.future.done():
                r.future._fail(error)
        self._pending.clear()
        self.pool.reset()

    def stop(self, drain: bool = True, timeout: float = 30.0):
        super().stop(drain=drain, timeout=timeout)
        self._fail_inflight(RuntimeError(
            f"{self._label()} stopped with this request in flight"))

    # ------------------------------------------------------------ scheduler
    def _run_loop(self):
        while not self._stop.is_set():
            _faults.fire("batcher.thread", tag=self.name)
            if not self._step_once():
                # idle: block briefly for an arrival
                try:
                    self._pending.append(self._queue.get(timeout=0.05))
                except queue.Empty:
                    continue

    def _step_once(self) -> bool:
        """One scheduler iteration: retire -> admit -> decode -> collect.
        Returns False when there was nothing to do (idle)."""
        while True:
            try:
                self._pending.append(self._queue.get_nowait())
            except queue.Empty:
                break
        if self._pending:
            self._pending = collections.deque(
                self._expire(list(self._pending)))
        try:
            # the whole iteration is one poison domain: an exception
            # anywhere (a partial admit that staged pages, a prefix
            # insert mid-refcount, a collect on poisoned state) must
            # release every page and fail every slot, not kill the
            # scheduler thread with pages still referenced.
            self._retire()
            admitted = self._admit()
            live = [i for i, s in enumerate(self._slots)
                    if s is not None and not s.finished]
            if not live:
                return admitted > 0
            self._ensure_capacity(live)
            live = [i for i, s in enumerate(self._slots)
                    if s is not None and not s.finished]
            if not live:
                return True
            t0 = time.perf_counter()
            out = self._dispatch(live)
            self._collect(live, out, t0)
        except Exception as e:  # noqa: BLE001 - fail the slots, not the thread
            self._poison(e)
        return True

    def _retire(self):
        """Resolve finished/expired slots and free their pages — the
        between-dispatches safe point."""
        now = time.perf_counter()
        reg = _tel.registry()
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            r = s.req
            if not s.finished and r.deadline is not None \
                    and now > r.deadline:
                reg.counter("serve/deadline_exceeded").inc()
                r.future._fail(DeadlineExceeded(
                    f"request deadline passed after {len(s.emitted)} of "
                    f"{r.max_new} tokens — retired mid-decode"))
                s.finished = True
            if not s.finished:
                continue
            # donate the retiring chain to the prefix trie BEFORE the
            # release: the trie's cache_acquire keeps the pages alive
            # (refcounted) while the slot's own references go away
            self._register_prefix(i, s)
            self.pool.release(i)
            self._slots[i] = None
            if not r.future.done():
                r.future.weights_version = s.version
                r.future.replica = self.name
                if s.active_at is not None:
                    base = dict(r.future.phases or {})
                    base["decode_ms"] = (now - s.active_at) * 1e3
                    r.future.phases = base
                    if _tracing.trace_enabled():
                        _tracing.span("trace.decode", _evus(s.active_at),
                                      {"replica": self.name,
                                       "tokens": len(s.emitted)},
                                      request_id=r.future.request_id,
                                      end_us=_evus(now))
                r.future._resolve(list(s.emitted))
            with self._stats_lock:
                self.stats["retired"] += 1
            reg.counter("infer/requests").inc()
            reg.counter("infer/tokens").inc(len(s.emitted))
            wd = self._watchdog
            if wd is not None:
                wd.note_request(request_id=r.future.request_id,
                                completed=1)

    def _adopt(self, slot: int, frames: dict) -> bool:
        """Adopt prefilled KV frames (``serving.disagg``) into ``slot``'s
        pages and cross buffers WITHOUT re-running the prefill — the
        decode half of a disaggregated handoff. Host-side ``.at[].set``
        scatters between dispatches; shapes/dtypes never change, so the
        decode program is untouched. Returns False on any geometry
        mismatch or failure (the caller then re-prefills from the
        prompt — zero lost requests by construction)."""
        import jax.numpy as jnp

        try:
            L = int(frames["length"])
            mvl = int(frames["mem_vl"])
            st = dict(self._state)
            if len(frames["k"]) != len(st["k_pools"]):
                return False
            if mvl > self.mem_len or L < 1 \
                    or L > self.pages_per_slot * self.page_size:
                return False
            if not self.pool.ensure(slot, L):
                return False
            # indices ride as TRACED operands (jnp scalars), never
            # Python ints: a concrete index bakes into the compiled
            # scatter, so every distinct slot/page combination would
            # compile its own program ON the scheduler thread mid-run —
            # measured as a multi-hundred-ms TTFT tail on the CPU rig
            slot_idx = jnp.asarray(slot, jnp.int32)
            kps, vps, cks, cvs = [], [], [], []
            for i in range(len(st["k_pools"])):
                kp, vp = st["k_pools"][i], st["v_pools"][i]
                ck, cv = st["cross_k"][i], st["cross_v"][i]
                k = _np.asarray(frames["k"][i])
                v = _np.asarray(frames["v"][i])
                if k.shape != (L,) + kp.shape[2:] or v.shape != k.shape:
                    return False
                for pi in range(_pages.pages_for(L, self.page_size)):
                    page = jnp.asarray(int(self.pool.table[slot, pi]),
                                       jnp.int32)
                    lo = pi * self.page_size
                    hi = min(L, lo + self.page_size)
                    kp = kp.at[page, :hi - lo].set(
                        jnp.asarray(k[lo:hi], kp.dtype))
                    vp = vp.at[page, :hi - lo].set(
                        jnp.asarray(v[lo:hi], vp.dtype))
                # zero-fill the slot's cross row beyond mem_vl so the
                # buffer matches what a local prefill_paged (which pads
                # the projections to mem_len) would have written —
                # bit-identical decode regardless of the slot's
                # previous occupant
                ckf = _np.zeros((self.mem_len,) + tuple(ck.shape[2:]),
                                _np.dtype(ck.dtype))
                cvf = _np.zeros_like(ckf)
                cka = _np.asarray(frames["ck"][i])
                cva = _np.asarray(frames["cv"][i])
                if cka.shape != (mvl,) + tuple(ck.shape[2:]) or \
                        cva.shape != cka.shape:
                    return False
                ckf[:mvl] = cka
                cvf[:mvl] = cva
                kps.append(kp)
                vps.append(vp)
                cks.append(ck.at[slot_idx].set(jnp.asarray(ckf, ck.dtype)))
                cvs.append(cv.at[slot_idx].set(jnp.asarray(cvf, cv.dtype)))
            st["k_pools"] = tuple(kps)
            st["v_pools"] = tuple(vps)
            st["cross_k"] = tuple(cks)
            st["cross_v"] = tuple(cvs)
            st["mem_vl"] = st["mem_vl"].at[slot_idx].set(mvl)
            self._state = st
            return True
        except Exception:  # noqa: BLE001 - torn frames = re-prefill
            return False

    # ------------------------------------------------------ prefix caching
    def _cross_frames_fit(self, mem_vl: int, ck, cv) -> bool:
        """Host-side geometry check for a cached root's cross frames —
        the validation half of the old per-request adoption, run at
        staging time so the batched apply never has to fail a single
        row. False sends the request down the cold path."""
        try:
            mvl = int(mem_vl)
            st = self._state
            if mvl < 1 or mvl > self.mem_len \
                    or ck is None or cv is None \
                    or len(ck) != len(st["cross_k"]) \
                    or len(cv) != len(st["cross_v"]):
                return False
            for i, c_k in enumerate(st["cross_k"]):
                want = (mvl,) + tuple(c_k.shape[2:])
                if tuple(_np.asarray(ck[i]).shape) != want \
                        or tuple(_np.asarray(cv[i]).shape) != want:
                    return False
            return True
        except Exception:  # noqa: BLE001 - torn frames = cold prefill
            return False

    def _apply_prefix_hits(self, hits) -> None:
        """ONE batched device update for every prefix hit admitted this
        iteration: a single gather/scatter duplicates all COW pages
        across every layer's K/V pool, and a single scatter lands the
        adopted cross frames + ``mem_vl`` rows. The per-request
        ``.at[].set`` chains this replaces ran sequentially on the
        scheduler thread and were measured at ~9 ms per hit on the CPU
        rig — more than the batched cold replay they were saving.
        Rows are padded to ``slots`` (COW pads as TRASH self-copies,
        cross rows as out-of-bounds drops), so one compiled program
        covers every admission-group size."""
        import jax
        import jax.numpy as jnp

        st = self._state
        rows = self.slots
        src = _np.zeros((rows,), _np.int32)   # TRASH -> TRASH no-ops
        dst = _np.zeros((rows,), _np.int32)
        sids = _np.full((rows,), rows, _np.int32)  # OOB rows dropped
        mvl = _np.zeros((rows,), _np.int32)
        cks = [_np.zeros((rows, self.mem_len) + tuple(c.shape[2:]),
                         _np.dtype(c.dtype)) for c in st["cross_k"]]
        cvs = [_np.zeros((rows, self.mem_len) + tuple(c.shape[2:]),
                         _np.dtype(c.dtype)) for c in st["cross_v"]]
        for i, (slot, hit) in enumerate(hits):
            if hit.cow is not None:
                src[i] = int(hit.cow[0])
                dst[i] = int(self.pool.table[slot, len(hit.full_pages)])
            sids[i] = slot
            mvl[i] = int(hit.mem_vl)
            for li in range(len(cks)):
                cks[li][i, :mvl[i]] = _np.asarray(hit.ck[li])
                cvs[li][i, :mvl[i]] = _np.asarray(hit.cv[li])
        if self._hits_fn is None:
            def _apply(kps, vps, c_k, c_v, mem, src, dst, sids, mvl,
                       cks, cvs):
                kps = tuple(kp.at[dst].set(kp[src]) for kp in kps)
                vps = tuple(vp.at[dst].set(vp[src]) for vp in vps)
                c_k = tuple(c.at[sids].set(f, mode="drop")
                            for c, f in zip(c_k, cks))
                c_v = tuple(c.at[sids].set(f, mode="drop")
                            for c, f in zip(c_v, cvs))
                mem = mem.at[sids].set(mvl, mode="drop")
                return kps, vps, c_k, c_v, mem
            self._hits_fn = jax.jit(_apply)
        out = self._hits_fn(st["k_pools"], st["v_pools"],
                            st["cross_k"], st["cross_v"], st["mem_vl"],
                            jnp.asarray(src), jnp.asarray(dst),
                            jnp.asarray(sids), jnp.asarray(mvl),
                            [jnp.asarray(a) for a in cks],
                            [jnp.asarray(a) for a in cvs])
        st = dict(st)
        (st["k_pools"], st["v_pools"], st["cross_k"], st["cross_v"],
         st["mem_vl"]) = out
        self._state = st

    def _register_prefix(self, slot: int, s) -> None:
        """Donate a retiring slot's page chain to the prefix trie so a
        later request sharing the prompt + target history adopts instead
        of recomputing. Cross frames are read back from the device only
        when the prompt is new to the trie (one sync per new root, on
        the retire path — never on the dispatch path)."""
        if not self.cache.enabled or s.length < 1:
            return
        r = s.req
        pre = [] if r.prefix is None else [int(t) for t in r.prefix]
        target = ([self._engine._bos] + pre
                  + [int(t) for t in s.emitted])[:s.length]
        mem_vl = ck = cv = None
        if not self.cache.has_root(r.prompt):
            import jax

            # ONE device round trip for the whole readback (mem_vl +
            # every layer's cross row) — per-layer ``asarray`` pulls
            # each paid a separate sync against the async dispatch queue
            st = self._state
            n = len(st["cross_k"])
            got = jax.device_get(
                [st["mem_vl"][slot]]
                + [c[slot] for c in st["cross_k"]]
                + [c[slot] for c in st["cross_v"]])
            mem_vl = int(got[0])
            if mem_vl < 1:
                return
            ck = [g[:mem_vl] for g in got[1:1 + n]]
            cv = [g[:mem_vl] for g in got[1 + n:]]
        pages = list(self.pool.owned(slot))[
            :_pages.pages_for(s.length, self.page_size)]
        self.cache.insert(r.prompt, target, pages, mem_vl=mem_vl,
                          ck=ck, cv=cv)

    def _seed_from_frames(self, slot: int, r, fr: dict) -> None:
        """A disaggregated handoff just adopted prefilled KV into
        ``slot``: register it in the prefix trie too, so later
        same-prompt requests on this decode worker hit the cache."""
        if not self.cache.enabled:
            return
        L = int(fr["length"])
        target = ([self._engine._bos]
                  + [int(t) for t in fr["emitted"]])[:L]
        pages = list(self.pool.owned(slot))[
            :_pages.pages_for(L, self.page_size)]
        self.cache.insert(r.prompt, target, pages,
                          mem_vl=int(fr["mem_vl"]),
                          ck=fr["ck"], cv=fr["cv"])

    def _ensure_with_evict(self, slot: int, upto: int) -> bool:
        """``pool.ensure`` with one retry after asking the trie to evict
        unreferenced cached pages — cached-but-idle KV yields to live
        requests before admission is refused."""
        if self.pool.ensure(slot, upto):
            return True
        need = _pages.pages_for(upto, self.page_size) \
            - len(self.pool.owned(slot))
        if self.cache.evict(max(need, 1)) == 0:
            return False
        return self.pool.ensure(slot, upto)

    def _stage_slot(self, slot: int, r):
        """Allocate (or adopt from the prefix trie) the pages ``slot``
        needs for the request's full forced target prefix, adopting the
        root's cross frames on a hit. Returns ``(ok, hit)``: ``ok``
        False means the pool cannot stage this request right now (the
        caller puts it back); ``hit`` None means the cold path — BOS
        prefill, then a teacher-forced suffix replay if the request
        carries a prefix."""
        target_len = 1 + (0 if r.prefix is None
                          else int(r.prefix.shape[0]))
        reg = _tel.registry()
        hit = None
        if r.frames is None and self.cache.enabled:
            target = [self._engine._bos] + ([] if r.prefix is None
                                            else [int(t) for t in r.prefix])
            hit = self.cache.match(r.prompt, target)
            with self._stats_lock:
                self.stats["prefix_lookups"] += 1
            if hit is not None and hit.matched < 1:
                # a bare root offers no adoptable pages, and the BOS
                # prime re-runs the encoder anyway — nothing to win
                hit = None
        if hit is not None:
            # geometry first: a root with torn cross frames must fall
            # back to the cold path BEFORE it acquires any pages
            ok = self._cross_frames_fit(hit.mem_vl, hit.ck, hit.cv)
            if ok:
                ok = self.pool.adopt_ref(slot, hit.full_pages)
            if ok:
                ok = self._ensure_with_evict(slot, target_len)
            if ok and hit.cow is not None:
                # the first page past the fully-adopted run becomes this
                # slot's private copy of the donor's partial page (the
                # replay appends into the copy, never the original); the
                # copy itself rides in the admission group's single
                # batched device update (``_apply_prefix_hits``)
                with self._stats_lock:
                    self.stats["cow_copies"] += 1
                reg.counter("infer/prefix_cow_copies").inc()
            if not ok:
                self.pool.release(slot)
                hit = None
            else:
                with self._stats_lock:
                    self.stats["prefix_hits"] += 1
                    self.stats["prefix_tokens_saved"] += \
                        int(r.prompt.shape[0]) + hit.matched
                reg.counter("infer/prefix_tokens_saved").inc(
                    int(r.prompt.shape[0]) + hit.matched)
        if hit is None:
            ok = self.pool.alloc(slot, 1) \
                or (self.cache.evict(1) > 0 and self.pool.alloc(slot, 1))
            if ok:
                ok = self._ensure_with_evict(slot, target_len)
            if not ok:
                self.pool.release(slot)
                return False, None
        return True, hit

    def prefix_stats(self) -> dict:
        """Prefix-cache snapshot (trie stats + batcher-side COW
        counter) — the worker health verb's prefix block."""
        out = self.cache.snapshot()
        with self._stats_lock:
            out["cow_copies"] = self.stats["cow_copies"]
        return out

    def prefix_digests(self, limit=None):
        """Most-recently-used root digests — the compact advertisement
        behind the router's prefix-affinity placement."""
        return self.cache.digests(limit)

    def _admit(self) -> int:
        """Fill vacated slots from the waiting line: requests carrying
        prefilled KV frames (disaggregated handoff) are ADOPTED straight
        into their slots; prefix-trie hits adopt their cached pages and
        replay only the uncached suffix; the rest go through ONE padded
        (slots, bucket) prefill-into-pages dispatch (cold rows with a
        forced prefix join the suffix replay afterwards); stream each
        admitted row's first token. Respects the free-page watermark,
        evicting idle cached pages before refusing admission."""
        free = [i for i, s in enumerate(self._slots) if s is None]
        if not free or not self._pending:
            return 0
        reg = _tel.registry()
        version = getattr(self._engine, "weights_version", None)
        if self.cache.enabled and version != self._cache_tag:
            # weights hot-swapped mid-serving: every cached page holds
            # KV from the OLD weights — serving it would silently mix
            # model versions
            self.cache.flush()
            self._cache_tag = version
        picked = []
        while free and self._pending:
            if self.pool.free_pages - len(picked) <= self._admit_free_pages \
                    and self.pool.pages_in_use > 0:
                # cached-but-unreferenced pages are reclaimable
                # headroom: the trie yields before admission stalls
                short = self._admit_free_pages + len(picked) + 1 \
                    - self.pool.free_pages
                if self.cache.evict(short) == 0:
                    break  # keep headroom for requests already decoding
            r = self._pending.popleft()
            slot = free.pop(0)
            ok, hit = self._stage_slot(slot, r)
            if not ok:
                self._pending.appendleft(r)
                free.insert(0, slot)
                break
            picked.append((slot, r, hit))
        reg.histogram("infer/admitted_per_iter").observe(len(picked))
        if not picked:
            return 0
        hit_rows = [(slot, hit) for slot, _r, hit in picked
                    if hit is not None]
        if hit_rows:
            try:
                _faults.fire("batcher.dispatch", tag=self.name)
                self._apply_prefix_hits(hit_rows)
            except Exception as e:  # noqa: BLE001 - fail futures, not thread
                for _slot, r, _hit in picked:
                    if not r.future.done():
                        r.future._fail(e)
                self._poison(e)
                return 0
        adopt, cold, suffix = [], [], []
        for slot, r, hit in picked:
            if r.frames is not None and self._adopt(slot, r.frames):
                adopt.append((slot, r))
                continue
            if r.frames is not None:
                # handoff arrived but cannot be adopted (mismatched
                # geometry / torn frames): fall back to a local
                # prefill from the prompt — the request still serves
                r.frames = None
                with self._stats_lock:
                    self.stats["re_prefills"] += 1
                reg.counter("disagg/re_prefills").inc()
            if hit is not None:
                suffix.append((slot, r, hit))
            else:
                cold.append((slot, r))
                if r.prefix is not None:
                    # forced history, nothing cached: BOS-prime first,
                    # then replay the whole prefix through the SAME
                    # suffix program a cache hit uses (bit-identity)
                    suffix.append((slot, r, None))
        n_admitted = 0
        if adopt:
            t_admit = time.perf_counter()
            for slot, r in adopt:
                fr = r.frames
                r.frames = None
                s = _Slot(r, self._seq)
                self._seq += 1
                s.length = int(fr["length"])
                s.carry = int(fr["carry"])
                s.emitted = [int(t) for t in fr["emitted"]]
                s.version = version
                s.active_at = t_admit
                self._slots[slot] = s
                self._seed_from_frames(slot, r, fr)
                r.future.queue_wait_ms = \
                    (t_admit - r.future.enqueued_at) * 1e3
                self._note_wait(max(r.future.queue_wait_ms, 0.0))
                reg.histogram("infer/queue_wait_ms").observe(
                    max(r.future.queue_wait_ms, 0.0))
                r.future.phases = {
                    "queue_ms": max(r.future.queue_wait_ms, 0.0),
                    "prefill_ms": 0.0, "adopted": True}
                if _tracing.trace_enabled():
                    _tracing.span("trace.queue",
                                  _evus(r.future.enqueued_at),
                                  {"replica": self.name},
                                  request_id=r.future.request_id,
                                  end_us=_evus(t_admit))
                    _tracing.span("trace.adopt", _evus(t_admit),
                                  {"replica": self.name,
                                   "tokens": len(s.emitted)},
                                  request_id=r.future.request_id)
                r.future._stream_tokens(list(s.emitted))
                ttft = (r.future.first_token_at
                        - r.future.enqueued_at) * 1e3
                reg.histogram("infer/ttft_ms").observe(ttft)
                self._note_ttft(ttft)
                if s.carry == self._engine._eos \
                        or len(s.emitted) >= r.max_new:
                    s.finished = True
            with self._stats_lock:
                self.stats["adopted"] += len(adopt)
            n_admitted += len(adopt)
            reg.counter("disagg/handoffs").inc(len(adopt))
        if cold:
            bucket = self._bucket_for(
                max(r.prompt.shape[0] for _, r in cold))
            # admission sub-batch menu: the prefill dispatch shape is
            # the smallest power-of-two row count covering the admitted
            # set, so a single-request admission costs a (1, bucket)
            # forward, not a full (slots, bucket) one — admission-heavy
            # (short-response) loads would otherwise spend more on
            # prefill than on decode
            rows = 1
            while rows < len(cold):
                rows *= 2
            rows = min(rows, self.slots)
            src = _np.full((rows, bucket), self._pad, _np.int32)
            vl = _np.full((rows,), bucket, _np.int32)
            slot_ids = _np.full((rows,), self.slots, _np.int32)  # OOB
            first_pages = _np.zeros((rows,), _np.int32)
            active = _np.zeros((rows,), bool)
            for i, (slot, r) in enumerate(cold):
                n = r.prompt.shape[0]
                src[i, :n] = r.prompt
                vl[i] = n
                slot_ids[i] = slot
                first_pages[i] = self.pool.table[slot, 0]
                active[i] = True
            t0 = time.perf_counter()
            try:
                _faults.fire("batcher.dispatch", tag=self.name)
                tok0, self._state = self._engine.prefill_paged(
                    self._state, src, vl, slot_ids, first_pages, active,
                    seed=self._iter, **self._sampling)
                if self._spec_on:
                    # prime the draft's KV over the same prompt rows;
                    # best-effort — prefix-hit/adopted rows skip this
                    # (an unprimed draft only lowers acceptance, never
                    # correctness: verification is always the target)
                    _, self._dstate = self._engine.draft.prefill_paged(
                        self._dstate, src, vl, slot_ids, first_pages,
                        active, seed=self._iter, **self._sampling)
                tok0 = tok0.asnumpy()
            except Exception as e:  # noqa: BLE001 - fail futures, not thread
                for slot, r, _hit in picked:
                    if not r.future.done():
                        r.future._fail(e)
                self._poison(e)
                return 0
            prefill_ms = (time.perf_counter() - t0) * 1e3
            reg.histogram("infer/prefill_ms").observe(prefill_ms)
            for i, (slot, r) in enumerate(cold):
                if r.prefix is not None:
                    # its first token comes from the suffix replay; the
                    # BOS-prime sample is overridden by the forced
                    # history
                    continue
                self._activate(slot, r, int(tok0[i]), t0, version, 1)
                n_admitted += 1
        if suffix:
            srows = 1
            while srows < len(suffix):
                srows *= 2
            srows = min(srows, self.slots)
            need = 0
            plans = []
            for slot, r, hit in suffix:
                target = [self._engine._bos] + [int(t) for t in r.prefix]
                start = hit.matched if hit is not None else 1
                plans.append((slot, r, target, start))
                need = max(need, len(target) - start)
            s_len = next(s for s in self._suffix_menu if s >= need)
            toks = _np.zeros((srows, s_len), _np.int32)
            vl_s = _np.ones((srows,), _np.int32)
            q_off = _np.zeros((srows,), _np.int32)
            tables = _np.zeros((srows, self.pages_per_slot), _np.int32)
            sids = _np.full((srows,), self.slots, _np.int32)  # OOB
            act = _np.zeros((srows,), bool)
            for i, (slot, r, target, start) in enumerate(plans):
                tail = target[start:]
                toks[i, :len(tail)] = tail
                vl_s[i] = len(tail)
                q_off[i] = start
                tables[i] = self.pool.table[slot]
                sids[i] = slot
                act[i] = True
            t1 = time.perf_counter()
            try:
                _faults.fire("batcher.dispatch", tag=self.name)
                tokS, self._state = self._engine.prefill_suffix_paged(
                    self._state, toks, vl_s, q_off, tables, sids, act,
                    seed=self._iter, wide=self.suffix_wide,
                    **self._sampling)
                tokS = tokS.asnumpy()
            except Exception as e:  # noqa: BLE001 - fail futures, not thread
                for slot, r, _hit in picked:
                    if not r.future.done():
                        r.future._fail(e)
                self._poison(e)
                return 0
            reg.histogram("infer/prefill_ms").observe(
                (time.perf_counter() - t1) * 1e3)
            for i, (slot, r, target, start) in enumerate(plans):
                self._activate(slot, r, int(tokS[i]), t1, version,
                               len(target))
                n_admitted += 1
        with self._stats_lock:
            self.stats["admitted"] += n_admitted
        if self.cache.enabled:
            reg.gauge("infer/prefix_hit_rate").set(self.cache.hit_rate())
            reg.gauge("infer/pages_shared").set(self.pool.shared_pages)
        return n_admitted

    def _activate(self, slot: int, r, first_tok: int, t0: float,
                  version, length: int) -> None:
        """Install the freshly-prefilled request into its slot and
        stream its first sampled token (TTFT instant): shared by the
        cold-prefill and suffix-replay admission paths."""
        reg = _tel.registry()
        s = _Slot(r, self._seq)
        self._seq += 1
        s.length = length  # cached target positions (prime + prefix)
        s.carry = first_tok
        s.version = version
        s.active_at = time.perf_counter()
        s.emitted.append(s.carry)
        self._slots[slot] = s
        r.future.queue_wait_ms = (t0 - r.future.enqueued_at) * 1e3
        self._note_wait(max(r.future.queue_wait_ms, 0.0))
        reg.histogram("infer/queue_wait_ms").observe(
            max(r.future.queue_wait_ms, 0.0))
        r.future.phases = {
            "queue_ms": max(r.future.queue_wait_ms, 0.0),
            "prefill_ms": (s.active_at - t0) * 1e3}
        if _tracing.trace_enabled():
            _tracing.span("trace.queue", _evus(r.future.enqueued_at),
                          {"replica": self.name},
                          request_id=r.future.request_id,
                          end_us=_evus(t0))
            _tracing.span("trace.prefill", _evus(t0),
                          {"replica": self.name},
                          request_id=r.future.request_id,
                          end_us=_evus(s.active_at))
        r.future._stream_tokens([s.carry])
        ttft = (r.future.first_token_at - r.future.enqueued_at) * 1e3
        reg.histogram("infer/ttft_ms").observe(ttft)
        self._note_ttft(ttft)
        if s.carry == self._engine._eos or len(s.emitted) >= r.max_new:
            s.finished = True

    def _ensure_capacity(self, live):
        """Grow page allocations so every live row can cache
        ``iter_tokens`` more entries; on pool exhaustion PREEMPT the
        youngest row (free its pages, restart it from its prompt at the
        queue head) rather than stalling the whole batch."""
        for i in list(live):
            s = self._slots[i]
            if s is None or s.finished:
                continue  # preempted/bounced by an earlier row's fight
            # a row near its max_new needs less than a full burst; beyond
            # its allocation the device's surplus burst steps land in the
            # trash page, so the cap is safe. A speculative round writes
            # up to spec_k entries ahead and ACCEPTED entries must land
            # in real pages, so the cap stretches by spec_k too.
            base = 1 + (0 if s.req.prefix is None
                        else int(s.req.prefix.shape[0]))
            if self._spec_on:
                grow = self.spec_k + 1
                cap = base + s.req.max_new + self.spec_k
            else:
                grow = self.iter_tokens
                cap = base + s.req.max_new
            upto = min(s.length + grow, cap)
            while not self.pool.ensure(i, upto):
                # idle cached pages yield before any live row is
                # preempted — the trie is a cache, not a tenant
                if self.cache.evict(1) > 0:
                    continue
                victims = [j for j in range(self.slots)
                           if self._slots[j] is not None
                           and not self._slots[j].finished and j != i]
                if not victims:
                    # nothing left to preempt: this request cannot make
                    # progress right now — bounce it back to the caller
                    with self._stats_lock:
                        self.stats["rejected"] += 1
                    _tel.registry().counter(
                        "infer/rejected_backpressure").inc()
                    s.req.future._fail(Backpressure(
                        f"{self._label()}: page pool exhausted "
                        f"({self.pool.free_pages} free) with nothing to "
                        "preempt"))
                    self.pool.release(i)
                    self._slots[i] = None
                    break
                j = max(victims,
                        key=lambda x: self._slots[x].admitted_seq)
                self._preempt(j)

    def _preempt(self, slot):
        """Recompute-style preemption: free the slot's pages and restart
        the request from its prompt at the head of the line (greedy
        decoding regenerates the identical tokens)."""
        s = self._slots[slot]
        self.pool.release(slot)
        self._slots[slot] = None
        s.req.future._stream_reset()
        self._pending.appendleft(s.req)
        with self._stats_lock:
            self.stats["preempted"] += 1
        _tel.registry().counter("infer/preempted").inc()

    def _dispatch(self, live):
        """One decode-iteration dispatch over the slot batch: pure
        staging + the jitted ``InferStep.decode_iter`` call — linted
        sync-free (``tools/check_no_sync_in_step.py``); the host reads
        happen in ``_collect`` after the device work is in flight.

        With speculation on, the iteration is one draft proposal burst
        (k tokens per live slot against the draft's pools) plus ONE
        target verification dispatch scoring all k+1 positions; both
        engines' weights come from one coherent ``spec_pair()`` snapshot
        so a concurrent hot swap can never mix draft/target versions."""
        _faults.fire("batcher.hang", tag=self.name)
        _faults.fire("batcher.dispatch", tag=self.name)
        tokens = _np.zeros((self.slots,), _np.int32)
        lengths = _np.zeros((self.slots,), _np.int32)
        active = _np.zeros((self.slots,), bool)
        for i in live:
            s = self._slots[i]
            tokens[i] = s.carry
            lengths[i] = s.length
            active[i] = True
        self._iter += 1
        if self._spec_on:
            pair = self._engine.spec_pair()
            t_d = time.perf_counter()
            dbuf, self._dstate = self._engine.spec_draft(
                self._dstate, self.pool.table, tokens, lengths, active,
                k=self.spec_k, pair=pair, seed=self._iter)
            draft_ms = (time.perf_counter() - t_d) * 1e3
            buf, self._state = self._engine.spec_verify(
                self._state, self.pool.table, dbuf, tokens, lengths,
                active, pair=pair, wide=self.spec_wide)
            return buf, pair[2], draft_ms
        version = getattr(self._engine, "weights_version", None)
        buf, self._state = self._engine.decode_iter(
            self._state, self.pool.table, tokens, lengths, active,
            steps=self.iter_tokens, seed=self._iter, **self._sampling)
        return buf, version

    def _collect(self, live, out, t0):
        """Read back the iteration's token block — the scheduler's ONE
        sync point — then stream, account lengths, and mark retirements
        for the next iteration's safe point."""
        if self._spec_on:
            buf, version, draft_ms = out
        else:
            buf, version = out
            draft_ms = None
        toks = buf.asnumpy()
        iter_ms = (time.perf_counter() - t0) * 1e3
        reg = _tel.registry()
        emitted_total = 0
        eos = self._engine._eos
        if draft_ms is not None:
            reg.histogram("infer/spec_draft_ms").observe(draft_ms)
        for i in live:
            s = self._slots[i]
            fresh = []
            if self._spec_on:
                # row layout: [t_0..t_k, count]; count = accepted
                # drafts + the bonus token (0 for inactive rows).
                # Every emitted token is the target's own greedy
                # argmax — acceptance only decides how many land per
                # round, never which.
                burst = int(toks[i, self.spec_k + 1])
                reg.histogram("infer/spec_accept_len").observe(
                    max(burst - 1, 0))
            else:
                burst = self.iter_tokens
            for j in range(burst):
                tok = int(toks[i, j])
                s.length += 1  # this step cached the previous carry
                s.carry = tok
                fresh.append(tok)
                if tok == eos or len(s.emitted) + len(fresh) \
                        >= s.req.max_new:
                    s.finished = True
                    break
            s.emitted.extend(fresh)
            s.version = version
            emitted_total += len(fresh)
            s.req.future._stream_tokens(fresh)
        occupancy = len(live) / self.slots
        with self._stats_lock:
            self.stats["iterations"] += 1
            self.stats["occupancy_sum"] += occupancy
            self.stats["tokens"] += emitted_total
        reg.gauge("infer/batch_occupancy").set(occupancy)
        reg.gauge("infer/pages_in_use").set(self.pool.pages_in_use)
        reg.gauge("infer/page_fragmentation").set(self.pool.fragmentation(
            [s.length if s is not None else 0 for s in self._slots]))
        if emitted_total:
            reg.histogram("infer/decode_ms_per_token").observe(
                iter_ms / emitted_total)
            reg.gauge("infer/tokens_per_sec").set(
                emitted_total / (iter_ms / 1e3))
        wd = self._watchdog
        if wd is not None:
            wd.notify_step(seconds=iter_ms / 1e3)
            wd.note_request(inflight=len(live) + len(self._pending))

    def _poison(self, err):
        """A decode dispatch failed: the donated pool state is gone, so
        fail every in-flight request, rebuild the pools, and keep the
        thread alive for fresh work (mirrors DynamicBatcher's
        fail-the-futures-not-the-thread contract)."""
        for i, s in enumerate(self._slots):
            if s is not None:
                if not s.req.future.done():
                    s.req.future._fail(err)
                self._slots[i] = None
        self.cache.flush()  # the pages the trie indexed no longer exist
        self.pool.reset()
        self._state = self._engine.init_paged_state(
            self.slots, self.num_pages, self.page_size, self.mem_len)
        if self._spec_on:
            self._dstate = self._engine.init_draft_state(
                self.slots, self.num_pages, self.page_size, self.mem_len)

    @property
    def sustained_occupancy(self) -> float:
        """Mean decode-batch occupancy across every iteration so far —
        the open-loop bench's headline gate (>= 0.9 under load)."""
        with self._stats_lock:
            n = self.stats["iterations"]
            return self.stats["occupancy_sum"] / n if n else 0.0
