"""Dynamic batcher: concurrent generation requests -> fixed-shape batches.

Serving traffic arrives one prompt at a time; the TPU wants full batches
of warmed shapes. ``DynamicBatcher`` bridges them:

- **Admission**: ``submit()`` enqueues a request and returns a
  ``GenerationResult`` future. A background dispatcher collects up to
  ``slots`` requests, waiting at most ``timeout_ms`` after the first
  arrival — the classic timeout-or-full policy (latency bound under
  trickle load, full batches under pressure).
- **Fixed (batch, bucket) slots**: every dispatch pads prompts to the
  smallest bucket-menu boundary that fits the batch and pads the batch
  itself to exactly ``slots`` rows (empty rows carry ``valid_length=0``,
  fully masked out of attention) — the engine only ever sees
  ``len(bucket_keys)`` decode signatures, all warmed by
  ``InferStep.warmup``, so steady-state serving never compiles.
- **Per-request detach**: each request resolves independently — its
  tokens are trimmed at ITS EOS (and its own ``max_new_tokens``) the
  moment the batch's decode returns, and the slot is free for the next
  dispatch; a long request never holds another request's result hostage.

Telemetry (``infer/`` family): ``queue_wait_ms`` per request,
``batch_occupancy`` per dispatch, ``prefill_ms``/``decode_ms_per_token``
/``tokens_per_sec`` per dispatch, ``requests``/``tokens`` counters.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Optional, Sequence

import numpy as _np

from ..base import MXNetError
from .. import telemetry as _tel
from . import faults as _faults

__all__ = ["DynamicBatcher", "GenerationResult", "DeadlineExceeded",
           "batcher_slots", "batcher_timeout_ms"]


class DeadlineExceeded(MXNetError):
    """A request's deadline passed while it was still queued (or before
    the router could place it) — it is FAILED, never dispatched late."""


def batcher_slots(default: int = 8) -> int:
    """``MXTPU_BATCHER_SLOTS``: batch rows per dispatch."""
    v = os.environ.get("MXTPU_BATCHER_SLOTS", "").strip()
    try:
        return int(v) if v else default
    except ValueError:
        return default


def batcher_timeout_ms(default: float = 10.0) -> float:
    """``MXTPU_BATCHER_TIMEOUT_MS``: admission window after the first
    request of a batch arrives."""
    v = os.environ.get("MXTPU_BATCHER_TIMEOUT_MS", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


class GenerationResult:
    """Future for one submitted request. ``result(timeout)`` blocks until
    the request's decode finished and returns the generated token list
    (trimmed at EOS); ``exception()`` surfaces a dispatch failure.
    ``weights_version`` tags which param set served the request (hot
    weight swap) and ``replica`` which engine replica ran it (router)."""

    __slots__ = ("_event", "_tokens", "_error", "enqueued_at",
                 "queue_wait_ms", "weights_version", "replica")

    def __init__(self):
        self._event = threading.Event()
        self._tokens = None
        self._error = None
        self.enqueued_at = time.perf_counter()
        self.queue_wait_ms = None
        self.weights_version = None
        self.replica = None

    def _resolve(self, tokens):
        self._tokens = tokens
        self._event.set()

    def _fail(self, err):
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def exception(self):
        return self._error

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("generation result not ready")
        if self._error is not None:
            raise self._error
        return self._tokens


class _Request:
    __slots__ = ("prompt", "max_new", "future", "deadline")

    def __init__(self, prompt, max_new, future, deadline=None):
        self.prompt = prompt
        self.max_new = max_new
        self.future = future
        self.deadline = deadline  # absolute perf_counter instant or None


class DynamicBatcher:
    """Admit concurrent generation requests into fixed (batch, bucket)
    engine dispatches.

    Parameters
    ----------
    engine : ``parallel.infer.InferStep`` over a decode-capable net.
    bucket_keys : ascending prompt-length menu (the warmup contract —
        ``engine.warmup([(slots, k) for k in bucket_keys], max_new)``
        compiles every shape this batcher can emit).
    slots : batch rows per dispatch (``MXTPU_BATCHER_SLOTS``).
    timeout_ms : admission window (``MXTPU_BATCHER_TIMEOUT_MS``).
    max_new_tokens : decode length of every dispatch (per-request
        ``max_new_tokens`` may only be <= this; results are trimmed).
    sampling : dict of ``decode_n`` sampling kwargs (method/top_k/
        temperature/seed) shared by the batch.
    warmup : drive the engine's prefill+decode programs for the whole
        menu at construction (recommended for serving).
    name : tag for telemetry and fault matching (``serving.faults``);
        the router names each replica's batcher after the replica.
    watchdog : optional ``telemetry.Watchdog`` notified after every
        resolved dispatch — its ``heartbeat.json`` is the router's
        liveness signal for this replica (a hung dispatch stops the
        notifications and the heartbeat goes stale).
    """

    def __init__(self, engine, bucket_keys: Sequence[int],
                 slots: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 max_new_tokens: int = 32, sampling: Optional[dict] = None,
                 pad_id: Optional[int] = None, warmup: bool = False,
                 start: bool = True, name: Optional[str] = None,
                 watchdog=None):
        if not getattr(engine, "supports_decode", False):
            raise MXNetError(
                "DynamicBatcher needs a decode-capable InferStep "
                "(net with prefill/decode_step)")
        self._engine = engine
        self.bucket_keys = sorted(int(k) for k in bucket_keys)
        if not self.bucket_keys:
            raise MXNetError("bucket_keys must be non-empty")
        self.slots = int(slots) if slots is not None else batcher_slots()
        self.timeout_s = (timeout_ms if timeout_ms is not None
                          else batcher_timeout_ms()) / 1e3
        self.max_new = int(max_new_tokens)
        self._sampling = dict(sampling or {})
        self._pad = int(pad_id) if pad_id is not None else engine._pad
        self.name = name
        self._watchdog = watchdog
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._stop = threading.Event()
        self._thread = None
        if warmup:
            engine.warmup([(self.slots, k) for k in self.bucket_keys],
                          max_new_tokens=self.max_new, **self._sampling)
        if start:
            self.start()

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mxtpu-batcher", daemon=True)
        self._thread.start()

    def stop(self, drain: bool = True, timeout: float = 30.0):
        """Stop the dispatcher; with ``drain`` (default) outstanding
        requests are dispatched first. Anything still queued when the
        thread is down is FAILED (a stopped batcher must never hold an
        unresolvable future)."""
        if drain and self.healthy:
            deadline = time.perf_counter() + timeout
            while not self._queue.empty() and \
                    time.perf_counter() < deadline:
                time.sleep(0.005)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        self.cancel_pending()

    @property
    def healthy(self) -> bool:
        """True while the dispatcher thread is alive and accepting — the
        router's per-replica liveness poll. Goes false on ``stop()`` and
        when the thread died (a crash outside the dispatch try)."""
        t = self._thread
        return t is not None and t.is_alive() and not self._stop.is_set()

    def cancel_pending(self, error: Optional[BaseException] = None) -> int:
        """Drain the queue, failing every undispatched request's future
        (default error: RuntimeError naming the batcher). The router uses
        this when evicting an unhealthy replica — the failed futures are
        its signal to resubmit those requests elsewhere. Returns how many
        requests were cancelled."""
        n = 0
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                return n
            r.future._fail(error if error is not None else RuntimeError(
                f"DynamicBatcher{f' {self.name!r}' if self.name else ''} "
                "stopped with this request still queued"))
            n += 1

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------- requests
    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> GenerationResult:
        """Enqueue one prompt (1-D int sequence). Returns a future whose
        ``result()`` is the generated token list, trimmed at EOS and at
        the request's ``max_new_tokens`` (<= the batcher's).

        ``deadline_ms`` bounds the request's total latency from NOW: a
        request still queued when its deadline passes is failed with
        ``DeadlineExceeded`` instead of being dispatched late.

        Submitting to a stopped (or crashed) batcher fails the future
        immediately with a RuntimeError — a request must never enqueue
        behind a dispatcher that will not run again."""
        prompt = _np.asarray(prompt_ids, dtype=_np.int32).reshape(-1)
        if prompt.shape[0] > self.bucket_keys[-1]:
            raise MXNetError(
                f"prompt length {prompt.shape[0]} exceeds the largest "
                f"bucket key {self.bucket_keys[-1]}")
        max_new = self.max_new if max_new_tokens is None \
            else int(max_new_tokens)
        if max_new > self.max_new:
            raise MXNetError(
                f"request max_new_tokens {max_new} > batcher "
                f"max_new_tokens {self.max_new}")
        fut = GenerationResult()
        if not self.healthy:
            fut._fail(RuntimeError(
                f"DynamicBatcher{f' {self.name!r}' if self.name else ''} "
                "is not accepting requests (stopped, or its dispatcher "
                "thread died) — the request would never resolve"))
            return fut
        deadline = None if deadline_ms is None \
            else time.perf_counter() + float(deadline_ms) / 1e3
        self._queue.put(_Request(prompt, max_new, fut, deadline))
        return fut

    # ------------------------------------------------------------ dispatcher
    def _run(self):
        try:
            self._run_loop()
        except BaseException as e:
            # the thread is dying (a crash outside the dispatch try, e.g.
            # the `batcher.thread` fault point): fail whatever is queued
            # so no future is left unresolvable, then let it die —
            # `healthy` flips false and the router (if any) takes over
            self.cancel_pending(RuntimeError(
                f"DynamicBatcher{f' {self.name!r}' if self.name else ''} "
                "dispatcher thread died"))
            # injected deaths exit quietly (the crash is the test's
            # point); real crashes re-raise for the interpreter's
            # thread-exception hook
            if not isinstance(e, _faults.FaultInjected):
                raise

    def _run_loop(self):
        while not self._stop.is_set():
            # fault point: an unhandled crash of the dispatcher thread
            # (NOT caught by the dispatch try below) — a dead replica
            _faults.fire("batcher.thread", tag=self.name)
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            reqs = [first]
            deadline = time.perf_counter() + self.timeout_s
            while len(reqs) < self.slots:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    reqs.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            reqs = self._expire(reqs)
            if not reqs:
                continue
            t0 = time.perf_counter()
            try:
                out = self._dispatch(reqs)
            except Exception as e:  # noqa: BLE001 - fail the futures, not the thread
                for r in reqs:
                    r.future._fail(e)
                continue
            self._resolve(reqs, out, t0)

    def _expire(self, reqs):
        """Fail (never dispatch) requests whose deadline passed while
        they were queued. Runs BEFORE batch assembly, so expired rows
        don't occupy slots and the occupancy/queue-wait telemetry of the
        dispatched batch is unaffected."""
        now = time.perf_counter()
        live = []
        for r in reqs:
            if r.deadline is not None and now > r.deadline:
                _tel.registry().counter("serve/deadline_exceeded").inc()
                r.future._fail(DeadlineExceeded(
                    f"request deadline passed after "
                    f"{(now - r.future.enqueued_at) * 1e3:.0f} ms in "
                    "queue — not dispatched"))
            else:
                live.append(r)
        return live

    def _bucket_for(self, max_len):
        for k in self.bucket_keys:
            if max_len <= k:
                return k
        raise MXNetError(
            f"prompt length {max_len} > largest bucket key "
            f"{self.bucket_keys[-1]}")

    def _dispatch(self, reqs):
        """Assemble one fixed (slots, bucket) batch and fire the engine.
        Pure staging + dispatch — linted sync-free
        (``tools/check_no_sync_in_step.py``): the host reads happen in
        ``_resolve`` after the device work is in flight."""
        _faults.fire("batcher.hang", tag=self.name)
        _faults.fire("batcher.dispatch", tag=self.name)
        bucket = self._bucket_for(max(r.prompt.shape[0] for r in reqs))
        src = _np.full((self.slots, bucket), self._pad, _np.int32)
        vl = _np.zeros((self.slots,), _np.int32)
        for i, r in enumerate(reqs):
            n = r.prompt.shape[0]
            src[i, :n] = r.prompt
            vl[i] = n
        # the version THIS dispatch serves, captured with the dispatch:
        # responses are tagged with it even if a hot swap flips the
        # engine's live buffer before the results are read back
        version = getattr(self._engine, "weights_version", None)
        out = self._engine.decode_n(
            src, vl, max_new_tokens=self.max_new, **self._sampling)
        return out, version

    def _resolve(self, reqs, out, t0):
        """Per-request detach: trim each row at its EOS / its own
        ``max_new_tokens`` and resolve its future. The host read here is
        the sync point of the whole pipeline."""
        (tokens_nd, lengths_nd), version = out
        tokens = tokens_nd.asnumpy()
        lengths = lengths_nd.asnumpy()
        dispatch_ms = (time.perf_counter() - t0) * 1e3
        now = time.perf_counter()
        reg = _tel.registry()
        emitted = 0
        for i, r in enumerate(reqs):
            n = min(int(lengths[i]), r.max_new)
            r.future.queue_wait_ms = (now - r.future.enqueued_at) * 1e3 \
                - dispatch_ms
            reg.histogram("infer/queue_wait_ms").observe(
                max(r.future.queue_wait_ms, 0.0))
            emitted += n
            r.future.weights_version = version
            r.future.replica = self.name
            r.future._resolve(tokens[i, :n].tolist())
        wd = self._watchdog
        if wd is not None:
            wd.notify_step(seconds=dispatch_ms / 1e3)
        reg.counter("infer/requests").inc(len(reqs))
        reg.counter("infer/tokens").inc(emitted)
        reg.gauge("infer/batch_occupancy").set(len(reqs) / self.slots)
        reg.histogram("infer/prefill_ms").observe(dispatch_ms)
        if emitted:
            reg.histogram("infer/decode_ms_per_token").observe(
                dispatch_ms / emitted)
            reg.gauge("infer/tokens_per_sec").set(
                emitted / (dispatch_ms / 1e3))
