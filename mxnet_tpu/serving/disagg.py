"""Disaggregated prefill/decode: KV handoff across the process boundary.

Prefill is compute-bound and bursty; decode is memory-bound and steady.
Co-scheduling them on one worker makes TTFT and tokens/sec fight for the
same dispatch slots (DistServe/Splitwise observation). This module is
the mechanics of splitting the roles over the PR-10 transport:

- ``worker_role()`` (``MXTPU_ROLE``): each ``serving.worker`` process is
  ``both`` (the co-scheduled default), ``prefill`` (runs admission
  prefills only, ships the filled KV out) or ``decode`` (owns a
  long-running page pool and adopts shipped KV without re-prefilling).
- ``PrefillEngine``: a one-request-at-a-time prefill-into-pages front
  over an ``InferStep``. It owns a tiny private paged state (1 slot,
  1 allocatable page) whose OWNERSHIP passes through a one-slot queue
  (baton passing — no lock is ever held across the device dispatch, the
  shape the mxlint lock-order pass flags), runs the exact
  ``prefill_paged`` program the continuous batcher would, and extracts
  the filled page frames + slot metadata as host arrays.
- ``pack_frames``/``unpack_frames``: the ``kv_push`` wire format — a
  JSON meta dict (lengths, carry token, per-array dtype/shape) plus raw
  length-prefixed binary frames riding the JSON-frame RPC
  (``serving.transport``), one buffer per array, no pickle.
- ``spill_frames``/``load_spilled``: the shared-filesystem fallback
  (``MXTPU_KV_SPILL_DIR``): the prefill worker writes ``<handoff>.npz``
  (tmp + atomic rename, the commit protocol every file in this repo
  uses) and the decode worker adopts from disk — for fleets without
  worker-to-worker connectivity.
- ``HandoffStash``: the decode-side arrival buffer — ``kv_push`` frames
  land here (keyed by handoff id, bounded, oldest-evicted) until the
  router's ``submit`` for the same handoff id claims them.

Failure contract — zero lost requests by construction: every handoff
``submit`` carries the FULL prompt, so a push that never arrived, a
prefill worker that died mid-push, or frames that fail adoption
(mismatched geometry, torn spill file) all degrade to the decode worker
re-prefilling from the prompt (counted ``disagg/re_prefills``); greedy
tokens are bit-identical either way because adoption reproduces exactly
the state ``prefill_paged`` would have written locally.

Telemetry (``disagg/`` family): ``kv_push_ms`` (push wall, prefill
side), ``kv_bytes`` (frames shipped), ``handoffs`` (adoptions),
``re_prefills`` (fallbacks), ``ttft_interactive_ms``/``ttft_batch_ms``
(per-class time-to-first-token, router side).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError
from .. import telemetry as _tel
from . import pages as _pages

__all__ = ["worker_role", "kv_spill_dir", "handoff_ttl_s", "PrefillEngine",
           "pack_frames", "unpack_frames", "spill_frames", "load_spilled",
           "HandoffStash", "frame_bytes"]

ROLES = ("both", "prefill", "decode")

# frames are shipped in four per-layer groups, in this fixed order
_GROUPS = ("k", "v", "ck", "cv")


def worker_role(default: str = "both") -> str:
    """``MXTPU_ROLE``: this serving worker's place in a disaggregated
    fleet — ``both`` (co-scheduled prefill+decode, the default),
    ``prefill`` (admission prefills only; KV ships out over ``kv_push``)
    or ``decode`` (long-running page pools; adopts shipped KV)."""
    v = os.environ.get("MXTPU_ROLE", "").strip().lower()
    return v if v in ROLES else default


def kv_spill_dir() -> Optional[str]:
    """``MXTPU_KV_SPILL_DIR``: when set, prefill workers spill KV frames
    to ``<dir>/<handoff>.npz`` (atomic rename) instead of pushing them
    over a worker-to-worker socket — the shared-filesystem handoff for
    fleets where workers cannot dial each other."""
    v = os.environ.get("MXTPU_KV_SPILL_DIR", "").strip()
    return v or None


def handoff_ttl_s(default: float = 120.0) -> float:
    """``MXTPU_HANDOFF_TTL_S``: how long pushed KV frames may sit in the
    decode worker's ``HandoffStash`` before they expire (seconds; 0
    disables the TTL). A push whose matching ``submit`` never arrives —
    router died between push and submit, caller gave up — would
    otherwise pin its KV bytes until capacity eviction; expiry costs
    nothing (an expired handoff re-prefills from the prompt)."""
    v = os.environ.get("MXTPU_HANDOFF_TTL_S", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


# ------------------------------------------------------------------ frames
def frame_bytes(frames: dict) -> int:
    """Total payload bytes of one handoff's arrays (``disagg/kv_bytes``)."""
    return sum(np.asarray(a).nbytes
               for g in _GROUPS for a in frames[g])


def pack_frames(frames: dict) -> Tuple[dict, List[bytes]]:
    """Split a frames dict into (JSON-safe meta, raw binary buffers) for
    the ``kv_push`` verb. Buffer order is the meta's ``arrays`` order:
    the four groups in ``_GROUPS`` order, each layer-major."""
    meta = {"length": int(frames["length"]),
            "carry": int(frames["carry"]),
            "emitted": [int(t) for t in frames["emitted"]],
            "mem_vl": int(frames["mem_vl"]),
            "layers": len(frames["k"]),
            "arrays": []}
    bufs: List[bytes] = []
    for g in _GROUPS:
        for a in frames[g]:
            a = np.ascontiguousarray(a)
            meta["arrays"].append({"group": g, "shape": list(a.shape),
                                   "dtype": a.dtype.name})
            bufs.append(a.tobytes())
    return meta, bufs


def unpack_frames(meta: dict, bufs: Sequence[bytes]) -> dict:
    """Inverse of :func:`pack_frames`; raises ``MXNetError`` on a
    meta/buffer mismatch (a torn push must fail adoption loudly, the
    caller then re-prefills)."""
    specs = meta.get("arrays", [])
    if len(specs) != len(bufs):
        raise MXNetError(
            f"kv_push carried {len(bufs)} binary frames for "
            f"{len(specs)} declared arrays")
    frames = {"length": int(meta["length"]), "carry": int(meta["carry"]),
              "emitted": [int(t) for t in meta.get("emitted", ())],
              "mem_vl": int(meta["mem_vl"])}
    for g in _GROUPS:
        frames[g] = []
    for spec, buf in zip(specs, bufs):
        a = np.frombuffer(buf, dtype=np.dtype(spec["dtype"]))
        a = a.reshape([int(d) for d in spec["shape"]])
        frames[spec["group"]].append(a)
    if len(frames["k"]) != meta.get("layers"):
        raise MXNetError("kv_push frame groups do not cover every layer")
    return frames


def spill_frames(directory: str, handoff: str, frames: dict) -> str:
    """Write one handoff to ``<directory>/<handoff>.npz`` (tmp + atomic
    rename: a reader never observes a torn file). Returns the path."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{handoff}.npz")
    tmp = f"{path}.{os.getpid()}.tmp"
    arrays = {"meta": np.frombuffer(json.dumps({
        "length": int(frames["length"]), "carry": int(frames["carry"]),
        "emitted": [int(t) for t in frames["emitted"]],
        "mem_vl": int(frames["mem_vl"]),
        "layers": len(frames["k"])}).encode("utf-8"), np.uint8)}
    for g in _GROUPS:
        for i, a in enumerate(frames[g]):
            arrays[f"{g}{i}"] = np.ascontiguousarray(a)
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)
    return path


def load_spilled(directory: str, handoff: str,
                 unlink: bool = True) -> Optional[dict]:
    """Load (and by default consume) one spilled handoff; None when the
    file does not exist or cannot be read (the caller re-prefills)."""
    path = os.path.join(directory, f"{handoff}.npz")
    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z["meta"]).decode("utf-8"))
            frames = {"length": int(meta["length"]),
                      "carry": int(meta["carry"]),
                      "emitted": [int(t) for t in meta["emitted"]],
                      "mem_vl": int(meta["mem_vl"])}
            for g in _GROUPS:
                frames[g] = [z[f"{g}{i}"]
                             for i in range(int(meta["layers"]))]
    except Exception:  # noqa: BLE001 - missing/torn spill = re-prefill
        return None
    if unlink:
        try:
            os.unlink(path)
        except OSError:
            pass
    return frames


class HandoffStash:
    """Decode-side arrival buffer for pushed KV frames.

    ``kv_push`` handlers (transport connection threads) ``put`` frames
    keyed by handoff id; the matching ``submit`` handler ``pop``s them.
    Bounded two ways: past ``capacity`` entries the OLDEST is dropped,
    and an entry older than ``ttl_s`` (``MXTPU_HANDOFF_TTL_S``) expires
    on the next touch — a push whose submit never arrives (router died
    between the two, caller abandoned the request) must not pin KV
    bytes until 64 later pushes shove it out. Either way the request
    re-prefills; nothing is lost. Every touch holds the stash lock;
    nothing blocking runs under it."""

    def __init__(self, capacity: int = 64, ttl_s: Optional[float] = None):
        self.capacity = int(capacity)
        self.ttl_s = handoff_ttl_s() if ttl_s is None else float(ttl_s)
        self._lock = threading.Lock()
        self._frames: Dict[str, dict] = {}
        self._stamp: Dict[str, float] = {}
        self._order: List[str] = []
        self.dropped = 0
        self.expired = 0

    def _expire_locked(self, now: float) -> None:
        if self.ttl_s <= 0:
            return
        stale = [h for h in self._order
                 if now - self._stamp.get(h, now) > self.ttl_s]
        for h in stale:
            self._order.remove(h)
            self._frames.pop(h, None)
            self._stamp.pop(h, None)
            self.expired += 1
            _tel.registry().counter("disagg/stash_expired").inc()

    def put(self, handoff: str, frames: dict) -> None:
        now = time.monotonic()
        with self._lock:
            self._expire_locked(now)
            if handoff not in self._frames:
                self._order.append(handoff)
            self._frames[handoff] = frames
            self._stamp[handoff] = now
            while len(self._order) > self.capacity:
                old = self._order.pop(0)
                self._frames.pop(old, None)
                self._stamp.pop(old, None)
                self.dropped += 1

    def pop(self, handoff: str) -> Optional[dict]:
        with self._lock:
            self._expire_locked(time.monotonic())
            frames = self._frames.pop(handoff, None)
            if frames is not None:
                self._order.remove(handoff)
                self._stamp.pop(handoff, None)
            return frames

    def __len__(self) -> int:
        with self._lock:
            return len(self._order)


# ----------------------------------------------------------- prefill engine
class _PrefillItem:
    """One waiting prefill request in the engine's batching queue."""

    __slots__ = ("prompt", "bucket", "done", "frames", "error")

    def __init__(self, prompt, bucket):
        self.prompt = prompt
        self.bucket = bucket
        self.done = threading.Event()
        self.frames = None
        self.error = None


class PrefillEngine:
    """Batched prefill-into-pages + frame extraction — the compute half
    of a disaggregated fleet's prefill worker.

    Owns a private paged state sized for ``rows`` concurrent requests
    (slot ``i`` always uses page ``i + 1``; page 0 stays the trash
    page): concurrent RPC handler threads enqueue their prompts and
    whichever thread holds the STATE BATON drains up to ``rows`` pending
    requests OF ONE BUCKET into a single padded ``prefill_paged``
    dispatch — the identical jitted admission program (and admission
    batching economics) the continuous batcher uses, so a burst of
    pushes costs one dispatch, not one per request. Grouping by bucket
    keeps short interactive prompts off the long-prompt pad width. The
    pools and cross buffers are read back to host in ONE transfer per
    array per batch, then sliced per request.

    State ownership passes through a one-slot queue (baton passing): no
    lock is ever held across device work — the shape the mxlint
    lock-order pass flags.

    Bit-exactness contract: with identical weights, the frames a decode
    worker adopts reproduce exactly the pool/slot contents its own
    ``prefill_paged`` would have written — greedy decode continues
    bit-identically to the co-scheduled path.
    """

    def __init__(self, engine, bucket_keys: Sequence[int],
                 rows: int = 4, page_size: Optional[int] = None,
                 sampling: Optional[dict] = None, warmup: bool = True,
                 baton_timeout_s: float = 60.0):
        if not getattr(engine, "supports_paged", False):
            raise MXNetError(
                "PrefillEngine needs a paged-protocol InferStep "
                "(net with prefill_paged)")
        self._engine = engine
        self.bucket_keys = sorted(int(k) for k in bucket_keys)
        if not self.bucket_keys:
            raise MXNetError("bucket_keys must be non-empty")
        self.rows = max(int(rows), 1)
        self.mem_len = self.bucket_keys[-1]
        self.page_size = int(page_size) if page_size is not None \
            else _pages.page_size_default()
        self._sampling = dict(sampling or {})
        self._sampling.pop("seed", None)
        self._pad = engine._pad
        self.baton_timeout_s = float(baton_timeout_s)
        self._queue: "queue.Queue[_PrefillItem]" = queue.Queue()
        self._baton: "queue.Queue" = queue.Queue(maxsize=1)
        self._baton.put(engine.init_paged_state(
            self.rows, self.rows, self.page_size, self.mem_len))
        self.prefills = 0
        self.batches = 0
        if warmup:
            self._warmup()

    def _warmup(self):
        """Compile the admission program per bucket with fully inert
        rows (OOB slots, trash page) — same trick as the batcher's
        warmup — then mark the guard steady."""
        import jax

        state = self._baton.get(timeout=self.baton_timeout_s)
        try:
            for bucket in self.bucket_keys:
                src = np.zeros((self.rows, bucket), np.int32)
                vl = np.full((self.rows,), bucket, np.int32)
                tok0, state = self._engine.prefill_paged(
                    state, src, vl,
                    np.full((self.rows,), self.rows, np.int32),
                    np.zeros((self.rows,), np.int32),
                    np.zeros((self.rows,), bool), **self._sampling)
                jax.block_until_ready(tok0.data)
        finally:
            self._baton.put(state)
        self._engine.compile_guard.mark_steady()

    def _bucket_for(self, n: int) -> int:
        for k in self.bucket_keys:
            if n <= k:
                return k
        raise MXNetError(f"prompt length {n} > largest bucket key "
                         f"{self.bucket_keys[-1]}")

    def prefill(self, prompt_ids) -> dict:
        """Prefill one prompt (batched opportunistically with concurrent
        callers) and return its handoff frames: ``{length, carry,
        emitted, mem_vl, k[], v[], ck[], cv[]}`` with per-layer host
        arrays — ``k``/``v`` hold the ``length`` filled self-KV entries,
        ``ck``/``cv`` the ``mem_vl`` valid cross-attention
        projections."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        item = _PrefillItem(prompt, self._bucket_for(prompt.shape[0]))
        self._queue.put(item)
        deadline = time.monotonic() + self.baton_timeout_s
        while not item.done.wait(0.001):
            if time.monotonic() > deadline:
                raise MXNetError(
                    f"prefill timed out after {self.baton_timeout_s}s "
                    "waiting for the engine baton")
            try:
                state = self._baton.get_nowait()
            except queue.Empty:
                continue  # another caller is dispatching our batch
            try:
                state = self._serve_locked_out_batch(state)
            finally:
                self._baton.put(state)
        if item.error is not None:
            raise item.error
        return item.frames

    def _serve_locked_out_batch(self, state):
        """Drain the pending queue, group by bucket, and dispatch the
        SMALLEST bucket group first (up to ``rows`` of it) — interactive
        short prompts never wait behind a long-prompt pad width, the
        prefill-side analogue of batch-sheds-first. The rest requeues
        for the next baton holder. Runs on whichever caller thread won
        the baton; returns the (new) state. NB: no lock held —
        exclusivity comes from baton ownership."""
        pending: List[_PrefillItem] = []
        while True:
            try:
                pending.append(self._queue.get_nowait())
            except queue.Empty:
                break
        if not pending:
            return state
        bucket = min(item.bucket for item in pending)
        picked = [i for i in pending if i.bucket == bucket][:self.rows]
        for item in pending:
            if item not in picked:
                self._queue.put(item)
        try:
            state = self._dispatch_batch(state, picked, bucket)
        except Exception as e:  # noqa: BLE001 - fail the items, not the baton
            for item in picked:
                item.error = e
                item.done.set()
        return state

    def _dispatch_batch(self, state, picked, bucket):
        """One padded ``prefill_paged`` over the picked items; slot i /
        page i+1 per row; bulk host readback, per-item slicing."""
        rows = self.rows
        src = np.full((rows, bucket), self._pad, np.int32)
        vl = np.full((rows,), bucket, np.int32)
        slot_ids = np.full((rows,), rows, np.int32)  # OOB = inert row
        first_pages = np.zeros((rows,), np.int32)
        active = np.zeros((rows,), bool)
        for i, item in enumerate(picked):
            n = item.prompt.shape[0]
            src[i, :n] = item.prompt
            vl[i] = n
            slot_ids[i] = i
            first_pages[i] = i + 1
            active[i] = True
        tok0, state = self._engine.prefill_paged(
            state, src, vl, slot_ids, first_pages, active,
            **self._sampling)
        tok0 = np.asarray(tok0.asnumpy()).reshape(-1)
        # ONE host transfer per array per batch; items slice host-side
        k_pools = [np.asarray(p) for p in state["k_pools"]]
        v_pools = [np.asarray(p) for p in state["v_pools"]]
        cross_k = [np.asarray(c) for c in state["cross_k"]]
        cross_v = [np.asarray(c) for c in state["cross_v"]]
        for i, item in enumerate(picked):
            n = item.prompt.shape[0]
            carry = int(tok0[i])
            frames = {"length": 1, "carry": carry, "emitted": [carry],
                      "mem_vl": n, "k": [], "v": [], "ck": [], "cv": []}
            for li in range(len(k_pools)):
                frames["k"].append(k_pools[li][i + 1, :1].copy())
                frames["v"].append(v_pools[li][i + 1, :1].copy())
                frames["ck"].append(cross_k[li][i, :n].copy())
                frames["cv"].append(cross_v[li][i, :n].copy())
            item.frames = frames
            item.done.set()
        self.prefills += len(picked)
        self.batches += 1
        return state
