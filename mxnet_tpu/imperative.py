"""Imperative runtime: eager op dispatch with optional autograd recording.

TPU-native analogue of ``Imperative::Invoke`` in
``src/imperative/imperative.cc`` [unverified]. The reference's invoke path was:
infer shape/type -> allocate deferred outputs -> (maybe) record tape node ->
push FCompute closure to the dependency engine. Here the "engine push" is the
jax op call itself (XLA async dispatch), shape/dtype inference is implicit in
tracing, and recording captures a ``jax.vjp`` closure per invocation — the
tape node analogue of ``AGInfo``.

Two entry points:

- ``invoke_fn(fn, *args)``: dispatch a pure jax-level function over a mix of
  NDArray / raw operands. Used by NDArray operators and generated namespaces.
- ``invoke(op, *args, **params)``: dispatch a registered ``Operator`` by
  binding its keyword params first (reference: op ``Param`` structs).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import numpy as _np
from jax.core import Tracer as _Tracer

from . import telemetry as _tel
from .base import MXNetError
from .engine import engine
from .ndarray.ndarray import NDArray, _Pending
from .ops.registry import Operator, get as get_op

__all__ = ["invoke", "invoke_fn"]


def _wrap_outputs(outs, rec_nodes=None):
    from . import autograd

    single = not isinstance(outs, (tuple, list))
    outs_t = (outs,) if single else tuple(outs)
    wrapped = []
    for i, o in enumerate(outs_t):
        if isinstance(o, NDArray):  # fn may pass through
            wrapped.append(o)
            continue
        nd = NDArray(o)
        if rec_nodes is not None:
            autograd._mark_output(nd, rec_nodes, i)
        wrapped.append(nd)
    eng = engine()
    if not eng.is_async():
        eng.on_outputs([w.data for w in wrapped])
    return wrapped[0] if single else tuple(wrapped)


_DERIVE = object()  # sentinel: derive the jit key from fn itself


def invoke_fn(fn: Callable, *args, _jit_key=_DERIVE, **static_params):
    """Dispatch ``fn(*arrays, **static_params)`` eagerly with autograd support.

    ``args`` may contain NDArrays (tracked for autograd), jax arrays, numpy
    arrays, or python scalars. ``static_params`` are closed over (never
    differentiated). ``_jit_key`` (private): hashable key for the per-op
    jit cache, ``None`` to force the un-jitted path, or left at the
    sentinel to derive one from ``fn``'s code identity.
    """
    from . import autograd

    if static_params:
        fn = functools.partial(fn, **static_params)
    if _jit_key is _DERIVE:
        _jit_key = _fn_jit_key(fn)
    if _jit_key is not None and _EAGER_FWD_CACHE.get(_jit_key) is _FAILED:
        _jit_key = None
    if _jit_key is not None and _bulk_fwd_enabled():
        lazy = [_lazy_data(a) for a in args]
        if any(isinstance(d, _Tracer) for d in lazy):
            # inside an outer jax trace (TrainStep/hybridize staging):
            # deferring would leak tracers out of the transform — run now
            q = None
        else:
            q = _try_enqueue(_jit_key, fn, args, lazy,
                             autograd._should_record(args))
        if q is not None:
            outs, multi, node = q
            if node is not None:
                for i, o in enumerate(outs):
                    autograd._mark_output(o, node, i)
            return tuple(outs) if multi else outs[0]
    datas = [a.data if isinstance(a, NDArray) else a for a in args]
    if autograd._should_record(args):
        if _jit_key is not None:
            try:
                outs, node = autograd._record_cached(
                    _fwd_jit(_jit_key, fn), _bwd_jit(_jit_key, fn),
                    fn, args, datas, bulk_key=_jit_key)
                return _wrap_outputs(outs, rec_nodes=node)
            except Exception:
                outs, node = autograd._record(fn, args, datas)
                # the plain path succeeded: the failure was jit-specific
                # (trace-hostile fn) — blacklist. A user error would have
                # raised again just above, leaving the cache untouched.
                _EAGER_FWD_CACHE[_jit_key] = _FAILED
                return _wrap_outputs(outs, rec_nodes=node)
        outs, node = autograd._record(fn, args, datas)
        return _wrap_outputs(outs, rec_nodes=node)
    if _jit_key is not None:
        try:
            return _wrap_outputs(_fwd_jit(_jit_key, fn)(*datas))
        except Exception:
            out = _wrap_outputs(fn(*datas))  # user errors re-raise here
            _EAGER_FWD_CACHE[_jit_key] = _FAILED  # jit-specific failure
            return out
    return _wrap_outputs(fn(*datas))


# ------------------------------------------------- per-op jit cache (eager)
# The reference engineered its imperative hot loop around engine-push cost
# (SURVEY section 3.1); ours is per-op dispatch overhead: an eager op body
# of K jnp calls costs K XLA executions plus, under autograd.record, a
# fresh Python linearization through jax.vjp EVERY call (~ms of host work
# per op — profiled as THE eager bottleneck). The cure is one cached pair
# of jitted callables per (op, params) key:
#   fwd(key):  jit(fn)                      — primal, C++ cache fast path
#   bwd(key):  jit(lambda xs, ct: vjp(fn, *xs)[1](ct))
#              — recomputes the (tiny, dispatch-bound) forward inside the
#                backward instead of keeping per-call residual closures;
#                host cost collapses to a cached pjit call
# Keyed on hashable params only; ops whose bodies consume global RNG or
# produce data-dependent shapes are denied (a failed trace blacklists the
# key and falls back to the un-jitted path). MXTPU_EAGER_JIT=0 disables.
_EAGER_FWD_CACHE: dict = {}
_EAGER_BWD_CACHE: dict = {}
_EAGER_JIT_DENY = {
    "Dropout",   # draws from mx.random inside the body: jit would freeze
    "shuffle",   # the key as a compile-time constant
    "RNN",       # dropout path inside the scan body
    "Custom",    # python-callback custom ops manage their own tape/state
    "unique",    # data-dependent output shape
    "_contrib_boolean_mask",  # data-dependent output shape (host mask)
    # registry random samplers: key drawn in the body, same freeze hazard
    "_random_uniform", "_random_normal", "_random_gamma",
    "_random_exponential", "_random_poisson", "_random_randint",
    "sample_uniform", "sample_normal", "sample_gamma",
    "sample_exponential", "sample_poisson", "sample_multinomial",
}
_FAILED = object()

# ops whose BODIES read env vars at trace time: the var's current value
# must be part of the cache key, or flipping it after the first call is
# silently ignored (the trace froze the old branch — found when a
# long-context example measured flash == dense EXACTLY because both hit
# one cached executable)
_ENV_KEYED_OPS = {
    # (MXTPU_FLASH_BWD is NOT here: it binds at import; the runtime
    # switch is set_flash_backward(), which clears jax caches itself)
    "_contrib_flash_attention": ("MXTPU_ATTN_DENSE_MAX",),
    "BatchNorm": ("MXTPU_FUSED_BN",),
    "linear_cross_entropy": ("MXTPU_CE_DENSE_MAX_BYTES",),
}


def _env_fingerprint(op_name):
    import os

    keys = _ENV_KEYED_OPS.get(op_name)
    if not keys:
        return ()
    return tuple(os.environ.get(k) for k in keys)


def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    return v


def _jit_enabled() -> bool:
    import os

    return os.environ.get("MXTPU_EAGER_JIT", "1") != "0" \
        and engine().is_async()


# ----------------------------------------------- forward bulking (queue)
# The reference bulked contiguous eager op pushes into engine segments
# (``MXNET_GLUON_EXEC_BULK_SIZE``, ``src/imperative/imperative_utils.h``
# [unverified]); the TPU analogue: queue eligible op calls as _Pending
# NDArrays (shape/dtype known from a cached abstract eval) and flush the
# run as ONE jitted segment — one executable launch instead of one per
# op, which is the whole cost on a dispatch-latency-bound backend. Any
# value read (.data/.asnumpy/non-bulkable op) flushes, so laziness is
# invisible: the worst case is a segment of length 1.


def _bulk_size() -> int:
    from .base import env_int

    return env_int("MXNET_GLUON_EXEC_BULK_SIZE", 15)


_AVAL_CACHE: dict = {}  # (op key, input aval key) -> (out structs, multi)
_SEG_CACHE: dict = {}   # segment structural key -> jitted runner
_SEG_CAP = 512


class _BulkEntry:
    __slots__ = ("key", "fn", "datas", "chunks", "pendings", "node")

    def __init__(self, key, fn, datas, chunks, pendings, node):
        self.key = key
        self.fn = fn
        self.datas = datas      # captured operands (values / _Pending)
        self.chunks = chunks    # output _Chunk cells to write back
        self.pendings = pendings
        self.node = node        # deferred tape node (or None)


def _resolve(d):
    if type(d) is _Pending:
        return d.value
    return d


def _resolve_strict(d):
    """Resolve an operand, re-raising the producing op's failure for a
    dead pending instead of handing None downstream."""
    if type(d) is _Pending:
        if d.value is None:
            raise d.error or MXNetError(
                "bulk-queued operand was never produced (upstream op "
                "failed)")
        return d.value
    return d


import threading as _tls_threading

_FLUSH_TLS = _tls_threading.local()


def _flushing_queues() -> set:
    """ids of the _BulkQueues THIS thread is currently flushing (the
    re-entrance guard for mutual cross-queue dependencies)."""
    s = getattr(_FLUSH_TLS, "s", None)
    if s is None:
        s = _FLUSH_TLS.s = set()
    return s


def _entry_done(e) -> bool:
    """True when every output of the entry already carries a value or an
    error (resolved entry-by-entry during a re-entrant flush)."""
    return all(p.value is not None or p.error is not None
               for p in e.pendings)


def _lazy_data(a):
    """Operand capture WITHOUT forcing the queue: a live _Pending stays a
    slot reference; everything else is its concrete value."""
    if isinstance(a, NDArray):
        if a._view is None:
            d = a._chunk.data
            if type(d) is _Pending and d.value is not None:
                return d.value
            return d
        return a.data  # views force (rare on the hot path)
    return a


class _BulkQueue:
    def __init__(self):
        self.entries = []
        # queues are thread-local, but the NDArrays holding their
        # _Pending outputs are shareable: a foreign thread's flush must
        # wait out an in-flight flush, not observe its half-done state
        import threading

        self._lock = threading.RLock()

    def enqueue(self, key, fn, datas, out_structs, multi, node):
        pendings = [
            _Pending(self, s.shape, s.dtype,
                     getattr(s, "weak_type", False))
            for s in out_structs
        ]
        outs = [NDArray(p) for p in pendings]
        chunks = [o._chunk for o in outs]
        self.entries.append(
            _BulkEntry(key, fn, tuple(datas), chunks, pendings, node))
        if len(self.entries) >= _bulk_size():
            self.flush()
        return outs, multi

    def flush(self):
        # re-entrance guard (ADVICE r5): two queues holding mutually
        # dependent pendings (A reads B's, B reads A's) would otherwise
        # recurse A.flush -> B.flush -> A.flush ... to RecursionError —
        # the per-queue RLock is re-entrant, so nothing breaks the cycle.
        # The guard is PER THREAD (a set of queues this thread is already
        # flushing): a concurrent foreign-thread flush must still block
        # on the lock, not skip.
        flushing = _flushing_queues()
        if id(self) in flushing:
            return
        flushing.add(id(self))
        try:
            # resolve cross-thread dependencies BEFORE taking our own
            # lock: flushing a foreign queue while holding ours could
            # ABBA-deadlock two threads exchanging NDArrays. Our entries
            # list is only ever appended by this thread, so scanning it
            # lock-free is safe.
            for e in self.entries:
                for d in e.datas:
                    if type(d) is _Pending and d.value is None \
                            and d.error is None and d.queue is not self:
                        d.queue.flush()
                        if d.value is None and d.error is None:
                            # the producing queue's flush was re-entrant
                            # (mutual cross-queue dependency): resolve
                            # just the producing entry, following the
                            # dataflow DAG entry-by-entry — data deps
                            # cannot cycle, so this terminates
                            d.queue._resolve_entry_of(d)
            with self._lock:
                if _tel._ENABLED and self.entries:
                    with _tel.span("imperative.bulk_flush",
                                   {"ops": len(self.entries)}):
                        self._flush_locked()
                else:
                    self._flush_locked()
        finally:
            flushing.discard(id(self))

    def _resolve_entry_of(self, p):
        """Execute ONLY the entry producing pending ``p`` (plus, by
        recursion, its unresolved operands). Used when this queue's
        whole-queue flush is already on the caller's stack; the executed
        entries stay in ``entries`` and are skipped by ``_flush_locked``
        once their pendings carry values."""
        for e in self.entries:
            if any(x is p for x in e.pendings):
                if not _entry_done(e):
                    self._run_entry(e)
                return

    def _run_entry(self, e):
        """Eagerly execute one queued entry through the per-op jit cache
        (the ``_flush_fallback`` recipe for a single entry)."""
        args = []
        for d in e.datas:
            if type(d) is _Pending and d.value is None and d.error is None:
                if d.queue is self:
                    self._resolve_entry_of(d)
                else:
                    d.queue.flush()
                    if d.value is None and d.error is None:
                        d.queue._resolve_entry_of(d)
            args.append(_resolve_strict(d))
        try:
            try:
                outs = _fwd_jit(e.key, e.fn)(*args)
            except Exception:
                outs = e.fn(*args)
                _EAGER_FWD_CACHE[e.key] = _FAILED
        except Exception as exc:  # noqa: BLE001 - recorded per pending
            for p in e.pendings:
                p.error = exc
            raise
        outs_t = outs if isinstance(outs, (tuple, list)) else (outs,)
        for chunk, p, v in zip(e.chunks, e.pendings, outs_t):
            p.value = v
            if chunk.data is p:
                chunk.data = v
                chunk.version += 1
        if e.node is not None:
            e.node.xs = tuple(args)

    def _flush_locked(self):
        entries, self.entries = self.entries, []
        # entries already executed individually by _resolve_entry_of
        # (re-entrant cross-queue resolution) have their values written
        # back; only the rest form the fused segment
        entries = [e for e in entries if not _entry_done(e)]
        if not entries:
            return
        slot_of = {}
        for pos, e in enumerate(entries):
            for oi, p in enumerate(e.pendings):
                slot_of[id(p)] = (pos, oi)
        ext = []
        parts = []
        wirings = []
        for e in entries:
            wiring = []
            for d in e.datas:
                if type(d) is _Pending and d.value is None:
                    tgt = slot_of.get(id(d))
                    if tgt is None:
                        # foreign-queue pending (pre-resolved in flush();
                        # raced or failed cases surface the op's error)
                        v = _resolve_strict(d)
                        wiring.append(("ext", len(ext),
                                       (tuple(v.shape), str(v.dtype))))
                        ext.append(v)
                    else:
                        wiring.append(("slot",) + tgt)
                else:
                    v = _resolve(d)
                    if hasattr(v, "shape") and hasattr(v, "dtype"):
                        wiring.append(("ext", len(ext),
                                       (tuple(v.shape), str(v.dtype))))
                    else:
                        wiring.append(("ext", len(ext),
                                       ("py", type(v).__name__)))
                    ext.append(v)
            wirings.append(wiring)
            parts.append((e.key, tuple(wiring), len(e.pendings)))
        seg_key = tuple(parts)
        runner = _SEG_CACHE.get(seg_key)
        if runner is None:
            fns = [e.fn for e in entries]
            multis = [len(e.pendings) for e in entries]
            wir = [tuple(w) for w in wirings]

            def run(ext_ops):
                vals = []
                for i, fn in enumerate(fns):
                    args = []
                    for w in wir[i]:
                        if w[0] == "ext":
                            args.append(ext_ops[w[1]])
                        else:
                            args.append(vals[w[1]][w[2]])
                    o = fn(*args)
                    vals.append(tuple(o) if isinstance(o, (tuple, list))
                                else (o,))
                flat = []
                for v in vals:
                    flat.extend(v)
                return tuple(flat)

            import jax

            if len(_SEG_CACHE) >= _SEG_CAP:
                _SEG_CACHE.pop(next(iter(_SEG_CACHE)))
            runner = _SEG_CACHE[seg_key] = jax.jit(run)
        if runner is _FAILED:
            self._flush_fallback(entries)
            return
        try:
            results = runner(tuple(ext))
        except Exception:
            _SEG_CACHE[seg_key] = _FAILED
            self._flush_fallback(entries)
            return
        k = 0
        for e in entries:
            for chunk, p in zip(e.chunks, e.pendings):
                p.value = results[k]
                if chunk.data is p:
                    chunk.data = results[k]
                    chunk.version += 1
                k += 1
            if e.node is not None:
                e.node.xs = tuple(_resolve(d) for d in e.datas)

    def _flush_fallback(self, entries):
        """Per-entry execution through the per-op jit cache — correctness
        backstop when the fused segment refuses to trace. A failing
        entry must not poison its siblings: every entry still executes
        (or records its error on its pendings), and the FIRST failure
        re-raises after the sweep."""
        first_err = None
        for e in entries:
            try:
                datas = [_resolve_strict(d) for d in e.datas]
                try:
                    outs = _fwd_jit(e.key, e.fn)(*datas)
                except Exception:
                    outs = e.fn(*datas)
                    _EAGER_FWD_CACHE[e.key] = _FAILED
            except Exception as exc:  # noqa: BLE001 - recorded per pending
                for p in e.pendings:
                    p.error = exc
                if first_err is None:
                    first_err = exc
                continue
            outs_t = outs if isinstance(outs, (tuple, list)) else (outs,)
            for chunk, p, v in zip(e.chunks, e.pendings, outs_t):
                p.value = v
                if chunk.data is p:
                    chunk.data = v
                    chunk.version += 1
            if e.node is not None:
                e.node.xs = tuple(datas)
        if first_err is not None:
            raise first_err


import threading as _threading  # noqa: E402

_QUEUE_TLS = _threading.local()


def _queue() -> _BulkQueue:
    q = getattr(_QUEUE_TLS, "q", None)
    if q is None:
        q = _QUEUE_TLS.q = _BulkQueue()
    return q


def flush_bulk():
    """Flush any queued eager ops (public sync seam; waitall calls it)."""
    _queue().flush()


def _bulk_fwd_enabled() -> bool:
    from .base import env_bool

    return _bulk_size() > 0 and env_bool("MXTPU_BULK_FWD", True)


def _aval_key(d):
    # np.dtype objects hash by value — no stringification on the hot
    # path; weak_type is part of the promotion semantics so it must be
    # part of the key (a weak f32 scalar times bf16 gives bf16)
    if type(d) is _Pending:
        return (d.shape, d.dtype, d.weak_type)
    if hasattr(d, "shape") and hasattr(d, "dtype"):
        return (tuple(d.shape), d.dtype, getattr(d, "weak_type", False))
    return ("py", type(d))


def _try_enqueue(key, fn, args, datas, record):
    """Queue this op call; returns (outs, node) of _Pending NDArrays, or
    None when the op must execute now (unknown aval, scalar-output probes
    are fine — only trace failures disqualify)."""
    from . import autograd

    akey = (key, tuple(_aval_key(d) for d in datas))
    hit = _AVAL_CACHE.get(akey)
    if hit is _FAILED:
        return None
    if hit is None:
        import jax

        try:
            spec = [
                jax.ShapeDtypeStruct(
                    d.shape, _np.dtype(d.dtype),
                    weak_type=getattr(d, "weak_type", False))
                if (type(d) is _Pending
                    or (hasattr(d, "shape") and hasattr(d, "dtype")))
                else d
                for d in datas
            ]
            out = jax.eval_shape(fn, *spec)
        except Exception:
            _AVAL_CACHE[akey] = _FAILED
            return None
        multi = isinstance(out, (tuple, list))
        structs = tuple(out) if multi else (out,)
        if len(_AVAL_CACHE) >= _EAGER_CACHE_CAP:
            _AVAL_CACHE.pop(next(iter(_AVAL_CACHE)))
        hit = _AVAL_CACHE[akey] = (structs, multi)
    structs, multi = hit
    node = None
    if record:
        node = autograd._record_deferred(
            _bwd_jit(key, fn), fn, args,
            [(s.shape, _np.dtype(s.dtype)) for s in structs], multi,
            bulk_key=key)
    outs, multi = _queue().enqueue(key, fn, datas, structs, multi, node)
    return outs, multi, node


def _op_jit_key(op, params):
    """Cache key for a registered-op dispatch; None = do not jit."""
    if not _jit_enabled() or op.name in _EAGER_JIT_DENY \
            or getattr(op, "self_recording", False):
        return None
    for v in params.values():
        if isinstance(v, NDArray) or hasattr(v, "shape"):
            # array-valued params would be baked in as constants (and
            # NDArray rebinding would silently stale them) — stay eager
            return None
    try:
        key = ("op", op.name, _freeze(tuple(sorted(params.items()))),
               _env_fingerprint(op.name))
        hash(key)
    except TypeError:
        return None
    return key


def _holds_ndarray(v):
    """True if v is (or transitively contains) an NDArray. NDArray hashes
    by identity, so it would survive _freeze+hash and be baked into the
    executable while a later _rebind() of the same object silently went
    stale. jnp/np arrays are unhashable and already rejected by hash();
    np.dtype/np.generic hash by value and are safe to bake."""
    if isinstance(v, NDArray):
        return True
    if isinstance(v, (list, tuple)):
        return any(_holds_ndarray(x) for x in v)
    if isinstance(v, dict):
        return any(_holds_ndarray(x) for x in v.values())
    return False


def _fn_jit_key(fn):
    """Cache key for a bare function/lambda dispatch (NDArray method
    lambdas): the code object identity + closure values. The code object
    itself is part of the key (kept alive by the cache), so id reuse
    after GC cannot alias two different functions."""
    if not _jit_enabled():
        return None
    if isinstance(fn, functools.partial):
        inner = _fn_jit_key(fn.func)
        if inner is None or _holds_ndarray(fn.args) \
                or _holds_ndarray(fn.keywords):
            return None
        try:
            key = ("partial", inner, _freeze(tuple(sorted(fn.keywords.items()))),
                   _freeze(fn.args))
            hash(key)
        except TypeError:
            return None
        return key
    code = getattr(fn, "__code__", None)
    if code is None:
        # jnp ufuncs (NDArray arithmetic dispatches them directly) have
        # no __code__ but are pure stateless globals: key by the object
        # (kept alive by the cache, so id reuse cannot alias)
        import jax.numpy as jnp

        if isinstance(fn, jnp.ufunc):
            return ("ufunc", fn)
        return None
    cells = ()
    if fn.__closure__:
        try:
            cells = tuple(c.cell_contents for c in fn.__closure__)
        except ValueError:
            return None
        if _holds_ndarray(cells):
            return None
        try:
            cells = _freeze(cells)
            hash(cells)
        except (TypeError, ValueError):
            return None
    try:
        key = ("code", code, cells)
        hash(key)
    except TypeError:
        return None
    return key


_EAGER_CACHE_CAP = 2048  # keys; value-varying closures (loop-dependent
# slice bounds, schedules passed as op params) would otherwise mint
# wrappers + compiled executables without bound. FIFO eviction: dropping
# a wrapper frees its executables; a re-hit just re-jits.


def _cache_put(cache, key, value):
    if len(cache) >= _EAGER_CACHE_CAP:
        cache.pop(next(iter(cache)))
    cache[key] = value
    return value


def _fwd_jit(key, fn):
    j = _EAGER_FWD_CACHE.get(key)
    if j is None:
        import jax

        j = _cache_put(_EAGER_FWD_CACHE, key, jax.jit(fn))
    return j


def _bwd_jit(key, fn):
    j = _EAGER_BWD_CACHE.get(key)
    if j is None:
        import jax

        def bwd(xs, ct):
            _, vjp_fn = jax.vjp(fn, *xs)
            return vjp_fn(ct)

        j = _cache_put(_EAGER_BWD_CACHE, key, jax.jit(bwd))
    return j


def invoke(op, *args, out=None, **params):
    """Dispatch a registered operator (reference: ``MXImperativeInvokeEx``)."""
    if not isinstance(op, Operator):
        op = get_op(op)
    fn = functools.partial(op.fn, **params) if params else op.fn
    key = _op_jit_key(op, params)
    return _invoke_with(op, fn, key, args, out)


def _invoke_with(op, fn, key, args, out):
    if op.mutates_input is not None:
        # fused in-place update ops (optimizers): run unrecorded, rebind input
        target = args[op.mutates_input]
        datas = [a.data if isinstance(a, NDArray) else a for a in args]
        call = fn
        if key is not None and _EAGER_FWD_CACHE.get(key) is not _FAILED:
            call = _fwd_jit(key, fn)
        try:
            outs = call(*datas)
        except Exception:
            if call is fn:
                raise
            outs = fn(*datas)  # user errors re-raise here, no blacklist
            _EAGER_FWD_CACHE[key] = _FAILED  # jit-specific failure
        outs_t = outs if isinstance(outs, (tuple, list)) else (outs,)
        if isinstance(target, NDArray):
            target._rebind(outs_t[0])
            rest = [NDArray(o) for o in outs_t[1:]]
            return target if not rest else (target, *rest)
        return _wrap_outputs(outs)
    if getattr(op, "self_recording", False):
        # the op's fn builds its own tape entry (python/C++ custom ops
        # whose host bodies cannot consume jax tracers): hand it the
        # ORIGINAL NDArrays so its Function links to the caller's graph
        result = _wrap_outputs(fn(*args))
    else:
        result = invoke_fn(fn, *args, _jit_key=key)
    if out is not None:
        _bind_out(out, result)
        return out
    return result


def _bind_out(out, result):
    if isinstance(out, NDArray) and isinstance(result, NDArray):
        out._rebind(result.data)
        out._ag = result._ag  # keep the tape connected through out=
    elif isinstance(out, (tuple, list)) and isinstance(result, (tuple, list)):
        for o, r in zip(out, result):
            _bind_out(o, r)
    else:
        raise MXNetError("out= structure does not match op outputs")
