"""Imperative runtime: eager op dispatch with optional autograd recording.

TPU-native analogue of ``Imperative::Invoke`` in
``src/imperative/imperative.cc`` [unverified]. The reference's invoke path was:
infer shape/type -> allocate deferred outputs -> (maybe) record tape node ->
push FCompute closure to the dependency engine. Here the "engine push" is the
jax op call itself (XLA async dispatch), shape/dtype inference is implicit in
tracing, and recording captures a ``jax.vjp`` closure per invocation — the
tape node analogue of ``AGInfo``.

Two entry points:

- ``invoke_fn(fn, *args)``: dispatch a pure jax-level function over a mix of
  NDArray / raw operands. Used by NDArray operators and generated namespaces.
- ``invoke(op, *args, **params)``: dispatch a registered ``Operator`` by
  binding its keyword params first (reference: op ``Param`` structs).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

from .base import MXNetError
from .engine import engine
from .ndarray.ndarray import NDArray
from .ops.registry import Operator, get as get_op

__all__ = ["invoke", "invoke_fn"]


def _wrap_outputs(outs, rec_nodes=None):
    from . import autograd

    single = not isinstance(outs, (tuple, list))
    outs_t = (outs,) if single else tuple(outs)
    wrapped = []
    for i, o in enumerate(outs_t):
        if isinstance(o, NDArray):  # fn may pass through
            wrapped.append(o)
            continue
        nd = NDArray(o)
        if rec_nodes is not None:
            autograd._mark_output(nd, rec_nodes, i)
        wrapped.append(nd)
    eng = engine()
    if not eng.is_async():
        eng.on_outputs([w.data for w in wrapped])
    return wrapped[0] if single else tuple(wrapped)


def invoke_fn(fn: Callable, *args, **static_params):
    """Dispatch ``fn(*arrays, **static_params)`` eagerly with autograd support.

    ``args`` may contain NDArrays (tracked for autograd), jax arrays, numpy
    arrays, or python scalars. ``static_params`` are closed over (never
    differentiated).
    """
    from . import autograd

    if static_params:
        fn = functools.partial(fn, **static_params)
    datas = [a.data if isinstance(a, NDArray) else a for a in args]
    if autograd._should_record(args):
        outs, node = autograd._record(fn, args, datas)
        return _wrap_outputs(outs, rec_nodes=node)
    return _wrap_outputs(fn(*datas))


def invoke(op, *args, out=None, **params):
    """Dispatch a registered operator (reference: ``MXImperativeInvokeEx``)."""
    if not isinstance(op, Operator):
        op = get_op(op)
    fn = functools.partial(op.fn, **params) if params else op.fn
    if op.mutates_input is not None:
        # fused in-place update ops (optimizers): run unrecorded, rebind input
        target = args[op.mutates_input]
        datas = [a.data if isinstance(a, NDArray) else a for a in args]
        outs = fn(*datas)
        outs_t = outs if isinstance(outs, (tuple, list)) else (outs,)
        if isinstance(target, NDArray):
            target._rebind(outs_t[0])
            rest = [NDArray(o) for o in outs_t[1:]]
            return target if not rest else (target, *rest)
        return _wrap_outputs(outs)
    if getattr(op, "self_recording", False):
        # the op's fn builds its own tape entry (python/C++ custom ops
        # whose host bodies cannot consume jax tracers): hand it the
        # ORIGINAL NDArrays so its Function links to the caller's graph
        result = _wrap_outputs(fn(*args))
    else:
        result = invoke_fn(fn, *args)
    if out is not None:
        _bind_out(out, result)
        return out
    return result


def _bind_out(out, result):
    if isinstance(out, NDArray) and isinstance(result, NDArray):
        out._rebind(result.data)
        out._ag = result._ag  # keep the tape connected through out=
    elif isinstance(out, (tuple, list)) and isinstance(result, (tuple, list)):
        for o, r in zip(out, result):
            _bind_out(o, r)
    else:
        raise MXNetError("out= structure does not match op outputs")
