"""Tensor ops: elementwise, broadcast, reduce, linalg, indexing, ordering.

TPU-native analogue of ``src/operator/tensor/`` [unverified]
(elemwise_unary/binary_op, broadcast_reduce_op, dot, matrix_op, indexing_op,
ordering_op, init_op). The reference implemented each as mshadow/CUDA kernels
with registered FCompute/FGradient; here each lowers to ``jax.numpy`` — XLA
fuses elementwise chains into single kernels (replacing the reference's RTC
pointwise fusion pass, ``src/operator/fusion`` [unverified]) and gradients
derive from ``jax.vjp``.

Op names and parameter spellings follow the reference's Python surface
(``mx.nd.*``) so model code ports unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import register, alias

_f32 = jnp.float32


# --------------------------------------------------------------- elementwise
def _reg_unary(name, fn, aliases=()):
    register(name, aliases=aliases)(lambda data, **kw: fn(data))


_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "round": jnp.round,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": lambda x: jnp.trunc(x),
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "relu": lambda x: jnp.maximum(x, 0),
    "negative": jnp.negative,
    "reciprocal": jnp.reciprocal,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype),
}

for _name, _fn in _UNARY.items():
    _reg_unary(_name, _fn)

register("identity", aliases=["_copy", "stop_gradient_identity"])(
    lambda data, **kw: data + 0
)
register("BlockGrad", aliases=["stop_gradient"], differentiable=False)(
    lambda data, **kw: jax.lax.stop_gradient(data)
)


@register("checkpoint_name")
def checkpoint_name(data, name="saveable", **kw):
    """Tag a value for names-based remat policies
    (``remat='names:attn_out,...'`` keeps only tagged values resident;
    see ``mxnet_tpu.remat``). Identity outside a checkpointed trace."""
    from jax.ad_checkpoint import checkpoint_name as _ck

    return _ck(data, str(name))
register("cast", aliases=["Cast"])(
    lambda data, dtype="float32", **kw: data.astype(jnp.dtype(dtype))
)
register("clip")(lambda data, a_min=None, a_max=None, **kw: jnp.clip(data, a_min, a_max))
def _leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, **kw):
    """Reference: ``src/operator/leaky_relu.cc`` [unverified]; 'prelu' takes a
    learned per-channel slope tensor as second input."""
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        return jax.nn.selu(data)
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "prelu":
        g = gamma
        if g.ndim == 1 and data.ndim > 1 and g.shape[0] > 1:
            shape = [1] * data.ndim
            shape[1] = g.shape[0]
            g = g.reshape(shape)
        return jnp.where(data >= 0, data, g * data)
    raise ValueError(f"unknown LeakyReLU act_type {act_type!r}")


register("LeakyReLU")(_leaky_relu)
register("hard_sigmoid")(
    lambda data, alpha=0.2, beta=0.5, **kw: jnp.clip(alpha * data + beta, 0.0, 1.0)
)


# ----------------------------------------------------------- broadcast binop
def _reg_binary(name, fn, aliases=()):
    register(name, aliases=aliases)(lambda lhs, rhs, **kw: fn(lhs, rhs))


_BINARY = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "arctan2": jnp.arctan2,
}
for _name, _fn in _BINARY.items():
    _reg_binary(_name, _fn)

alias("elemwise_add", "broadcast_add")
alias("elemwise_sub", "broadcast_sub")
alias("elemwise_mul", "broadcast_mul")
alias("elemwise_div", "broadcast_div")
alias("maximum", "broadcast_maximum")
alias("minimum", "broadcast_minimum")

for _name, _fn in {
    "broadcast_equal": jnp.equal,
    "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater,
    "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less,
    "broadcast_lesser_equal": jnp.less_equal,
    "broadcast_logical_and": jnp.logical_and,
    "broadcast_logical_or": jnp.logical_or,
    "broadcast_logical_xor": jnp.logical_xor,
}.items():
    register(_name, differentiable=False)(
        lambda lhs, rhs, _fn=_fn, **kw: _fn(lhs, rhs).astype(lhs.dtype)
    )

register("broadcast_like")(
    lambda lhs, rhs, **kw: jnp.broadcast_to(lhs, rhs.shape)
)
register("broadcast_to")(
    lambda data, shape=None, **kw: jnp.broadcast_to(
        data, tuple(s if s != 0 else d for s, d in zip(shape, data.shape))
    )
)
register("broadcast_axis", aliases=["broadcast_axes"])(
    lambda data, axis=None, size=None, **kw: _broadcast_axis(data, axis, size)
)


def _broadcast_axis(data, axis, size):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    sizes = size if isinstance(size, (list, tuple)) else [size]
    shape = list(data.shape)
    for a, s in zip(axes, sizes):
        shape[a] = s
    return jnp.broadcast_to(data, tuple(shape))


# ------------------------------------------------------------------- reduce
def _reg_reduce(name, fn, aliases=()):
    def wrapper(data, axis=None, keepdims=False, exclude=False, **kw):
        ax = axis
        if exclude and ax is not None:
            axt = (ax,) if isinstance(ax, int) else tuple(ax)
            ax = tuple(i for i in range(data.ndim) if i not in axt)
        if isinstance(ax, list):
            ax = tuple(ax)
        return fn(data, axis=ax, keepdims=keepdims)

    register(name, aliases=aliases)(wrapper)


_reg_reduce("sum", jnp.sum, aliases=["sum_axis"])
_reg_reduce("mean", jnp.mean)
_reg_reduce("prod", jnp.prod)
_reg_reduce("nansum", jnp.nansum)
_reg_reduce("nanprod", jnp.nanprod)
_reg_reduce("max", jnp.max, aliases=["max_axis"])
_reg_reduce("min", jnp.min, aliases=["min_axis"])

register("norm")(
    lambda data, ord=2, axis=None, keepdims=False, **kw: jnp.linalg.norm(
        data, ord=ord, axis=axis if not isinstance(axis, list) else tuple(axis),
        keepdims=keepdims
    )
)
register("L2Normalization")(
    lambda data, eps=1e-10, mode="instance", **kw: data
    / jnp.sqrt(
        jnp.sum(
            jnp.square(data),
            axis=tuple(range(1, data.ndim)) if mode == "instance" else -1,
            keepdims=True,
        )
        + eps
    )
)
register("logsumexp")(
    lambda data, axis=None, keepdims=False, **kw: jax.scipy.special.logsumexp(
        data, axis=axis, keepdims=keepdims
    )
)

register("argmax", differentiable=False)(
    lambda data, axis=None, keepdims=False, **kw: _arg_reduce(jnp.argmax, data, axis, keepdims)
)
register("argmin", differentiable=False)(
    lambda data, axis=None, keepdims=False, **kw: _arg_reduce(jnp.argmin, data, axis, keepdims)
)


def _arg_reduce(fn, data, axis, keepdims):
    out = fn(data, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(_f32)


# ------------------------------------------------------------------- linalg
register("dot")(
    lambda lhs, rhs, transpose_a=False, transpose_b=False, **kw: jnp.dot(
        lhs.T if transpose_a else lhs, rhs.T if transpose_b else rhs
    )
)
register("batch_dot")(
    lambda lhs, rhs, transpose_a=False, transpose_b=False, **kw: jnp.matmul(
        jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs,
        jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs,
    )
)
register("khatri_rao")(lambda *args, **kw: _khatri_rao(args))


def _khatri_rao(mats):
    out = mats[0]
    for m in mats[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(-1, out.shape[-1])
    return out


# --------------------------------------------------------------- matrix ops
register("transpose")(
    lambda data, axes=None, **kw: jnp.transpose(data, tuple(axes) if axes else None)
)
register("expand_dims")(lambda data, axis=0, **kw: jnp.expand_dims(data, axis))
register("squeeze")(
    lambda data, axis=None, **kw: jnp.squeeze(
        data, tuple(axis) if isinstance(axis, (list, tuple)) else axis
    )
)
register("Reshape", aliases=["reshape"])(
    lambda data, shape=None, reverse=False, **kw: _mx_reshape(data, shape, reverse)
)


def _mx_reshape(data, shape, reverse=False):
    """MXNet reshape with 0 (copy dim) / -1 (infer) / -2.. special codes."""
    if reverse:
        # mxnet semantics: apply the special codes right-to-left
        out = _mx_reshape(jnp.reshape(data, data.shape[::-1]), tuple(shape)[::-1])
        return jnp.reshape(out, out.shape[::-1])
    new, src_i = [], 0
    shape = tuple(shape)
    for s in shape:
        if s == 0:
            new.append(data.shape[src_i])
            src_i += 1
        elif s == -2:
            new.extend(data.shape[src_i:])
            src_i = len(data.shape)
        elif s == -3:
            new.append(data.shape[src_i] * data.shape[src_i + 1])
            src_i += 2
        elif s == -4:
            continue  # handled by following two entries in mxnet; rare — skip
        else:
            new.append(s)
            if s != -1:
                src_i += 1
    return jnp.reshape(data, tuple(new))


register("Flatten", aliases=["flatten"])(
    lambda data, **kw: jnp.reshape(data, (data.shape[0], -1))
)
register("concat", aliases=["Concat"])(
    lambda *args, dim=1, **kw: jnp.concatenate(args, axis=dim)
)
register("stack")(lambda *args, axis=0, **kw: jnp.stack(args, axis=axis))


@register("split", aliases=["SliceChannel"], num_outputs=None)
def _split(data, num_outputs=1, axis=1, squeeze_axis=False, **kw):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if len(parts) > 1 else parts[0]


register("split_v2", num_outputs=None)(
    lambda data, indices_or_sections=1, axis=0, squeeze_axis=False, **kw: tuple(
        jnp.split(data, indices_or_sections, axis=axis)
    )
)

register("slice")(
    lambda data, begin=None, end=None, step=None, **kw: data[
        tuple(
            slice(b, e if e is not None else None, s)
            for b, e, s in zip(begin, end, step or [None] * len(begin))
        )
    ]
)
register("slice_axis")(
    lambda data, axis=0, begin=0, end=None, **kw: jax.lax.slice_in_dim(
        data, begin, end if end is not None else data.shape[axis], axis=axis
    )
)
register("slice_like")(lambda data, shape_like, axes=None, **kw: _slice_like(data, shape_like, axes))


def _slice_like(data, like, axes):
    axes = axes or range(data.ndim)
    idx = [slice(None)] * data.ndim
    for a in axes:
        idx[a] = slice(0, like.shape[a])
    return data[tuple(idx)]


register("tile")(lambda data, reps=None, **kw: jnp.tile(data, tuple(reps)))
register("repeat")(
    lambda data, repeats=1, axis=None, **kw: jnp.repeat(data, repeats, axis=axis)
)
register("flip", aliases=["reverse"])(
    lambda data, axis=0, **kw: jnp.flip(
        data, axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis
    )
)
register("pad", aliases=["Pad"])(
    lambda data, mode="constant", pad_width=None, constant_value=0, **kw: jnp.pad(
        data,
        [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)],
        mode={"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode],
        **({"constant_values": constant_value} if mode == "constant" else {}),
    )
)
register("depth_to_space")(
    lambda data, block_size=2, **kw: _depth_to_space(data, block_size)
)
register("space_to_depth")(
    lambda data, block_size=2, **kw: _space_to_depth(data, block_size)
)


def _depth_to_space(x, b):
    n, c, h, w = x.shape
    x = x.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


def _space_to_depth(x, b):
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


# ----------------------------------------------------------------- indexing
register("take")(
    lambda a, indices, axis=0, mode="clip", **kw: jnp.take(
        a, indices.astype(jnp.int32), axis=axis,
        mode={"clip": "clip", "wrap": "wrap", "raise": "clip"}[mode]
    )
)
register("Embedding")(
    lambda data, weight, input_dim=None, output_dim=None, dtype=None, sparse_grad=False,
    **kw: jnp.take(weight, data.astype(jnp.int32), axis=0)
)
register("one_hot", differentiable=False)(
    lambda indices, depth=None, on_value=1.0, off_value=0.0, dtype="float32", **kw:
    (jax.nn.one_hot(indices.astype(jnp.int32), depth) * (on_value - off_value)
     + off_value).astype(jnp.dtype(dtype))
)
register("pick")(
    lambda data, index, axis=-1, keepdims=False, mode="clip", **kw: _pick(
        data, index, axis, keepdims
    )
)


def _pick(data, index, axis, keepdims):
    out = jnp.take_along_axis(
        data, jnp.expand_dims(index.astype(jnp.int32), axis), axis=axis
    )
    return out if keepdims else jnp.squeeze(out, axis)


register("gather_nd")(
    lambda data, indices, **kw: data[tuple(indices.astype(jnp.int32))]
)
register("scatter_nd")(
    lambda data, indices, shape=None, **kw: jnp.zeros(tuple(shape), data.dtype)
    .at[tuple(indices.astype(jnp.int32))]
    .set(data)
)
register("where")(
    lambda condition, x, y, **kw: jnp.where(condition.astype(bool), x, y)
)
register("boolean_mask", differentiable=False)(
    lambda data, index, axis=0, **kw: jnp.compress(
        index.astype(bool), data, axis=axis
    )
)
register("SequenceMask", aliases=["sequence_mask"])(
    lambda data, sequence_length=None, use_sequence_length=False, value=0.0, axis=0,
    **kw: _sequence_mask(data, sequence_length, use_sequence_length, value, axis)
)


def _sequence_mask(data, seq_len, use_len, value, axis):
    if not use_len or seq_len is None:
        return data
    max_len = data.shape[axis]
    steps = jnp.arange(max_len)
    if axis == 0:  # (T, B, ...)
        mask = steps[:, None] < seq_len[None, :].astype(jnp.int32)
    else:  # axis == 1: (B, T, ...)
        mask = steps[None, :] < seq_len[:, None].astype(jnp.int32)
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


register("SequenceLast")(
    lambda data, sequence_length=None, use_sequence_length=False, axis=0, **kw:
    jnp.take(data, data.shape[axis] - 1, axis=axis) if not use_sequence_length
    else jnp.take_along_axis(
        data,
        (sequence_length.astype(jnp.int32) - 1).reshape(
            (1, -1) + (1,) * (data.ndim - 2)
        ),
        axis=axis,
    ).squeeze(axis)
)
register("SequenceReverse")(
    lambda data, sequence_length=None, use_sequence_length=False, axis=0, **kw:
    jnp.flip(data, axis=axis)
)

# ------------------------------------------------------------------ ordering
register("sort", differentiable=False)(
    lambda data, axis=-1, is_ascend=True, **kw: jnp.sort(data, axis=axis)
    if is_ascend
    else -jnp.sort(-data, axis=axis)
)
register("argsort", differentiable=False)(
    lambda data, axis=-1, is_ascend=True, dtype="float32", **kw: (
        jnp.argsort(data, axis=axis)
        if is_ascend
        else jnp.argsort(-data, axis=axis)
    ).astype(jnp.dtype(dtype))
)


@register("topk", num_outputs=None, differentiable=False)
def _topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32", **kw):
    d = jnp.moveaxis(data, axis, -1)
    vals, idx = jax.lax.top_k(-d if is_ascend else d, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(jnp.dtype(dtype))
    if ret_typ == "indices":
        return idx
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx
    # 'mask': one-hot per top-k entry, summed along k (per-row scatter)
    idx_last = jnp.moveaxis(idx, axis, -1).astype(jnp.int32)
    mask = jax.nn.one_hot(idx_last, data.shape[axis], dtype=data.dtype).sum(-2)
    return jnp.moveaxis(mask, -1, axis)


register("shuffle", differentiable=False)(lambda data, **kw: _shuffle(data))


def _shuffle(data):
    from ..random import next_key

    return jax.random.permutation(next_key(), data, axis=0)


register("unique", differentiable=False, num_outputs=None)(
    lambda data, **kw: jnp.unique(data)
)

# --------------------------------------------------------------------- diag
register("diag")(lambda data, k=0, **kw: jnp.diag(data, k) if data.ndim <= 2 else jnp.diagonal(data, k))
register("eye", differentiable=False)(
    lambda N=1, M=0, k=0, dtype="float32", **kw: jnp.eye(
        int(N), int(M) if M else None, k=int(k), dtype=jnp.dtype(dtype)
    )
)

# ----------------------------------------------------------- round-4 tail
# add_n / swapaxes / reshape_like: reference ``elemwise_sum.cc``,
# ``matrix_op.cc`` [unverified]
register("add_n", aliases=["ElementWiseSum"])(
    lambda *args, **kw: functools.reduce(jnp.add, args)
)
register("swapaxes", aliases=["SwapAxis"])(
    lambda data, dim1=0, dim2=0, **kw: jnp.swapaxes(data, dim1, dim2)
)
register("reshape_like")(
    lambda lhs, rhs, **kw: jnp.reshape(lhs, rhs.shape)
)

register("cumsum")(
    lambda data, axis=None, dtype=None, **kw: jnp.cumsum(
        data, axis=axis, dtype=jnp.dtype(dtype) if dtype else None)
)
register("ravel_multi_index", aliases=["_ravel_multi_index"],
         differentiable=False)(
    lambda data, shape=None, **kw: jnp.ravel_multi_index(
        tuple(data.astype(jnp.int32)), tuple(int(s) for s in shape),
        mode="clip")
)
register("unravel_index", aliases=["_unravel_index"],
         differentiable=False)(
    lambda data, shape=None, **kw: jnp.stack(
        jnp.unravel_index(data.astype(jnp.int32),
                          tuple(int(s) for s in shape)))
)
register("batch_take")(
    lambda a, indices, **kw: jnp.take_along_axis(
        a, indices.astype(jnp.int32)[:, None], axis=1)[:, 0]
)

# ------------------------------------------------------------- scalar ops
# Reference ``elemwise_binary_scalar_op.cc`` [unverified]: tensor-scalar
# arithmetic registered as distinct ops — the names appear in symbol
# JSON exported by reference MXNet, so graph loading needs each of them.
_SCALAR_OPS = {
    "_plus_scalar": lambda d, s: d + s,
    "_minus_scalar": lambda d, s: d - s,
    "_rminus_scalar": lambda d, s: s - d,
    "_mul_scalar": lambda d, s: d * s,
    "_div_scalar": lambda d, s: d / s,
    "_rdiv_scalar": lambda d, s: s / d,
    "_power_scalar": lambda d, s: jnp.power(d, s),
    "_rpower_scalar": lambda d, s: jnp.power(s, d),
    "_maximum_scalar": lambda d, s: jnp.maximum(d, s),
    "_minimum_scalar": lambda d, s: jnp.minimum(d, s),
    "_mod_scalar": lambda d, s: jnp.mod(d, s),
    "_rmod_scalar": lambda d, s: jnp.mod(s, d),
    "_hypot_scalar": lambda d, s: jnp.hypot(d, s),
}
_SCALAR_CMP = {
    "_equal_scalar": jnp.equal,
    "_not_equal_scalar": jnp.not_equal,
    "_greater_scalar": jnp.greater,
    "_greater_equal_scalar": jnp.greater_equal,
    "_lesser_scalar": jnp.less,
    "_lesser_equal_scalar": jnp.less_equal,
}


def _reg_scalar(name, fn, differentiable=True):
    def op(data, scalar=1.0, **kw):
        return fn(data, jnp.asarray(scalar, data.dtype))

    op.__name__ = name
    register(name, differentiable=differentiable)(op)


for _name, _fn in _SCALAR_OPS.items():
    _reg_scalar(_name, _fn)
for _name, _fn in _SCALAR_CMP.items():
    def _mk_cmp(f):
        return lambda d, s: f(d, s).astype(d.dtype)

    _reg_scalar(_name, _mk_cmp(_fn), differentiable=False)

# ------------------------------------------- creation + legacy-alias tail
# Reference ``init_op.cc`` / legacy v1 names [unverified]: the creation
# ops appear as `_zeros`/`_ones`/`_full`/`_arange` nodes in symbol JSON
# exported by reference MXNet, so graph loading needs them registered.
register("_zeros", differentiable=False)(
    lambda shape=None, dtype="float32", **kw: jnp.zeros(
        tuple(shape) if not isinstance(shape, int) else (shape,),
        jnp.dtype(dtype or "float32"))
)
register("_ones", differentiable=False)(
    lambda shape=None, dtype="float32", **kw: jnp.ones(
        tuple(shape) if not isinstance(shape, int) else (shape,),
        jnp.dtype(dtype or "float32"))
)
register("_full", differentiable=False)(
    lambda shape=None, value=0.0, dtype="float32", **kw: jnp.full(
        tuple(shape) if not isinstance(shape, int) else (shape,),
        value, jnp.dtype(dtype or "float32"))
)
register("_arange", differentiable=False)(
    lambda start=0.0, stop=None, step=1.0, repeat=1, dtype="float32",
    **kw: jnp.repeat(
        jnp.arange(start, stop, step, jnp.dtype(dtype or "float32")),
        int(repeat)) if repeat != 1 else jnp.arange(
            start, stop, step, jnp.dtype(dtype or "float32"))
)
register("zeros_like")(lambda data, **kw: jnp.zeros_like(data))
register("ones_like")(lambda data, **kw: jnp.ones_like(data))
register("full_like")(
    lambda data, fill_value=0.0, **kw: jnp.full_like(data, fill_value)
)
register("reverse")(
    lambda data, axis=0, **kw: jnp.flip(
        data, axis=tuple(axis) if isinstance(axis, (tuple, list)) else axis)
)
register("degrees")(lambda data, **kw: jnp.degrees(data))
register("radians")(lambda data, **kw: jnp.radians(data))
register("digamma")(lambda data, **kw: jax.scipy.special.digamma(data))
register("logical_and", differentiable=False)(
    lambda lhs, rhs, **kw: jnp.logical_and(lhs, rhs).astype(lhs.dtype))
register("logical_or", differentiable=False)(
    lambda lhs, rhs, **kw: jnp.logical_or(lhs, rhs).astype(lhs.dtype))
register("logical_xor", differentiable=False)(
    lambda lhs, rhs, **kw: jnp.logical_xor(lhs, rhs).astype(lhs.dtype))
register("argmax_channel", differentiable=False)(
    lambda data, **kw: jnp.argmax(data, axis=1).astype(jnp.float32))
alias("sum_axis", "sum")
alias("max_axis", "max")
alias("min_axis", "min")
alias("_maximum", "broadcast_maximum")
alias("_minimum", "broadcast_minimum")
alias("choose_element_0index", "pick")


@register("Crop")
def crop(data, *like, offset=(0, 0), h_w=(0, 0), num_args=1,
         center_crop=False, **kw):
    """Legacy spatial crop (reference ``crop.cc`` [unverified]): crop
    data (N, C, H, W) to ``h_w`` — or to the second input's spatial
    size when two inputs are given. Offset from top-left, or centered."""
    if like:
        th, tw = like[0].shape[2], like[0].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    H, W = data.shape[2], data.shape[3]
    if th > H or tw > W:
        raise ValueError(
            f"Crop: target ({th}, {tw}) larger than input ({H}, {W})")
    if center_crop:
        oy, ox = (H - th) // 2, (W - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
        if oy + th > H or ox + tw > W:
            raise ValueError(
                f"Crop: offset ({oy}, {ox}) + target ({th}, {tw}) runs "
                f"past the input ({H}, {W})")
    return data[:, :, oy:oy + th, ox:ox + tw]

# ----------------------------------------------------------- round-5 tail
# shape/size probes, moments, full, AMP casts, all-finite guards
# (reference: ``src/operator/tensor/elemwise_unary_op_basic.cc``,
# ``src/operator/all_finite.cc``, ``src/operator/tensor/amp_cast.cc``
# [unverified])
# int32 (not the reference's int64): jax x64 is off by default and
# would silently truncate anyway — match the backend's native width
register("shape_array", differentiable=False)(
    lambda data, **kw: jnp.asarray(data.shape, jnp.int32)
)
register("size_array", differentiable=False)(
    lambda data, **kw: jnp.asarray(
        functools.reduce(lambda a, b: a * b, data.shape, 1), jnp.int32)
)


@register("moments")
def moments(data, axes=None, keepdims=False, **kw):
    """(mean, var) in one pass (reference ``moments``)."""
    ax = tuple(axes) if axes is not None else None
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.mean(jnp.square(data - mean), axis=ax, keepdims=keepdims)
    if not keepdims:
        mean = mean.reshape(var.shape)
    return mean, var


register("amp_cast")(
    lambda data, dtype="float32", **kw: data.astype(jnp.dtype(dtype))
)


@register("amp_multicast", num_outputs=None)
def amp_multicast(*data, num_outputs=None, cast_narrow=False, **kw):
    """Cast every input to a common dtype: the WIDEST by default (the
    reference's mixed-precision harmonizer), the narrowest with
    ``cast_narrow``."""
    dts = [d.dtype for d in data]
    target = dts[0]
    for dt in dts[1:]:
        wider = jnp.promote_types(target, dt)
        if cast_narrow:
            target = dt if wider == target else target
        else:
            target = wider
    return tuple(d.astype(target) for d in data)


@register("all_finite", differentiable=False)
def all_finite(data, init_output=True, **kw):
    """1.0 iff every element is finite (reference ``all_finite`` — the
    AMP loss-scale overflow probe)."""
    return jnp.isfinite(data).all().astype(jnp.float32).reshape(1)


@register("multi_all_finite", differentiable=False)
def multi_all_finite(*data, num_arrays=None, init_output=True, **kw):
    ok = jnp.asarray(True)
    for d in data:
        ok = ok & jnp.isfinite(d).all()
    return ok.astype(jnp.float32).reshape(1)
