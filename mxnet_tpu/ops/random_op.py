"""Registry forms of the random samplers (reference:
``src/operator/random/sample_op.cc`` and ``multisample_op.cc``
[unverified]): ``_random_*`` draw a tensor of the given shape from
scalar distribution params; ``sample_*`` draw per-element — one batch of
``shape`` samples for every element of the (broadcast) param tensors.

Keys come from the global ``mxnet_tpu.random`` state (eager semantics;
key-supply scope under hybridize keeps traced graphs pure) — which is
why these ops sit on the eager-jit deny list like Dropout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _key():
    from ..random import next_key

    return next_key()


def _threefry_key():
    from ..random import next_threefry_key

    return next_threefry_key()


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


def _dt(dtype):
    return jnp.dtype(dtype if dtype not in (None, "None") else "float32")


@register("_random_uniform", aliases=["random_uniform"],
          differentiable=False)
def _random_uniform(low=0.0, high=1.0, shape=None, dtype="float32", **kw):
    return jax.random.uniform(_key(), _shape(shape), _dt(dtype),
                              minval=float(low), maxval=float(high))


@register("_random_normal", aliases=["random_normal"],
          differentiable=False)
def _random_normal(loc=0.0, scale=1.0, shape=None, dtype="float32", **kw):
    return jax.random.normal(_key(), _shape(shape), _dt(dtype)) \
        * float(scale) + float(loc)


@register("_random_gamma", aliases=["random_gamma"], differentiable=False)
def _random_gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", **kw):
    return jax.random.gamma(_key(), float(alpha), _shape(shape),
                            _dt(dtype)) * float(beta)


@register("_random_exponential", aliases=["random_exponential"],
          differentiable=False)
def _random_exponential(lam=1.0, shape=None, dtype="float32", **kw):
    return jax.random.exponential(_key(), _shape(shape), _dt(dtype)) \
        / float(lam)


@register("_random_poisson", aliases=["random_poisson"],
          differentiable=False)
def _random_poisson(lam=1.0, shape=None, dtype="float32", **kw):
    return jax.random.poisson(_threefry_key(), float(lam),
                              _shape(shape)).astype(_dt(dtype))


@register("_random_randint", aliases=["random_randint"],
          differentiable=False)
def _random_randint(low=0, high=1, shape=None, dtype="int32", **kw):
    dt = jnp.dtype(dtype if dtype not in (None, "None") else "int32")
    return jax.random.randint(_key(), _shape(shape), int(low), int(high),
                              dt)


def _per_element(draw, key_fn=None):
    """sample_*: params (any broadcastable shapes) -> output
    broadcast(params).shape + shape, one draw batch per element.
    ``key_fn`` overrides the key source (poisson needs threefry)."""

    def op(*params, shape=None, dtype="float32", **kw):
        ps = jnp.broadcast_arrays(*[jnp.asarray(p, jnp.float32)
                                    for p in params])
        tail = _shape(shape)
        out = draw((key_fn or _key)(), [p.reshape(-1) for p in ps], tail)
        return out.reshape(ps[0].shape + tail).astype(_dt(dtype))

    return op


def _vmap_draw(fn):
    def draw(key, flat_params, tail):
        n = flat_params[0].shape[0]
        keys = jax.random.split(key, n)
        return jax.vmap(lambda k, *p: fn(k, p, tail))(keys, *flat_params)

    return draw


register("sample_uniform", differentiable=False)(_per_element(_vmap_draw(
    lambda k, p, tail: jax.random.uniform(
        k, tail, minval=p[0], maxval=p[1]))))
register("sample_normal", differentiable=False)(_per_element(_vmap_draw(
    lambda k, p, tail: jax.random.normal(k, tail) * p[1] + p[0])))
register("sample_gamma", differentiable=False)(_per_element(_vmap_draw(
    lambda k, p, tail: jax.random.gamma(k, p[0], tail) * p[1])))
register("sample_exponential", differentiable=False)(
    _per_element(_vmap_draw(
        lambda k, p, tail: jax.random.exponential(k, tail) / p[0])))
register("sample_poisson", differentiable=False)(_per_element(_vmap_draw(
    lambda k, p, tail: jax.random.poisson(k, p[0], tail).astype(
        jnp.float32)), key_fn=_threefry_key))


@register("sample_multinomial", aliases=["_sample_multinomial"],
          differentiable=False)
def sample_multinomial(data, shape=None, get_prob=False, dtype="int32",
                      **kw):
    """Categorical draws from probability rows (reference
    ``sample_multinomial``, ``src/operator/random/multisample_op.cc``
    [unverified]): data (..., K) of (unnormalized-OK) probabilities ->
    int draws of shape data.shape[:-1] + shape; ``get_prob=True`` also
    returns the log-probability of each draw (the REINFORCE helper,
    matching the reference's second output)."""
    d = jnp.asarray(data)
    tail = _shape(shape)
    n_draw = 1
    for t in tail:
        n_draw *= int(t)
    flat = d.reshape(-1, d.shape[-1]).astype(jnp.float32)
    logp = jnp.log(jnp.clip(flat, 1e-37, None))
    logp = logp - jax.scipy.special.logsumexp(logp, axis=-1,
                                              keepdims=True)
    keys = jax.random.split(_key(), flat.shape[0])
    draws = jax.vmap(
        lambda k, lp: jax.random.categorical(k, lp, shape=(n_draw,))
    )(keys, logp)  # (N, n_draw)
    out = draws.reshape(d.shape[:-1] + tail).astype(_dt(dtype))
    if get_prob:
        lp = jnp.take_along_axis(logp, draws, axis=1)
        return out, lp.reshape(d.shape[:-1] + tail).astype(jnp.float32)
    return out
