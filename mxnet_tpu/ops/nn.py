"""Neural-network ops: conv, pooling, normalization, dropout, softmax, RNN.

TPU-native analogue of ``src/operator/nn/`` [unverified] (convolution.cc,
fully_connected.cc, batch_norm.cc, layer_norm.cc, softmax.cc, pooling.cc,
dropout.cc, rnn.cc with its cuDNN fused path). Layout follows the reference's
NCHW/NCW/NCDHW default; ``jax.lax.conv_general_dilated`` takes the layout
spec directly, and XLA lays tensors out for the MXU internally, so no NHWC
rewrite is imposed on user code.

Stateful pieces of the reference are made functional:
- BatchNorm returns (out, batch_mean, batch_var); the Gluon layer owns the
  moving-stat update (the reference mutated aux states inside the op).
- Dropout draws its mask key from ``mxnet_tpu.random`` (global state eagerly,
  key-supply under jit tracing).
- RNN is a ``lax.scan`` over time with the reference's packed-parameter
  layout (i2h/h2h weights+biases per layer/direction), replacing the cuDNN
  descriptor path.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, alias


def _tuplify(x, n):
    if x is None:
        return (1,) * n
    if isinstance(x, int):
        return (x,) * n
    t = tuple(int(v) for v in x)
    return t if len(t) == n else t * n


# ------------------------------------------------------------------ softmax
@register("softmax")
def softmax(data, length=None, axis=-1, temperature=None, dtype=None, use_length=False, **kw):
    # length may arrive as a keyword NDArray (bypasses invoke unwrapping);
    # NOT getattr(..., "data"): numpy arrays expose a .data memoryview
    if hasattr(length, "asnumpy"):
        length = length.data
    d = data / temperature if temperature else data
    if use_length and length is not None:
        steps = jnp.arange(d.shape[axis])
        shape = [1] * d.ndim
        shape[axis] = d.shape[axis]
        mask = steps.reshape(shape) < length.reshape(
            length.shape + (1,) * (d.ndim - length.ndim)
        ).astype(jnp.int32)
        d = jnp.where(mask, d, -jnp.inf)
    out = jax.nn.softmax(d, axis=axis)
    return out.astype(jnp.dtype(dtype)) if dtype else out


register("log_softmax")(
    lambda data, axis=-1, temperature=None, dtype=None, **kw: jax.nn.log_softmax(
        data / temperature if temperature else data, axis=axis
    )
)
register("softmin")(
    lambda data, axis=-1, **kw: jax.nn.softmax(-data, axis=axis)
)
register("SoftmaxActivation")(
    lambda data, mode="instance", **kw: jax.nn.softmax(
        data, axis=1 if mode == "channel" else -1
    )
)


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label, **kw):
    logp = jax.nn.log_softmax(data, axis=-1)
    nll = -jnp.take_along_axis(
        logp, label.astype(jnp.int32)[..., None], axis=-1
    ).squeeze(-1)
    return jnp.sum(nll)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _softmax_output_core(data, label, grad_scale, ignore_label, use_ignore,
                         smooth_alpha):
    return jax.nn.softmax(data, axis=-1)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, use_ignore,
                        smooth_alpha):
    prob = jax.nn.softmax(data, axis=-1)
    return prob, (prob, label)


def _softmax_output_bwd(grad_scale, ignore_label, use_ignore, smooth_alpha,
                        res, g):
    # loss-layer semantics (reference src/operator/softmax_output.cc
    # [unverified]): incoming cotangent is IGNORED; d(data) is the cross-
    # entropy gradient softmax(data) - onehot(label), optionally masked
    prob, label = res
    n_class = prob.shape[-1]
    onehot = jax.nn.one_hot(label.astype(jnp.int32), n_class,
                            dtype=prob.dtype)
    if smooth_alpha > 0:
        onehot = onehot * (1 - smooth_alpha) + smooth_alpha / n_class
    grad = (prob - onehot) * grad_scale
    if use_ignore:
        mask = (label.astype(jnp.int32) != int(ignore_label)).astype(prob.dtype)
        grad = grad * mask[..., None]
    if jnp.issubdtype(label.dtype, jnp.floating):
        label_ct = jnp.zeros_like(label)
    else:
        # integer primals require a float0 cotangent under custom_vjp
        label_ct = np.zeros(label.shape, jax.dtypes.float0)
    return grad, label_ct


_softmax_output_core.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput")
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1, multi_output=False,
                   use_ignore=False, preserve_shape=False, normalization="null",
                   out_grad=False, smooth_alpha=0.0, **kw):
    """Legacy op: forward = softmax; backward = (softmax - onehot(label))."""
    return _softmax_output_core(data, label, float(grad_scale),
                                int(ignore_label), bool(use_ignore),
                                float(smooth_alpha))


register("smooth_l1")(
    lambda data, scalar=1.0, **kw: jnp.where(
        jnp.abs(data) < 1.0 / (scalar * scalar),
        0.5 * jnp.square(data * scalar * scalar) / (scalar * scalar),
        jnp.abs(data) - 0.5 / (scalar * scalar),
    )
)


# --------------------------------------------------------------- activation
@register("Activation")
def activation(data, act_type="relu", **kw):
    return {
        "relu": lambda d: jnp.maximum(d, 0),
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "softrelu": jax.nn.softplus,
        "softsign": jax.nn.soft_sign,
        "gelu": lambda d: jax.nn.gelu(d, approximate=False),
        "gelu_tanh": lambda d: jax.nn.gelu(d, approximate=True),
        "silu": jax.nn.silu,
        "swish": jax.nn.silu,
        "mish": lambda d: d * jnp.tanh(jax.nn.softplus(d)),
    }[act_type](data)


# ----------------------------------------------------------- fully connected
@register("FullyConnected")
def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True, **kw):
    """Reference: ``src/operator/nn/fully_connected.cc`` [unverified].

    weight is (num_hidden, in_units) like the reference; the matmul rides the
    MXU as data @ weight.T.
    """
    x = data.reshape(data.shape[0], -1) if flatten and data.ndim > 2 else data
    out = jnp.matmul(x, weight.T)
    if bias is not None and not no_bias:
        out = out + bias
    return out


# -------------------------------------------------------------- convolution
@register("Convolution")
def convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, workspace=1024,
                no_bias=False, cudnn_tune=None, cudnn_off=False, layout=None, **kw):
    """Reference: ``src/operator/nn/convolution.cc`` [unverified].

    N-D conv in NC[DHW] layout over ``jax.lax.conv_general_dilated`` —
    XLA tiles it onto the MXU (the reference dispatched to cuDNN algos).
    """
    nd = data.ndim - 2
    stride = _tuplify(stride, nd)
    dilate = _tuplify(dilate, nd)
    pad = _tuplify(pad if pad is not None else 0, nd)
    if isinstance(pad, tuple) and pad == (0,) * nd and kw.get("pad_mode") == "same":
        padding = "SAME"
    else:
        padding = [(p, p) for p in pad]
    spatial = "DHW"[-nd:] if nd <= 3 else None
    # layout: channel-first (NCHW, reference default) or channel-last
    # (NHWC — the TPU-preferred layout: channels ride the lane dimension,
    # so per-channel BatchNorm reductions and conv epilogues fuse without
    # strided access). Weights stay (O, I/g, *k) in BOTH layouts so
    # checkpoints are layout-portable.
    channel_last = bool(layout) and layout[-1] == "C"
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    rhs_spec = "OI" + spatial
    out = jax.lax.conv_general_dilated(
        data,
        weight,
        window_strides=stride,
        padding=padding,
        rhs_dilation=dilate,
        dimension_numbers=(lhs_spec, rhs_spec, lhs_spec),
        feature_group_count=num_group,
    )
    if bias is not None and not no_bias:
        bshape = ((1,) * (nd + 1) + (-1,)) if channel_last \
            else ((1, -1) + (1,) * nd)
        out = out + bias.reshape(bshape)
    return out


@register("Deconvolution")
def deconvolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                  pad=None, adj=None, target_shape=None, num_filter=None,
                  num_group=1, no_bias=True, layout=None, **kw):
    """Transposed conv (reference: ``src/operator/nn/deconvolution.cc``)."""
    if layout is not None and layout[-1] == "C":
        raise NotImplementedError(
            "channel-last Deconvolution not supported yet; use NC* layouts"
        )
    nd = data.ndim - 2
    stride = _tuplify(stride, nd)
    dilate = _tuplify(dilate, nd)
    pad = _tuplify(pad if pad is not None else 0, nd)
    adj = _tuplify(adj if adj is not None else 0, nd)
    if num_group != 1:
        raise NotImplementedError("grouped Deconvolution not supported yet")
    spatial = "DHW"[-nd:]
    kernel = _tuplify(kernel if kernel is not None else weight.shape[2:], nd)
    # gradient-of-conv semantics (out = (i-1)*s + k' - 2p + adj, k' = dilated
    # kernel extent): pad the stride-dilated input by k'-1-p per side, adj on
    # the high side; weight layout is (in, out, *k) like the reference, read
    # as OI + transpose_kernel so XLA flips/swaps into the grad kernel.
    pads = []
    for k, d, p, a in zip(kernel, dilate, pad, adj):
        eff_k = (k - 1) * d + 1
        pads.append((eff_k - 1 - p, eff_k - 1 - p + a))
    out = jax.lax.conv_transpose(
        data,
        weight,
        strides=stride,
        padding=pads,
        rhs_dilation=dilate,
        dimension_numbers=("NC" + spatial, "OI" + spatial, "NC" + spatial),
        transpose_kernel=True,
    )
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# ------------------------------------------------------------------ pooling
@register("Pooling")
def pooling(data, kernel=None, pool_type="max", global_pool=False, cudnn_off=False,
            pooling_convention="valid", stride=None, pad=None, p_value=2,
            count_include_pad=True, layout=None, **kw):
    """Reference: ``src/operator/nn/pooling.cc`` [unverified]. ``layout``
    ending in C selects channel-last (spatial dims at 1..ndim-2)."""
    nd = data.ndim - 2
    channel_last = bool(layout) and layout[-1] == "C"
    sp0 = 1 if channel_last else 2  # first spatial axis
    if global_pool:
        axes = tuple(range(sp0, sp0 + nd))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        return jnp.mean(data, axis=axes, keepdims=True)
    kernel = _tuplify(kernel, nd)
    stride = _tuplify(stride if stride is not None else 1, nd)
    pad = _tuplify(pad if pad is not None else 0, nd)
    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        base_pads = ((0, 0),) + tuple((p, p) for p in pad) + ((0, 0),)
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        base_pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    pads = base_pads
    if pooling_convention == "full":
        # ceil-mode: extend padding on the high side so the last window fits
        extra = []
        for i in range(nd):
            size = data.shape[sp0 + i] + 2 * pad[i] - kernel[i]
            rem = size % stride[i]
            extra.append(stride[i] - rem if rem else 0)
        sp_pads = tuple((p, p + e) for p, e in zip(pad, extra))
        pads = (((0, 0),) + sp_pads + ((0, 0),)) if channel_last \
            else (((0, 0), (0, 0)) + sp_pads)
    if pool_type == "max":
        init = -jnp.inf
        out = jax.lax.reduce_window(data, init, jax.lax.max, window, strides, pads)
        return out.astype(data.dtype)
    if pool_type in ("avg", "sum"):
        summed = jax.lax.reduce_window(data, 0.0, jax.lax.add, window, strides, pads)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = 1.0
            for k in kernel:
                denom *= k
            return summed / denom
        ones = jnp.ones_like(data)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
        return summed / counts
    if pool_type == "lp":
        powed = jax.lax.reduce_window(
            jnp.power(jnp.abs(data), p_value), 0.0, jax.lax.add, window, strides, pads
        )
        return jnp.power(powed, 1.0 / p_value)
    raise ValueError(f"unknown pool_type {pool_type}")


# ------------------------------------------------------------ normalization
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train(data, gamma, beta, eps, axis):
    """Training BN returning (out, batch_mean, batch_var); the stat
    outputs are moving-average side products and carry no gradient (the
    reference treated them as aux states)."""
    return _bn_train_fwd_rule(data, gamma, beta, eps, axis)[0]


def _bn_mode() -> str:
    """MXTPU_FUSED_BN: '1' shifted one-pass jnp (default), 'pallas' the
    Pallas kernels (channel-last only), '0' round-3 two-pass jnp. Read
    per call."""
    import os

    return os.environ.get("MXTPU_FUSED_BN", "1").lower()


def _bn_fused_ok(data, axis):
    from .pallas import batch_norm as _pbn

    return _bn_mode() == "pallas" and _pbn.supports(data, axis)


def _bn_stats(data, axis):
    red = tuple(i for i in range(data.ndim) if i != (axis % data.ndim))
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    if _bn_fused_ok(data, axis):
        # Pallas one-read stats (channel-last layers only; opt-in — on
        # the v5e trace the jnp form below compiles to the same single
        # pass WITHOUT the layout copies Pallas operands force)
        from .pallas import batch_norm as _pbn

        C = data.shape[-1]
        mean, var = _pbn.bn_stats(data.reshape(-1, C))
        return mean, var, red, bshape
    mode = _bn_mode()
    if mode != "0":
        # SHIFTED one-pass statistics, f32 accumulators: subtract a
        # per-channel sample s (one element of the channel) before the
        # sum/sumsq — XLA's multi-output fusion computes both reductions
        # in a single read of x (measured round 3: 26.86 vs 29.28 ms on
        # the ResNet-50 step). The raw one-pass E[x^2]-E[x]^2 form was
        # REVERTED in round 3: it cancels catastrophically whenever
        # |mean| >> std. With the shift, E[x-s] is ~std-sized (s sits
        # within a few std of the mean with overwhelming probability),
        # so E[(x-s)^2] - E[x-s]^2 only cancels O(1) bits — safe in f32
        # for any channel distribution.
        n = 1
        for i in red:
            n *= data.shape[i]
        idx = tuple(slice(None) if i == (axis % data.ndim) else 0
                    for i in range(data.ndim))
        s = jax.lax.stop_gradient(data[idx]).astype(jnp.float32)
        xs = data.astype(jnp.float32) - s.reshape(bshape)
        s1 = jnp.sum(xs, axis=red)
        s2 = jnp.sum(jnp.square(xs), axis=red)
        mean = s + s1 / n
        var = s2 / n - jnp.square(s1 / n)
        return mean, var, red, bshape
    # two-pass statistics, f32 accumulators, nothing materialized;
    # one READ of the activation more than the shifted form above
    mean = jnp.mean(data, axis=red, dtype=jnp.float32)
    cdiff = data.astype(jnp.float32) - mean.reshape(bshape)
    var = jnp.mean(jnp.square(cdiff), axis=red)
    return mean, var, red, bshape


def _bn_apply(data, mean, var, gamma, beta, eps, bshape):
    # normalize as ONE fma in the activation dtype: precompute per-channel
    # scale/shift in f32, cast once — the (B,H,W)-sized math stays bf16
    # under AMP instead of promoting to f32 through a broadcast subtract
    inv = jax.lax.rsqrt(var + eps)
    scale = inv * gamma.astype(jnp.float32)
    shift = beta.astype(jnp.float32) - mean * scale
    out = data * scale.astype(data.dtype).reshape(bshape) \
        + shift.astype(data.dtype).reshape(bshape)
    return out, inv


def _bn_train_fwd_rule(data, gamma, beta, eps, axis):
    mean, var, red, bshape = _bn_stats(data, axis)
    out, inv = _bn_apply(data, mean, var, gamma, beta, eps, bshape)
    return (out, mean, var), (data, gamma, mean, inv, beta)


def _bn_train_bwd_rule(eps, axis, res, cts):
    """Closed-form fused BN backward (the hand-derived 2-pass kernel the
    reference wrote in CUDA): one fused pass for the two reductions
    (sum dy, sum dy*xhat — through the Pallas ``bn_bwd_reduce`` kernel
    when the layout supports it, guaranteeing the single joint read of
    (x, dy) rather than hoping XLA's multi-output fusion merges them),
    one jnp pass for dx that XLA fuses with neighbors. XLA's autodiff of
    the forward chain emits ~6 reduction/elementwise passes instead.

    Cotangents for the mean/var outputs are ignored: they are
    moving-average aux products, not differentiable paths (reference
    semantics)."""
    data, gamma, mean, inv, beta = res
    dy = cts[0]
    red = tuple(i for i in range(data.ndim) if i != (axis % data.ndim))
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    n = 1
    for i in red:
        n *= data.shape[i]
    dyf = dy.astype(jnp.float32)
    xhat = (data.astype(jnp.float32) - mean.reshape(bshape)) \
        * inv.reshape(bshape)
    if _bn_fused_ok(data, axis):
        from .pallas import batch_norm as _pbn

        C = data.shape[-1]
        sum_dy, sum_dy_xhat = _pbn.bn_bwd_reduce(
            data.reshape(-1, C), dy.reshape(-1, C), mean, inv)
    else:
        sum_dy = jnp.sum(dyf, axis=red)
        sum_dy_xhat = jnp.sum(dyf * xhat, axis=red)
    gscale = (gamma.astype(jnp.float32) * inv).reshape(bshape)
    dx = gscale * (
        dyf - (sum_dy / n).reshape(bshape)
        - xhat * (sum_dy_xhat / n).reshape(bshape)
    )
    dgamma = sum_dy_xhat.astype(gamma.dtype)
    dbeta = sum_dy.astype(beta.dtype)
    return dx.astype(data.dtype), dgamma, dbeta


_bn_train.defvjp(_bn_train_fwd_rule, _bn_train_bwd_rule)


@register("BatchNorm", num_outputs=None)
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3, momentum=0.9,
               fix_gamma=True, use_global_stats=False, output_mean_var=False,
               axis=1, cudnn_off=False, training=False, **kw):
    """Reference: ``src/operator/nn/batch_norm.cc`` [unverified].

    Pure: returns (out, batch_mean, batch_var); the caller (gluon BatchNorm
    layer / CachedOp state threading) applies the moving-average update the
    reference performed in-place on aux states. Training gradients use the
    closed-form fused backward (``_bn_train_bwd_rule``).
    """
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    if training and not use_global_stats:
        out, mean, var = _bn_train(data, g, beta, float(eps),
                                   axis % data.ndim)
        mean = jax.lax.stop_gradient(mean)
        var = jax.lax.stop_gradient(var)
        return (out, mean.astype(moving_mean.dtype),
                var.astype(moving_var.dtype))
    mean = moving_mean.astype(jnp.float32)
    var = moving_var.astype(jnp.float32)
    out, _ = _bn_apply(data, mean, var, g, beta, eps, bshape)
    return out, mean.astype(moving_mean.dtype), var.astype(moving_var.dtype)


@register("LayerNorm")
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False, **kw):
    """Reference: ``src/operator/nn/layer_norm.cc`` [unverified].

    Last-axis norms with lane-aligned channels go through the fused Pallas
    kernel (single pass fwd, single pass bwd — see ``pallas/layer_norm``);
    everything else uses the jnp composition XLA fuses itself."""
    from .pallas import layer_norm as _pln

    if not output_mean_var and _pln.supports(data, axis) \
            and gamma.dtype == data.dtype:
        C = data.shape[-1]
        out2d = _pln.layer_norm_fused(
            data.reshape(-1, C), gamma, beta, float(eps)
        )
        return out2d.reshape(data.shape)
    # statistics in f32, output in the ACTIVATION dtype: under AMP the
    # layer's params stay fp32 masters (amp.lists) while activations run
    # bf16/f16 — a dtype-preserving norm keeps the low-precision stream
    # low-precision instead of promoting everything downstream to f32
    # (f32 in -> f32 out is bit-identical to the old path)
    xf = data.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axis, keepdims=True)
    var = jnp.var(xf, axis=axis, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    out = (xf - mean) * inv * gamma.astype(jnp.float32).reshape(shape) \
        + beta.astype(jnp.float32).reshape(shape)
    out = out.astype(data.dtype)
    if output_mean_var:
        return (out, jnp.squeeze(mean, axis).astype(data.dtype),
                jnp.squeeze(var, axis).astype(data.dtype))
    return out


@register("GroupNorm")
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5, **kw):
    n, c = data.shape[:2]
    x = data.astype(jnp.float32).reshape(
        (n, num_groups, c // num_groups) + data.shape[2:])
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    shape = (1, c) + (1,) * (data.ndim - 2)
    out = x * gamma.astype(jnp.float32).reshape(shape) \
        + beta.astype(jnp.float32).reshape(shape)
    return out.astype(data.dtype)  # dtype-preserving (see layer_norm)


@register("InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3, **kw):
    xf = data.astype(jnp.float32)
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(xf, axis=red, keepdims=True)
    var = jnp.var(xf, axis=red, keepdims=True)
    shape = (1, data.shape[1]) + (1,) * (data.ndim - 2)
    out = (xf - mean) * jax.lax.rsqrt(var + eps) \
        * gamma.astype(jnp.float32).reshape(shape) \
        + beta.astype(jnp.float32).reshape(shape)
    return out.astype(data.dtype)  # dtype-preserving (see layer_norm)


@register("LRN")
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **kw):
    sq = jnp.square(data)
    pad = nsize // 2
    summed = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add, (1, nsize, 1, 1), (1, 1, 1, 1),
        ((0, 0), (pad, pad), (0, 0), (0, 0)),
    )
    return data / jnp.power(knorm + alpha * summed / nsize, beta)


# ------------------------------------------------------------------ dropout
@register("Dropout")
def dropout(data, p=0.5, mode="training", axes=None, cudnn_off=False,
            training=None, **kw):
    """Reference: ``src/operator/nn/dropout.cc`` [unverified].

    Key comes from mxnet_tpu.random (supply-scoped under jit so hybridized
    graphs stay pure while masks vary per step).
    """
    from .. import autograd
    from ..random import next_key

    if training is None:
        training = autograd.is_training()
    if not training and mode != "always":
        return data
    if p <= 0.0:
        return data
    shape = data.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(next_key(), keep, shape)
    return jnp.where(mask, data / keep, jnp.zeros_like(data))


# ---------------------------------------------------------------------- rnn
@register("RNN", num_outputs=None)
def rnn(data, parameters, state, state_cell=None, state_size=None, num_layers=1,
        bidirectional=False, mode="lstm", p=0.0, state_outputs=False,
        projection_size=None, sequence_length=None, use_sequence_length=False,
        training=False, **kw):
    """Fused multi-layer RNN (reference: ``src/operator/rnn.cc`` + cuDNN path
    [unverified]). data: (T, N, I); packed ``parameters`` use the reference
    layout: for each layer & direction, i2h_weight, h2h_weight then all
    biases (i2h_bias, h2h_bias).

    Implemented as ``lax.scan`` over time — XLA compiles the step once and
    keeps the matmuls on the MXU.
    """
    T, N, I = data.shape
    H = int(state_size)
    D = 2 if bidirectional else 1
    ngates = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]

    # unpack parameter vector
    offset = 0
    layers = []

    def take(n, shape):
        nonlocal offset
        w = jax.lax.dynamic_slice_in_dim(parameters, offset, n).reshape(shape)
        offset += n
        return w

    sizes = []
    for layer in range(num_layers):
        inp = I if layer == 0 else H * D
        for d in range(D):
            sizes.append((ngates * H, inp))
            sizes.append((ngates * H, H))
    weights = []
    for shp in sizes:
        weights.append(take(shp[0] * shp[1], shp))
    biases = []
    for shp in sizes:
        biases.append(take(shp[0], (shp[0],)))

    def cell_step(mode, x, h, c, wx, wh, bx, bh):
        gates = x @ wx.T + bx + h @ wh.T + bh
        if mode == "lstm":
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
            return h2, c2
        if mode == "gru":
            xr, xz, xn = jnp.split(x @ wx.T + bx, 3, axis=-1)
            hr, hz, hn = jnp.split(h @ wh.T + bh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h2 = (1 - z) * n + z * h
            return h2, c
        act = jnp.tanh if mode == "rnn_tanh" else (lambda v: jnp.maximum(v, 0))
        h2 = act(gates)
        return h2, c

    x = data
    h_out, c_out = [], []
    wi = 0
    for layer in range(num_layers):
        outs = []
        for d in range(D):
            wx, wh = weights[wi * 2], weights[wi * 2 + 1]
            bx, bh = biases[wi * 2], biases[wi * 2 + 1]
            wi += 1
            h0 = state[layer * D + d]
            c0 = state_cell[layer * D + d] if state_cell is not None else jnp.zeros_like(h0)
            seq = x if d == 0 else jnp.flip(x, axis=0)

            def step(carry, xt, wx=wx, wh=wh, bx=bx, bh=bh):
                h, c = carry
                h2, c2 = cell_step(mode, xt, h, c, wx, wh, bx, bh)
                return (h2, c2), h2

            (hT, cT), ys = jax.lax.scan(step, (h0, c0), seq)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            outs.append(ys)
            h_out.append(hT)
            c_out.append(cT)
        x = outs[0] if D == 1 else jnp.concatenate(outs, axis=-1)

    hN = jnp.stack(h_out)
    if mode == "lstm":
        return x, hN, jnp.stack(c_out)
    return x, hN


# ---------------------------------------------------------------- upsampling
@register("UpSampling")
def upsampling(*args, scale=1, sample_type="nearest", num_args=1, **kw):
    data = args[0]
    if sample_type == "nearest":
        return jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
    n, c, h, w = data.shape
    return jax.image.resize(data, (n, c, h * scale, w * scale), method="bilinear")


# ------------------------------------------------- legacy regression heads
# Reference: ``src/operator/regression_output.cc``, ``make_loss.cc``,
# ``svm_output.cc`` [unverified] — loss-layer ops whose FORWARD is the
# prediction (identity / sigmoid) and whose BACKWARD injects the loss
# gradient directly, ignoring the incoming cotangent (Module-era training
# heads; the same custom_vjp shape as SoftmaxOutput above).
def _reg_head(fwd_fn, grad_fn):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def core(data, label, grad_scale):
        return fwd_fn(data)

    def fwd(data, label, grad_scale):
        out = fwd_fn(data)
        return out, (out, label)

    def bwd(grad_scale, res, g):
        out, label = res
        n = 1
        for d in label.shape[1:]:
            n *= d
        grad = grad_fn(out, label.reshape(out.shape).astype(out.dtype)) \
            * (grad_scale / n)
        if jnp.issubdtype(label.dtype, jnp.floating):
            lct = jnp.zeros_like(label)
        else:
            # integer primals require a float0 cotangent under custom_vjp
            lct = np.zeros(label.shape, jax.dtypes.float0)
        return grad.astype(out.dtype), lct

    core.defvjp(fwd, bwd)
    return core


_lin_reg = _reg_head(lambda d: d, lambda o, l: o - l)
_mae_reg = _reg_head(lambda d: d, lambda o, l: jnp.sign(o - l))
_log_reg = _reg_head(jax.nn.sigmoid, lambda o, l: o - l)


@register("LinearRegressionOutput")
def linear_regression_output(data, label, grad_scale=1.0, **kw):
    """forward = data; backward = (data - label) * grad_scale / n."""
    return _lin_reg(data, label, float(grad_scale))


@register("MAERegressionOutput")
def mae_regression_output(data, label, grad_scale=1.0, **kw):
    return _mae_reg(data, label, float(grad_scale))


@register("LogisticRegressionOutput")
def logistic_regression_output(data, label, grad_scale=1.0, **kw):
    """forward = sigmoid(data); backward = (sigmoid(data) - label)."""
    return _log_reg(data, label, float(grad_scale))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _make_loss_core(data, grad_scale, valid_thresh):
    return data


def _make_loss_fwd(data, grad_scale, valid_thresh):
    return data, None


def _make_loss_bwd(grad_scale, valid_thresh, res, g):
    # reference make_loss: d(data) = grad_scale (the head IS the loss);
    # normalization folds into grad_scale before the call
    return (jnp.full_like(g, grad_scale),)


_make_loss_core.defvjp(_make_loss_fwd, _make_loss_bwd)


@register("MakeLoss")
def make_loss(data, grad_scale=1.0, valid_thresh=0.0,
              normalization="null", **kw):
    """forward = data (reference: identity); backward seeds
    d(data) = grad_scale, divided by batch size under
    normalization='batch' (the scale reaches the GRADIENT, where the
    reference applied it)."""
    scale = float(grad_scale)
    if normalization == "batch":
        scale /= data.shape[0]
    return _make_loss_core(data, scale, float(valid_thresh))


def _svm_grad(out, label, margin, reg_coef, use_linear):
    n_class = out.shape[-1]
    lab = jax.nn.one_hot(label.astype(jnp.int32), n_class, dtype=out.dtype)
    # hinge: grad = -1 at label where violated, +1 at violating others
    score_at_label = jnp.sum(out * lab, axis=-1, keepdims=True)
    if use_linear:
        viol_other = ((out - score_at_label + margin) > 0) & (lab == 0)
        grad = viol_other.astype(out.dtype)
        grad = grad - lab * jnp.sum(grad, axis=-1, keepdims=True)
    else:  # squared hinge
        m = jnp.maximum(out - score_at_label + margin, 0) * (lab == 0)
        grad = 2 * m
        grad = grad - lab * jnp.sum(grad, axis=-1, keepdims=True)
    return grad * reg_coef


def _svm_head():
    @functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
    def core(data, label, margin, reg_coef, use_linear):
        return data

    def fwd(data, label, margin, reg_coef, use_linear):
        return data, (data, label)

    def bwd(margin, reg_coef, use_linear, res, g):
        data, label = res
        grad = _svm_grad(data, label, margin, reg_coef, use_linear)
        if jnp.issubdtype(label.dtype, jnp.floating):
            lct = jnp.zeros_like(label)
        else:
            lct = np.zeros(label.shape, jax.dtypes.float0)
        return grad.astype(data.dtype), lct

    core.defvjp(fwd, bwd)
    return core


_svm_core = _svm_head()


@register("SVMOutput")
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False, **kw):
    """forward = data (scores); backward = hinge-loss gradient
    (reference svm_output.cc)."""
    return _svm_core(data, label, float(margin),
                     float(regularization_coefficient), bool(use_linear))


@register("CTCLoss", aliases=["ctc_loss", "_contrib_CTCLoss",
                              "_contrib_ctc_loss"])
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first", **kw):
    """Connectionist temporal classification loss (reference:
    ``src/operator/nn/ctc_loss.cc`` over warp-ctc [unverified]; here the
    optax forward-algorithm implementation drives the same contract).

    data: (T, N, C) unnormalized activations (reference layout);
    label: (N, L) int class ids, 0-padded unless label lengths given.
    Returns (N,) negative log-likelihoods. ``blank_label``: 'first'
    (blank = id 0, labels 1-based like the reference default) or 'last'
    (blank = C-1, labels 0-based).
    """
    import optax

    T, N, C = data.shape
    logits = jnp.transpose(data, (1, 0, 2)).astype(jnp.float32)  # (N,T,C)
    lab = label.astype(jnp.int32)
    if use_data_lengths and data_lengths is not None:
        dl = data_lengths.astype(jnp.int32)
        logit_pad = (jnp.arange(T)[None, :] >= dl[:, None]
                     ).astype(jnp.float32)
    else:
        logit_pad = jnp.zeros((N, T), jnp.float32)
    if use_label_lengths and label_lengths is not None:
        ll = label_lengths.astype(jnp.int32)
        label_pad = (jnp.arange(lab.shape[1])[None, :] >= ll[:, None]
                     ).astype(jnp.float32)
    else:
        # reference padding conventions without explicit lengths:
        # 0 marks padding under blank_label='first' (labels 1-based),
        # -1 under blank_label='last' (labels 0-based)
        pad_id = 0 if blank_label == "first" else -1
        label_pad = (lab == pad_id).astype(jnp.float32)
    if blank_label == "first":
        blank_id = 0
    elif blank_label == "last":
        blank_id = C - 1
    else:
        raise ValueError(f"blank_label must be 'first' or 'last', got "
                         f"{blank_label!r}")
    lab = jnp.where(label_pad > 0, 0, lab)  # padded slots: any valid id
    return optax.ctc_loss(logits, logit_pad, lab, label_pad,
                          blank_id=blank_id)
