"""Fused optimizer update ops (reference: ``src/operator/optimizer_op.cc``,
``src/operator/contrib/adamw.cc``, multi-tensor ``multi_sgd_update``
[unverified]).

Each op is a pure function ``(weight, grad, *states, **hyper) ->
(new_weight, *new_states)``. The imperative layer rebinds the input NDArrays
(MXNet semantics: optimizer ops mutate weight/state in place); the Trainer's
fused path stacks many parameters into ONE jitted call so the whole optimizer
step is a single XLA executable with donated buffers — the TPU equivalent of
the reference's multi-tensor CUDA kernels.
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register

__all__ = []


def _apply_wd_rescale(weight, grad, wd, rescale_grad, clip_gradient):
    grad = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        grad = jnp.clip(grad, -clip_gradient, clip_gradient)
    return grad + wd * weight


@register("sgd_update", mutates_input=0, differentiable=False)
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    return weight - lr * g


@register("sgd_mom_update", mutates_input=0, differentiable=False)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("nag_mom_update", mutates_input=0, differentiable=False)
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", mutates_input=0, differentiable=False)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    return (weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon),
            new_mean, new_var)


@register("adamw_update", aliases=["_adamw_update"], mutates_input=0,
          differentiable=False)
def adamw_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                 clip_gradient=-1.0, **kw):
    # decoupled weight decay (Loshchilov & Hutter) — wd is NOT in the moments
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    update = new_mean / (jnp.sqrt(new_var) + epsilon) + wd * weight
    return weight - eta * lr * update, new_mean, new_var


@register("lamb_update_phase1", mutates_input=None, differentiable=False)
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    if bias_correction:
        mean_hat = new_mean / (1.0 - beta1 ** t)
        var_hat = new_var / (1.0 - beta2 ** t)
    else:
        mean_hat, var_hat = new_mean, new_var
    update = mean_hat / (jnp.sqrt(var_hat) + epsilon) + wd * weight
    return update, new_mean, new_var


@register("lamb_update_phase2", mutates_input=0, differentiable=False)
def lamb_update_phase2(weight, g, r1, r2, lr=0.001, lower_bound=-1.0,
                       upper_bound=-1.0, **kw):
    if lower_bound >= 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound >= 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2, 1.0)
    return weight - lr * ratio * g


@register("rmsprop_update", mutates_input=0, differentiable=False)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights >= 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register("rmspropalex_update", mutates_input=0, differentiable=False)
def rmspropalex_update(weight, grad, n, g_state, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    # Graves 2013 / reference rmspropalex_update: BOTH accumulators decay
    # with gamma1; gamma2 is only the momentum on delta
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_g = gamma1 * g_state + (1 - gamma1) * g
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    new_w = weight + new_delta
    if clip_weights >= 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n, new_g, new_delta


@register("ftrl_update", mutates_input=0, differentiable=False)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) > lamda1,
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd),
        0.0,
    )
    return new_w.astype(weight.dtype), new_z, new_n


@register("signsgd_update", mutates_input=0, differentiable=False)
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    return weight - lr * jnp.sign(g)


@register("signum_update", mutates_input=0, differentiable=False)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_mom = momentum * mom - (1 - momentum) * g
    new_w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return new_w, new_mom


@register("mp_sgd_update", mutates_input=0, differentiable=False)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, **kw):
    # multi-precision: master fp32 copy updated, low-precision weight recast
    g = _apply_wd_rescale(weight32, grad.astype(jnp.float32), wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_w32 = weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", mutates_input=0, differentiable=False)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = _apply_wd_rescale(weight32, grad.astype(jnp.float32), wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


# ------------------------------------------------- multi-tensor kernels
# Reference: multi_sgd_update / multi_sum_sq / multi_lars in
# ``src/operator/optimizer_op.cc`` and ``contrib/multi_sum_sq.cc``
# [unverified] — one CUDA kernel walking many tensors to kill per-op
# launch overhead. Here each op takes the flat variadic tensor list the
# reference took; called under one jit, XLA compiles the whole update
# into a single executable, which is the same dispatch-amortization win
# (the eager Trainer's fused path feeds these).

def _norm_seq(v, n):
    if isinstance(v, (tuple, list)):
        return [float(x) for x in v]
    return [float(v)] * n


@register("multi_sum_sq", differentiable=False, num_outputs=None)
def multi_sum_sq(*arrays, num_arrays=None, **kw):
    """Per-array sum of squares, returned as one (num_arrays,) vector."""
    return jnp.stack([jnp.sum(jnp.square(a.astype(jnp.float32)))
                      for a in arrays])


@register("multi_lars", differentiable=False)
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
               eps=1e-8, rescale_grad=1.0, **kw):
    """LARS layer-wise lr scaling on stacked per-layer scalars
    (reference multi_lars): lr_i *= eta*||w||/(||g||+wd*||w||+eps)."""
    wn = jnp.sqrt(weights_sum_sq)
    gn = jnp.sqrt(grads_sum_sq) * rescale_grad
    coef = eta * wn / (gn + wds * wn + eps)
    return jnp.where(jnp.logical_and(wn > 0, gn > 0), lrs * coef, lrs)


@register("multi_sgd_update", differentiable=False, num_outputs=None)
def multi_sgd_update(*weights_grads, lrs=0.01, wds=0.0, rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=None, **kw):
    """Interleaved (w0, g0, w1, g1, ...) -> tuple of updated weights."""
    n = num_weights or len(weights_grads) // 2
    lrs, wds = _norm_seq(lrs, n), _norm_seq(wds, n)
    clip = clip_gradient if clip_gradient >= 0 else None
    out = []
    for i in range(n):
        w, g = weights_grads[2 * i], weights_grads[2 * i + 1]
        gg = _apply_wd_rescale(w, g, wds[i], rescale_grad, clip)
        out.append(w - lrs[i] * gg)
    return tuple(out)


@register("multi_sgd_mom_update", differentiable=False, num_outputs=None)
def multi_sgd_mom_update(*wgm, lrs=0.01, wds=0.0, momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         num_weights=None, **kw):
    """Interleaved (w0, g0, m0, ...) -> (w0', m0', w1', m1', ...)."""
    n = num_weights or len(wgm) // 3
    lrs, wds = _norm_seq(lrs, n), _norm_seq(wds, n)
    clip = clip_gradient if clip_gradient >= 0 else None
    out = []
    for i in range(n):
        w, g, m = wgm[3 * i], wgm[3 * i + 1], wgm[3 * i + 2]
        gg = _apply_wd_rescale(w, g, wds[i], rescale_grad, clip)
        nm = momentum * m - lrs[i] * gg
        out.extend([w + nm, nm])
    return tuple(out)


@register("multi_mp_sgd_update", differentiable=False, num_outputs=None)
def multi_mp_sgd_update(*wgw32, lrs=0.01, wds=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, num_weights=None, **kw):
    """Interleaved (w0, g0, w32_0, ...) -> (w0', w32_0', ...)."""
    n = num_weights or len(wgw32) // 3
    lrs, wds = _norm_seq(lrs, n), _norm_seq(wds, n)
    clip = clip_gradient if clip_gradient >= 0 else None
    out = []
    for i in range(n):
        w, g, w32 = wgw32[3 * i], wgw32[3 * i + 1], wgw32[3 * i + 2]
        gg = _apply_wd_rescale(w32, g.astype(jnp.float32), wds[i],
                               rescale_grad, clip)
        nw32 = w32 - lrs[i] * gg
        out.extend([nw32.astype(w.dtype), nw32])
    return tuple(out)


@register("multi_mp_sgd_mom_update", differentiable=False,
          num_outputs=None)
def multi_mp_sgd_mom_update(*wgmw32, lrs=0.01, wds=0.0, momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0,
                            num_weights=None, **kw):
    """Interleaved (w0, g0, m0, w32_0, ...) -> (w', m', w32', ...)."""
    n = num_weights or len(wgmw32) // 4
    lrs, wds = _norm_seq(lrs, n), _norm_seq(wds, n)
    clip = clip_gradient if clip_gradient >= 0 else None
    out = []
    for i in range(n):
        w, g, m, w32 = wgmw32[4 * i:4 * i + 4]
        gg = _apply_wd_rescale(w32, g.astype(jnp.float32), wds[i],
                               rescale_grad, clip)
        nm = momentum * m - lrs[i] * gg
        nw32 = w32 + nm
        out.extend([nw32.astype(w.dtype), nm, nw32])
    return tuple(out)
