"""Fused optimizer update ops (reference: ``src/operator/optimizer_op.cc``,
``src/operator/contrib/adamw.cc``, multi-tensor ``multi_sgd_update``
[unverified]).

Each op is a pure function ``(weight, grad, *states, **hyper) ->
(new_weight, *new_states)``. The imperative layer rebinds the input NDArrays
(MXNet semantics: optimizer ops mutate weight/state in place); the Trainer's
fused path stacks many parameters into ONE jitted call so the whole optimizer
step is a single XLA executable with donated buffers — the TPU equivalent of
the reference's multi-tensor CUDA kernels.
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register

__all__ = []


def _apply_wd_rescale(weight, grad, wd, rescale_grad, clip_gradient):
    grad = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        grad = jnp.clip(grad, -clip_gradient, clip_gradient)
    return grad + wd * weight


@register("sgd_update", mutates_input=0, differentiable=False)
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    return weight - lr * g


@register("sgd_mom_update", mutates_input=0, differentiable=False)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("nag_mom_update", mutates_input=0, differentiable=False)
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", mutates_input=0, differentiable=False)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    return (weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon),
            new_mean, new_var)


@register("adamw_update", aliases=["_adamw_update"], mutates_input=0,
          differentiable=False)
def adamw_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                 clip_gradient=-1.0, **kw):
    # decoupled weight decay (Loshchilov & Hutter) — wd is NOT in the moments
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    update = new_mean / (jnp.sqrt(new_var) + epsilon) + wd * weight
    return weight - eta * lr * update, new_mean, new_var


@register("lamb_update_phase1", mutates_input=None, differentiable=False)
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    if bias_correction:
        mean_hat = new_mean / (1.0 - beta1 ** t)
        var_hat = new_var / (1.0 - beta2 ** t)
    else:
        mean_hat, var_hat = new_mean, new_var
    update = mean_hat / (jnp.sqrt(var_hat) + epsilon) + wd * weight
    return update, new_mean, new_var


@register("lamb_update_phase2", mutates_input=0, differentiable=False)
def lamb_update_phase2(weight, g, r1, r2, lr=0.001, lower_bound=-1.0,
                       upper_bound=-1.0, **kw):
    if lower_bound >= 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound >= 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2, 1.0)
    return weight - lr * ratio * g


@register("rmsprop_update", mutates_input=0, differentiable=False)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights >= 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register("rmspropalex_update", mutates_input=0, differentiable=False)
def rmspropalex_update(weight, grad, n, g_state, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    # Graves 2013 / reference rmspropalex_update: BOTH accumulators decay
    # with gamma1; gamma2 is only the momentum on delta
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_g = gamma1 * g_state + (1 - gamma1) * g
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    new_w = weight + new_delta
    if clip_weights >= 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n, new_g, new_delta


@register("ftrl_update", mutates_input=0, differentiable=False)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) > lamda1,
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd),
        0.0,
    )
    return new_w.astype(weight.dtype), new_z, new_n


@register("signsgd_update", mutates_input=0, differentiable=False)
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    return weight - lr * jnp.sign(g)


@register("signum_update", mutates_input=0, differentiable=False)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0, **kw):
    g = _apply_wd_rescale(weight, grad, wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_mom = momentum * mom - (1 - momentum) * g
    new_w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return new_w, new_mom


@register("mp_sgd_update", mutates_input=0, differentiable=False)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, **kw):
    # multi-precision: master fp32 copy updated, low-precision weight recast
    g = _apply_wd_rescale(weight32, grad.astype(jnp.float32), wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_w32 = weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", mutates_input=0, differentiable=False)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = _apply_wd_rescale(weight32, grad.astype(jnp.float32), wd, rescale_grad,
                          clip_gradient if clip_gradient >= 0 else None)
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


# ------------------------------------------------- multi-tensor kernels
# Reference: multi_sgd_update / multi_sum_sq / multi_lars in
# ``src/operator/optimizer_op.cc`` and ``contrib/multi_sum_sq.cc``
# [unverified] — one CUDA kernel walking many tensors to kill per-op
# launch overhead. Here each op takes the flat variadic tensor list the
# reference took; called under one jit, XLA compiles the whole update
# into a single executable, which is the same dispatch-amortization win
# (the eager Trainer's fused path feeds these).

def _norm_seq(v, n):
    if isinstance(v, (tuple, list)):
        return [float(x) for x in v]
    return [float(v)] * n


@register("multi_sum_sq", differentiable=False, num_outputs=None)
def multi_sum_sq(*arrays, num_arrays=None, **kw):
    """Per-array sum of squares, returned as one (num_arrays,) vector."""
    return jnp.stack([jnp.sum(jnp.square(a.astype(jnp.float32)))
                      for a in arrays])


@register("multi_lars", differentiable=False)
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
               eps=1e-8, rescale_grad=1.0, **kw):
    """LARS layer-wise lr scaling on stacked per-layer scalars
    (reference multi_lars): lr_i *= eta*||w||/(||g||+wd*||w||+eps)."""
    wn = jnp.sqrt(weights_sum_sq)
    gn = jnp.sqrt(grads_sum_sq) * rescale_grad
    coef = eta * wn / (gn + wds * wn + eps)
    return jnp.where(jnp.logical_and(wn > 0, gn > 0), lrs * coef, lrs)


@register("multi_sgd_update", differentiable=False, num_outputs=None)
def multi_sgd_update(*weights_grads, lrs=0.01, wds=0.0, rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=None, **kw):
    """Interleaved (w0, g0, w1, g1, ...) -> tuple of updated weights."""
    n = num_weights or len(weights_grads) // 2
    lrs, wds = _norm_seq(lrs, n), _norm_seq(wds, n)
    clip = clip_gradient if clip_gradient >= 0 else None
    out = []
    for i in range(n):
        w, g = weights_grads[2 * i], weights_grads[2 * i + 1]
        gg = _apply_wd_rescale(w, g, wds[i], rescale_grad, clip)
        out.append(w - lrs[i] * gg)
    return tuple(out)


@register("multi_sgd_mom_update", differentiable=False, num_outputs=None)
def multi_sgd_mom_update(*wgm, lrs=0.01, wds=0.0, momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         num_weights=None, **kw):
    """Interleaved (w0, g0, m0, ...) -> (w0', m0', w1', m1', ...)."""
    n = num_weights or len(wgm) // 3
    lrs, wds = _norm_seq(lrs, n), _norm_seq(wds, n)
    clip = clip_gradient if clip_gradient >= 0 else None
    out = []
    for i in range(n):
        w, g, m = wgm[3 * i], wgm[3 * i + 1], wgm[3 * i + 2]
        gg = _apply_wd_rescale(w, g, wds[i], rescale_grad, clip)
        nm = momentum * m - lrs[i] * gg
        out.extend([w + nm, nm])
    return tuple(out)


@register("multi_mp_sgd_update", differentiable=False, num_outputs=None)
def multi_mp_sgd_update(*wgw32, lrs=0.01, wds=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, num_weights=None, **kw):
    """Interleaved (w0, g0, w32_0, ...) -> (w0', w32_0', ...)."""
    n = num_weights or len(wgw32) // 3
    lrs, wds = _norm_seq(lrs, n), _norm_seq(wds, n)
    clip = clip_gradient if clip_gradient >= 0 else None
    out = []
    for i in range(n):
        w, g, w32 = wgw32[3 * i], wgw32[3 * i + 1], wgw32[3 * i + 2]
        gg = _apply_wd_rescale(w32, g.astype(jnp.float32), wds[i],
                               rescale_grad, clip)
        nw32 = w32 - lrs[i] * gg
        out.extend([nw32.astype(w.dtype), nw32])
    return tuple(out)


@register("multi_mp_sgd_mom_update", differentiable=False,
          num_outputs=None)
def multi_mp_sgd_mom_update(*wgmw32, lrs=0.01, wds=0.0, momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0,
                            num_weights=None, **kw):
    """Interleaved (w0, g0, m0, w32_0, ...) -> (w', m', w32', ...)."""
    n = num_weights or len(wgmw32) // 4
    lrs, wds = _norm_seq(lrs, n), _norm_seq(wds, n)
    clip = clip_gradient if clip_gradient >= 0 else None
    out = []
    for i in range(n):
        w, g, m, w32 = wgmw32[4 * i:4 * i + 4]
        gg = _apply_wd_rescale(w32, g.astype(jnp.float32), wds[i],
                               rescale_grad, clip)
        nm = momentum * m - lrs[i] * gg
        nw32 = w32 + nm
        out.extend([nw32.astype(w.dtype), nm, nw32])
    return tuple(out)


# ------------------------------------------------ round-5 optimizer tail
@register("ftml_update", mutates_input=0, differentiable=False)
def ftml_update(weight, grad, d, v, z, lr=0.0025, beta1=0.6, beta2=0.999,
                epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                clip_grad=-1.0, **kw):
    """FTML (reference ``ftml_update``, ``src/operator/optimizer_op.cc``
    [unverified]; Zheng & Kwok 2017): follow-the-moving-leader."""
    g = grad * rescale_grad + wd * weight
    if clip_grad >= 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    tf = jnp.float32(t)
    new_v = beta2 * v + (1.0 - beta2) * jnp.square(g)
    d_t = (1.0 - beta1 ** tf) / lr * (
        jnp.sqrt(new_v / (1.0 - beta2 ** tf)) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1.0 - beta1) * g - sigma * weight
    new_w = -new_z / d_t
    return new_w.astype(weight.dtype), d_t, new_v, new_z


@register("_contrib_group_adagrad_update",
          aliases=["group_adagrad_update"], mutates_input=0,
          differentiable=False)
def group_adagrad_update(weight, grad, history, lr=0.01, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5, **kw):
    """Row-wise (grouped) AdaGrad (reference
    ``src/operator/contrib/optimizer_op.cc`` [unverified]): one history
    scalar per ROW of the weight (embedding-style)."""
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mean_sq = jnp.mean(jnp.square(g), axis=tuple(range(1, g.ndim)))
    new_h = history + mean_sq.reshape(history.shape)
    denom = jnp.sqrt(new_h).reshape((-1,) + (1,) * (g.ndim - 1)) + epsilon
    return (weight - lr * g / denom).astype(weight.dtype), new_h


@register("_contrib_multi_adamw_update", aliases=["multi_adamw_update"],
          differentiable=False, num_outputs=None)
def multi_adamw_update(*wgmv, lrs=0.001, wds=0.0, etas=1.0, beta1=0.9,
                       beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                       num_weights=None, rescale_grad=1.0, **kw):
    """Multi-tensor AdamW (reference ``_contrib_multi_adamw_update``
    [unverified]): interleaved (w, g, m, v) x N -> (w', m', v') x N;
    ``etas`` is the per-tensor schedule multiplier the contrib op took."""
    n = num_weights or len(wgmv) // 4
    lrs, wds = _norm_seq(lrs, n), _norm_seq(wds, n)
    etas = _norm_seq(etas, n)
    out = []
    for i in range(n):
        w, g, m, v = wgmv[4 * i:4 * i + 4]
        gg = g * rescale_grad
        if clip_gradient >= 0:
            gg = jnp.clip(gg, -clip_gradient, clip_gradient)
        nm = beta1 * m + (1.0 - beta1) * gg
        nv = beta2 * v + (1.0 - beta2) * jnp.square(gg)
        upd = nm / (jnp.sqrt(nv) + epsilon) + wds[i] * w
        out.extend([(w - etas[i] * lrs[i] * upd).astype(w.dtype), nm, nv])
    return tuple(out)


@register("preloaded_multi_sgd_update", differentiable=False,
          num_outputs=None)
def preloaded_multi_sgd_update(*args, rescale_grad=1.0, clip_gradient=-1.0,
                               num_weights=None, **kw):
    """Reference ``preloaded_multi_sgd_update`` [unverified]: like
    multi_sgd_update but lrs/wds arrive as DEVICE arrays (trailing two
    operands) so schedule changes never re-trace."""
    lrs, wds = args[-2], args[-1]
    wg = args[:-2]
    n = num_weights or len(wg) // 2
    clip = clip_gradient if clip_gradient >= 0 else None
    out = []
    for i in range(n):
        w, g = wg[2 * i], wg[2 * i + 1]
        gg = _apply_wd_rescale(w, g, wds[i], rescale_grad, clip)
        out.append(w - lrs[i] * gg)
    return tuple(out)


@register("preloaded_multi_sgd_mom_update", differentiable=False,
          num_outputs=None)
def preloaded_multi_sgd_mom_update(*args, momentum=0.0, rescale_grad=1.0,
                                   clip_gradient=-1.0, num_weights=None,
                                   **kw):
    lrs, wds = args[-2], args[-1]
    wgm = args[:-2]
    n = num_weights or len(wgm) // 3
    clip = clip_gradient if clip_gradient >= 0 else None
    out = []
    for i in range(n):
        w, g, m = wgm[3 * i], wgm[3 * i + 1], wgm[3 * i + 2]
        gg = _apply_wd_rescale(w, g, wds[i], rescale_grad, clip)
        nm = momentum * m - lrs[i] * gg
        out.extend([w + nm, nm])
    return tuple(out)


@register("_contrib_lans_update_phase1", aliases=["lans_update_phase1"],
          differentiable=False)
def lans_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, **kw):
    """LANS phase 1 (reference ``src/operator/contrib/adamw.cc`` LANS
    [unverified]; Zheng et al. 2020): gradient is NORMALIZED before the
    moments; returns the two candidate update directions interleaved
    along a leading axis of 2 (m-part, g-part) plus new moments."""
    g = grad * rescale_grad
    gnorm = jnp.sqrt(jnp.sum(jnp.square(g)))
    g = g / jnp.maximum(gnorm, 1e-12)
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    tf = jnp.float32(t)
    nm = beta1 * mean + (1.0 - beta1) * g
    nv = beta2 * var + (1.0 - beta2) * jnp.square(g)
    m_hat = nm / (1.0 - beta1 ** tf)
    v_hat = nv / (1.0 - beta2 ** tf)
    r1 = m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * weight
    r2 = g / (jnp.sqrt(v_hat) + epsilon) + wd * weight
    return jnp.stack([r1, r2]), nm, nv


@register("_contrib_lans_update_phase2", aliases=["lans_update_phase2"],
          mutates_input=0, differentiable=False)
def lans_update_phase2(weight, gpair, wnorm, gnorms, lr=0.001, beta1=0.9,
                       lower_bound=-1.0, upper_bound=-1.0, **kw):
    """LANS phase 2: trust-ratio-scaled blend of the two phase-1
    directions; gpair is the stacked (2, ...) output of phase 1,
    gnorms the (2,) norms of those directions."""
    ratio = jnp.where(gnorms > 0, wnorm / jnp.maximum(gnorms, 1e-12), 1.0)
    if lower_bound >= 0:
        ratio = jnp.maximum(ratio, lower_bound)
    if upper_bound >= 0:
        ratio = jnp.minimum(ratio, upper_bound)
    step = beta1 * ratio[0] * gpair[0] + (1.0 - beta1) * ratio[1] * gpair[1]
    return (weight - lr * step).astype(weight.dtype)
