"""Operator library.

TPU-native analogue of ``src/operator/**`` [unverified]: every op is a pure
function over jax arrays registered in ``registry``; XLA replaces the
reference's hand-written CPU/CUDA kernels for everything ``tensor/``-like,
and Pallas kernels (``ops.pallas``) replace hand-written CUDA where fusion
alone is not enough (attention, fused optimizers).
"""

from . import registry
from .registry import Operator, register, get, list_ops, alias
from . import tensor  # noqa: F401 - registers tensor ops
from . import nn  # noqa: F401 - registers nn ops
from . import contrib  # noqa: F401 - registers contrib ops
from . import optimizer_op  # noqa: F401 - registers fused optimizer updates
from . import fused_loss  # noqa: F401 - registers blocked vocab-proj + CE
from . import linalg  # noqa: F401 - registers linalg_* (la_op family)
from . import spatial  # noqa: F401 - registers spatial transformer group
from . import random_op  # noqa: F401 - registers _random_*/sample_* ops
from . import params  # noqa: F401 - typed op-param schemas (dmlc::Parameter)
from .params import P, op_params, describe_op, validate_params, \
    schema_to_json, list_documented_ops
