"""Contrib ops: transformer attention, detection ops, resize/pooling extras.

TPU-native analogue of ``src/operator/contrib/`` [unverified]:
- ``transformer.cc``: the interleaved multi-head attention matmuls used by
  GluonNLP BERT (``_contrib_interleaved_matmul_selfatt_qk`` etc.) and
  ``div_sqrt_dim``. Here they are thin einsum compositions — under
  ``hybridize()`` XLA fuses them; the flash-attention Pallas kernel in
  ``ops.pallas`` is the fast path that subsumes the qk/valatt pair.
- ``bounding_box.cc``: ``box_nms``, ``box_iou``, ``box_encode/decode``.
- ``roi_align.cc``, ``adaptive_avg_pooling.cc``, ``bilinear_resize.cc``.

Shapes/conventions follow the reference ops so GluonNLP/GluonCV-style model
code ports unchanged.
"""

from __future__ import annotations

import math
import os as _os

import numpy as _np
import jax
import jax.numpy as jnp

from .registry import register

_NEG = -1e18


# ----------------------------------------------------- transformer (BERT ops)
@register("_contrib_div_sqrt_dim", aliases=["div_sqrt_dim"])
def div_sqrt_dim(data, **kw):
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))


@register("_contrib_interleaved_matmul_selfatt_qk", aliases=["interleaved_matmul_selfatt_qk"])
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1, **kw):
    """Input (L, B, H*3*C) with per-head interleaved q,k,v; output (B*H, L, L)."""
    L, B, P = queries_keys_values.shape
    C = P // (3 * heads)
    x = queries_keys_values.reshape(L, B, heads, 3, C)
    q = x[:, :, :, 0, :]  # (L, B, H, C)
    k = x[:, :, :, 1, :]
    scores = jnp.einsum("lbhc,mbhc->bhlm", q, k)
    return scores.reshape(B * heads, L, L)


@register("_contrib_interleaved_matmul_selfatt_valatt", aliases=["interleaved_matmul_selfatt_valatt"])
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads=1, **kw):
    """attention (B*H, L, L) x values from (L, B, H*3*C) -> (L, B, H*C)."""
    L, B, P = queries_keys_values.shape
    C = P // (3 * heads)
    v = queries_keys_values.reshape(L, B, heads, 3, C)[:, :, :, 2, :]
    att = attention.reshape(B, heads, L, L)
    out = jnp.einsum("bhlm,mbhc->lbhc", att, v)
    return out.reshape(L, B, heads * C)


@register("_contrib_interleaved_matmul_encdec_qk", aliases=["interleaved_matmul_encdec_qk"])
def interleaved_matmul_encdec_qk(queries, keys_values, heads=1, **kw):
    Lq, B, P = queries.shape
    C = P // heads
    Lk = keys_values.shape[0]
    q = queries.reshape(Lq, B, heads, C)
    k = keys_values.reshape(Lk, B, heads, 2, C)[:, :, :, 0, :]
    return jnp.einsum("lbhc,mbhc->bhlm", q, k).reshape(B * heads, Lq, Lk)


@register("_contrib_interleaved_matmul_encdec_valatt", aliases=["interleaved_matmul_encdec_valatt"])
def interleaved_matmul_encdec_valatt(keys_values, attention, heads=1, **kw):
    Lk, B, P = keys_values.shape
    C = P // (2 * heads)
    v = keys_values.reshape(Lk, B, heads, 2, C)[:, :, :, 1, :]
    Lq = attention.shape[1]
    att = attention.reshape(B, heads, Lq, Lk)
    out = jnp.einsum("bhlm,mbhc->lbhc", att, v)
    return out.reshape(Lq, B, heads * C)


@register("_contrib_arange_like", aliases=["arange_like"], differentiable=False)
def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None, **kw):
    if axis is None:
        n = data.size
        return (jnp.arange(n) * step + start).reshape(data.shape).astype(data.dtype)
    n = data.shape[axis]
    return (jnp.arange(n) * step + start).astype(data.dtype)


# --------------------------------------------------------------- bounding box
def _corner(boxes, fmt):
    if fmt == "corner":
        return boxes
    x, y, w, h = jnp.split(boxes, 4, axis=-1)
    return jnp.concatenate([x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


@register("_contrib_box_iou", aliases=["box_iou"], differentiable=False)
def box_iou(lhs, rhs, format="corner", **kw):
    """IoU matrix: lhs (..., N, 4), rhs (..., M, 4) -> (..., N, M)."""
    a = _corner(lhs, format)[..., :, None, :]
    b = _corner(rhs, format)[..., None, :, :]
    xx1 = jnp.maximum(a[..., 0], b[..., 0])
    yy1 = jnp.maximum(a[..., 1], b[..., 1])
    xx2 = jnp.minimum(a[..., 2], b[..., 2])
    yy2 = jnp.minimum(a[..., 3], b[..., 3])
    inter = jnp.clip(xx2 - xx1, 0) * jnp.clip(yy2 - yy1, 0)
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_b = (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


@register("_contrib_box_nms", aliases=["box_nms"], differentiable=False)
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2,
            score_index=1, id_index=-1, background_id=-1, force_suppress=False,
            in_format="corner", out_format="corner", **kw):
    """Mask-based NMS (reference: ``bounding_box.cc`` box_nms [unverified]).

    data (..., N, K) with score at score_index and box at coord_start:+4.
    Suppressed entries have score set to -1, matching the reference.
    O(N^2) IoU matrix + sequential suppression via lax.scan — static shapes
    keep XLA happy (no dynamic compaction on device).
    """
    batch_shape = data.shape[:-2]
    N, K = data.shape[-2:]
    flat = data.reshape((-1, N, K))

    def one(batch):
        scores = batch[:, score_index]
        boxes = _corner(batch[:, coord_start:coord_start + 4], in_format)
        valid = scores > valid_thresh
        if background_id >= 0 and id_index >= 0:
            valid = valid & (batch[:, id_index] != background_id)
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
        # entries beyond topk can neither survive NOR suppress (a suppressor
        # must itself be kept, and keep0 is False past topk), so restricting
        # the IoU matrix and the suppression scan to the top-M sorted entries
        # is exact — O(topk^2) instead of O(N^2), O(topk) scan steps
        M = min(N, topk) if topk > 0 else N
        order_m = order[:M]
        sboxes = boxes[order_m]
        svalid = valid[order_m]
        iou = box_iou(sboxes, sboxes)
        if not force_suppress and id_index >= 0:
            ids = batch[:, id_index][order_m]
            same = ids[:, None] == ids[None, :]
            iou = jnp.where(same, iou, 0.0)

        def step(keep, i):
            sup = (iou[i] > overlap_thresh) & (jnp.arange(M) > i) & keep[i]
            keep = keep & ~sup
            return keep, 0

        keep0 = svalid
        # unrolled x10: batches of sequential (M,)-vector steps fuse into
        # straight-line kernels, cutting the device-loop per-iteration
        # overhead ~10x without the compile blowup a FULL unroll causes
        # on big batches (the suppression order stays exactly greedy)
        keep, _ = jax.lax.scan(step, keep0, jnp.arange(M), unroll=10)
        # scatter back to original positions (beyond-topk stays suppressed)
        keep_orig = jnp.zeros((N,), bool).at[order_m].set(keep)
        out = batch.at[:, score_index].set(
            jnp.where(keep_orig, batch[:, score_index], -1.0)
        )
        return out

    out = jax.vmap(one)(flat)
    return out.reshape(batch_shape + (N, K))


@register("_contrib_box_encode", aliases=["box_encode"], differentiable=False)
def box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
               stds=(0.1, 0.1, 0.2, 0.2), **kw):
    """SSD-style target encode (reference: bounding_box.cc [unverified]).

    samples (B, N) in {-1, 0, 1}; matches (B, N) indices into refs;
    anchors (B, N, 4), refs (B, M, 4) corner format.
    Returns (targets (B, N, 4), masks (B, N, 4)).
    """
    m = matches.astype(jnp.int32)
    ref = jnp.take_along_axis(refs, m[..., None], axis=1)
    ax1, ay1, ax2, ay2 = jnp.split(anchors, 4, axis=-1)
    gx1, gy1, gx2, gy2 = jnp.split(ref, 4, axis=-1)
    aw, ah = ax2 - ax1, ay2 - ay1
    acx, acy = ax1 + aw / 2, ay1 + ah / 2
    gw, gh = gx2 - gx1, gy2 - gy1
    gcx, gcy = gx1 + gw / 2, gy1 + gh / 2
    t0 = ((gcx - acx) / jnp.maximum(aw, 1e-12) - means[0]) / stds[0]
    t1 = ((gcy - acy) / jnp.maximum(ah, 1e-12) - means[1]) / stds[1]
    t2 = (jnp.log(jnp.maximum(gw, 1e-12) / jnp.maximum(aw, 1e-12)) - means[2]) / stds[2]
    t3 = (jnp.log(jnp.maximum(gh, 1e-12) / jnp.maximum(ah, 1e-12)) - means[3]) / stds[3]
    targets = jnp.concatenate([t0, t1, t2, t3], axis=-1)
    mask = (samples > 0.5)[..., None].astype(targets.dtype) * jnp.ones_like(targets)
    return targets * mask, mask


@register("_contrib_box_decode", aliases=["box_decode"], differentiable=False)
def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="corner", **kw):
    a = _corner(anchors, format)
    ax1, ay1, ax2, ay2 = jnp.split(a, 4, axis=-1)
    aw, ah = ax2 - ax1, ay2 - ay1
    acx, acy = ax1 + aw / 2, ay1 + ah / 2
    d0, d1, d2, d3 = jnp.split(data, 4, axis=-1)
    cx = d0 * std0 * aw + acx
    cy = d1 * std1 * ah + acy
    dw, dh = d2 * std2, d3 * std3
    if clip > 0:
        dw, dh = jnp.minimum(dw, clip), jnp.minimum(dh, clip)
    w, h = jnp.exp(dw) * aw / 2, jnp.exp(dh) * ah / 2
    return jnp.concatenate([cx - w, cy - h, cx + w, cy + h], axis=-1)


# ------------------------------------------------------------------ ROIAlign
def _roi_sample(data, rois, pooled_size, spatial_scale, sample_ratio, aligned,
                reduce_fn):
    """Shared bilinear ROI sampler: sample sr×sr points per output bin, then
    reduce with ``reduce_fn`` (mean → ROIAlign, max → legacy ROIPooling).

    rois (R, 5) rows [batch_idx, x1, y1, x2, y2] — reference layout — or
    (B, K, 4|5) per-image rois (batched fast path: with flat rois every
    ROI dynamically gathers its whole (C, H, W) image, which at detection
    sizes moves GBs through HBM; the batched form maps over images so no
    cross-image gather exists)."""
    ph, pw = pooled_size if isinstance(pooled_size, (tuple, list)) else (pooled_size,) * 2
    sr = sample_ratio if sample_ratio > 0 else 2
    offset = 0.5 if aligned else 0.0

    H, W = data.shape[2], data.shape[3]

    def _weights(roi):
        """roi (4,) [x1,y1,x2,y2] -> bilinear weight mats (s,H), (t,W).

        Separable bilinear interpolation as two matmuls (MXU path; a
        per-point gather formulation is scatter-bound on TPU): weight of
        pixel h for sample y is the bilinear hat max(0, 1-|y-h|), which is
        exactly map_coordinates(order=1, mode="constant", cval=0)."""
        x1, y1, x2, y2 = (roi[0] * spatial_scale - offset,
                          roi[1] * spatial_scale - offset,
                          roi[2] * spatial_scale - offset,
                          roi[3] * spatial_scale - offset)
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        ys = y1 + (jnp.arange(ph * sr) + 0.5) * rh / (ph * sr)
        xs = x1 + (jnp.arange(pw * sr) + 0.5) * rw / (pw * sr)
        wy = jnp.maximum(0.0, 1.0 - jnp.abs(ys[:, None] - jnp.arange(H)[None, :]))
        wx = jnp.maximum(0.0, 1.0 - jnp.abs(xs[:, None] - jnp.arange(W)[None, :]))
        return wy, wx

    if rois.ndim == 3:
        # batched fast path: (B, K, 4|5) rois belong to data[b] by position
        coords = rois[..., -4:]

        def one_img(img, r):  # img (C, H, W), r (K, 4)
            wy, wx = jax.vmap(_weights)(r)  # (K, s, H), (K, t, W)
            t1 = jnp.einsum("ksh,chw->kcsw", wy, img)
            sampled = jnp.einsum("kcsw,ktw->kcst", t1, wx)
            sampled = sampled.reshape(r.shape[0], img.shape[0], ph, sr, pw, sr)
            return reduce_fn(sampled, (3, 5))

        return jax.vmap(one_img)(data, coords)  # (B, K, C, ph, pw)

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        img = data[bidx]  # (C, H, W)
        wy, wx = _weights(roi[1:5])
        t1 = jnp.einsum("sh,chw->csw", wy, img)
        sampled = jnp.einsum("csw,tw->cst", t1, wx)
        sampled = sampled.reshape(img.shape[0], ph, sr, pw, sr)
        return reduce_fn(sampled, (2, 4))

    return jax.vmap(one_roi)(rois)


@register("_contrib_ROIAlign", aliases=["ROIAlign", "roi_align"])
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0, sample_ratio=-1,
              position_sensitive=False, aligned=False, **kw):
    """Bilinear ROI pooling (reference: ``roi_align.cc`` [unverified]).

    data (N, C, H, W); rois (R, 5) rows [batch_idx, x1, y1, x2, y2]
    -> (R, C, ph, pw), or the batched fast path rois (B, K, 4|5)
    -> (B, K, C, ph, pw) where rois[b] belong to data[b] (no cross-image
    gather — use this from detection heads).
    Average of sampled bilinear points per bin, matching the reference.
    """
    return _roi_sample(data, rois, pooled_size, spatial_scale, sample_ratio,
                       aligned, jnp.mean)


@register("ROIPooling", aliases=["roi_pooling"])
def roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0, **kw):
    """Exact quantized max ROI pooling (legacy op, ``roi_pooling.cc``
    [unverified]): integer bin boundaries, max over cells — computed with
    static-shape range masks so XLA sees no dynamic gathers."""
    ph, pw = pooled_size if isinstance(pooled_size, (tuple, list)) else (pooled_size,) * 2
    N, C, H, W = data.shape
    rows = jnp.arange(H)
    cols = jnp.arange(W)
    obins_h = jnp.arange(ph)
    obins_w = jnp.arange(pw)

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        img = data[bidx]  # (C, H, W)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        hlen = jnp.maximum(y2 - y1 + 1, 1)
        wlen = jnp.maximum(x2 - x1 + 1, 1)
        sh = y1 + (obins_h * hlen) // ph
        eh = y1 + -((-(obins_h + 1) * hlen) // ph)  # ceil division
        sw = x1 + (obins_w * wlen) // pw
        ew = x1 + -((-(obins_w + 1) * wlen) // pw)
        mask_r = (rows[None, :] >= sh[:, None]) & (rows[None, :] < eh[:, None])  # (ph, H)
        mask_c = (cols[None, :] >= sw[:, None]) & (cols[None, :] < ew[:, None])  # (pw, W)
        mask = mask_r[:, None, :, None] & mask_c[None, :, None, :]  # (ph, pw, H, W)
        big = jnp.where(mask[None], img[:, None, None, :, :], -jnp.inf)
        out = big.max(axis=(3, 4))  # (C, ph, pw)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(rois)


@register("_contrib_PSROIPooling", aliases=["PSROIPooling", "psroipooling"])
def psroi_pooling(data, rois, spatial_scale=1.0, output_dim=None,
                  pooled_size=7, group_size=0, **kw):
    """Position-sensitive ROI pooling (R-FCN; reference:
    ``src/operator/contrib/psroi_pooling.cc`` [unverified]).

    data (B, C, H, W) with C = output_dim * group_size**2; rois (R, 5)
    rows [batch_idx, x1, y1, x2, y2] -> (R, output_dim, ps, ps). Output
    bin (i, j) of class channel k AVERAGES its own channel slice
    c = (k * gs + gy) * gs + gx over the bin's pixels (reference hard
    integer bins: floor/ceil bounds, empty bin -> 0).

    TPU-first formulation: per-bin membership is a pair of static-shape
    range masks (like ROIPooling above) so the whole op is masked
    reductions + one static gather — no dynamic shapes, fully
    differentiable w.r.t. data."""
    ps = int(pooled_size)
    gs = int(group_size) or ps
    B, C, H, W = data.shape
    K = int(output_dim) if output_dim else C // (gs * gs)
    if C != K * gs * gs:
        raise ValueError(
            f"PSROIPooling: C={C} must equal output_dim*group_size^2 "
            f"= {K}*{gs}^2")
    rows = jnp.arange(H)
    cols = jnp.arange(W)
    bins = jnp.arange(ps)
    # channel index per (k, i, j): position-sensitive slice selection
    gy = (jnp.arange(ps) * gs) // ps
    cidx = ((jnp.arange(K)[:, None, None] * gs + gy[None, :, None]) * gs
            + gy[None, None, :])  # (K, ps, ps)

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        img = data[bidx]  # (C, H, W)
        x1 = jnp.round(roi[1]) * spatial_scale
        y1 = jnp.round(roi[2]) * spatial_scale
        x2 = jnp.round(roi[3] + 1.0) * spatial_scale
        y2 = jnp.round(roi[4] + 1.0) * spatial_scale
        bh = jnp.maximum(y2 - y1, 0.1) / ps
        bw = jnp.maximum(x2 - x1, 0.1) / ps
        sh = jnp.clip(jnp.floor(y1 + bins * bh), 0, H).astype(jnp.int32)
        eh = jnp.clip(jnp.ceil(y1 + (bins + 1) * bh), 0, H).astype(jnp.int32)
        sw = jnp.clip(jnp.floor(x1 + bins * bw), 0, W).astype(jnp.int32)
        ew = jnp.clip(jnp.ceil(x1 + (bins + 1) * bw), 0, W).astype(jnp.int32)
        mask_r = (rows[None, :] >= sh[:, None]) & \
            (rows[None, :] < eh[:, None])   # (ps, H)
        mask_c = (cols[None, :] >= sw[:, None]) & \
            (cols[None, :] < ew[:, None])   # (ps, W)
        # per-bin sums as two masked matmuls (MXU path)
        t = jnp.einsum("ih,chw->ciw", mask_r.astype(img.dtype), img)
        sums = jnp.einsum("ciw,jw->cij", t, mask_c.astype(img.dtype))
        cnt = (eh - sh)[:, None] * (ew - sw)[None, :]  # (ps, ps)
        avg = sums / jnp.maximum(cnt, 1)[None]
        avg = jnp.where((cnt > 0)[None], avg, 0.0)     # empty bin -> 0
        ii = jnp.arange(ps)[:, None]
        jj = jnp.arange(ps)[None, :]
        return avg[cidx, ii[None], jj[None]]           # (K, ps, ps)

    return jax.vmap(one_roi)(rois)


# ----------------------------------------------------------- pooling/resize
def _adaptive_matrix(in_size: int, out_size: int):
    w = _np.zeros((out_size, in_size), dtype=_np.float32)
    for o in range(out_size):
        s = (o * in_size) // out_size
        e = -((-(o + 1) * in_size) // out_size)  # ceil
        w[o, s:e] = 1.0 / (e - s)
    return jnp.asarray(w)


@register("_contrib_AdaptiveAvgPooling2D", aliases=["AdaptiveAvgPooling2D"])
def adaptive_avg_pooling(data, output_size=1, **kw):
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else tuple(output_size)
    wh = _adaptive_matrix(data.shape[2], oh)
    ww = _adaptive_matrix(data.shape[3], ow)
    return jnp.einsum("nchw,oh,pw->ncop", data, wh, ww)


@register("_contrib_BilinearResize2D", aliases=["BilinearResize2D"])
def bilinear_resize(data, height=None, width=None, scale_height=None,
                    scale_width=None, mode="size", align_corners=True, **kw):
    n, c, h, w = data.shape
    oh = int(height) if height else int(h * scale_height)
    ow = int(width) if width else int(w * scale_width)
    if align_corners and oh > 1 and ow > 1:
        ys = jnp.linspace(0, h - 1, oh)
        xs = jnp.linspace(0, w - 1, ow)
        yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
        coords = jnp.stack([yy.ravel(), xx.ravel()])

        def per_chan(ch):
            return jax.scipy.ndimage.map_coordinates(ch, coords, order=1).reshape(oh, ow)

        flat = data.reshape(n * c, h, w)
        return jax.vmap(per_chan)(flat).reshape(n, c, oh, ow)
    return jax.image.resize(data, (n, c, oh, ow), method="bilinear")


@register("_contrib_count_sketch", aliases=["count_sketch"],
          differentiable=False)
def count_sketch(data, h, s, out_dim=None, **kw):  # rarely used; minimal
    idx = h.astype(jnp.int32)
    signed = data * s
    out = jnp.zeros(data.shape[:-1] + (int(out_dim),), data.dtype)
    return out.at[..., idx].add(signed)


# ------------------------------------------------------- fused attention
# Below this key length the exact dense path beats the flash kernel on TPU:
# the whole (B,H,Sq,Sk) score tile fits comfortably in HBM/VMEM and XLA
# fuses qk->softmax->pv better than the kernel's block machinery amortizes
# (measured on v5e-lite, BERT b64 s128: dense 50.6 ms/step vs flash 57.3).
def _dense_max_seq() -> int:
    # read per call (advisor round-3): setting the var after import must
    # take effect; jit caching keys on the resulting branch anyway
    return int(_os.environ.get("MXTPU_ATTN_DENSE_MAX", "256"))


def _masked_softmax_probs(s, valid_length, causal, q_offset=None):
    """Shared mask+softmax semantics for both dense layouts: scores s
    are ALWAYS (B, H, Sq, Sk); keys past valid_length and acausal
    positions drop out; fully-masked rows (valid_length == 0) zero
    instead of NaN, like the flash kernel.

    ``q_offset`` shifts the query positions for the causal mask: query
    row i sits at absolute position ``q_offset + i``, so a single-token
    query attending over a KV cache of ``q_offset`` earlier entries gets
    the correct non-square mask (the incremental-decode contract) instead
    of the historical ``(L, L)`` square assumption. Scalar or per-row
    (B,), traced values welcome."""
    if valid_length is not None:
        mask = jnp.arange(s.shape[3])[None, None, None, :] < \
            valid_length.astype(jnp.int32)[:, None, None, None]
        s = jnp.where(mask, s, -jnp.inf)
    if causal:
        qi = jnp.arange(s.shape[2])[None, None, :, None]
        ki = jnp.arange(s.shape[3])[None, None, None, :]
        if q_offset is not None:
            off = jnp.asarray(q_offset, jnp.int32)
            # scalar offset broadcasts whole-batch; (B,) is per-row
            qi = qi + off.reshape((-1, 1, 1, 1))
        s = jnp.where(qi >= ki, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    if valid_length is not None:
        p = jnp.where(jnp.isfinite(s).any(-1, keepdims=True), p, 0.0)
    return p


def _dense_attention(q, k, v, valid_length, causal, sm_scale,
                     q_offset=None):
    """Exact softmax attention over (B, H, S, D); f32 mask/softmax, grad
    via XLA autodiff. The score dot runs in the OPERAND dtype and
    upcasts after (identical for f32 inputs; the MXU accumulates bf16
    dots in f32 internally anyway): routing the upcast through astype
    makes the backward cast ds down BEFORE the dq/dk matmuls, so under
    AMP every dot stays low-precision — a `preferred_element_type=f32`
    score dot would leak an f32 cotangent into bf16 matmuls
    (tools/check_amp_purity.py flags exactly that)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    p = _masked_softmax_probs(s, valid_length, causal, q_offset)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


def _dense_attention_bshd(q, k, v, valid_length, causal, sm_scale,
                          q_offset=None):
    """Exact softmax attention over (B, S, H, D) operands: the einsums
    carry the head batch dim in place, so the model never writes a head
    transpose. Measured perf-NEUTRAL on v5e (the per-layer QKV copies
    in the BERT trace are XLA's backward-residual layout choice, not
    the transposes — see traces/README round-4 copy audit); kept as the
    default for the simpler graphs."""
    # score dot in operand dtype, f32 after (see _dense_attention: keeps
    # the backward's dq/dk matmuls low-precision under AMP)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * sm_scale
    p = _masked_softmax_probs(s, valid_length, causal, q_offset)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)


@register("_contrib_flash_attention", aliases=["flash_attention"])
def _flash_attention_op(query, key, value, valid_length=None, causal=False,
                        sm_scale=None, block_q=128, block_k=128,
                        layout="BHSD", q_offset=None, **kw):
    """Fused O(S)-memory attention (beyond-reference: replaces the O(L^2)
    interleaved ops of src/operator/contrib/transformer.cc [unverified] as
    the long-context path). ``layout``: "BHSD" (default) takes
    (B, H, S, D) operands; "BSHD" takes (B, S, H, D) — transpose-free
    for layers whose projections emit sequence-major tensors.
    ``valid_length`` (B,) masks padding keys (reference softmax
    ``use_length`` semantics).

    Short sequences (Sk <= MXTPU_ATTN_DENSE_MAX, default 256; read per
    call) take an exact dense path — at these sizes the score tile is
    small and XLA's fusion beats the flash kernel's block overhead; long
    sequences take the O(S)-memory Pallas flash kernel. Both are
    numerically exact softmax attention. NOTE the dense path materializes
    the O(Sq*Sk) score tensor: callers choosing this op specifically for
    O(S) memory at short S should set MXTPU_ATTN_DENSE_MAX=0.

    ``q_offset`` (scalar or (B,), traced ok) shifts causal query
    positions: query row i is at absolute position ``q_offset + i``.
    This is the incremental-decode mask — a ``query_len=1`` query over a
    KV cache of ``q_offset`` earlier entries. Offset and single-token
    queries always run the dense path: a (B, H, 1, Sk) score row IS
    O(Sk) memory, so the flash kernel's block machinery (which bakes in
    square (L, L) position math) buys nothing there."""
    from .pallas import flash_attention as _fa

    # keyword args bypass invoke()'s NDArray unwrapping — accept both
    # styles; NOT getattr(..., "data"): numpy arrays expose a memoryview
    if hasattr(valid_length, "asnumpy"):
        valid_length = valid_length.data
    if hasattr(q_offset, "asnumpy"):
        q_offset = q_offset.data
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(query.shape[-1])
    if layout == "BSHD":
        # transpose-free short-seq path; the Pallas kernel wants BHSD,
        # so long sequences pay the transpose only when they must
        if q_offset is not None or query.shape[1] == 1 or \
                max(query.shape[1], key.shape[1]) <= _dense_max_seq():
            return _dense_attention_bshd(query, key, value, valid_length,
                                         bool(causal), float(sm_scale),
                                         q_offset)
        tq, tk, tv = (x.transpose(0, 2, 1, 3)
                      for x in (query, key, value))
        out = _fa(tq, tk, tv, valid_length, bool(causal), sm_scale,
                  int(block_q), int(block_k))
        return out.transpose(0, 2, 1, 3)
    if q_offset is not None or query.shape[2] == 1 or \
            max(query.shape[2], key.shape[2]) <= _dense_max_seq():
        return _dense_attention(query, key, value, valid_length,
                                bool(causal), float(sm_scale), q_offset)
    return _fa(query, key, value, valid_length, bool(causal), sm_scale,
               int(block_q), int(block_k))


# ------------------------------------------------------------------ multibox
# SSD op trio (reference: ``src/operator/contrib/multibox_prior.cc``,
# ``multibox_target.cc``, ``multibox_detection.cc`` [unverified]). All pure
# jax: anchor generation is iota math, target assignment is an argmax
# bipartite match + optional hard negative mining, detection reuses
# box_decode + box_nms — each jit/vmap friendly.

@register("_contrib_MultiBoxPrior", aliases=["MultiBoxPrior"],
          differentiable=False)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5), **kw):
    """Anchor boxes for one feature map. data (B, C, H, W) ->
    (1, H*W*(len(sizes)+len(ratios)-1), 4) corner boxes, normalized.

    Reference conventions: ``steps``/``offsets`` are (y, x); anchor k at
    each pixel uses (size_k, ratio_0) for k < len(sizes), else
    (size_0, ratio_{k-len(sizes)+1}); widths carry the H/W aspect factor
    so a size-s ratio-1 anchor is square in image pixels."""
    H, W = data.shape[2], data.shape[3]
    sizes = tuple(float(s) for s in sizes)
    ratios = tuple(float(r) for r in ratios)
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + offsets[1]) * step_x
    cxg, cyg = jnp.meshgrid(cx, cy)  # (H, W)

    aspect = H / W  # size-s ratio-1 anchors stay square in pixel space
    ws, hs = [], []
    for k in range(len(sizes)):
        s, r = sizes[k], ratios[0]
        ws.append(s * aspect * math.sqrt(r))
        hs.append(s / math.sqrt(r))
    for j in range(1, len(ratios)):
        s, r = sizes[0], ratios[j]
        ws.append(s * aspect * math.sqrt(r))
        hs.append(s / math.sqrt(r))
    ws = jnp.asarray(ws, jnp.float32)  # (A,)
    hs = jnp.asarray(hs, jnp.float32)

    cxg = cxg[..., None]  # (H, W, 1)
    cyg = cyg[..., None]
    boxes = jnp.stack(
        [
            cxg - ws / 2, cyg - hs / 2, cxg + ws / 2, cyg + hs / 2,
        ],
        axis=-1,
    )  # (H, W, A, 4)
    out = boxes.reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


_VARIANCES = (0.1, 0.1, 0.2, 0.2)


@register("_contrib_MultiBoxTarget", aliases=["MultiBoxTarget"],
          num_outputs=3, differentiable=False)
def multibox_target(anchors, labels, cls_preds, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    variances=_VARIANCES, **kw):
    """Training targets. anchors (1, N, 4) corner; labels (B, M, 5)
    [cls, xmin, ymin, xmax, ymax] padded with cls=-1; cls_preds
    (B, num_cls+1, N).

    -> (box_target (B, N*4), box_mask (B, N*4), cls_target (B, N) with
    0 = background, c+1 = object class c). Reference semantics: each
    ground truth claims its best anchor; other anchors match their best
    gt when IoU >= overlap_threshold. With ``negative_mining_ratio > 0``
    only the hardest ratio*num_pos negatives stay background; the rest
    get ``ignore_label`` (reference hard negative mining — ties at the
    confidence cutoff may keep a few extra negatives)."""
    anchors = anchors.reshape(-1, 4)
    N = anchors.shape[0]

    def per_image(lab, cp):
        cls = lab[:, 0]
        valid = cls >= 0  # (M,)
        M = lab.shape[0]
        gt = lab[:, 1:5]
        iou = box_iou(anchors[None], gt[None])[0]  # (N, M)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)  # (N,)
        best_iou = jnp.max(iou, axis=1)
        matched = jnp.logical_and(best_iou >= overlap_threshold,
                                  best_iou > 0)
        # greedy bipartite matching (reference dmlc matcher): M rounds of
        # global-argmax over still-available (anchor, gt) pairs, so two
        # gts sharing a best anchor each claim a distinct one
        def bipartite_round(carry, _):
            gt_of, avail_a, avail_g = carry
            masked = jnp.where(
                jnp.logical_and(avail_a[:, None], avail_g[None, :]),
                iou, -1.0,
            )
            flat = jnp.argmax(masked)
            i, j = flat // M, flat % M
            ok = masked.reshape(-1)[flat] > 1e-12
            gt_of = jnp.where(
                ok, gt_of.at[i].set(j.astype(jnp.int32)), gt_of
            )
            avail_a = jnp.where(ok, avail_a.at[i].set(False), avail_a)
            avail_g = jnp.where(ok, avail_g.at[j].set(False), avail_g)
            return (gt_of, avail_a, avail_g), 0

        (gt_of_forced, _, _), _ = jax.lax.scan(
            bipartite_round,
            (jnp.full((N,), -1, jnp.int32), jnp.ones((N,), bool), valid),
            None, length=M,
        )
        forced = gt_of_forced >= 0
        assign = jnp.where(forced, jnp.maximum(gt_of_forced, 0), best_gt)
        pos = jnp.logical_or(matched, forced)

        # encode via the shared box_encode kernel (batch of 1)
        targets, mask = box_encode(
            pos[None].astype(jnp.float32), assign[None], anchors[None],
            gt[None], stds=tuple(variances),
        )
        bt = targets[0].reshape(-1)
        bm = mask[0].reshape(-1)
        ct = jnp.where(pos, cls[assign].astype(jnp.int32) + 1, 0)
        ct = ct.astype(jnp.float32)
        if negative_mining_ratio > 0:
            probs = jax.nn.softmax(cp, axis=0)  # (num_cls+1, N)
            neg_conf = jnp.where(pos, -jnp.inf, 1.0 - probs[0])
            k = (negative_mining_ratio * jnp.sum(pos)).astype(jnp.int32)
            k = jnp.clip(k, 0, N - 1)
            thresh = jnp.sort(neg_conf)[::-1][jnp.maximum(k - 1, 0)]
            keep_neg = jnp.logical_and(
                jnp.logical_and(~pos, neg_conf >= thresh), k > 0
            )
            ct = jnp.where(jnp.logical_or(pos, keep_neg), ct,
                           jnp.float32(ignore_label))
        return bt, bm, ct

    bt, bm, ct = jax.vmap(per_image)(labels, cls_preds)
    return bt, bm, ct


@register("_contrib_MultiBoxDetection", aliases=["MultiBoxDetection"],
          differentiable=False)
def multibox_detection(cls_probs, loc_preds, anchors, clip=True,
                       threshold=0.01, nms_threshold=0.5, force_suppress=False,
                       nms_topk=-1, variances=_VARIANCES, **kw):
    """Decode + NMS. cls_probs (B, num_cls+1, N) softmaxed (class 0 =
    background); loc_preds (B, N*4); anchors (1, N, 4) ->
    (B, N, 6) rows [cls_id, score, xmin, ymin, xmax, ymax], suppressed
    rows get cls_id -1 (reference output convention)."""
    anchors = anchors.reshape(-1, 4)
    N = anchors.shape[0]
    v = tuple(variances)

    def per_image(probs, locs):
        # best foreground class per anchor
        fg = probs[1:]  # (num_cls, N)
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)
        score = jnp.max(fg, axis=0)
        keep = score > threshold
        cls_id = jnp.where(keep, cls_id, -1.0)
        boxes = box_decode(
            locs.reshape(1, N, 4), anchors[None], std0=v[0], std1=v[1],
            std2=v[2], std3=v[3], clip=10.0,
        )[0]
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        det = jnp.concatenate(
            [cls_id[:, None], score[:, None], boxes], axis=-1
        )  # (N, 6)
        out = box_nms(det[None], overlap_thresh=nms_threshold,
                      valid_thresh=threshold, topk=nms_topk, coord_start=2,
                      score_index=1, id_index=0,
                      force_suppress=force_suppress)[0]
        # box_nms flags suppression by score=-1; the reference's detection
        # output convention is cls_id=-1 for invalid rows
        return out.at[:, 0].set(jnp.where(out[:, 1] < 0, -1.0, out[:, 0]))

    return jax.vmap(per_image)(cls_probs, loc_preds)


# -------------------------------------------------------------- faster-rcnn
def _rpn_anchors(H, W, feature_stride, scales, ratios):
    """Pixel-space base anchors at every feature position.

    Reference ``src/operator/contrib/proposal.cc`` GenerateAnchors
    [unverified]: a base box of side ``feature_stride`` centered on each
    position, reshaped per (ratio, scale) keeping area (ratio) / scaling
    sides (scale). Returns (H*W*A, 4) corner boxes, A = len(ratios)*len(scales).
    """
    base = float(feature_stride)
    cx = (jnp.arange(W, dtype=jnp.float32) + 0.5) * base
    cy = (jnp.arange(H, dtype=jnp.float32) + 0.5) * base
    ws, hs = [], []
    for r in ratios:
        for s in scales:
            w = base * float(s) / math.sqrt(float(r))
            h = base * float(s) * math.sqrt(float(r))
            ws.append(w)
            hs.append(h)
    ws = jnp.asarray(ws, jnp.float32)  # (A,)
    hs = jnp.asarray(hs, jnp.float32)
    cxg, cyg = jnp.meshgrid(cx, cy)  # (H, W)
    cxg = cxg[:, :, None]
    cyg = cyg[:, :, None]
    boxes = jnp.stack([
        cxg - ws / 2, cyg - hs / 2, cxg + ws / 2, cyg + hs / 2,
    ], axis=-1)  # (H, W, A, 4)
    return boxes.reshape(-1, 4)


def _rcnn_decode(anchors, deltas, clip_hw=None):
    """Standard R-CNN box decoding (no stds): anchors/deltas (..., 4)."""
    ax1, ay1, ax2, ay2 = jnp.split(anchors, 4, axis=-1)
    dx, dy, dw, dh = jnp.split(deltas, 4, axis=-1)
    aw, ah = ax2 - ax1, ay2 - ay1
    acx, acy = ax1 + aw / 2, ay1 + ah / 2
    cx = acx + dx * aw
    cy = acy + dy * ah
    w = aw * jnp.exp(jnp.clip(dw, -10.0, 10.0))
    h = ah * jnp.exp(jnp.clip(dh, -10.0, 10.0))
    out = jnp.concatenate([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                          axis=-1)
    if clip_hw is not None:
        hlim, wlim = clip_hw
        out = jnp.stack([
            jnp.clip(out[..., 0], 0, wlim - 1.0),
            jnp.clip(out[..., 1], 0, hlim - 1.0),
            jnp.clip(out[..., 2], 0, wlim - 1.0),
            jnp.clip(out[..., 3], 0, hlim - 1.0),
        ], axis=-1)
    return out


@register("_contrib_Proposal", aliases=["Proposal"], num_outputs=None,
          differentiable=False)
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, layout="batched",
             **kw):
    """RPN proposal generation (reference ``proposal.cc`` [unverified]).

    cls_prob (B, 2A, H, W) — [:, :A] background, [:, A:] foreground
    scores; bbox_pred (B, 4A, H, W); im_info (B, 3) rows [h, w, scale].

    TPU-first deviations from the reference, both static-shape driven:
    rois come back BATCHED as (B, rpn_post_nms_top_n, 5) rows
    [batch_idx, x1, y1, x2, y2] (the flat (B*N, 5) reference layout is a
    reshape away; the batched form feeds the batched ROIAlign directly),
    and slots past the survivor count hold the highest-scoring suppressed
    boxes (score -1 in the score output) rather than shrinking.
    """
    B = cls_prob.shape[0]
    H, W = cls_prob.shape[2], cls_prob.shape[3]
    A = cls_prob.shape[1] // 2
    if A != len(scales) * len(ratios):
        raise ValueError(
            f"cls_prob carries {A} anchors/position but scales x ratios "
            f"defines {len(scales) * len(ratios)}"
        )
    anchors = _rpn_anchors(H, W, feature_stride, scales, ratios)  # (HWA, 4)
    N = anchors.shape[0]

    # (B, A, H, W) -> (B, H, W, A) -> (B, HWA): match the anchor layout
    fg = jnp.transpose(cls_prob[:, A:], (0, 2, 3, 1)).reshape(B, N)
    deltas = bbox_pred.reshape(B, A, 4, H, W)
    deltas = jnp.transpose(deltas, (0, 3, 4, 1, 2)).reshape(B, N, 4)

    def one(fg_b, deltas_b, info):
        boxes = _rcnn_decode(anchors, deltas_b, clip_hw=(info[0], info[1]))
        ws = boxes[:, 2] - boxes[:, 0] + 1.0
        hs = boxes[:, 3] - boxes[:, 1] + 1.0
        min_sz = rpn_min_size * info[2]
        score = jnp.where((ws >= min_sz) & (hs >= min_sz), fg_b, -jnp.inf)
        k1 = min(int(rpn_pre_nms_top_n), N)
        top_scores, top_idx = jax.lax.top_k(score, k1)
        top_boxes = boxes[top_idx]
        dets = jnp.concatenate([
            jnp.zeros((k1, 1)), top_scores[:, None], top_boxes,
        ], axis=-1)
        kept = box_nms(dets, overlap_thresh=threshold,
                       topk=int(rpn_post_nms_top_n), coord_start=2,
                       score_index=1, id_index=0)
        ord_scores, ord_idx = jax.lax.top_k(kept[:, 1],
                                            int(rpn_post_nms_top_n))
        rois = kept[ord_idx, 2:6]
        return rois, ord_scores

    rois, scores = jax.vmap(one)(fg, deltas, im_info)
    bidx = jnp.broadcast_to(
        jnp.arange(B, dtype=rois.dtype)[:, None, None],
        (B, rois.shape[1], 1),
    )
    rois = jnp.concatenate([bidx, rois], axis=-1)
    if layout == "flat":
        # reference proposal.cc emitted flat (B*N, 5) rows — one reshape
        # away from the batched form (advisor round 3: ported consumers
        # index this layout)
        rois = rois.reshape(-1, 5)
        if output_score:
            return rois, scores.reshape(-1, 1)
        return rois
    if output_score:
        return rois, scores[..., None]
    return rois


@register("_contrib_rcnn_target_sampler", aliases=["rcnn_target_sampler"],
          num_outputs=4, differentiable=False)
def rcnn_target_sampler(rois, gt_boxes, num_sample=128, pos_ratio=0.25,
                        pos_iou_thresh=0.5, bg_iou_low=0.0,
                        box_stds=(0.1, 0.1, 0.2, 0.2), **kw):
    """Second-stage target sampling + encoding (reference: the rcnn
    ``proposal_target`` operator / GluonCV RCNNTargetSampler+Generator
    [unverified]) with static shapes.

    rois (B, R, 4|5) proposals (batch-idx column ignored if present);
    gt_boxes (B, M, 5) rows [cls, x1, y1, x2, y2], cls < 0 = padding.

    Returns (sampled_rois (B, S, 4), cls_targets (B, S) int32 with
    0 = background and gt cls k -> k+1, box_targets (B, S, 4),
    box_masks (B, S, 4)); S = num_sample. Selection is deterministic
    top-by-IoU (foregrounds first, capped at pos_ratio*S, then the
    highest-IoU backgrounds) — the reference sampled randomly; determinism
    is the jit-friendly choice and tests/training treat it as the
    hardest-example variant.
    """
    rois = rois[..., -4:]
    S = int(num_sample)
    num_fg = int(round(S * float(pos_ratio)))

    def one(rois_b, gt_b):
        gt_cls = gt_b[:, 0]
        gt_box = gt_b[:, 1:5]
        valid_gt = gt_cls >= 0
        iou = box_iou(rois_b, gt_box)  # (R, M)
        iou = jnp.where(valid_gt[None, :], iou, 0.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        is_fg = best_iou >= pos_iou_thresh
        fg_key = jnp.where(is_fg, best_iou, -jnp.inf)
        _, fg_idx = jax.lax.top_k(fg_key, num_fg)
        bg_key = jnp.where(~is_fg & (best_iou >= bg_iou_low), best_iou,
                           -jnp.inf)
        _, bg_idx = jax.lax.top_k(bg_key, S - num_fg)
        sel = jnp.concatenate([fg_idx, bg_idx])
        sel_rois = rois_b[sel]
        sel_iou = best_iou[sel]
        sel_fg = is_fg[sel]
        # fg slots past the actual fg count carry non-fg rois; their
        # sel_fg is False so they fall through to background cleanly
        sel_gt = best_gt[sel]
        cls_t = jnp.where(sel_fg, gt_cls[sel_gt].astype(jnp.int32) + 1, 0)
        matched = gt_box[sel_gt]
        # center-form encoding with stds (the reference's bbox_transform)
        ax1, ay1, ax2, ay2 = jnp.split(sel_rois, 4, axis=-1)
        gx1, gy1, gx2, gy2 = jnp.split(matched, 4, axis=-1)
        aw = jnp.maximum(ax2 - ax1, 1e-6)
        ah = jnp.maximum(ay2 - ay1, 1e-6)
        gw = jnp.maximum(gx2 - gx1, 1e-6)
        gh = jnp.maximum(gy2 - gy1, 1e-6)
        t = jnp.concatenate([
            ((gx1 + gw / 2) - (ax1 + aw / 2)) / aw / box_stds[0],
            ((gy1 + gh / 2) - (ay1 + ah / 2)) / ah / box_stds[1],
            jnp.log(gw / aw) / box_stds[2],
            jnp.log(gh / ah) / box_stds[3],
        ], axis=-1)
        mask = sel_fg[:, None].astype(t.dtype) * jnp.ones_like(t)
        return sel_rois, cls_t, t * mask, mask

    return jax.vmap(one)(rois, gt_boxes)


# ------------------------------------------------------ deformable conv
def _deform_columns(data, offset, kernel, stride, dilate, pad,
                    num_deformable_group=1, num_group=1):
    """Deformed im2col: ONE vectorized bilinear gather (map_coordinates
    order=1, zeros outside) -> (B, C, kh*kw, Ho, Wo). Shared by
    DeformableConvolution v1 and the modulated v2."""
    from jax.scipy.ndimage import map_coordinates

    if num_group != 1:
        raise NotImplementedError(
            "grouped deformable convolution not supported yet"
        )
    kh, kw = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    dh, dw = (dilate, dilate) if isinstance(dilate, int) else tuple(dilate)
    ph, pw = (pad, pad) if isinstance(pad, int) else tuple(pad)
    B, C, H, W = data.shape
    G = int(num_deformable_group)
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    K = kh * kw

    # base sampling grid per output position and tap (Ho, Wo) + (K,)
    oy = jnp.arange(Ho) * sh - ph
    ox = jnp.arange(Wo) * sw - pw
    ty = jnp.arange(kh) * dh
    tx = jnp.arange(kw) * dw
    base_y = oy[None, :, None] + ty.repeat(kw)[:, None, None]  # (K, Ho, 1)
    base_x = jnp.tile(tx, kh)[:, None, None] + ox[None, None, :]  # (K,1,Wo)

    if offset.shape[2] != Ho or offset.shape[3] != Wo:
        raise ValueError(
            f"offset spatial shape {offset.shape[2:]} must equal the "
            f"OUTPUT spatial shape ({Ho}, {Wo}) (reference contract); "
            "with stride > 1 an input-resolution offset map would be "
            "silently misaligned"
        )
    off = offset.reshape(B, G, K, 2, Ho, Wo)
    sy = base_y[None, None] + off[:, :, :, 0]   # (B, G, K, Ho, Wo)
    sx = base_x[None, None] + off[:, :, :, 1]

    cg = C // G  # channels per deformable group

    def sample_one(img2d, yy, xx):
        # img2d (H, W); yy/xx (K, Ho, Wo) -> (K, Ho, Wo)
        return map_coordinates(img2d, [yy, xx], order=1, mode="constant",
                               cval=0.0)

    # vmap over channels within a group, groups, batch
    sample_c = jax.vmap(sample_one, in_axes=(0, None, None))     # C_g imgs
    sample_g = jax.vmap(sample_c, in_axes=(0, 0, 0))             # groups
    sample_b = jax.vmap(sample_g, in_axes=(0, 0, 0))             # batch
    dg = data.reshape(B, G, cg, H, W)
    cols = sample_b(dg, sy, sx)          # (B, G, cg, K, Ho, Wo)
    return cols.reshape(B, C, K, Ho, Wo)


@register("_contrib_DeformableConvolution",
          aliases=["DeformableConvolution", "deformable_convolution"])
def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=None, num_deformable_group=1,
                           num_group=1, no_bias=False, **kw):
    """Deformable convolution v1 (reference:
    ``src/operator/contrib/deformable_convolution.cc`` [unverified]).

    data (B, C, H, W); offset (B, 2*G*kh*kw, H', W') with per-position
    (dy, dx) for every kernel tap, G = num_deformable_group (channel
    groups sharing an offset field); weight (O, C/num_group, kh, kw).

    TPU-first formulation: the deformed sampling is ONE vectorized
    bilinear gather (jax.scipy map_coordinates order=1, zero padding
    outside — the reference's im2col-with-offsets), producing the
    (B, C, kh*kw, H', W') column tensor, and the conv collapses to a
    single einsum on the MXU. Fully differentiable w.r.t. data, offset,
    and weight through XLA autodiff — the reference hand-wrote those
    three backward kernels.
    """
    B, C, H, W = data.shape
    cols = _deform_columns(data, offset, kernel, stride, dilate, pad,
                           num_deformable_group=num_deformable_group,
                           num_group=num_group)
    wflat = weight.reshape(weight.shape[0], C, cols.shape[2])
    out = jnp.einsum("bckhw,ock->bohw", cols, wflat)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


@register("_contrib_ModulatedDeformableConvolution",
          aliases=["ModulatedDeformableConvolution",
                   "modulated_deformable_convolution"])
def modulated_deformable_convolution(data, offset, mask, weight, bias=None,
                                     kernel=(3, 3), stride=(1, 1),
                                     dilate=(1, 1), pad=(0, 0),
                                     num_filter=None,
                                     num_deformable_group=1, num_group=1,
                                     no_bias=False, **kw):
    """Deformable convolution v2 (reference:
    ``src/operator/contrib/modulated_deformable_convolution.cc``
    [unverified]): v1 plus a learned per-tap modulation scalar —
    ``mask`` (B, G*kh*kw, H', W'), already sigmoid-activated by the
    caller per the reference contract — multiplying each sampled column.

    Same TPU-first formulation as v1: one vectorized bilinear gather
    builds the column tensor, the modulation is a broadcast multiply
    XLA fuses into it, and the conv is a single MXU einsum; all three
    hand-written reference backward kernels come from autodiff."""
    kh, kw = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
    B, C, H, W = data.shape
    G = int(num_deformable_group)
    K = kh * kw
    cols = _deform_columns(data, offset, kernel, stride, dilate, pad,
                           num_deformable_group=G, num_group=num_group)
    Ho, Wo = cols.shape[-2:]
    if mask.shape != (B, G * K, Ho, Wo):
        raise ValueError(
            f"mask shape {mask.shape} must be (B, G*kh*kw, Ho, Wo) = "
            f"({B}, {G * K}, {Ho}, {Wo})")
    m = mask.reshape(B, G, 1, K, Ho, Wo)
    cols = (cols.reshape(B, G, C // G, K, Ho, Wo) * m).reshape(
        B, C, K, Ho, Wo)
    wflat = weight.reshape(weight.shape[0], C, K)
    out = jnp.einsum("bckhw,ock->bohw", cols, wflat)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


# ------------------------------------------------------- round-5 contrib tail
@register("_contrib_quadratic", aliases=["quadratic"])
def quadratic(data, a=0.0, b=0.0, c=0.0, **kw):
    """a*x^2 + b*x + c (the reference's tutorial contrib op,
    ``src/operator/contrib/quadratic_op.cc`` [unverified])."""
    return a * jnp.square(data) + b * data + c


@register("_contrib_allclose", aliases=["allclose"], differentiable=False)
def allclose_op(a, b, rtol=1e-5, atol=1e-8, equal_nan=False, **kw):
    """1.0 iff allclose (reference ``_contrib_allclose``)."""
    return jnp.allclose(a, b, rtol=rtol, atol=atol,
                        equal_nan=equal_nan).astype(jnp.float32).reshape(1)


@register("_contrib_index_copy", aliases=["index_copy"])
def index_copy(old, index, new, **kw):
    """Copy rows of ``new`` into ``old`` at ``index`` (reference
    ``src/operator/contrib/index_copy.cc`` [unverified]); functional
    result, differentiable through both data inputs."""
    return old.at[index.astype(jnp.int32)].set(new)


@register("_contrib_index_array", aliases=["index_array"],
          differentiable=False)
def index_array(data, axes=None, **kw):
    """Per-element N-d indices (reference ``index_array``): output
    data.shape + (len(axes),)."""
    nd_ = data.ndim
    ax = tuple(axes) if axes is not None else tuple(range(nd_))
    grids = jnp.meshgrid(*[jnp.arange(s) for s in data.shape],
                         indexing="ij")
    return jnp.stack([grids[a] for a in ax], axis=-1).astype(jnp.int32)


def _grad_mult_fwd(data, scalar):
    return data, scalar


def _grad_mult_bwd(res, ct):
    return ct * res, None


@jax.custom_vjp
def _grad_mult(data, scalar):
    return data


_grad_mult.defvjp(lambda d, s: (d, s), lambda s, ct: (ct * s, None))


@register("_contrib_gradientmultiplier", aliases=["gradientmultiplier"])
def gradientmultiplier(data, scalar=1.0, **kw):
    """Identity forward, gradient scaled by ``scalar`` (reference
    ``src/operator/contrib/gradient_multiplier_op.cc`` [unverified] —
    the GRL building block with negative scalar)."""
    return _grad_mult(data, jnp.asarray(scalar, data.dtype))


@jax.custom_vjp
def _rounded_ste(data):
    return jnp.round(data)


_rounded_ste.defvjp(lambda d: (jnp.round(d), None), lambda _, ct: (ct,))


@register("_contrib_round_ste", aliases=["round_ste", "rounded_ste",
                                         "_contrib_rounded_ste"])
def round_ste(data, **kw):
    """Straight-through round (reference ``_contrib_round_ste``,
    quantization-aware training)."""
    return _rounded_ste(data)


@jax.custom_vjp
def _sign_ste(data):
    return jnp.sign(data)


_sign_ste.defvjp(lambda d: (jnp.sign(d), None), lambda _, ct: (ct,))


@register("_contrib_sign_ste", aliases=["sign_ste"])
def sign_ste(data, **kw):
    return _sign_ste(data)


@register("_contrib_boolean_mask", aliases=["boolean_mask"],
          differentiable=False)
def boolean_mask(data, index, axis=0, **kw):
    """Select rows where index != 0 (reference
    ``src/operator/contrib/boolean_mask.cc`` [unverified]).

    Data-dependent OUTPUT SHAPE: like ``unique``, this op cannot live
    under jit/bulking (it is deny-listed) — it materializes the mask on
    host and returns the packed selection, matching the reference's
    dynamic-shape contract."""
    import numpy as _onp

    m = _onp.asarray(index) != 0
    return jnp.take(data, jnp.asarray(_onp.nonzero(m)[0]), axis=axis)


@register("_contrib_edge_id", aliases=["edge_id"], differentiable=False)
def edge_id(data, u, v, **kw):
    """Edge ids for (u, v) pairs in a dense adjacency-style matrix
    (reference DGL helper ``src/operator/contrib/dgl_graph.cc``
    [unverified]): returns data[u[i], v[i]] per pair, -1 where the
    entry is zero (no edge)."""
    uu = u.astype(jnp.int32)
    vv = v.astype(jnp.int32)
    vals = data[uu, vv]
    return jnp.where(vals != 0, vals, -1.0).astype(data.dtype)
