"""Typed operator-parameter schemas — the ``dmlc::Parameter`` analogue.

Reference: every reference op declared a ``dmlc::Parameter`` struct
(name, type, default, range, description) that powered generated python
docstrings, argument validation at the C API boundary, and op-config
serialization (``include/dmlc/parameter.h`` [unverified]). Here the same
schema is a Python declaration attached to a registered op:

    @op_params(
        P("kernel", "Shape", required=True, doc="convolution window"),
        P("stride", "Shape", default=1, doc="window stride"),
        P("num_filter", "int", required=True, low=1, doc="output channels"),
    )
    @register("Convolution")
    def convolution(...): ...

What it powers:
- ``describe_op(name)`` / ``Operator.param_schema`` — structured
  introspection (the reference's ``MXSymbolGetAtomicSymbolInfo``);
- generated docstring PARAMETER sections (appended to the op's own);
- ``validate_params(name, kwargs)`` — typed coercion + range checks,
  used by the frontends that accept string attrs (symbol JSON);
- schema serialization via ``schema_to_json`` (op-config round trips).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional, Sequence

from .registry import get as _get_op, maybe_get as _maybe_get

__all__ = ["P", "op_params", "describe_op", "validate_params",
           "schema_to_json", "list_documented_ops"]

def _parse_seq(v):
    """Accept '(1, 2)' / '[1,2]' strings (symbol-JSON attrs) as sequences."""
    if isinstance(v, str):
        import ast

        v = ast.literal_eval(v)
    return v


# name -> coercion callable; mirrors the dmlc type names the reference
# printed in docstrings
_TYPES: Dict[str, Callable[[Any], Any]] = {
    "int": int,
    "float": float,
    "bool": lambda v: v if isinstance(v, bool) else str(v).lower()
    in ("1", "true", "yes", "on"),
    "str": str,
    "Shape": lambda v: (lambda s: tuple(int(x) for x in s)
                        if isinstance(s, (tuple, list))
                        else (int(s),))(_parse_seq(v)),
    "tuple_of_float": lambda v: tuple(float(x) for x in _parse_seq(v)),
    "any": lambda v: v,
}


class P:
    """One parameter declaration."""

    __slots__ = ("name", "type", "default", "required", "low", "high",
                 "choices", "doc")

    def __init__(self, name: str, type: str = "any", default: Any = None,
                 required: bool = False, low=None, high=None,
                 choices: Optional[Sequence] = None, doc: str = ""):
        if type not in _TYPES:
            raise ValueError(f"unknown param type {type!r}")
        self.name = name
        self.type = type
        self.default = default
        self.required = required
        self.low = low
        self.high = high
        self.choices = tuple(choices) if choices else None
        self.doc = doc

    def describe(self) -> dict:
        d = {"name": self.name, "type": self.type, "doc": self.doc}
        if self.required:
            d["required"] = True
        else:
            d["default"] = self.default
        if self.low is not None:
            d["low"] = self.low
        if self.high is not None:
            d["high"] = self.high
        if self.choices is not None:
            d["choices"] = list(self.choices)
        return d

    def coerce(self, value):
        out = _TYPES[self.type](value)
        if self.low is not None and out < self.low:
            raise ValueError(
                f"param {self.name}={out!r} below minimum {self.low}"
            )
        if self.high is not None and out > self.high:
            raise ValueError(
                f"param {self.name}={out!r} above maximum {self.high}"
            )
        if self.choices is not None and out not in self.choices:
            raise ValueError(
                f"param {self.name}={out!r} not in {self.choices}"
            )
        return out


def _docstring_section(schema: Sequence[P]) -> str:
    lines = ["", "", "Op Parameters", "-------------"]
    for p in schema:
        head = f"{p.name} : {p.type}"
        head += ", required" if p.required else f", default={p.default!r}"
        if p.choices:
            head += f", choices={list(p.choices)}"
        lines.append(head)
        if p.doc:
            lines.append(f"    {p.doc}")
    return "\n".join(lines)


def op_params(*schema: P):
    """Attach a typed parameter schema to a registered op function.

    Apply ABOVE ``@register`` (decorates the raw fn after registration);
    the schema lands on the Operator entry and the fn's docstring grows a
    generated PARAMETERS section."""

    def deco(fn):
        opname = getattr(fn, "__mx_op_name__", fn.__name__)
        op = _maybe_get(opname)
        if op is None:
            # fall back: find the op whose fn is this function
            from .registry import _REGISTRY

            for name, entry in _REGISTRY.items():
                if entry.fn is fn:
                    op = entry
                    break
        if op is None:
            raise ValueError(
                f"op_params: no registered op found for {fn.__name__}; "
                "apply above @register"
            )
        op.param_schema = list(schema)
        fn.__doc__ = (fn.__doc__ or "") + _docstring_section(schema)
        return fn

    return deco


def describe_op(name: str) -> dict:
    """Structured op description (reference: GetAtomicSymbolInfo)."""
    op = _get_op(name)
    schema = getattr(op, "param_schema", None)
    return {
        "name": op.name,
        "aliases": list(op.aliases),
        "doc": (op.fn.__doc__ or "").strip(),
        "params": [p.describe() for p in schema] if schema else [],
    }


def validate_params(name: str, kwargs: dict, allow_unknown: bool = True
                    ) -> dict:
    """Coerce/validate kwargs against the op's schema (typed attrs from
    symbol JSON arrive as strings — this is the boundary that fixes
    them). Unknown keys pass through unless allow_unknown=False."""
    op = _get_op(name)
    schema = getattr(op, "param_schema", None)
    if not schema:
        return dict(kwargs)
    by_name = {p.name: p for p in schema}
    out = {}
    for k, v in kwargs.items():
        p = by_name.get(k)
        if p is None:
            if not allow_unknown:
                raise ValueError(f"op {name}: unknown param {k!r}")
            out[k] = v
        else:
            out[k] = p.coerce(v)
    missing = [p.name for p in schema
               if p.required and p.name not in kwargs]
    if missing:
        raise ValueError(f"op {name}: missing required params {missing}")
    return out


def schema_to_json(name: str) -> str:
    return json.dumps(describe_op(name), indent=2)


def list_documented_ops():
    """Ops carrying a schema. An EMPTY schema counts: it is the explicit
    declaration 'this op takes no parameters' (plain elementwise ops),
    exactly like a dmlc::Parameter struct with no fields."""
    from .registry import _REGISTRY

    return sorted(n for n, e in _REGISTRY.items()
                  if getattr(e, "param_schema", None) is not None)


# ------------------------------------------------ signature-derived schemas
def _infer_type(default) -> str:
    if isinstance(default, bool):
        return "bool"
    if isinstance(default, int):
        return "int"
    if isinstance(default, float):
        return "float"
    if isinstance(default, str):
        return "str"
    if isinstance(default, (tuple, list)):
        if default and all(isinstance(x, int) for x in default):
            return "Shape"
        if default and all(isinstance(x, (int, float)) for x in default):
            return "tuple_of_float"
    return "any"


def autogen_schema(op) -> None:
    """Derive a schema from the op function's signature (the mechanical
    part of what dmlc::Parameter declared: name, type, default).

    Every keyword argument with a default becomes a P() entry; typed by
    its default value. Optional array inputs (default None) land as type
    'any', which coerces as pass-through — harmless for validation and
    still listed for introspection, the way the reference docs listed
    optional inputs. Hand-written schemas (richer: ranges, choices,
    docs) always win; this only fills ops that have none."""
    import inspect

    if op.param_schema is not None:
        return
    try:
        sig = inspect.signature(op.fn)
    except (TypeError, ValueError):
        op.param_schema = []
        return
    schema = []
    for pname, p in sig.parameters.items():
        if p.kind in (inspect.Parameter.VAR_POSITIONAL,
                      inspect.Parameter.VAR_KEYWORD):
            continue
        if p.default is inspect.Parameter.empty:
            continue  # positional tensor input
        schema.append(P(pname, _infer_type(p.default), default=p.default))
    op.param_schema = schema


def autogen_all() -> None:
    from .registry import _REGISTRY

    for op in _REGISTRY.values():
        autogen_schema(op)


def assert_registry_documented() -> None:
    """Invariant the reference enforced structurally (no op without its
    dmlc::Parameter struct): every registered op carries a schema."""
    from .registry import list_ops

    missing = [n for n in list_ops() if n not in set(list_documented_ops())
               and _get_op(n).param_schema is None]
    if missing:
        raise RuntimeError(f"ops registered without param schema: {missing}")


def _install_builtin_schemas():
    """Schemas for the heavily-parameterized builtin ops (the reference
    declared one dmlc::Parameter struct per op; the long tail of simple
    elementwise ops has nothing to declare)."""
    from .registry import maybe_get

    def attach(name, *schema):
        op = maybe_get(name)
        if op is not None and op.param_schema is None:
            op.param_schema = list(schema)
            op.fn.__doc__ = (op.fn.__doc__ or "") + _docstring_section(schema)

    attach(
        "Convolution",
        P("kernel", "Shape", required=True, doc="convolution window"),
        P("stride", "Shape", default=1, doc="window strides"),
        P("dilate", "Shape", default=1, doc="kernel dilation"),
        P("pad", "Shape", default=0, doc="symmetric zero padding"),
        P("num_filter", "int", required=True, low=1, doc="output channels"),
        P("num_group", "int", default=1, low=1, doc="grouped-conv groups"),
        P("no_bias", "bool", default=False, doc="skip the bias add"),
        P("layout", "str", default="NCHW",
          choices=("NCW", "NCHW", "NCDHW", "NWC", "NHWC", "NDHWC"),
          doc="channel-first (reference default) or channel-last (TPU)"),
    )
    attach(
        "Pooling",
        P("kernel", "Shape", default=1, doc="pooling window"),
        P("pool_type", "str", default="max",
          choices=("max", "avg", "sum", "lp"), doc="reduction kind"),
        P("global_pool", "bool", default=False, doc="pool whole spatial"),
        P("stride", "Shape", default=1, doc="window strides"),
        P("pad", "Shape", default=0, doc="symmetric padding"),
        P("pooling_convention", "str", default="valid",
          choices=("valid", "full"), doc="floor vs ceil output size"),
        P("count_include_pad", "bool", default=True,
          doc="avg divides by window size incl. padding"),
        P("layout", "str", default="NCHW", doc="NC* or N*C data layout"),
    )
    attach(
        "BatchNorm",
        P("eps", "float", default=1e-3, low=0.0, doc="variance epsilon"),
        P("momentum", "float", default=0.9, low=0.0, high=1.0,
          doc="moving-average momentum"),
        P("fix_gamma", "bool", default=True, doc="freeze gamma at 1"),
        P("use_global_stats", "bool", default=False,
          doc="normalize with moving stats even in training"),
        P("axis", "int", default=1, doc="channel axis"),
    )
    attach(
        "Dropout",
        P("p", "float", default=0.5, low=0.0, high=1.0, doc="drop rate"),
        P("mode", "str", default="training",
          choices=("training", "always"), doc="when masks apply"),
    )
    attach(
        "_contrib_box_nms",
        P("overlap_thresh", "float", default=0.5, low=0.0, high=1.0,
          doc="IoU suppression threshold"),
        P("valid_thresh", "float", default=0.0, doc="min score to enter"),
        P("topk", "int", default=-1, doc="max survivors (-1: all)"),
        P("coord_start", "int", default=2, doc="box column offset"),
        P("score_index", "int", default=1, doc="score column"),
        P("id_index", "int", default=-1, doc="class-id column (-1: none)"),
        P("force_suppress", "bool", default=False,
          doc="suppress across class ids"),
        P("in_format", "str", default="corner", choices=("corner", "center"),
          doc="input box encoding"),
    )
    attach(
        "_contrib_Proposal",
        P("rpn_pre_nms_top_n", "int", default=6000, low=1,
          doc="candidates entering NMS"),
        P("rpn_post_nms_top_n", "int", default=300, low=1,
          doc="static proposal count emitted"),
        P("threshold", "float", default=0.7, low=0.0, high=1.0,
          doc="NMS IoU threshold"),
        P("rpn_min_size", "int", default=16, low=0,
          doc="min box side in image pixels"),
        P("scales", "tuple_of_float", default=(4, 8, 16, 32),
          doc="anchor scales (feature-stride units)"),
        P("ratios", "tuple_of_float", default=(0.5, 1, 2),
          doc="anchor aspect ratios"),
        P("feature_stride", "int", default=16, doc="input stride of the map"),
        P("output_score", "bool", default=False, doc="also return scores"),
        P("layout", "str", default="batched", choices=("batched", "flat"),
          doc="(B, N, 5) TPU-native or the reference's flat (B*N, 5)"),
    )
    attach(
        "_contrib_flash_attention",
        P("causal", "bool", default=False, doc="causal mask"),
        P("sm_scale", "float", default=None, doc="softmax scale (None: 1/sqrt(D))"),
        P("block_q", "int", default=128, low=8, doc="query tile"),
        P("block_k", "int", default=128, low=8, doc="key tile"),
        P("layout", "str", default="BHSD", choices=("BHSD", "BSHD"),
          doc="operand layout: head-major or sequence-major (transpose-free)"),
    )
    attach(
        "Embedding",
        P("input_dim", "int", required=True, low=1, doc="vocabulary size"),
        P("output_dim", "int", required=True, low=1, doc="embedding width"),
    )
    attach(
        "linear_cross_entropy",
        P("block_size", "int", default=8192, low=256, doc="vocab tile"),
        P("ignore_label", "int", default=None, doc="label id with zero loss"),
        P("mode", "str", default="auto", choices=("auto", "dense", "blocked"),
          doc="dense logits vs online-logsumexp scan (auto: by byte budget)"),
    )


_install_builtin_schemas()
autogen_all()
# registrations that happen after this module is loaded (extensions,
# tests) get their schema from the hook in registry.register
_READY = True
