"""Control-flow operators: ``foreach``, ``while_loop``, ``cond``.

TPU-native analogue of ``src/operator/control_flow.cc`` +
``python/mxnet/ndarray/contrib.py`` control-flow helpers [unverified].

The reference implements these as subgraph operators: the body is traced
into a nested symbolic graph and an imperative executor loops over it. The
TPU-native design maps them onto XLA's structured control flow instead:

- ``foreach``   -> ``lax.scan``       (one fused XLA While, MXU-friendly)
- ``while_loop``-> bounded ``lax.scan`` with an active-predicate carry
                   (static shapes; reverse-mode differentiable, unlike a raw
                   ``lax.while_loop``)
- ``cond``      -> ``lax.cond``

Execution modes, chosen automatically per call:

1. **Staged** (inputs are jax tracers — i.e. inside a ``hybridize()`` /
   ``jax.jit`` trace): lower directly to the lax primitive. Closed-over
   NDArrays that wrap tracers (e.g. Gluon parameters inside a CachedOp
   trace) participate in the outer jit's autodiff for free.
2. **Eager, recording** (``autograd.record()`` with tracked arrays, concrete
   values): run a Python loop dispatching ops per iteration, exactly like the
   reference's imperative path — so gradients flow to *closed-over* tracked
   arrays (RNN-cell weights), which a single fused ``jax.vjp`` over the scan
   could not see.
3. **Eager, not recording**: lower to the lax primitive and dispatch once
   (fast inference path).

Bodies receive NDArrays (possibly wrapping tracers) and may use any
registered op, matching the reference contract that the body is ordinary
frontend code.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond"]


def _is_nd(x):
    return isinstance(x, NDArray)


def _data(x):
    return x.data if isinstance(x, NDArray) else jnp.asarray(x)


def _flatten(tree):
    leaves, treedef = jax.tree.flatten(tree, is_leaf=_is_nd)
    return leaves, treedef


def _is_traced(leaves) -> bool:
    return any(isinstance(_data(l), jax.core.Tracer) for l in leaves)


def _recording_eager(leaves) -> bool:
    from .. import autograd

    return autograd.is_recording() and not _is_traced(leaves)


def _wrap(treedef, datas):
    return jax.tree.unflatten(treedef, [NDArray(d) for d in datas])


def _stack0(arrays: Sequence[NDArray]) -> NDArray:
    from ..imperative import invoke_fn

    return invoke_fn(lambda *xs: jnp.stack(xs, axis=0), *arrays)


def _check_state_match(init_leaves, new_leaves, what: str):
    if len(init_leaves) != len(new_leaves):
        raise MXNetError(
            f"{what}: loop state structure changed inside the body "
            f"({len(init_leaves)} leaves became {len(new_leaves)})"
        )
    for i, (a, b) in enumerate(zip(init_leaves, new_leaves)):
        da, db = _data(a), _data(b)
        if da.shape != db.shape or da.dtype != db.dtype:
            raise MXNetError(
                f"{what}: state leaf {i} changed shape/dtype inside the body: "
                f"{da.shape}/{da.dtype} -> {db.shape}/{db.dtype}"
            )


# ------------------------------------------------------------------- foreach
def foreach(body: Callable, data, init_states) -> Tuple[Any, Any]:
    """Iterate ``body`` over the leading axis of ``data`` carrying states.

    ``body(data_slice, states) -> (outputs, new_states)``. ``data``,
    ``init_states`` and the body results may be NDArrays or (nested)
    lists/tuples of NDArrays. Returns ``(outputs, final_states)`` with every
    output stacked along a new leading axis of length ``data.shape[0]``.

    Reference semantics: ``mx.nd.contrib.foreach``
    (``python/mxnet/ndarray/contrib.py`` [unverified]).
    """
    data_leaves, data_tree = _flatten(data)
    state_leaves, state_tree = _flatten(init_states)
    if not data_leaves:
        raise MXNetError("foreach: data must contain at least one array")
    n = _data(data_leaves[0]).shape[0]
    for l in data_leaves[1:]:
        if _data(l).shape[0] != n:
            raise MXNetError(
                "foreach: all data arrays must share the leading axis length"
            )

    # n == 0 falls through to the fused path: lax.scan infers the output
    # structure by tracing without executing, which a Python loop cannot
    if n > 0 and _recording_eager(data_leaves + state_leaves):
        # Python loop: per-iteration op recording (reference imperative path).
        states = init_states
        step_outs: List[List[NDArray]] = []
        out_tree = None
        for i in range(int(n)):
            slice_i = jax.tree.unflatten(
                data_tree, [l[i] for l in data_leaves]
            )
            outs, states = body(slice_i, states)
            new_state_leaves, _ = _flatten(states)
            _check_state_match(state_leaves, new_state_leaves, "foreach")
            out_leaves, out_tree = _flatten(outs)
            step_outs.append(out_leaves)
        stacked = [
            _stack0([step[j] for step in step_outs])
            for j in range(len(step_outs[0]))
        ]
        return jax.tree.unflatten(out_tree, stacked), states

    from .. import autograd
    from ..imperative import invoke_fn

    meta = {}

    def pure(*leaves):
        d = leaves[: len(data_leaves)]
        s = leaves[len(data_leaves):]

        def step(carry, xs):
            x_nd = _wrap(data_tree, xs)
            s_nd = _wrap(state_tree, carry)
            with autograd.pause():
                outs, new_states = body(x_nd, s_nd)
            out_leaves, meta["out_tree"] = _flatten(outs)
            ns_leaves, _ = _flatten(new_states)
            _check_state_match(s, ns_leaves, "foreach")
            meta["n_out"] = len(out_leaves)
            return (
                tuple(_data(l) for l in ns_leaves),
                tuple(_data(l) for l in out_leaves),
            )

        final, stacked = lax.scan(step, tuple(s), tuple(d))
        return tuple(stacked) + tuple(final)

    flat = invoke_fn(pure, *data_leaves, *state_leaves)
    flat = flat if isinstance(flat, tuple) else (flat,)
    outs = jax.tree.unflatten(meta["out_tree"], list(flat[: meta["n_out"]]))
    states = jax.tree.unflatten(state_tree, list(flat[meta["n_out"]:]))
    return outs, states


# ---------------------------------------------------------------- while_loop
def while_loop(
    cond_fn: Callable,
    func: Callable,
    loop_vars,
    max_iterations: Optional[int] = None,
) -> Tuple[Any, Any]:
    """``while cond_fn(*loop_vars): outputs, loop_vars = func(*loop_vars)``.

    Returns ``(stacked_outputs, final_loop_vars)``. On the eager paths the
    stacked outputs are trimmed to the realized step count (reference
    imperative semantics); inside a jit trace they are padded to
    ``max_iterations`` with zeros beyond the last active step (XLA needs
    static shapes — the reference's symbolic ``while_loop`` pads identically).

    ``max_iterations`` is required except on the eager recording path.
    Reference: ``mx.nd.contrib.while_loop`` [unverified].
    """
    var_leaves, var_tree = _flatten(loop_vars)
    if not var_leaves:
        raise MXNetError("while_loop: loop_vars must contain at least one array")

    if _recording_eager(var_leaves):
        states = loop_vars
        step_outs: List[List[NDArray]] = []
        out_tree = None
        steps = 0
        while bool(_np.asarray(_data(cond_fn(*_as_args(states))))):
            if max_iterations is not None and steps >= max_iterations:
                break
            outs, states = func(*_as_args(states))
            new_leaves, _ = _flatten(states)
            _check_state_match(var_leaves, new_leaves, "while_loop")
            out_leaves, out_tree = _flatten(outs)
            step_outs.append(out_leaves)
            steps += 1
        if not step_outs:
            raise MXNetError("while_loop: condition was false on entry")
        stacked = [
            _stack0([step[j] for step in step_outs])
            for j in range(len(step_outs[0]))
        ]
        return jax.tree.unflatten(out_tree, stacked), states

    if max_iterations is None:
        raise MXNetError(
            "while_loop: max_iterations is required outside autograd.record() "
            "(static shapes under XLA)"
        )

    from .. import autograd
    from ..imperative import invoke_fn

    meta = {}

    def pure(*leaves):
        def step(carry, _):
            active, vars_ = carry
            v_nd = _wrap(var_tree, vars_)
            with autograd.pause():
                pred = cond_fn(*_as_args(v_nd))
                outs, new_vars = func(*_as_args(v_nd))
            out_leaves, meta["out_tree"] = _flatten(outs)
            nv_leaves, _ = _flatten(new_vars)
            _check_state_match(vars_, nv_leaves, "while_loop")
            meta["n_out"] = len(out_leaves)
            act = jnp.logical_and(
                active, jnp.reshape(_data(pred), ()).astype(bool)
            )
            kept = tuple(
                jnp.where(act, _data(nv), v)
                for nv, v in zip(nv_leaves, vars_)
            )
            emitted = tuple(
                jnp.where(act, _data(o), jnp.zeros_like(_data(o)))
                for o in out_leaves
            )
            return (act, kept), emitted + (act.astype(jnp.int32),)

        (_, final), ys = lax.scan(
            step, (jnp.asarray(True), tuple(leaves)), None,
            length=max_iterations,
        )
        n_steps = jnp.sum(ys[-1])
        return tuple(ys[:-1]) + tuple(final) + (n_steps,)

    flat = invoke_fn(pure, *var_leaves)
    flat = flat if isinstance(flat, tuple) else (flat,)
    n_out = meta["n_out"]
    outs_padded = list(flat[:n_out])
    final_vars = jax.tree.unflatten(
        var_tree, list(flat[n_out: n_out + len(var_leaves)])
    )
    n_steps = flat[-1]
    if not isinstance(_data(n_steps), jax.core.Tracer):
        k = int(_np.asarray(_data(n_steps)))
        if k == 0:
            # match the recording path: zero realized iterations is an error
            # on the eager paths (traced programs return padded outputs)
            raise MXNetError("while_loop: condition was false on entry")
        outs_padded = [o[:k] for o in outs_padded]
    outs = jax.tree.unflatten(meta["out_tree"], outs_padded)
    return outs, final_vars


def _as_args(tree):
    """loop_vars may be a single NDArray or a list; func takes them splatted."""
    return tuple(tree) if isinstance(tree, (list, tuple)) else (tree,)


# ---------------------------------------------------------------------- cond
def cond(pred, then_func: Callable, else_func: Callable):
    """``then_func() if pred else else_func()``.

    Eager (concrete pred): evaluates the predicate and runs the chosen branch
    as ordinary imperative code (reference imperative semantics — recorded ops
    in the branch participate in autograd, including closures). Inside a jit
    trace: lowers to ``lax.cond`` over both branches; structures must match.

    Reference: ``mx.nd.contrib.cond`` [unverified].
    """
    p = _data(pred)
    if not isinstance(p, jax.core.Tracer):
        branch = then_func if bool(_np.asarray(p)) else else_func
        return branch()

    from .. import autograd

    meta = {}

    def run(branch, slot):
        def f(_):
            with autograd.pause():
                out = branch()
            leaves, meta[slot] = _flatten(out)
            return tuple(_data(l) for l in leaves)

        return f

    flat = lax.cond(
        jnp.reshape(p, ()).astype(bool),
        run(then_func, "then_tree"),
        run(else_func, "else_tree"),
        None,
    )
    if meta["then_tree"] != meta["else_tree"]:
        raise MXNetError(
            "cond: then_func and else_func returned different structures: "
            f"{meta['then_tree']} vs {meta['else_tree']}"
        )
    return jax.tree.unflatten(meta["then_tree"], [NDArray(l) for l in flat])
