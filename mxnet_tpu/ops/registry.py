"""Operator registry: the single registry serving every frontend namespace.

TPU-native analogue of the reference's nnvm op registry
(``NNVM_REGISTER_OP`` + attribute maps, ``3rdparty/tvm/nnvm`` [unverified]).
Key structural fact preserved from the reference (SURVEY.md section 1): ONE op
registry is consumed by the imperative path, the hybridized (jit) path, and
the generated Python namespaces (``mx.nd.*`` / ``mx.np.*``), whose functions
are built at import time by listing this registry.

What changed for TPU: an op here is a *pure function over jax.Arrays*
(compute == FCompute; shape/dtype inference comes free from jax tracing, so
there are no separate FInferShape/FInferType attrs; gradients come from
``jax.vjp`` over the same function, so there is no FGradient registry except
for ops that opt into a custom VJP, e.g. Pallas kernels).
"""

from __future__ import annotations

import functools
import inspect
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["Operator", "register", "get", "maybe_get", "list_ops", "alias"]


class Operator:
    """A registered op.

    Attributes:
        name: canonical registered name (e.g. ``"dot"``, ``"Convolution"``).
        fn: pure function ``fn(*jax_arrays, **params) -> array | tuple``.
        num_outputs: static output count (None if param-dependent).
        namespaces: which generated namespaces expose it ('nd', 'np', 'npx').
        wrap_outputs: if False the fn returns non-array python data.
        differentiable: participates in autograd recording.
        mutates_input: index of input mutated in-place (fused optimizer
            update ops write their first arg, reference
            ``src/operator/optimizer_op`` [unverified]); the imperative
            runtime rebinds that NDArray to output 0.
    """

    __slots__ = (
        "name",
        "fn",
        "num_outputs",
        "namespaces",
        "differentiable",
        "mutates_input",
        "aliases",
        "param_schema",  # typed op-param declarations (ops.params)
        "self_recording",  # fn manages its own autograd tape entry
    )

    def __init__(
        self,
        name: str,
        fn: Callable,
        num_outputs: Optional[int] = 1,
        namespaces: Sequence[str] = ("nd",),
        differentiable: bool = True,
        mutates_input: Optional[int] = None,
    ):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.namespaces = tuple(namespaces)
        self.differentiable = differentiable
        self.mutates_input = mutates_input
        self.aliases: List[str] = []
        self.param_schema = None
        self.self_recording = False

    def __repr__(self):
        return f"<Operator {self.name}>"


_REGISTRY: Dict[str, Operator] = {}
_ALIASES: Dict[str, str] = {}


def register(
    name: Optional[str] = None,
    *,
    aliases: Sequence[str] = (),
    num_outputs: Optional[int] = 1,
    namespaces: Sequence[str] = ("nd",),
    differentiable: bool = True,
    mutates_input: Optional[int] = None,
    self_recording: bool = False,
):
    """Decorator registering a pure jax-level function as a framework op."""

    def deco(fn: Callable) -> Callable:
        opname = name or fn.__name__
        if opname in _REGISTRY:
            raise ValueError(f"op {opname!r} registered twice")
        op = Operator(
            opname,
            fn,
            num_outputs=num_outputs,
            namespaces=namespaces,
            differentiable=differentiable,
            mutates_input=mutates_input,
        )
        op.self_recording = self_recording
        _REGISTRY[opname] = op
        for a in aliases:
            alias(a, opname)
        fn.op = op  # backlink for introspection
        # late registrations (extensions, tests) get a signature-derived
        # schema immediately; during package import the params module
        # runs autogen_all() once every op module has loaded
        import sys

        params_mod = sys.modules.get(__package__ + ".params")
        if params_mod is not None and getattr(params_mod, "_READY", False):
            params_mod.autogen_schema(op)
        return fn

    return deco


def alias(new_name: str, existing: str):
    if existing not in _REGISTRY:
        raise KeyError(f"alias target {existing!r} not registered")
    _ALIASES[new_name] = existing
    _REGISTRY[existing].aliases.append(new_name)


def get(name: str) -> Operator:
    op = maybe_get(name)
    if op is None:
        raise KeyError(f"operator {name!r} is not registered")
    return op


def maybe_get(name: str) -> Optional[Operator]:
    if name in _REGISTRY:
        return _REGISTRY[name]
    target = _ALIASES.get(name)
    return _REGISTRY.get(target) if target else None


def list_ops(namespace: Optional[str] = None) -> List[str]:
    if namespace is None:
        return sorted(_REGISTRY)
    return sorted(n for n, op in _REGISTRY.items() if namespace in op.namespaces)
