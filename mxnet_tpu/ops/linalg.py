"""Linear-algebra operators (the reference ``linalg_*`` family).

Reference: ``src/operator/tensor/la_op.cc`` [unverified] — thin wrappers
over LAPACK/cuSOLVER. Here each op lowers to the corresponding
``jax.numpy.linalg`` / ``jax.scipy.linalg`` primitive, which XLA maps to
its TPU-side QR/Cholesky/triangular-solve custom calls; batching comes
from the leading dimensions exactly as the reference's batched mode did.

All ops accept stacked batches: a (..., m, n) operand applies the
operation to every trailing matrix. Gradients flow through jax's
built-in JVP/transpose rules for the decompositions (the reference
hand-wrote these backward kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _t(x):
    return jnp.swapaxes(x, -1, -2)


@register("linalg_gemm", aliases=["_linalg_gemm"])
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2, **kw):
    """C <- alpha * op(A) @ op(B) + beta * C (reference linalg_gemm)."""
    if axis != -2:
        raise NotImplementedError(
            "linalg_gemm: only the default axis=-2 (trailing-matrix) "
            "layout is implemented; transpose your operands instead of "
            "passing axis"
        )
    a = _t(A) if transpose_a else A
    b = _t(B) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("linalg_gemm2", aliases=["_linalg_gemm2"])
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0,
                 **kw):
    """alpha * op(A) @ op(B) (reference linalg_gemm2)."""
    a = _t(A) if transpose_a else A
    b = _t(B) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf", aliases=["_linalg_potrf"])
def linalg_potrf(A, **kw):
    """Cholesky factor L of a symmetric positive-definite A (lower)."""
    return jnp.linalg.cholesky(A)


@register("linalg_potri", aliases=["_linalg_potri"])
def linalg_potri(A, **kw):
    """Inverse of the SPD matrix whose Cholesky factor is A:
    potri(L) = (L @ L^T)^-1 (reference semantics: input IS the factor)."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = jax.scipy.linalg.solve_triangular(A, eye, lower=True)
    return jnp.matmul(_t(linv), linv)


@register("linalg_trsm", aliases=["_linalg_trsm"])
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0, **kw):
    """Solve op(A) X = alpha B (or X op(A) = alpha B with rightside)."""
    if rightside:
        # X op(A) = aB  <=>  op(A)^T X^T = a B^T; with op = id that is
        # A^T Y = aB^T (trans=1), with op = T it is A Y = aB^T (trans=0)
        sol = jax.scipy.linalg.solve_triangular(
            A, _t(alpha * B), lower=lower, trans=0 if transpose else 1)
        return _t(sol)
    return jax.scipy.linalg.solve_triangular(
        A, alpha * B, lower=lower, trans=1 if transpose else 0)


@register("linalg_trmm", aliases=["_linalg_trmm"])
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0, **kw):
    """Triangular matrix multiply: alpha op(A) @ B (or B @ op(A))."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    op_a = _t(tri) if transpose else tri
    return alpha * (jnp.matmul(B, op_a) if rightside else jnp.matmul(op_a, B))


@register("linalg_syrk", aliases=["_linalg_syrk"])
def linalg_syrk(A, transpose=False, alpha=1.0, **kw):
    """alpha * A @ A^T (or A^T @ A with transpose)."""
    return alpha * (jnp.matmul(_t(A), A) if transpose
                    else jnp.matmul(A, _t(A)))


@register("linalg_sumlogdiag", aliases=["_linalg_sumlogdiag"])
def linalg_sumlogdiag(A, **kw):
    """sum(log(diag(A))) per trailing matrix (log-det of a Cholesky)."""
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_extractdiag", aliases=["_linalg_extractdiag"])
def linalg_extractdiag(A, offset=0, **kw):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("linalg_makediag", aliases=["_linalg_makediag"])
def linalg_makediag(A, offset=0, **kw):
    n = A.shape[-1] + abs(offset)
    eye = jnp.eye(n, k=offset, dtype=A.dtype)
    idx = jnp.arange(A.shape[-1])
    rows = idx + max(-offset, 0)
    cols = idx + max(offset, 0)
    out = jnp.zeros(A.shape[:-1] + (n, n), dtype=A.dtype)
    return out.at[..., rows, cols].set(A)


@register("linalg_extracttrian", aliases=["_linalg_extracttrian"])
def linalg_extracttrian(A, offset=0, lower=True, **kw):
    """Pack the (lower/upper) triangle into a vector, row-major over the
    kept entries (reference layout)."""
    n = A.shape[-1]
    r, c = jnp.tril_indices(n, k=offset) if lower \
        else jnp.triu_indices(n, k=offset)
    return A[..., r, c]


def _trian_count(n, offset, lower):
    """Entries kept by tril/triu_indices(n, k=offset)."""
    if not lower:
        # triu(n, k) keeps what tril(n, -k) keeps, mirrored
        return _trian_count(n, -offset, True)
    total = 0
    for r in range(n):
        total += max(0, min(n, r + offset + 1))
    return total


@register("linalg_maketrian", aliases=["_linalg_maketrian"])
def linalg_maketrian(A, offset=0, lower=True, **kw):
    """Inverse of ``linalg_extracttrian``: scatter the packed vector back
    into an (n, n) triangle. The matrix size is recovered by searching
    the (strictly increasing in n) kept-entry count — exact for every
    offset the extract side supports, in both band directions."""
    k = A.shape[-1]
    n = 1
    while _trian_count(n, offset, lower) < k:
        n += 1
    if _trian_count(n, offset, lower) != k:
        raise ValueError(
            f"linalg_maketrian: {k} entries do not fill any triangle "
            f"with offset={offset}"
        )
    r, c = (jnp.tril_indices(n, k=offset) if lower
            else jnp.triu_indices(n, k=offset))
    out = jnp.zeros(A.shape[:-1] + (n, n), dtype=A.dtype)
    return out.at[..., r, c].set(A)


@register("linalg_inverse", aliases=["_linalg_inverse"])
def linalg_inverse(A, **kw):
    return jnp.linalg.inv(A)


@register("linalg_det", aliases=["_linalg_det"])
def linalg_det(A, **kw):
    return jnp.linalg.det(A)


@register("linalg_slogdet", aliases=["_linalg_slogdet"], num_outputs=2)
def linalg_slogdet(A, **kw):
    sign, logabs = jnp.linalg.slogdet(A)
    return sign, logabs


@register("linalg_syevd", aliases=["_linalg_syevd"], num_outputs=2)
def linalg_syevd(A, **kw):
    """Symmetric eigendecomposition; returns (U, lambda) with rows of U
    the eigenvectors (reference layout: A = U^T diag(L) U)."""
    w, v = jnp.linalg.eigh(A)
    return _t(v), w


@register("linalg_gelqf", aliases=["_linalg_gelqf"], num_outputs=2)
def linalg_gelqf(A, **kw):
    """LQ factorization of a full-rank (m, n) A, m <= n: A = L Q with Q
    orthonormal rows (reference gelqf)."""
    q, r = jnp.linalg.qr(_t(A), mode="reduced")
    return _t(r), _t(q)


@register("linalg_gesvd", aliases=["_linalg_gesvd", "SVD"], num_outputs=3)
def linalg_gesvd(A, **kw):
    """Singular value decomposition of (..., m, n) A with m <= n:
    A = U diag(L) V, V with orthonormal ROWS (reference gesvd layout:
    ``src/operator/tensor/la_op.cc`` [unverified] returns UT/L/V such
    that A = UT * diag(L) * V). Lowers to jnp.linalg.svd
    (XLA's one-sided Jacobi on TPU)."""
    u, s, vt = jnp.linalg.svd(A, full_matrices=False)
    return u, s, vt
