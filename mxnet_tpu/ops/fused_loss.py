"""Fused (blocked) vocab-projection + softmax cross-entropy.

TPU-native replacement for the reference's ``FullyConnected -> SoftmaxOutput``
tail on language models (reference: ``src/operator/nn/fully_connected.cc`` +
``src/operator/nn/softmax.cc`` [unverified]).  On a 30k+ vocabulary the naive
pipeline materializes a (B*S, V) logits tensor *and its gradient* in HBM —
at B*S=8192, V=30522 that is ~1 GB of f32 traffic per step, and it dominated
the BERT/Transformer benchmarks in round 2 (see BASELINE.md).

The fused form never materializes logits.  Forward runs an online-logsumexp
scan over vocabulary blocks (the flash-attention trick applied to the
classifier head): each block computes an (N, Vb) logits tile on the MXU,
folds it into running (max, sumexp) statistics, and discards it.  The label
logit comes from a row gather of W.  Backward re-runs the scan, rebuilding
each softmax tile from the saved statistics and accumulating

    dx  = sum_b (g * p_b) @ W_b          - g * W[labels]
    dW_b = (g * p_b)^T @ x               (scatter  -g*x  into label rows)

so peak memory is one (N, Vb) tile instead of (N, V).  All matmuls accumulate
in f32 (``preferred_element_type``) regardless of the bf16 inputs.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .registry import register

__all__ = ["linear_cross_entropy"]


def _pad_vocab(w, block):
    v = w.shape[0]
    vpad = ((v + block - 1) // block) * block
    if vpad != v:
        w = jnp.pad(w, ((0, vpad - v), (0, 0)))
    return w, vpad


def _fwd_scan(x, w, block, valid_v):
    """Online logsumexp over vocab blocks. Returns (m, s): (N,) f32 each."""
    n = x.shape[0]
    wp, vpad = _pad_vocab(w, block)
    nblocks = vpad // block
    wb_all = wp.reshape(nblocks, block, wp.shape[1])

    def body(carry, wb_i):
        m, s = carry
        wb, i = wb_i
        logits = jnp.dot(x, wb.T, preferred_element_type=jnp.float32)
        # mask vocab padding (only the last block can contain it)
        col = i * block + jax.lax.iota(jnp.int32, block)
        logits = jnp.where(col[None, :] < valid_v, logits, -jnp.inf)
        bm = jnp.max(logits, axis=-1)
        nm = jnp.maximum(m, bm)
        s = s * jnp.exp(m - nm) + jnp.sum(jnp.exp(logits - nm[:, None]), axis=-1)
        return (nm, s), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32), jnp.zeros((n,), jnp.float32))
    (m, s), _ = jax.lax.scan(body, init, (wb_all, jnp.arange(nblocks)))
    return m, s


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _linear_ce(x, w, labels, block, ignore_label):
    m, s = _fwd_scan(x, w, block, w.shape[0])
    wl = jnp.take(w, labels, axis=0)  # (N, H)
    label_logit = jnp.sum(
        x.astype(jnp.float32) * wl.astype(jnp.float32), axis=-1
    )
    loss = (m + jnp.log(s)) - label_logit
    if ignore_label is not None:
        loss = jnp.where(labels == ignore_label, 0.0, loss)
    return loss


def _linear_ce_fwd(x, w, labels, block, ignore_label):
    m, s = _fwd_scan(x, w, block, w.shape[0])
    wl = jnp.take(w, labels, axis=0)
    label_logit = jnp.sum(
        x.astype(jnp.float32) * wl.astype(jnp.float32), axis=-1
    )
    loss = (m + jnp.log(s)) - label_logit
    if ignore_label is not None:
        loss = jnp.where(labels == ignore_label, 0.0, loss)
    return loss, (x, w, labels, m, s)


def _linear_ce_bwd(block, ignore_label, res, g):
    x, w, labels, m, s = res
    n, h = x.shape
    v = w.shape[0]
    if ignore_label is not None:
        g = jnp.where(labels == ignore_label, 0.0, g)
    g = g.astype(jnp.float32)
    wp, vpad = _pad_vocab(w, block)
    nblocks = vpad // block
    wb_all = wp.reshape(nblocks, block, h)
    log_z = (m + jnp.log(s))[:, None]  # (N, 1)

    def body(dx, wb_i):
        wb, i = wb_i
        logits = jnp.dot(x, wb.T, preferred_element_type=jnp.float32)
        col = i * block + jax.lax.iota(jnp.int32, block)
        logits = jnp.where(col[None, :] < v, logits, -jnp.inf)
        gp = g[:, None] * jnp.exp(logits - log_z)  # (N, Vb) f32
        gp_c = gp.astype(x.dtype)
        dx = dx + jnp.dot(gp_c, wb, preferred_element_type=jnp.float32)
        dwb = jnp.dot(gp_c.T, x, preferred_element_type=jnp.float32)
        return dx, dwb

    dx0 = jnp.zeros((n, h), jnp.float32)
    dx, dw_blocks = jax.lax.scan(body, dx0, (wb_all, jnp.arange(nblocks)))
    dw = dw_blocks.reshape(vpad, h)[:v]
    # label-row corrections: dx -= g*W[labels];  dW[labels] -= g*x
    wl = jnp.take(w, labels, axis=0).astype(jnp.float32)
    dx = dx - g[:, None] * wl
    dw = dw - jax.ops.segment_sum(
        g[:, None] * x.astype(jnp.float32), labels, num_segments=v
    )
    return dx.astype(x.dtype), dw.astype(w.dtype), None


_linear_ce.defvjp(_linear_ce_fwd, _linear_ce_bwd)


@register("linear_cross_entropy", namespaces=("nd", "npx"))
def linear_cross_entropy(x, weight, labels, block_size=8192,
                         ignore_label: Optional[int] = None, mode="auto",
                         **kw):
    """Cross-entropy of ``softmax(x @ weight.T)`` against integer ``labels``.

    ``mode`` selects the implementation (round-4 regime sweep,
    ``benchmarks.bench_linear_ce`` on v5e):

    - ``"dense"``: materialize the (N, V) f32 logits — measured 2.5-3x
      FASTER than the blocked scan whenever they fit (XLA pipelines the
      big matmul + fused logsumexp better than a scan amortizes;
      V=30k N=8k: 7.5 vs 21.7 ms; V=262k N=8k: 68.7 vs 174.3 ms).
    - ``"blocked"``: online-logsumexp scan, O(N*block) memory — the only
      feasible path once logits exceed HBM (naive OOMs at V=131k N=32k
      on the 16 GB chip; blocked runs it at 344 ms).
    - ``"auto"`` (default): dense while the transient logits footprint
      (~3 copies of N*V f32: logits + grad + workspace) stays under
      ``MXTPU_CE_DENSE_MAX_BYTES`` (default 6e9), else blocked.

    Args:
        x: (..., H) activations (any leading shape; flattened internally).
        weight: (V, H) classifier / tied-embedding matrix.
        labels: (...,) int class ids, same leading shape as ``x``.
        block_size: vocab tile width of the online-softmax scan.
        ignore_label: optional label id whose rows contribute zero loss
            (the reference's ``ignore_label`` on SoftmaxOutput).

    Returns:
        (...,) per-element losses (f32) with the leading shape of ``labels``.
    """
    lead = labels.shape
    h = x.shape[-1]
    xf = x.reshape(-1, h)
    lf = labels.reshape(-1).astype(jnp.int32)
    if mode == "auto":
        import os

        budget = float(os.environ.get("MXTPU_CE_DENSE_MAX_BYTES", 6e9))
        dense_bytes = 3.0 * xf.shape[0] * weight.shape[0] * 4
        mode = "dense" if dense_bytes <= budget else "blocked"
    if mode == "dense":
        logits = jnp.dot(xf, weight.T, preferred_element_type=jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        lab = jnp.take_along_axis(logits, lf[:, None], 1)[:, 0]
        loss = lse - lab
        if ignore_label is not None:
            loss = jnp.where(lf == ignore_label, 0.0, loss)
        return loss.reshape(lead)
    block = int(min(block_size, max(256, weight.shape[0])))
    loss = _linear_ce(xf, weight, lf, block, ignore_label)
    return loss.reshape(lead)
