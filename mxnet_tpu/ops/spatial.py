"""Spatial-transformer operator group + im2col/col2im.

Reference: ``src/operator/spatial_transformer.cc``, ``grid_generator.cc``,
``bilinear_sampler.cc``, ``src/operator/correlation.cc``, and the im2col
helpers in ``src/operator/nn/im2col.h`` [all unverified].

TPU-first notes: sampling is expressed as gather + FMA (differentiable
through jax's autodiff — the reference hand-wrote every backward);
``im2col`` lowers to ``lax.conv_general_dilated_patches`` (XLA emits the
same unfold loop a hand kernel would); ``col2im`` is defined as the
adjoint of ``im2col`` via ``jax.vjp``, which gives the exact scatter-add
semantics of the reference kernel with zero new kernel code.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _bilinear_sample(data, gx, gy):
    """Sample data (N,C,H,W) at continuous pixel coords gx, gy (N,Ho,Wo).

    Out-of-range samples clamp to the border pixel weighted by the
    in-range fraction — matching the reference's zero-padding semantics:
    weights of out-of-bounds corners are zeroed."""
    N, C, H, W = data.shape
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def corner(yi, xi):
        inb = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        # gather per batch: (N, Ho, Wo) indices into (N, C, H, W)
        v = jax.vmap(lambda d, yy, xx: d[:, yy, xx])(data, yc, xc)
        return v * inb[:, None].astype(data.dtype)

    # corner() returns (N, C, Ho, Wo) via vmap over batch; weights
    # broadcast over C
    def wexp(w):
        return w[:, None].astype(data.dtype)

    out = (corner(y0, x0) * wexp((1 - wy) * (1 - wx))
           + corner(y0, x0 + 1) * wexp((1 - wy) * wx)
           + corner(y0 + 1, x0) * wexp(wy * (1 - wx))
           + corner(y0 + 1, x0 + 1) * wexp(wy * wx))
    return out


@register("BilinearSampler")
def bilinear_sampler(data, grid, **kw):
    """data (N,C,H,W), grid (N,2,Ho,Wo) with x,y in [-1,1] (reference
    convention: grid[:,0] = x, grid[:,1] = y, -1 = left/top edge)."""
    N, C, H, W = data.shape
    gx = (grid[:, 0] + 1.0) * (W - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (H - 1) / 2.0
    return _bilinear_sample(data, gx, gy)


@register("GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=(0, 0),
                   **kw):
    """affine: data (N, 6) row-major 2x3 -> grid (N, 2, H, W) over the
    normalized [-1,1] mesh; warp: data (N, 2, H, W) optical flow added to
    the identity pixel mesh, output normalized (reference semantics)."""
    if transform_type == "affine":
        H, W = int(target_shape[0]), int(target_shape[1])
        theta = data.reshape(-1, 2, 3)
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)
        out = jnp.einsum("nij,jk->nik", theta, base.astype(data.dtype))
        return out.reshape(-1, 2, H, W)
    if transform_type == "warp":
        N, _, H, W = data.shape
        gy, gx = jnp.meshgrid(jnp.arange(H, dtype=data.dtype),
                              jnp.arange(W, dtype=data.dtype),
                              indexing="ij")
        px = data[:, 0] + gx
        py = data[:, 1] + gy
        nx = 2.0 * px / max(W - 1, 1) - 1.0
        ny = 2.0 * py / max(H - 1, 1) - 1.0
        return jnp.stack([nx, ny], axis=1)
    raise ValueError(f"unknown transform_type {transform_type!r}")


@register("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine",
                        sampler_type="bilinear", **kw):
    """Affine spatial transformer: loc (N, 6) localization output, data
    (N, C, H, W) -> (N, C, *target_shape)."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise ValueError("reference supports affine + bilinear only")
    grid = grid_generator(loc, "affine", target_shape)
    return bilinear_sampler(data, grid)


@register("Correlation", num_outputs=1)
def correlation(data1, data2, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, pad_size=0, is_multiply=True, **kw):
    """FlowNet correlation layer (reference ``correlation.cc``): for each
    displacement (dy, dx) on the stride2 grid within max_displacement,
    emit mean over channels&kernel-window of data1 * shifted(data2)
    (or |a-b| sum when is_multiply=False). Static displacement loop —
    unrolled into one fused XLA program. Output spatial size matches the
    reference: the padded grid cropped by border = max_displacement +
    kernel_radius on each side, then strided by stride1."""
    N, C, H, W = data1.shape
    p = int(pad_size)
    d1 = jnp.pad(data1, ((0, 0), (0, 0), (p, p), (p, p)))
    d2 = jnp.pad(data2, ((0, 0), (0, 0), (p, p), (p, p)))
    md, s2 = int(max_displacement), int(stride2)
    ndisp = 2 * (md // s2) + 1
    k = int(kernel_size)
    kr = k // 2
    Hp, Wp = H + 2 * p, W + 2 * p
    outs = []
    norm = C * k * k
    for dy in range(-(md // s2) * s2, (md // s2) * s2 + 1, s2):
        for dx in range(-(md // s2) * s2, (md // s2) * s2 + 1, s2):
            shifted = jnp.roll(d2, shift=(-dy, -dx), axis=(2, 3))
            # zero the wrapped region (reference pads with zeros)
            ys = jnp.arange(Hp) + dy
            xs = jnp.arange(Wp) + dx
            valid = ((ys >= 0) & (ys < Hp))[:, None] \
                & ((xs >= 0) & (xs < Wp))[None, :]
            shifted = shifted * valid[None, None].astype(shifted.dtype)
            prod = d1 * shifted if is_multiply else jnp.abs(d1 - shifted)
            s = jnp.sum(prod, axis=1, keepdims=True)  # over channels
            if k > 1:
                s = jax.lax.reduce_window(
                    s, 0.0, jax.lax.add, (1, 1, k, k), (1, 1, 1, 1),
                    ((0, 0), (0, 0), (kr, kr), (kr, kr)))
            outs.append(s / norm)
    out = jnp.concatenate(outs, axis=1)  # (N, ndisp*ndisp, Hp, Wp)
    border = md + kr
    if border:
        if 2 * border >= min(Hp, Wp):
            raise ValueError(
                f"Correlation: border {border} consumes the whole "
                f"{Hp}x{Wp} padded input; increase pad_size"
            )
        out = out[:, :, border:Hp - border, border:Wp - border]
    if int(stride1) > 1:
        out = out[:, :, ::int(stride1), ::int(stride1)]
    return out


@register("im2col")
def im2col(data, kernel, stride=1, dilate=1, pad=0, **kw):
    """(N, C, H, W) -> (N, C*kh*kw, L) column matrix (reference
    ``im2col.h`` layout: feature dim ordered channel-major, then kernel
    rows, then kernel cols; L = output locations row-major)."""
    kh, kw_ = (kernel if isinstance(kernel, (tuple, list)) else (kernel,) * 2)
    sh, sw = (stride if isinstance(stride, (tuple, list)) else (stride,) * 2)
    dh, dw = (dilate if isinstance(dilate, (tuple, list)) else (dilate,) * 2)
    ph, pw = (pad if isinstance(pad, (tuple, list)) else (pad,) * 2)
    patches = jax.lax.conv_general_dilated_patches(
        data, (int(kh), int(kw_)), (int(sh), int(sw)),
        [(int(ph), int(ph)), (int(pw), int(pw))],
        rhs_dilation=(int(dh), int(dw)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    N = data.shape[0]
    return patches.reshape(N, patches.shape[1], -1)


@register("col2im")
def col2im(data, input_shape, kernel, stride=1, dilate=1, pad=0, **kw):
    """Adjoint of ``im2col``: scatter-add columns back to (N, C, H, W).

    Defined as the vjp of im2col — bit-exact adjoint semantics without a
    hand-written scatter kernel."""
    shape = tuple(int(s) for s in input_shape)
    zeros = jnp.zeros(shape, dtype=data.dtype)
    _, vjp = jax.vjp(
        lambda x: im2col(x, kernel, stride=stride, dilate=dilate, pad=pad),
        zeros)
    return vjp(data)[0]
