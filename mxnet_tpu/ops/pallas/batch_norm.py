"""Fused BatchNorm reductions: Pallas TPU kernels (channel-last layout).

Replaces the stat passes of the reference's hand-written BN kernel
(``src/operator/nn/batch_norm.cu`` [unverified]) the TPU way. Round-3
profiling (benchmarks/traces/README.md) showed ResNet-50's BN reductions
running at XLA's HBM roofline with the *two-pass* centered statistics:
one full read of x for the mean, a second for the variance. The obvious
one-pass rewrite (E[x^2]-E[x]^2) was built and REVERTED in round 3 — it
cancels catastrophically whenever |mean| >> std, even with f32
accumulators.

These kernels get the one-pass traffic without the cancellation:

* ``bn_stats``      — ONE read of x. Blocks of the (M, C) channel-last
  view accumulate shifted partials sum(x-s) and sum((x-s)^2) in f32
  VMEM, where the per-channel shift ``s`` is the channel's first row (a
  single sample sits within ~std of the true mean, so
  var = E[(x-s)^2] - E[x-s]^2 only cancels O(1) bits, never the
  catastrophic mean^2/var ratio of the uncentered form).
* ``bn_bwd_reduce`` — ONE joint read of (x, dy) producing sum(dy) and
  sum(dy * xhat). The jnp backward relies on XLA multi-output fusion to
  merge those two reductions; the kernel makes the single pass a
  guarantee.

Layout matters more than the kernel: a first NCHW row-view attempt
measured 2x SLOWER end-to-end because Pallas operands take row-major
layout, and materializing an (N*C, L) view of what XLA keeps in its
internal (channel-minor) conv layout cost a full transpose + copy per
call. Channel-last input makes the (M, C) view genuinely free AND puts
C on the lane axis, so the row reduction never crosses lanes — which is
why ``supports()`` only accepts axis == ndim-1. Run BN-heavy models
with ``layout="NHWC"`` (the model zoo option) to engage it.

The normalize forward and the dx epilogue stay in jnp on purpose: they
are single-FMA elementwise passes XLA fuses into neighboring ops
(ReLU, residual adds), which a hand kernel would break.

Narrow layers (C < 128) would waste most of the 128-lane register; the
wrapper folds k = 128 // C rows into the lane axis (each lane column is
channel ``lane % C``) so conv1-era C=64 layers still run full-width.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _use_interpret() -> bool:
    """``MXTPU_FLASH_INTERPRET``: force (``1``) or forbid (``0``) Pallas
    interpret mode; default ``auto`` interprets off-TPU (CPU testing)."""
    import os

    v = os.environ.get("MXTPU_FLASH_INTERPRET", "").strip().lower()
    if v in ("1", "true", "force", "on"):
        return True
    if v in ("0", "false", "off"):
        return False
    return jax.default_backend() != "tpu"


def _scratch(shapes):
    from jax.experimental.pallas import tpu as pltpu

    return [pltpu.VMEM(s, jnp.float32) for s in shapes]


_TARGET_ROWS = 1024  # rows per block: x block is TARGET_ROWS*C_LANES*4 bytes


def _row_tiles(M: int, C: int):
    lanes = min(512, ((C + 127) // 128) * 128)
    rows = max(8, min(_TARGET_ROWS, (1 << 18) // lanes))
    return rows, lanes


def _stats_kernel(x_ref, s1_ref, s2_ref, sh_ref, acc1, acc2, shift, *, M, C):
    i = pl.program_id(1)          # row-block sweep (inner grid dim)
    x = x_ref[...].astype(jnp.float32)
    rows, lanes = x.shape

    @pl.when(i == 0)
    def _init():
        shift[...] = x[0:1, :]
        acc1[...] = jnp.zeros_like(acc1)
        acc2[...] = jnp.zeros_like(acc2)

    ridx = i * rows + jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 0)
    cidx = pl.program_id(0) * lanes \
        + jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 1)
    mask = (ridx < M) & (cidx < C)
    xs = jnp.where(mask, x - shift[...], 0.0)
    acc1[...] += jnp.sum(xs, axis=0, keepdims=True)
    acc2[...] += jnp.sum(xs * xs, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(1) - 1)
    def _flush():
        s1_ref[...] = acc1[...]
        s2_ref[...] = acc2[...]
        sh_ref[...] = shift[...]


@jax.jit
def _stats_call(x2d):
    M, C = x2d.shape
    rows, lanes = _row_tiles(M, C)
    nc = (C + lanes - 1) // lanes
    grid = (nc, (M + rows - 1) // rows)
    return pl.pallas_call(
        functools.partial(_stats_kernel, M=M, C=C),
        grid=grid,
        in_specs=[pl.BlockSpec((rows, lanes), lambda c, i: (i, c))],
        out_specs=[pl.BlockSpec((1, lanes), lambda c, i: (0, c))] * 3,
        out_shape=[jax.ShapeDtypeStruct((1, nc * lanes), jnp.float32)] * 3,
        scratch_shapes=_scratch([(1, lanes)] * 3),
        interpret=_use_interpret(),
    )(x2d)


def _fold_narrow(M: int, C: int):
    """Fold k rows into lanes for narrow layers: (M, C) -> (M/k, k*C)."""
    if C >= 128 or 128 % C or C < 1:
        return 1
    k = 128 // C
    while k > 1 and M % k:
        k //= 2
    return k


def bn_stats(x2d):
    """Per-channel (mean, var) of channel-last x viewed as (M, C); f32.

    One HBM read of x; shifted one-pass partials per lane column,
    combined across the lane-folded copies in a tiny f32 epilogue."""
    M, C = x2d.shape
    k = _fold_narrow(M, C)
    xv = x2d.reshape(M // k, k * C)
    s1, s2, sh = _stats_call(xv)
    Cv = k * C
    s1, s2, sh = s1[0, :Cv], s2[0, :Cv], sh[0, :Cv]
    if k > 1:
        # each folded copy j covers rows j mod k: combine as k subgroups
        # of equal count via Chan's formula (all on (k, C)-sized arrays)
        n_g = M // k
        s1, s2, sh = (a.reshape(k, C) for a in (s1, s2, sh))
        mean_g = sh + s1 / n_g
        m2_g = s2 - s1 * s1 / n_g
        mean = jnp.mean(mean_g, axis=0)
        m2 = jnp.sum(m2_g, axis=0) + n_g * jnp.sum(
            jnp.square(mean_g - mean[None, :]), axis=0)
        return mean, m2 / M
    mean = sh + s1 / M
    var = s2 / M - jnp.square(s1 / M)
    return mean, var


def _bwd_kernel(x_ref, dy_ref, mi_ref, sd_ref, sdx_ref, acc1, acc2, *, M, C):
    i = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    rows, lanes = x.shape

    @pl.when(i == 0)
    def _init():
        acc1[...] = jnp.zeros_like(acc1)
        acc2[...] = jnp.zeros_like(acc2)

    ridx = i * rows + jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 0)
    cidx = pl.program_id(0) * lanes \
        + jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 1)
    mask = (ridx < M) & (cidx < C)
    mean = mi_ref[0:1, :]
    inv = mi_ref[1:2, :]
    # mask BEFORE the product: padded lanes of x/mi hold garbage and
    # 0 * NaN would poison the accumulator
    xhat = jnp.where(mask, (x - mean) * inv, 0.0)
    dym = jnp.where(mask, dy, 0.0)
    acc1[...] += jnp.sum(dym, axis=0, keepdims=True)
    acc2[...] += jnp.sum(dym * xhat, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(1) - 1)
    def _flush():
        sd_ref[...] = acc1[...]
        sdx_ref[...] = acc2[...]


@jax.jit
def _bwd_call(x2d, dy2d, mi):
    M, C = x2d.shape
    rows, lanes = _row_tiles(M, C)
    nc = (C + lanes - 1) // lanes
    grid = (nc, (M + rows - 1) // rows)
    return pl.pallas_call(
        functools.partial(_bwd_kernel, M=M, C=C),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, lanes), lambda c, i: (i, c)),
            pl.BlockSpec((rows, lanes), lambda c, i: (i, c)),
            pl.BlockSpec((2, lanes), lambda c, i: (0, c)),
        ],
        out_specs=[pl.BlockSpec((1, lanes), lambda c, i: (0, c))] * 2,
        out_shape=[jax.ShapeDtypeStruct((1, nc * lanes), jnp.float32)] * 2,
        scratch_shapes=_scratch([(1, lanes)] * 2),
        interpret=_use_interpret(),
    )(x2d, dy2d, mi)


def bn_bwd_reduce(x2d, dy2d, mean, inv):
    """(sum dy, sum dy*xhat) per channel in ONE read of (x, dy);
    channel-last (M, C) views, f32 outputs."""
    M, C = x2d.shape
    k = _fold_narrow(M, C)
    Cv = k * C
    mi = jnp.stack([jnp.tile(mean, k), jnp.tile(inv, k)])  # (2, k*C)
    sd, sdx = _bwd_call(
        x2d.reshape(M // k, Cv), dy2d.reshape(M // k, Cv), mi)
    sd, sdx = sd[0, :Cv], sdx[0, :Cv]
    if k > 1:
        sd = jnp.sum(sd.reshape(k, C), axis=0)
        sdx = jnp.sum(sdx.reshape(k, C), axis=0)
    return sd, sdx


def supports(x, axis) -> bool:
    """Channel-last BN only: the (M, C) view must be layout-free (see
    module docstring for why NCHW goes through the jnp path)."""
    if x.ndim < 2 or axis not in (-1, x.ndim - 1):
        return False
    C = x.shape[-1]
    M = 1
    for d in x.shape[:-1]:
        M *= d
    return M >= 2 and C >= 1
