"""Fused LayerNorm: Pallas TPU kernel, forward + backward.

Replaces the reference's ``src/operator/nn/layer_norm.cc`` hot path
[unverified]. Profiling the BERT step showed XLA's LayerNorm lowering
(convert_reduce / multiply_reduce fusions) running far below HBM bandwidth
— each (rows, C) tensor makes several passes for mean/var/normalize and
again for the three backward reductions. One Pallas kernel per direction
does a single pass: row statistics live in registers/VMEM, and the
gamma/beta gradients accumulate in-kernel into one (1, C) buffer that
every (sequential) grid step revisits.

Constraints: normalization over the LAST axis with C % 128 == 0 (TPU lane
tiling); anything else falls back to the jnp composition in ``ops/nn.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _use_interpret() -> bool:
    """``MXTPU_FLASH_INTERPRET``: force (``1``) or forbid (``0``) Pallas
    interpret mode; default ``auto`` interprets off-TPU (CPU testing)."""
    import os

    v = os.environ.get("MXTPU_FLASH_INTERPRET", "").strip().lower()
    if v in ("1", "true", "force", "on"):
        return True
    if v in ("0", "false", "off"):
        return False
    return jax.default_backend() != "tpu"


def _fwd_kernel(x_ref, g_ref, b_ref, o_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)  # (R, C)
    mean = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    g = g_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    o_ref[...] = (xc * rstd * g + b).astype(o_ref.dtype)
    mean_ref[...] = mean
    rstd_ref[...] = rstd


def _bwd_kernel(x_ref, g_ref, mean_ref, rstd_ref, dy_ref, dx_ref, dg_ref,
                db_ref):
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    mean = mean_ref[...]
    rstd = rstd_ref[...]
    xhat = (x - mean) * rstd
    dyg = dy * g_ref[...].astype(jnp.float32)
    m1 = jnp.mean(dyg, axis=1, keepdims=True)
    m2 = jnp.mean(dyg * xhat, axis=1, keepdims=True)
    dx_ref[...] = ((dyg - m1 - xhat * m2) * rstd).astype(dx_ref.dtype)
    # gamma/beta grads: one (1, C) accumulator revisited by every grid
    # step — TPU grids run sequentially, so += is race-free
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dg_ref[...] = jnp.zeros_like(dg_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    dg_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[...] += jnp.sum(dy, axis=0, keepdims=True)


_BLOCK_ROWS = 256


def _pad_rows(x, block):
    pad = (-x.shape[0]) % block
    return jnp.pad(x, ((0, pad), (0, 0))) if pad else x, x.shape[0]


@functools.partial(jax.jit, static_argnames=("eps",))
def _ln_fwd_impl(x, gamma, beta, eps):
    N, C = x.shape
    xp, n = _pad_rows(x, _BLOCK_ROWS)
    Np = xp.shape[0]
    grid = (Np // _BLOCK_ROWS,)
    out, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, C), lambda i: (i, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK_ROWS, C), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Np, C), x.dtype),
            jax.ShapeDtypeStruct((Np, 1), jnp.float32),
            jax.ShapeDtypeStruct((Np, 1), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(xp, gamma.reshape(1, C), beta.reshape(1, C))
    return out[:n], mean[:n], rstd[:n]


@jax.jit
def _ln_bwd_impl(x, gamma, mean, rstd, dy):
    N, C = x.shape
    xp, n = _pad_rows(x, _BLOCK_ROWS)
    dyp, _ = _pad_rows(dy, _BLOCK_ROWS)
    meanp, _ = _pad_rows(mean, _BLOCK_ROWS)
    # rstd of zero-padded rows must stay finite; pad with ones
    pad = xp.shape[0] - N
    rstdp = jnp.pad(rstd, ((0, pad), (0, 0)), constant_values=1.0) \
        if pad else rstd
    Np = xp.shape[0]
    nb = Np // _BLOCK_ROWS
    dx, dg, db = pl.pallas_call(
        _bwd_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, C), lambda i: (i, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, C), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK_ROWS, C), lambda i: (i, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Np, C), x.dtype),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(xp, gamma.reshape(1, C), meanp, rstdp, dyp)
    return dx[:n], dg.reshape(C), db.reshape(C)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layer_norm_fused(x, gamma, beta, eps=1e-5):
    """LayerNorm over the last axis of 2-D ``x`` (rows, C)."""
    out, _, _ = _ln_fwd_impl(x, gamma, beta, eps)
    return out


def _ln_fwd(x, gamma, beta, eps):
    out, mean, rstd = _ln_fwd_impl(x, gamma, beta, eps)
    return out, (x, gamma, mean, rstd)


def _ln_bwd(eps, res, dy):
    x, gamma, mean, rstd = res
    dx, dg, db = _ln_bwd_impl(x, gamma, mean, rstd, dy)
    return dx, dg.astype(gamma.dtype), db.astype(gamma.dtype)


layer_norm_fused.defvjp(_ln_fwd, _ln_bwd)


def supports(data, axis) -> bool:
    """Can the fused kernel serve this call?

    Bounds C so the backward's three (block_rows, C) f32 VMEM buffers fit
    the ~16 MB budget; wider norms fall back to the jnp path."""
    C = data.shape[-1]
    return (axis in (-1, data.ndim - 1)) and C % 128 == 0 \
        and 128 <= C <= 4096 and data.ndim >= 2
