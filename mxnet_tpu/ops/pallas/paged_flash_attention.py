"""Paged flash attention: Pallas kernels that read the serving stack's
``(num_pages, page_size, H, D)`` KV pools IN PLACE.

The continuous batcher's decode path (PR 8) gathers K/V through the page
table into a materialized ``(B, P*page_size, H, D)`` view and then runs
dense attention over it — two full copies of every cached key/value per
decoded token, plus an O(L) score row in HBM. These kernels close that
gap (the FlashAttention/PagedAttention fusion, ROADMAP item 2): the page
table rides the grid as a scalar-prefetch operand, each grid step DMAs
one page directly out of the pool, and an online-softmax carry in VMEM
scratch accumulates across the sequential page dimension — the gather
never materializes and scores never leave VMEM.

Two variants, mirroring ``flash_attention.py``'s forward:

- ``paged_decode_attention`` — single query token per row (the decode
  hot path). Grid ``(B, pages_per_row)``; row ``b``'s step ``p`` reads
  pool block ``page_table[b, p]`` and masks keys at absolute positions
  ``> pos[b]``.
- ``paged_window_attention`` — an S-token query window per row, each
  query ``i`` at absolute position ``q_offset[b] + i`` (causal within
  and across the window). This is the q_offset-aware PREFILL variant:
  suffix-only prefix-cache replay and speculative verification both
  score a short window against a long paged history in one pass.

Both keep ``MXTPU_FLASH_INTERPRET`` (force/forbid/auto, shared with
``flash_attention.py``) and ship a dense jnp reference
(``*_reference``) used by the tolerance tests; the MODULE-level
fallback when the kernel gate is off is the attention layer's existing
gather+dense path, which stays bitwise-unchanged. ``MXTPU_FLASH_PAGED``
gates routing: force on (``1``/``force``/``on``), force off
(``0``/``off``/``false``), default auto = on only when the backend is a
real TPU (the CPU rig would only ever run the kernels interpreted,
which is slower than the dense path it replaces).
"""

from __future__ import annotations

import functools
import os as _os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import _NEG_INF, _use_interpret

try:  # TPU backend module; absent in some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["paged_decode_attention", "paged_window_attention",
           "paged_decode_reference", "paged_window_reference",
           "flash_paged_enabled"]

# online-softmax m/l scratch is lane-replicated to the TPU register
# width (the flash-kernel convention): every lane of a row holds the
# same running max / denominator, so the elementwise update needs no
# cross-lane reduction beyond the score-block max itself
_LANES = 128


def flash_paged_enabled() -> bool:
    """``MXTPU_FLASH_PAGED``: route paged attention through the Pallas
    kernels (``1``/``true``/``force``/``on``), keep the dense
    gather fallback (``0``/``false``/``off``), or — default auto —
    kernels only on a real TPU backend (interpreted kernels on the CPU
    rig are slower than the dense path they replace)."""
    v = _os.environ.get("MXTPU_FLASH_PAGED", "").strip().lower()
    if v in ("1", "true", "force", "on"):
        return True
    if v in ("0", "false", "off"):
        return False
    return _HAS_PLTPU and jax.default_backend() == "tpu"


def _require_pltpu():
    if pltpu is None:  # pragma: no cover - CPU builds ship pltpu
        raise RuntimeError(
            "MXTPU_FLASH_PAGED forced the paged Pallas kernels on, but "
            "jax.experimental.pallas.tpu is not importable in this "
            "build — unset MXTPU_FLASH_PAGED to use the dense fallback")


def _decode_kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, page_size, sm_scale):
    """Grid (B, pages_per_row), pages sequential per row: one pool page
    per step, online-softmax carry (m, l, acc) in VMEM scratch."""
    b = pl.program_id(0)
    p = pl.program_id(1)
    n_pages = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    pos = pos_ref[b]

    # pages whose first slot is already past this row's position hold
    # nothing visible — skip the whole block (page 0 is never skipped,
    # so l is never all-zero for a live row)
    @pl.when(p * page_size <= pos)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)          # (H, D)
        k = k_ref[0].astype(jnp.float32)          # (ps, H, D)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * sm_scale                               # (H, ps)
        key_abs = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        s = jnp.where(key_abs <= pos, s, _NEG_INF)
        m_prev = m_ref[...]                        # (H, LANES)
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # (H, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p_act = jnp.exp(s - m_new[:, :1])          # (H, ps)
        l_new = alpha * l_prev + jnp.sum(p_act, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + jax.lax.dot_general(
            p_act, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )                                          # (H, D)
        m_ref[...] = m_new
        l_ref[...] = l_new

    @pl.when(p == n_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, page_table, pos, *,
                           sm_scale):
    """Single-token paged attention, pools read in place.

    q ``(B, H, D)``; pools ``(num_pages, page_size, H, D)``;
    ``page_table`` ``(B, P)`` int32; ``pos`` ``(B,)`` int32 — row ``b``
    attends keys at absolute positions ``<= pos[b]`` (the caller has
    already scattered position ``pos`` into the pool). Returns
    ``(B, H, D)``."""
    _require_pltpu()
    B, H, D = q.shape
    ps = k_pool.shape[1]
    P = page_table.shape[1]
    kernel = functools.partial(_decode_kernel, page_size=ps,
                               sm_scale=sm_scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, P),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, p, pt, ps_: (b, 0, 0)),
            pl.BlockSpec((1, ps, H, D),
                         lambda b, p, pt, ps_: (pt[b, p], 0, 0, 0)),
            pl.BlockSpec((1, ps, H, D),
                         lambda b, p, pt, ps_: (pt[b, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, p, pt, ps_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, _LANES), jnp.float32),
            pltpu.VMEM((H, _LANES), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=_use_interpret(),
    )(page_table.astype(jnp.int32), pos.astype(jnp.int32),
      q, k_pool, v_pool)


def _window_kernel(pt_ref, off_ref, vl_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, page_size, sm_scale,
                   window):
    """Like ``_decode_kernel`` but an S-query window rides each row:
    query ``i`` sits at absolute position ``off + i`` and masks keys
    above it; queries ``>= vl`` are padding and finalize to zero."""
    b = pl.program_id(0)
    p = pl.program_id(1)
    n_pages = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    off = off_ref[b]
    vl = vl_ref[b]

    # the window's LAST query (off + window - 1) bounds what any query
    # can see — pages wholly past it contribute nothing
    @pl.when(p * page_size <= off + window - 1)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)          # (H, S, D)
        k = k_ref[0].astype(jnp.float32)          # (ps, H, D)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * sm_scale                               # (H, S, ps)
        key_abs = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2)
        q_abs = off + jax.lax.broadcasted_iota(
            jnp.int32, (1, window, 1), 1)
        s = jnp.where(key_abs <= q_abs, s, _NEG_INF)
        m_prev = m_ref[...]                        # (H, S, LANES)
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=2, keepdims=True)  # (H, S, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p_act = jnp.exp(s - m_new[:, :, :1])       # (H, S, ps)
        l_new = alpha * l_prev + jnp.sum(p_act, axis=2, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha[:, :, :1] + jax.lax.dot_general(
            p_act, v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )                                          # (H, S, D)
        m_ref[...] = m_new
        l_ref[...] = l_new

    @pl.when(p == n_pages - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :, :1], 1e-30)
        out = acc_ref[...] / l                     # (H, S, D)
        live = jax.lax.broadcasted_iota(
            jnp.int32, (1, window, 1), 1) < vl
        o_ref[0] = jnp.where(live, out, 0.0).astype(o_ref.dtype)


def paged_window_attention(q, k_pool, v_pool, page_table, q_offset,
                           window_vl=None, *, sm_scale):
    """S-token query window over a paged history, pools read in place.

    q ``(B, S, H, D)``; query ``i`` of row ``b`` sits at absolute
    position ``q_offset[b] + i`` and attends keys at positions ``<=``
    it (causal across the cached history AND within the window — the
    caller has already scattered the window's K/V into the pool).
    ``window_vl`` ``(B,)`` optionally marks queries ``>= window_vl[b]``
    as padding (their outputs are zeroed). Returns ``(B, S, H, D)``."""
    _require_pltpu()
    B, S, H, D = q.shape
    ps = k_pool.shape[1]
    P = page_table.shape[1]
    if window_vl is None:
        window_vl = jnp.full((B,), S, jnp.int32)
    qt = jnp.swapaxes(q, 1, 2)                     # (B, H, S, D)
    kernel = functools.partial(_window_kernel, page_size=ps,
                               sm_scale=sm_scale, window=S)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, P),
        in_specs=[
            pl.BlockSpec((1, H, S, D),
                         lambda b, p, pt, off, vl: (b, 0, 0, 0)),
            pl.BlockSpec((1, ps, H, D),
                         lambda b, p, pt, off, vl: (pt[b, p], 0, 0, 0)),
            pl.BlockSpec((1, ps, H, D),
                         lambda b, p, pt, off, vl: (pt[b, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, S, D),
                               lambda b, p, pt, off, vl: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, S, _LANES), jnp.float32),
            pltpu.VMEM((H, S, _LANES), jnp.float32),
            pltpu.VMEM((H, S, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        interpret=_use_interpret(),
    )(page_table.astype(jnp.int32), q_offset.astype(jnp.int32),
      window_vl.astype(jnp.int32), qt, k_pool, v_pool)
    return jnp.swapaxes(out, 1, 2)                 # (B, S, H, D)


# ------------------------------------------------------------ references
def paged_decode_reference(q, k_pool, v_pool, page_table, pos, *,
                           sm_scale):
    """Dense jnp reference for ``paged_decode_attention`` (gathers the
    pages the kernel reads in place) — the tolerance-test oracle."""
    B, H, D = q.shape
    ps = k_pool.shape[1]
    P = page_table.shape[1]
    k = k_pool[page_table].reshape(B, P * ps, H, D).astype(jnp.float32)
    v = v_pool[page_table].reshape(B, P * ps, H, D).astype(jnp.float32)
    s = jnp.einsum("bhd,blhd->bhl", q.astype(jnp.float32), k) * sm_scale
    mask = jnp.arange(P * ps)[None, None, :] <= pos[:, None, None]
    s = jnp.where(mask, s, _NEG_INF)
    probs = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhl,blhd->bhd", probs, v).astype(q.dtype)


def paged_window_reference(q, k_pool, v_pool, page_table, q_offset,
                           window_vl=None, *, sm_scale):
    """Dense jnp reference for ``paged_window_attention``."""
    B, S, H, D = q.shape
    ps = k_pool.shape[1]
    P = page_table.shape[1]
    if window_vl is None:
        window_vl = jnp.full((B,), S, jnp.int32)
    k = k_pool[page_table].reshape(B, P * ps, H, D).astype(jnp.float32)
    v = v_pool[page_table].reshape(B, P * ps, H, D).astype(jnp.float32)
    s = jnp.einsum("bshd,blhd->bhsl", q.astype(jnp.float32), k) * sm_scale
    key_abs = jnp.arange(P * ps)[None, None, None, :]
    q_abs = (q_offset[:, None, None, None]
             + jnp.arange(S)[None, None, :, None])
    s = jnp.where(key_abs <= q_abs, s, _NEG_INF)
    probs = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhsl,blhd->bshd", probs, v)
    live = jnp.arange(S)[None, :, None, None] < \
        window_vl[:, None, None, None]
    return jnp.where(live, out, 0.0).astype(q.dtype)
