"""Flash attention: Pallas TPU kernel, online-softmax forward + blockwise
recompute backward (both O(S) memory).

Replaces the reference's ``src/operator/contrib/transformer.cc`` interleaved
attention ops [unverified], which materialize the full O(L²) score matrix —
the reference's long-context ceiling (SURVEY.md §5). Design follows the
standard flash algorithm: Q blocks ride the grid, K/V blocks stream through
an inner loop carrying (running max, denominator, accumulator); the MXU sees
(block_q × d) @ (d × block_k) tiles, VMEM holds one head's K/V.

Backward recomputes P blockwise from the saved logsumexp under ``lax.scan``
(XLA fuses it into one loop); a hand-written Pallas backward is a later
optimization — the recompute pass is already fused and O(S)-memory.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend module; absent in some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel(q_ref, k_ref, v_ref, *refs, sm_scale, block_k, kv_len,
                causal, block_q, use_vl):
    # refs: q (1, block_q, d), k/v (1, padded_kv, d); with use_vl an extra
    # vl (B*H, 1) int32 ref (full array — tiny, so every grid step sees it
    # whole; a (1,1) block would violate the TPU (8,128) tiling rule);
    # then o (1, block_q, d), lse (1, block_q, 1) — leading dim is the
    # (b*h) grid block of size 1. vl is this batch row's valid key length
    # (reference softmax use_length semantics: keys >= vl are padding);
    # the dense path compiles without the vl operand at all.
    if use_vl:
        vl_ref, o_ref, lse_ref = refs
        vl = jnp.minimum(vl_ref[pl.program_id(0), 0], kv_len)
    else:
        o_ref, lse_ref = refs
        vl = kv_len
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    d = q.shape[-1]
    padded_kv = k_ref.shape[1]
    nk = padded_kv // block_k

    q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    def body(jk, carry):
        m_prev, l_prev, acc = carry
        k_blk = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (block_q, block_k)
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        mask = k_pos < vl
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, _NEG_INF)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    # blocks past the valid length contribute nothing — skip them
    nk_eff = jnp.minimum(nk, pl.cdiv(vl, block_k)) if use_vl else nk
    if causal:
        # blocks fully above the diagonal contribute nothing — skip them
        nk_eff = jnp.minimum(
            nk_eff, pl.cdiv((iq + 1) * block_q, block_k)
        )
    m, l, acc = jax.lax.fori_loop(0, nk_eff, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l)).astype(jnp.float32)


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k")
)
def _flash_fwd_impl(q, k, v, vl, causal, sm_scale, block_q, block_k):
    """q (B,H,Sq,D), k/v (B,H,Sk,D), vl (B,) int32
    -> out (B,H,Sq,D), lse (B,H,Sq)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bq = min(block_q, max(Sq, 8))
    bk = min(block_k, max(Sk, 8))
    qp = _pad_to(q, 2, bq)
    kp = _pad_to(k, 2, bk)
    vp = _pad_to(v, 2, bk)
    Sq_p, Sk_p = qp.shape[2], kp.shape[2]
    qp = qp.reshape(B * H, Sq_p, D)
    kp = kp.reshape(B * H, Sk_p, D)
    vp = vp.reshape(B * H, Sk_p, D)
    use_vl = vl is not None
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, Sk_p, D), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, Sk_p, D), lambda b, i: (b, 0, 0)),
    ]
    operands = [qp, kp, vp]
    if use_vl:
        # one valid-length scalar per (b*h) grid row, b-major per reshape
        operands.append(jnp.repeat(vl.astype(jnp.int32), H).reshape(B * H, 1))
        in_specs.append(pl.BlockSpec((B * H, 1), lambda b, i: (0, 0)))
    grid = (B * H, Sq_p // bq)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, block_k=bk, kv_len=Sk,
        causal=causal, block_q=bq, use_vl=use_vl,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sq_p, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sq_p, 1), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(*operands)
    out = out.reshape(B, H, Sq_p, D)[:, :, :Sq]
    lse = lse.reshape(B, H, Sq_p)[:, :, :Sq]
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_attention(q, k, v, valid_length=None, causal=False, sm_scale=None,
                    block_q=128, block_k=128):
    """Fused softmax(q·kᵀ·scale)·v. Shapes (B, H, S, D); O(S) memory.

    ``valid_length`` (B,) int: per-row count of non-padding keys (reference
    softmax ``use_length`` / ``contrib/transformer.cc`` mask semantics
    [unverified]); keys at positions >= valid_length are ignored."""
    out, _ = _flash_fwd(q, k, v, valid_length, causal, sm_scale, block_q,
                        block_k)
    return out


def _flash_fwd(q, k, v, valid_length, causal, sm_scale, block_q, block_k):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    vl = None if valid_length is None else valid_length.astype(jnp.int32)
    return _flash_fwd_impl(q, k, v, vl, causal, float(sm_scale), block_q,
                           block_k)


def _fwd_rule(q, k, v, valid_length, causal, sm_scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, valid_length, causal, sm_scale, block_q,
                          block_k)
    return out, (q, k, v, valid_length, out, lse)


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_k")
)
def _flash_bwd_impl(q, k, v, vl, out, lse, do, causal, sm_scale, block_k):
    """Blockwise recompute backward (scan over K blocks, O(S·block) memory)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bk = min(block_k, Sk)
    kp = _pad_to(k, 2, bk)
    vp = _pad_to(v, 2, bk)
    Sk_p = kp.shape[2]
    nk = Sk_p // bk

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # (B,H,Sq)
    q_pos = jnp.arange(Sq)[:, None]
    vl4 = jnp.minimum(vl, Sk).reshape(B, 1, 1, 1)

    def body(dq_acc, jk):
        kb = jax.lax.dynamic_slice_in_dim(kp, jk * bk, bk, 2).astype(jnp.float32)
        vb = jax.lax.dynamic_slice_in_dim(vp, jk * bk, bk, 2).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb) * sm_scale
        k_pos = jk * bk + jnp.arange(bk)[None, :]
        mask = k_pos[None, None] < vl4  # (B,1,1,bk)
        if causal:
            mask = jnp.logical_and(mask, (k_pos <= q_pos)[None, None])
        s = jnp.where(mask, s, _NEG_INF)
        # explicit zero outside the mask: a fully-masked row has lse ~
        # _NEG_INF too, where exp(s - lse) would wrongly give 1
        p = jnp.where(mask, jnp.exp(s - lse[..., None]), 0.0)  # (B,H,Sq,bk)
        dv_b = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vb)
        ds = p * (dp - delta[..., None]) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, kb)
        dk_b = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        return dq_acc, (dk_b, dv_b)

    dq0 = jnp.zeros_like(qf)
    dq, (dks, dvs) = jax.lax.scan(body, dq0, jnp.arange(nk))
    # dks: (nk, B, H, bk, D) -> (B, H, Sk_p, D)
    dk = jnp.moveaxis(dks, 0, 2).reshape(B, H, Sk_p, D)[:, :, :Sk]
    dv = jnp.moveaxis(dvs, 0, 2).reshape(B, H, Sk_p, D)[:, :, :Sk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _bwd_rule(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, valid_length, out, lse = res
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    Sk = k.shape[2]
    vl = (jnp.full((q.shape[0],), Sk, jnp.int32) if valid_length is None
          else valid_length.astype(jnp.int32))
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, vl, out, lse, g, causal, float(sm_scale), block_k
    )
    if valid_length is None:
        dvl = None
    elif jnp.issubdtype(valid_length.dtype, jnp.floating):
        dvl = jnp.zeros_like(valid_length)
    else:
        import numpy as _onp

        dvl = _onp.zeros(valid_length.shape, jax.dtypes.float0)
    return dq, dk, dv, dvl


flash_attention.defvjp(_fwd_rule, _bwd_rule)
