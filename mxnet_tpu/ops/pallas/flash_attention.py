"""Flash attention: Pallas TPU kernel, online-softmax forward + blockwise
recompute backward (both O(S) memory).

Replaces the reference's ``src/operator/contrib/transformer.cc`` interleaved
attention ops [unverified], which materialize the full O(L²) score matrix —
the reference's long-context ceiling (SURVEY.md §5). Design follows the
standard flash algorithm: Q blocks ride the grid, K/V blocks stream through
an inner loop carrying (running max, denominator, accumulator); the MXU sees
(block_q × d) @ (d × block_k) tiles, VMEM holds one head's K/V.

Backward recomputes P blockwise from the saved logsumexp under ``lax.scan``
(XLA fuses it into one loop); a hand-written Pallas backward is a later
optimization — the recompute pass is already fused and O(S)-memory.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend module; absent in some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _use_interpret() -> bool:
    """``MXTPU_FLASH_INTERPRET``: force (``1``) or forbid (``0``) Pallas
    interpret mode; default ``auto`` interprets off-TPU (CPU testing)."""
    import os

    v = os.environ.get("MXTPU_FLASH_INTERPRET", "").strip().lower()
    if v in ("1", "true", "force", "on"):
        return True
    if v in ("0", "false", "off"):
        return False
    return jax.default_backend() != "tpu"


def _fwd_kernel(q_ref, k_ref, v_ref, *refs, sm_scale, block_k, kv_len,
                causal, block_q, use_vl):
    # refs: q (1, block_q, d), k/v (1, padded_kv, d); with use_vl an extra
    # vl (B*H, 1) int32 ref (full array — tiny, so every grid step sees it
    # whole; a (1,1) block would violate the TPU (8,128) tiling rule);
    # then o (1, block_q, d), lse (1, block_q, 1) — leading dim is the
    # (b*h) grid block of size 1. vl is this batch row's valid key length
    # (reference softmax use_length semantics: keys >= vl are padding);
    # the dense path compiles without the vl operand at all.
    if use_vl:
        vl_ref, o_ref, lse_ref = refs
        vl = jnp.minimum(vl_ref[pl.program_id(0), 0], kv_len)
    else:
        o_ref, lse_ref = refs
        vl = kv_len
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    d = q.shape[-1]
    padded_kv = k_ref.shape[1]
    nk = padded_kv // block_k

    q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    def body(jk, carry):
        m_prev, l_prev, acc = carry
        k_blk = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (block_q, block_k)
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        mask = k_pos < vl
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, _NEG_INF)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    # blocks past the valid length contribute nothing — skip them
    nk_eff = jnp.minimum(nk, pl.cdiv(vl, block_k)) if use_vl else nk
    if causal:
        # blocks fully above the diagonal contribute nothing — skip them
        nk_eff = jnp.minimum(
            nk_eff, pl.cdiv((iq + 1) * block_q, block_k)
        )
    m, l, acc = jax.lax.fori_loop(0, nk_eff, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l)).astype(jnp.float32)


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k")
)
def _flash_fwd_impl(q, k, v, vl, causal, sm_scale, block_q, block_k):
    """q (B,H,Sq,D), k/v (B,H,Sk,D), vl (B,) int32
    -> out (B,H,Sq,D), lse (B,H,Sq)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bq = min(block_q, max(Sq, 8))
    bk = min(block_k, max(Sk, 8))
    qp = _pad_to(q, 2, bq)
    kp = _pad_to(k, 2, bk)
    vp = _pad_to(v, 2, bk)
    Sq_p, Sk_p = qp.shape[2], kp.shape[2]
    qp = qp.reshape(B * H, Sq_p, D)
    kp = kp.reshape(B * H, Sk_p, D)
    vp = vp.reshape(B * H, Sk_p, D)
    use_vl = vl is not None
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, Sk_p, D), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, Sk_p, D), lambda b, i: (b, 0, 0)),
    ]
    operands = [qp, kp, vp]
    if use_vl:
        # one valid-length scalar per (b*h) grid row, b-major per reshape
        operands.append(jnp.repeat(vl.astype(jnp.int32), H).reshape(B * H, 1))
        in_specs.append(pl.BlockSpec((B * H, 1), lambda b, i: (0, 0)))
    grid = (B * H, Sq_p // bq)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, block_k=bk, kv_len=Sk,
        causal=causal, block_q=bq, use_vl=use_vl,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sq_p, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sq_p, 1), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(*operands)
    out = out.reshape(B, H, Sq_p, D)[:, :, :Sq]
    lse = lse.reshape(B, H, Sq_p)[:, :, :Sq]
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_attention(q, k, v, valid_length=None, causal=False, sm_scale=None,
                    block_q=128, block_k=128):
    """Fused softmax(q·kᵀ·scale)·v. Shapes (B, H, S, D); O(S) memory.

    ``valid_length`` (B,) int: per-row count of non-padding keys (reference
    softmax ``use_length`` / ``contrib/transformer.cc`` mask semantics
    [unverified]); keys at positions >= valid_length are ignored."""
    out, _ = _flash_fwd(q, k, v, valid_length, causal, sm_scale, block_q,
                        block_k)
    return out


def _flash_fwd(q, k, v, valid_length, causal, sm_scale, block_q, block_k):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    vl = None if valid_length is None else valid_length.astype(jnp.int32)
    return _flash_fwd_impl(q, k, v, vl, causal, float(sm_scale), block_q,
                           block_k)


def _fwd_rule(q, k, v, valid_length, causal, sm_scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, valid_length, causal, sm_scale, block_q,
                          block_k)
    return out, (q, k, v, valid_length, out, lse)


def _s_p_block(q_blk, k_blk, lse_blk, k_pos, vl, iq, block_q, causal,
               sm_scale):
    """Recompute the (bq, bk) probability tile from saved lse."""
    s = jax.lax.dot_general(
        q_blk, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * sm_scale
    mask = k_pos < vl
    if causal:
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0
        )
        mask = jnp.logical_and(mask, k_pos <= q_pos)
    # explicit zero outside the mask: a fully-masked row has lse ~ -inf
    # too, where exp(s - lse) would wrongly give 1
    return jnp.where(mask, jnp.exp(s - lse_blk), 0.0)


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     vl_ref, dk_ref, dv_ref, *, sm_scale, block_q, block_k,
                     kv_len, causal):
    """Grid (B*H, Sk/block_k): one K/V block per step, stream Q blocks.
    Write-once outputs — the canonical two-kernel flash backward's first
    half (dq comes from its own kernel with the transposed streaming)."""
    jk = pl.program_id(1)
    k_blk = k_ref[0].astype(jnp.float32)  # (bk, D)
    v_blk = v_ref[0].astype(jnp.float32)
    vl = jnp.minimum(vl_ref[pl.program_id(0), 0], kv_len)
    k_pos = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1
    )
    nq = q_ref.shape[1] // block_q

    def body(iq, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(iq * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(iq * block_q, block_q), :].astype(
            jnp.float32
        )
        lse_blk = lse_ref[0, pl.ds(iq * block_q, block_q), :]
        dl_blk = delta_ref[0, pl.ds(iq * block_q, block_q), :]
        p = _s_p_block(q_blk, k_blk, lse_blk, k_pos, vl, iq, block_q,
                       causal, sm_scale)
        dv_acc = dv_acc + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dl_blk) * sm_scale
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_acc, dv_acc

    # causal: Q blocks strictly above this K block's diagonal are fully
    # masked — start past them (traced bound, like the forward's nk_eff)
    start = (jk * block_k) // block_q if causal else 0
    dk_acc = jnp.zeros((block_k, k_ref.shape[2]), jnp.float32)
    dv_acc = jnp.zeros((block_k, v_ref.shape[2]), jnp.float32)
    dk_acc, dv_acc = jax.lax.fori_loop(start, nq, body, (dk_acc, dv_acc))
    dk_ref[0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, vl_ref,
                   dq_ref, *, sm_scale, block_q, block_k, kv_len, causal):
    """Grid (B*H, Sq/block_q): one Q block per step, stream K/V blocks."""
    iq = pl.program_id(1)
    q_blk = q_ref[0].astype(jnp.float32)  # (bq, D)
    do_blk = do_ref[0].astype(jnp.float32)
    lse_blk = lse_ref[0]
    dl_blk = delta_ref[0]
    vl = jnp.minimum(vl_ref[pl.program_id(0), 0], kv_len)
    nk = k_ref.shape[1] // block_k

    def body(jk, dq_acc):
        k_blk = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        p = _s_p_block(q_blk, k_blk, lse_blk, k_pos, vl, iq, block_q,
                       causal, sm_scale)
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dl_blk) * sm_scale
        return dq_acc + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # skip K blocks past the valid length / above the causal diagonal
    # (traced bounds, mirroring the forward kernel's nk_eff)
    nk_eff = jnp.minimum(nk, pl.cdiv(vl, block_k))
    if causal:
        nk_eff = jnp.minimum(nk_eff, pl.cdiv((iq + 1) * block_q, block_k))
    dq_acc = jnp.zeros((block_q, q_ref.shape[2]), jnp.float32)
    dq_ref[0] = jax.lax.fori_loop(0, nk_eff, body, dq_acc).astype(
        dq_ref.dtype
    )


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k")
)
def _flash_bwd_pallas(q, k, v, vl, out, lse, do, causal, sm_scale,
                      block_q=128, block_k=128):
    """Pallas backward: P/dS tiles never leave VMEM (the XLA-scan fallback
    below materializes (B,H,Sq,block) probability tensors in HBM)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bq = min(block_q, max(Sq, 8))
    bk = min(block_k, max(Sk, 8))
    qp = _pad_to(q, 2, bq)
    dop = _pad_to(do, 2, bq)
    kp = _pad_to(k, 2, bk)
    vp = _pad_to(v, 2, bk)
    Sq_p, Sk_p = qp.shape[2], kp.shape[2]
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # (B,H,Sq)
    # padded q rows: lse stays 0 -> p = exp(0-0) = 1 would pollute dk/dv;
    # push their lse to +inf so p underflows to exactly 0
    lse_p = _pad_to(
        lse.reshape(B * H, Sq, 1), 1, bq
    )
    if Sq_p != Sq:
        pad_rows = jax.lax.broadcasted_iota(
            jnp.int32, (B * H, Sq_p, 1), 1
        ) >= Sq
        lse_p = jnp.where(pad_rows, jnp.float32(-_NEG_INF), lse_p)
    delta_p = _pad_to(delta.reshape(B * H, Sq, 1), 1, bq)
    # vl is always a concrete (B,) array here — _bwd_rule and the ring
    # backward materialize full-length vectors when no mask is in play
    q3 = qp.reshape(B * H, Sq_p, D)
    k3 = kp.reshape(B * H, Sk_p, D)
    v3 = vp.reshape(B * H, Sk_p, D)
    do3 = dop.reshape(B * H, Sq_p, D)
    vl_op = jnp.repeat(vl.astype(jnp.int32), H).reshape(B * H, 1)
    vl_spec = lambda: pl.BlockSpec((B * H, 1), lambda b, j: (0, 0))  # noqa: E731
    common = dict(sm_scale=sm_scale, block_q=bq, block_k=bk, kv_len=Sk,
                  causal=causal)

    # kernel 1: dk/dv — grid over K blocks, stream Q
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, **common),
        grid=(B * H, Sk_p // bk),
        in_specs=[
            pl.BlockSpec((1, Sq_p, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, Sq_p, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Sq_p, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Sq_p, 1), lambda b, j: (b, 0, 0)),
            vl_spec(),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sk_p, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Sk_p, D), v.dtype),
        ],
        interpret=_use_interpret(),
    )(q3, k3, v3, do3, lse_p, delta_p, vl_op)

    # kernel 2: dq — grid over Q blocks, stream K/V
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(B * H, Sq_p // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk_p, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk_p, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
            vl_spec(),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq_p, D), q.dtype),
        interpret=_use_interpret(),
    )(q3, k3, v3, do3, lse_p, delta_p, vl_op)

    dq = dq.reshape(B, H, Sq_p, D)[:, :, :Sq]
    dk = dk.reshape(B, H, Sk_p, D)[:, :, :Sk]
    dv = dv.reshape(B, H, Sk_p, D)[:, :, :Sk]
    return dq, dk, dv


# backward implementation choice; initialized from MXTPU_FLASH_BWD at
# import. Change at runtime through set_flash_backward() — NOT by mutating
# the env var: the choice is baked into traced programs, so the setter
# clears jax's compilation caches.
import os as _os  # noqa: E402

_BWD_IMPL = _os.environ.get("MXTPU_FLASH_BWD", "xla")


def set_flash_backward(impl: str):
    """Select the flash-attention backward: 'xla' (default) or 'pallas'.

    Clears jax's jit caches so already-compiled train steps pick up the
    change (the choice is a trace-time constant)."""
    global _BWD_IMPL
    if impl not in ("xla", "pallas"):
        raise ValueError(f"unknown flash backward {impl!r}")
    _BWD_IMPL = impl
    jax.clear_caches()


def _flash_bwd_impl(q, k, v, vl, out, lse, do, causal, sm_scale, block_k,
                    block_q=128):
    """Backward dispatcher.

    Two implementations, same math (parity-tested):
    - XLA blockwise-recompute scan (default): measured FASTER on v5e-lite
      (13.4 vs 15.4 ms at S=2048, 60.9 vs 73.8 ms at S=8192, fwd+bwd,
      B4 H8 D64 bf16) — XLA pipelines the recompute einsums well here.
    - hand-written two-kernel Pallas backward
      (``set_flash_backward('pallas')`` or env MXTPU_FLASH_BWD at import):
      P/dS tiles never leave VMEM; kept for hardware where the scan's HBM
      traffic binds, and as the tuning baseline.
    """
    if _BWD_IMPL == "pallas":
        return _flash_bwd_pallas(q, k, v, vl, out, lse, do, causal,
                                 sm_scale, block_q, block_k)
    return _flash_bwd_xla(q, k, v, vl, out, lse, do, causal, sm_scale,
                          block_k)


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_k")
)
def _flash_bwd_xla(q, k, v, vl, out, lse, do, causal, sm_scale, block_k):
    """Blockwise recompute backward (scan over K blocks, O(S·block) memory)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bk = min(block_k, Sk)
    kp = _pad_to(k, 2, bk)
    vp = _pad_to(v, 2, bk)
    Sk_p = kp.shape[2]
    nk = Sk_p // bk

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # (B,H,Sq)
    q_pos = jnp.arange(Sq)[:, None]
    vl4 = jnp.minimum(vl, Sk).reshape(B, 1, 1, 1)

    def body(dq_acc, jk):
        kb = jax.lax.dynamic_slice_in_dim(kp, jk * bk, bk, 2).astype(jnp.float32)
        vb = jax.lax.dynamic_slice_in_dim(vp, jk * bk, bk, 2).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb) * sm_scale
        k_pos = jk * bk + jnp.arange(bk)[None, :]
        mask = k_pos[None, None] < vl4  # (B,1,1,bk)
        if causal:
            mask = jnp.logical_and(mask, (k_pos <= q_pos)[None, None])
        s = jnp.where(mask, s, _NEG_INF)
        # explicit zero outside the mask: a fully-masked row has lse ~
        # _NEG_INF too, where exp(s - lse) would wrongly give 1
        p = jnp.where(mask, jnp.exp(s - lse[..., None]), 0.0)  # (B,H,Sq,bk)
        dv_b = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vb)
        ds = p * (dp - delta[..., None]) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, kb)
        dk_b = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        return dq_acc, (dk_b, dv_b)

    dq0 = jnp.zeros_like(qf)
    dq, (dks, dvs) = jax.lax.scan(body, dq0, jnp.arange(nk))
    # dks: (nk, B, H, bk, D) -> (B, H, Sk_p, D)
    dk = jnp.moveaxis(dks, 0, 2).reshape(B, H, Sk_p, D)[:, :, :Sk]
    dv = jnp.moveaxis(dvs, 0, 2).reshape(B, H, Sk_p, D)[:, :, :Sk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _bwd_rule(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, valid_length, out, lse = res
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    Sk = k.shape[2]
    vl = (jnp.full((q.shape[0],), Sk, jnp.int32) if valid_length is None
          else valid_length.astype(jnp.int32))
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, vl, out, lse, g, causal, float(sm_scale), block_k,
        block_q=block_q,
    )
    if valid_length is None:
        dvl = None
    elif jnp.issubdtype(valid_length.dtype, jnp.floating):
        dvl = jnp.zeros_like(valid_length)
    else:
        import numpy as _onp

        dvl = _onp.zeros(valid_length.shape, jax.dtypes.float0)
    return dq, dk, dv, dvl


flash_attention.defvjp(_fwd_rule, _bwd_rule)
