"""Pallas TPU kernels — the native-kernel budget of this framework
(SURVEY.md §7: attention fwd/bwd, layer_norm, softmax, fused optimizers go
to hand kernels where the reference had CUDA).

Kernels fall back to interpreter mode off-TPU so the one test suite runs on
the virtual CPU mesh unchanged (reference trick: one suite, many contexts).
"""

from .flash_attention import flash_attention  # noqa: F401

__all__ = ["flash_attention"]
