"""Symbolic API (reference: ``python/mxnet/symbol/`` over nnvm
[unverified])."""

from .symbol import Symbol, Variable, var, Group, load, load_json
from . import register as _register
import sys as _sys

from .. import ops as _ops  # ensure registry populated
from ..ops import registry as _registry

_register.populate_module(_sys.modules[__name__])

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]
