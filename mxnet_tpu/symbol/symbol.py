"""Symbol: a lazy operator DAG (reference: ``python/mxnet/symbol/symbol.py``
over ``nnvm::Symbol`` [unverified]).

TPU-native design (SURVEY.md §7 stance: "no dual IR" in the hot path): a
Symbol is a thin recorded-call graph over the SAME op registry the
imperative path uses. ``bind``/``simple_bind`` compile the whole graph with
``jax.jit`` — the nnvm passes (InferShape via eval_shape, Gradient via
jax.grad, PlanMemory via XLA's buffer assignment) all collapse into the XLA
pipeline. This keeps the legacy Module/SymbolBlock API surface working
without maintaining a second IR."""

from __future__ import annotations

import contextlib as _contextlib
import json
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..ops import registry as _registry

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]

_UID = [0]


# nnvm semantics: a multi-output node fed to a consumer without explicit
# indexing contributes its FIRST output (reference: NodeEntry default)
def _first_output(sym, value):
    if isinstance(value, tuple) and sym._op is not None \
            and sym._out_index is None:
        return value[0]
    return value


# ops whose kernels switch on train/predict mode (reference: ops reading
# ``ctx.is_train``); the executor sets the mode around evaluation
_MODE_OPS = {"BatchNorm", "Dropout"}
_TRAIN_MODE = [False]


@_contextlib.contextmanager
def train_mode_scope(flag: bool):
    prev = _TRAIN_MODE[0]
    _TRAIN_MODE[0] = bool(flag)
    try:
        yield
    finally:
        _TRAIN_MODE[0] = prev


def _next_name(hint):
    _UID[0] += 1
    return f"{hint}{_UID[0] - 1}"


class Symbol:
    """A node in the symbolic graph."""

    def __init__(self, op: Optional[str], inputs: Sequence["Symbol"],
                 attrs: Optional[dict] = None, name: Optional[str] = None,
                 out_index: Optional[int] = None, num_outputs: int = 1):
        self._op = op  # None for variables / groups
        self._inputs = list(inputs)
        self._attrs = dict(attrs or {})
        self._name = name or (_next_name(op.lower()) if op else _next_name("sym"))
        self._out_index = out_index
        self._num_outputs = num_outputs

    # ------------------------------------------------------------- metadata
    @property
    def name(self):
        return self._name

    def attr(self, key):
        return self._attrs.get(key)

    def list_attr(self):
        return dict(self._attrs)

    def _is_var(self):
        return self._op is None and not self._inputs

    def _walk_vars(self, predicate) -> List[str]:
        seen, order = set(), []

        def walk(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            for i in s._inputs:
                walk(i)
            if s._is_var() and predicate(s):
                order.append(s._name)

        walk(self)
        return order

    def list_arguments(self) -> List[str]:
        return self._walk_vars(lambda s: not s._attrs.get("__aux__"))

    def list_auxiliary_states(self) -> List[str]:
        """Aux-state variables (reference: BatchNorm moving_mean/var —
        updated by forward, excluded from gradients)."""
        return self._walk_vars(lambda s: bool(s._attrs.get("__aux__")))

    def _var_attrs(self) -> Dict[str, dict]:
        return {
            s._name: s._attrs
            for s in self.get_internals()._inputs
            if s._is_var()
        }

    def list_outputs(self) -> List[str]:
        if self._op is None and self._inputs:  # group
            out = []
            for i in self._inputs:
                out.extend(i.list_outputs())
            return out
        if self._num_outputs == 1:
            return [self._name + "_output"]
        return [f"{self._name}_output{i}" for i in range(self._num_outputs)]

    def get_internals(self):
        seen, nodes = set(), []

        def walk(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            for i in s._inputs:
                walk(i)
            nodes.append(s)

        walk(self)
        return Group(nodes)

    def __getitem__(self, index):
        if isinstance(index, str):
            for s in self.get_internals()._inputs:
                if s.list_outputs()[0] == index or s._name == index:
                    return s
            raise MXNetError(f"no output named {index}")
        if self._op is None and self._inputs:  # group indexing
            return self._inputs[index]
        if self._num_outputs == 1:
            if index != 0:
                raise MXNetError("index out of range")
            return self
        return Symbol(self._op, self._inputs, self._attrs, self._name,
                      out_index=index, num_outputs=self._num_outputs)

    def __repr__(self):
        return f"<Symbol {self._name}>"

    def __iter__(self):
        n = self._num_outputs if not (self._op is None and self._inputs) \
            else len(self._inputs)
        return (self[i] for i in range(n))

    # ------------------------------------------------------------ evaluation
    def _eval(self, values: Dict[str, jnp.ndarray], cache: Dict[int, object]):
        """Iterative post-order evaluation (an explicit stack — deep
        chains like unrolled sequences or imported 1000-op graphs must
        not hit Python's recursion limit)."""

        def indexed(s):
            out = cache[id(s)]
            return out[s._out_index] if s._out_index is not None else out

        stack = [self]
        while stack:
            s = stack[-1]
            if id(s) in cache:
                stack.pop()
                continue
            if type(s)._eval is not Symbol._eval:
                # subclasses with their own evaluation (_Const) keep
                # their polymorphic hook
                cache[id(s)] = s._eval(values, cache)
                stack.pop()
                continue
            if s._is_var():
                if s._name not in values:
                    raise MXNetError(
                        f"missing value for argument {s._name}")
                cache[id(s)] = values[s._name]
                stack.pop()
                continue
            pending = [i for i in s._inputs if id(i) not in cache]
            if pending:
                stack.extend(reversed(pending))
                continue
            if s._op is None:  # group: members contribute first outputs
                cache[id(s)] = tuple(
                    _first_output(i, indexed(i)) for i in s._inputs)
            else:
                op = _registry.get(s._op)
                args = [_first_output(i, indexed(i)) for i in s._inputs]
                attrs = s._attrs
                if s._op in _MODE_OPS and "training" not in attrs:
                    # executor-driven train/predict mode (reference:
                    # is_train on the graph executor; nnvm ops read the
                    # mode, not an attr)
                    attrs = dict(attrs, training=_TRAIN_MODE[0])
                cache[id(s)] = op.fn(*args, **attrs)
            stack.pop()
        return indexed(self)

    def eval(self, ctx=None, **kwargs):
        """Evaluate eagerly from name->NDArray kwargs (reference API)."""
        from ..ndarray.ndarray import NDArray

        values = {
            k: (v.data if isinstance(v, NDArray) else jnp.asarray(v))
            for k, v in kwargs.items()
        }
        out = self._eval(values, {})
        outs = out if isinstance(out, tuple) else (out,)
        # a multi-output op head exposes only its declared output count
        # (internal extras like BatchNorm batch stats stay internal)
        if self._op is not None and self._out_index is None:
            outs = outs[: self._num_outputs]
        return [NDArray(o) for o in outs]

    # ----------------------------------------------------------- shape/type
    def infer_shape(self, **kwargs):
        args = self.list_arguments()
        known = {k: jnp.zeros(v, jnp.float32) if isinstance(v, tuple) else v
                 for k, v in kwargs.items()}

        def run(vals):
            return self._eval(vals, {})

        try:
            out = jax.eval_shape(run, {
                k: jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
                if hasattr(v, "shape") else v
                for k, v in known.items()
            })
        except Exception as e:
            raise MXNetError(f"shape inference failed: {e}") from e
        outs = out if isinstance(out, tuple) else (out,)
        arg_shapes = [tuple(known[a].shape) if a in known else None
                      for a in args]
        return arg_shapes, [tuple(o.shape) for o in outs], []

    def infer_type(self, **kwargs):
        args = self.list_arguments()
        return ([kwargs.get(a) for a in args], [_np.float32], [])

    def _infer_all_shapes(self, known: Dict[str, tuple]) -> Dict[str, tuple]:
        """Forward shape propagation filling in parameter-variable shapes
        (the nnvm InferShape role): walk topologically; unshaped variable
        inputs of parameterized ops get shapes from `_PARAM_SHAPE_RULES`;
        each op's output shape comes from jax.eval_shape of its kernel."""
        shapes = dict(known)
        node_out: Dict[int, object] = {}

        def out_shape(s):
            if id(s) in node_out:
                return node_out[id(s)]
            if isinstance(s, _Const):
                res = jax.ShapeDtypeStruct(tuple(s._value.shape),
                                           s._value.dtype)
            elif s._is_var():
                if s._name not in shapes:
                    raise MXNetError(
                        f"cannot infer shape of variable {s._name}; provide "
                        "it to simple_bind"
                    )
                res = jax.ShapeDtypeStruct(tuple(shapes[s._name]), _np.float32)
            elif s._op is None:  # group
                res = tuple(out_shape(i) for i in s._inputs)
            else:
                in_specs = []
                rule = _PARAM_SHAPE_RULES.get(s._op)
                first = None
                if s._inputs:
                    first = _first_output(s._inputs[0],
                                          out_shape(s._inputs[0]))
                for pos, inp in enumerate(s._inputs):
                    if (inp._is_var() and inp._name not in shapes
                            and rule is not None and pos > 0):
                        inferred = rule(pos, tuple(first.shape), s._attrs)
                        if inferred is None:
                            raise MXNetError(
                                f"cannot infer shape of {inp._name} "
                                f"(input {pos} of {s._op})"
                            )
                        shapes[inp._name] = inferred
                    in_specs.append(_first_output(inp, out_shape(inp)))
                op = _registry.get(s._op)
                try:
                    res = jax.eval_shape(
                        lambda *a: op.fn(*a, **s._attrs), *in_specs
                    )
                except Exception as e:
                    raise MXNetError(
                        f"shape inference through {s._op} failed: {e}"
                    ) from e
            node_out[id(s)] = res
            return res

        out_shape(self)
        return shapes

    # ------------------------------------------------------------- binding
    def simple_bind(self, ctx=None, grad_req="write", **shapes):
        from ..executor import Executor

        return Executor(self, ctx, shapes, grad_req)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        from ..executor import Executor

        return Executor(self, ctx, None, grad_req, args=args,
                        args_grad=args_grad, aux_states=aux_states)

    # ---------------------------------------------------------- arithmetic
    def _binop(self, other, opname, reverse=False):
        if not isinstance(other, Symbol):
            other = _Const(other)
        a, b = (other, self) if reverse else (self, other)
        return Symbol(opname, [a, b])

    def __add__(self, other):
        return self._binop(other, "broadcast_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, "broadcast_sub")

    def __rsub__(self, other):
        return self._binop(other, "broadcast_sub", reverse=True)

    def __mul__(self, other):
        return self._binop(other, "broadcast_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, "broadcast_div")

    def __rtruediv__(self, other):
        return self._binop(other, "broadcast_div", reverse=True)

    def __pow__(self, other):
        return self._binop(other, "broadcast_power")

    def __neg__(self):
        return Symbol("negative", [self])

    # ------------------------------------------------------------- serialize
    def tojson(self):
        nodes = []
        index = {}

        def walk(s):
            if id(s) in index:
                return index[id(s)]
            inputs = [walk(i) for i in s._inputs]
            idx = len(nodes)
            attrs = {k: str(v) for k, v in s._attrs.items()}
            if isinstance(s, _Const):
                # literal operands (sym * 2.0) must round-trip through JSON
                attrs["__const_value__"] = json.dumps(
                    _np.asarray(s._value).tolist()
                )
                attrs["__const_dtype__"] = str(s._value.dtype)
            nodes.append({
                "op": s._op or "null",
                "name": s._name,
                "attrs": attrs,
                "inputs": [[i, 0, 0] for i in inputs],
            })
            index[id(s)] = idx
            return idx

        # a Group serializes as one head per member (the reference's
        # multi-output heads list), not as a node of its own
        if self._op is None and self._inputs:
            heads = [[walk(i), 0, 0] for i in self._inputs]
        else:
            walk(self)
            heads = [[len(nodes) - 1, 0, 0]]
        return json.dumps(
            {"nodes": nodes, "heads": heads, "mxnet_tpu_version": 1},
            indent=2,
        )

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())


class _Const(Symbol):
    def __init__(self, value):
        super().__init__(None, [], name=_next_name("const"))
        self._value = jnp.asarray(value)

    def _is_var(self):
        return False

    def _eval(self, values, cache):
        return self._value

    def list_arguments(self):
        return []


def Variable(name, attr=None, shape=None, dtype=None, init=None, **kwargs):
    s = Symbol(None, [], attrs=attr, name=name)
    if shape is not None:
        s._attrs["__shape__"] = shape
    return s


var = Variable


def Group(symbols):
    return Symbol(None, list(symbols), name=_next_name("group"))


def load_json(json_str):
    data = json.loads(json_str)
    nodes = data["nodes"]
    built = []
    for node in nodes:
        if node["op"] == "null":
            attrs = node.get("attrs", {})
            if "__const_value__" in attrs:
                c = _Const(_np.asarray(
                    json.loads(attrs["__const_value__"]),
                    dtype=attrs.get("__const_dtype__", "float32"),
                ))
                c._name = node["name"]
                built.append(c)
            else:
                v = Variable(node["name"])
                v._attrs = {k: _parse_attr(a) for k, a in attrs.items()}
                built.append(v)
        else:
            inputs = [built[i[0]] for i in node["inputs"]]
            attrs = {k: _parse_attr(v) for k, v in node.get("attrs", {}).items()}
            built.append(Symbol(node["op"], inputs, attrs, node["name"]))
    heads = data["heads"]
    if len(heads) > 1:
        return Group([built[h[0]] for h in heads])
    return built[heads[0][0]]


def _parse_attr(v):
    try:
        return json.loads(v)
    except (ValueError, TypeError):
        try:
            import ast

            return ast.literal_eval(v)
        except (ValueError, SyntaxError):
            return v


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _tup(v, n):
    if v is None:
        return (1,) * n
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _fc_rule(pos, data_shape, attrs):
    nh = int(attrs["num_hidden"])
    flatten = attrs.get("flatten", True)
    in_units = _prod(data_shape[1:]) if flatten else int(data_shape[-1])
    if pos == 1:
        return (nh, in_units)
    if pos == 2:
        return (nh,)
    return None


def _conv_rule(pos, data_shape, attrs):
    nf = int(attrs["num_filter"])
    groups = int(attrs.get("num_group", 1))
    nd_sp = len(data_shape) - 2
    kernel = _tup(attrs.get("kernel"), nd_sp)
    if pos == 1:
        return (nf, int(data_shape[1]) // groups) + kernel
    if pos == 2:
        return (nf,)
    return None


def _deconv_rule(pos, data_shape, attrs):
    nf = int(attrs["num_filter"])
    groups = int(attrs.get("num_group", 1))
    nd_sp = len(data_shape) - 2
    kernel = _tup(attrs.get("kernel"), nd_sp)
    if pos == 1:
        return (int(data_shape[1]), nf // groups) + kernel
    if pos == 2:
        return (nf,)
    return None


def _bn_rule(pos, data_shape, attrs):
    axis = int(attrs.get("axis", 1))
    return (int(data_shape[axis]),)


def _ln_rule(pos, data_shape, attrs):
    axis = int(attrs.get("axis", -1))
    return (int(data_shape[axis]),)


def _embed_rule(pos, data_shape, attrs):
    if pos == 1:
        return (int(attrs["input_dim"]), int(attrs["output_dim"]))
    return None


def _softmax_output_rule(pos, data_shape, attrs):
    # label: one class index per row (enables label-less inference binds)
    if pos == 1:
        return tuple(data_shape[:-1])
    return None


# pos -> expected shape given the first input's shape and op attrs
# (reference: per-op FInferShape attrs on the nnvm registry [unverified])
_PARAM_SHAPE_RULES = {
    "FullyConnected": _fc_rule,
    "Convolution": _conv_rule,
    "Deconvolution": _deconv_rule,
    "BatchNorm": _bn_rule,
    "InstanceNorm": _bn_rule,
    "GroupNorm": _bn_rule,
    "LayerNorm": _ln_rule,
    "Embedding": _embed_rule,
    "SoftmaxOutput": _softmax_output_rule,
}
