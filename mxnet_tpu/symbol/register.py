"""Generate ``mx.sym.*`` from the shared op registry (reference:
``python/mxnet/symbol/register.py`` [unverified]) — one registry, every
frontend (SURVEY.md §1 key fact)."""

from __future__ import annotations

from ..ops import registry as _registry
from .symbol import Symbol, Variable, _next_name

# Parameter inputs auto-created as hidden variables named
# ``{opname}_{suffix}`` when the caller passes data only — reference
# behavior (nnvm FListInputNames + the Python name manager [unverified]);
# ``Module`` relies on it to discover arg names like ``c1_weight``.
_AUTO_PARAMS = {
    "FullyConnected": ("weight", "bias"),
    "Convolution": ("weight", "bias"),
    "Deconvolution": ("weight", "bias"),
    "BatchNorm": ("gamma", "beta", "moving_mean", "moving_var"),
    "InstanceNorm": ("gamma", "beta"),
    "GroupNorm": ("gamma", "beta"),
    "LayerNorm": ("gamma", "beta"),
    "Embedding": ("weight",),
    "SoftmaxOutput": ("label",),
}

# suffixes that are AUXILIARY STATES, not trainable arguments (reference:
# nnvm FMutateInputs — updated by forward, no gradients); the attr carries
# the simple_bind initialization
_AUX_ATTRS = {
    "moving_mean": {"__aux__": True, "__init__": "zeros"},
    "moving_var": {"__aux__": True, "__init__": "ones"},
}


def _no_bias_default(op):
    import inspect

    try:
        p = inspect.signature(op.fn).parameters.get("no_bias")
        return bool(p.default) if p is not None else False
    except (TypeError, ValueError):  # pragma: no cover
        return False


def _make_sym_func(op):
    no_bias_default = _no_bias_default(op)

    def sym_func(*args, name=None, **kwargs):
        inputs = [a for a in args if isinstance(a, Symbol)]
        if len(inputs) != len(args):
            raise TypeError(
                f"sym.{op.name} expects Symbol inputs; got "
                f"{[type(a).__name__ for a in args]}"
            )
        suffixes = _AUTO_PARAMS.get(op.name)
        if suffixes is not None:
            want = list(suffixes)
            if kwargs.get("no_bias", no_bias_default) and "bias" in want:
                want.remove("bias")
            expected = 1 + len(want)
            if 0 < len(inputs) < expected:
                if name is None:
                    name = _next_name(op.name.lower())
                for suffix in want[len(inputs) - 1:]:
                    v = Variable(f"{name}_{suffix}")
                    if suffix in _AUX_ATTRS:
                        v._attrs.update(_AUX_ATTRS[suffix])
                    inputs.append(v)
        return Symbol(op.name, inputs, attrs=kwargs, name=name,
                      num_outputs=op.num_outputs or 1)

    sym_func.__name__ = op.name
    sym_func.__doc__ = (op.fn.__doc__ or "") + "\n(symbolic variant)"
    return sym_func


def populate_module(module):
    installed = []
    for name in _registry.list_ops():
        op = _registry.get(name)
        fn = _make_sym_func(op)
        setattr(module, name, fn)
        installed.append(name)
        for a in op.aliases:
            setattr(module, a, fn)
    return installed
