"""Generate ``mx.sym.*`` from the shared op registry (reference:
``python/mxnet/symbol/register.py`` [unverified]) — one registry, every
frontend (SURVEY.md §1 key fact)."""

from __future__ import annotations

from ..ops import registry as _registry
from .symbol import Symbol


def _make_sym_func(op):
    def sym_func(*args, name=None, **kwargs):
        inputs = [a for a in args if isinstance(a, Symbol)]
        if len(inputs) != len(args):
            raise TypeError(
                f"sym.{op.name} expects Symbol inputs; got "
                f"{[type(a).__name__ for a in args]}"
            )
        return Symbol(op.name, inputs, attrs=kwargs, name=name,
                      num_outputs=op.num_outputs or 1)

    sym_func.__name__ = op.name
    sym_func.__doc__ = (op.fn.__doc__ or "") + "\n(symbolic variant)"
    return sym_func


def populate_module(module):
    installed = []
    for name in _registry.list_ops():
        op = _registry.get(name)
        fn = _make_sym_func(op)
        setattr(module, name, fn)
        installed.append(name)
        for a in op.aliases:
            setattr(module, a, fn)
    return installed
