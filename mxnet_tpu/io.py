"""Data iterators (reference: ``python/mxnet/io/io.py`` over ``src/io/``
[unverified]): ``DataIter`` protocol, ``NDArrayIter``, ``CSVIter``,
``PrefetchingIter``, ``ResizeIter``."""

from __future__ import annotations

import threading
from collections import namedtuple
from typing import List, Optional

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray
from .ndarray import array as nd_array

__all__ = [
    "DataDesc",
    "DataBatch",
    "DataIter",
    "NDArrayIter",
    "CSVIter",
    "ResizeIter",
    "PrefetchingIter",
]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        return super().__new__(cls, name, shape, dtype, layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None:
            assert isinstance(data, (list, tuple)), "data must be a list"
        if label is not None:
            assert isinstance(label, (list, tuple)), "label must be a list"
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return f"{self.__class__.__name__}: data shapes: {data_shapes} " \
               f"label shapes: {label_shapes}"


class DataIter:
    """Iterator protocol of the reference (next/reset/provide_data)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(), pad=self.getpad(),
                index=self.getindex(),
            )
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def getdata(self):
        return None

    def getlabel(self):
        return None

    def getindex(self):
        return None

    def getpad(self):
        return None


def _init_data(data, allow_empty, default_name):
    """Normalize to list of (name, numpy array) (reference helper)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of them "
                        "or dict with them as values")
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, _np.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate numpy/NDArray data in batches (reference: ``NDArrayIter``
    with shuffle + pad/discard/roll_over last-batch handling)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.idx = _np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self.num_data = self.idx.shape[0]
        self._cache_data = None
        self._cache_label = None
        self.reset()

    @property
    def provide_data(self):
        return [
            DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
            for k, v in self.data
        ]

    @property
    def provide_label(self):
        return [
            DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
            for k, v in self.label
        ]

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and \
                0 < self.cursor < self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % \
                self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        end = min(self.cursor + self.batch_size, self.num_data)
        s = self.idx[max(self.cursor, 0) : end]
        out = [_np.take(v, s, axis=0) for _, v in data_source]
        pad = self.getpad()
        if pad:
            # wrap around (reference 'pad' mode duplicates from the start)
            extra = [_np.take(v, self.idx[:pad], axis=0) for _, v in data_source]
            out = [_np.concatenate([o, e], axis=0) for o, e in zip(out, extra)]
        return [nd_array(o) for o in out]

    def getdata(self):
        if self.last_batch_handle == "discard" and self.getpad():
            raise StopIteration
        return self._getdata(self.data)

    def getlabel(self):
        if not self.label:
            return []
        return self._getdata(self.label)

    def getpad(self):
        if self.cursor + self.batch_size > self.num_data:
            if self.last_batch_handle == "discard":
                return 0
            return self.cursor + self.batch_size - self.num_data
        return 0

    def next(self):
        if not self.iter_next():
            raise StopIteration
        if self.last_batch_handle == "discard" and self.getpad():
            raise StopIteration
        return DataBatch(
            data=self.getdata(), label=self.getlabel(), pad=self.getpad(),
            index=None,
        )


class CSVIter(DataIter):
    """CSV reader (reference: C++ ``CSVIter``; host-side numpy here)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = _np.loadtxt(data_csv, delimiter=",", dtype=_np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=_np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        self._inner = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="pad" if round_batch else "discard",
        )

    def reset(self):
        self._inner.reset()

    def iter_next(self):
        return self._inner.iter_next()

    def next(self):
        return self._inner.next()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (reference API)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iters (reference:
    ``PrefetchingIter`` over ``dmlc::ThreadedIter``)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(len(iters))
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.current_batch = [None] * self.n_iter
        self.next_batch = [None] * self.n_iter
        self.started = True
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()

        def prefetch(i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch, args=[i], daemon=True)
            for i in range(self.n_iter)
        ]
        for t in self.prefetch_threads:
            t.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        if self.next_batch[0] is None:
            return False
        self.current_batch = self.next_batch[0] if self.n_iter == 1 else \
            DataBatch(
                sum([b.data for b in self.next_batch], []),
                sum([b.label for b in self.next_batch], []),
                self.next_batch[0].pad,
                self.next_batch[0].index,
            )
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration
