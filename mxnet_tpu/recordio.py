"""RecordIO (reference: ``python/mxnet/recordio.py`` over
``dmlc-core/recordio`` [unverified]).

Same wire format as the reference (magic ``0xced7230a``, 4-byte-aligned
records, lrecord continuation codes) so ``.rec``/``.idx`` shards pack/unpack
interchangeably. Hot-path batch decode is done by the native C++ pipeline
(``src/io`` milestone); this module is the format + single-record API.
"""

from __future__ import annotations

import os
import struct
from collections import namedtuple

import numpy as _np

from .base import MXNetError

__all__ = [
    "MXRecordIO",
    "MXIndexedRecordIO",
    "IndexedRecordIO",
    "IRHeader",
    "pack",
    "unpack",
    "pack_img",
    "unpack_img",
]

_MAGIC = 0xCED7230A
# continuation codes (dmlc recordio splits records > kMaxRecSize)
_K_MAX = (1 << 29) - 1


def _encode_lrec(cflag, length):
    return (cflag << 29) | length


def _decode_lrec(header):
    return header >> 29, header & _K_MAX


class MXRecordIO:
    """Sequential .rec reader/writer (reference: ``MXRecordIO``)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.record = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError(f"invalid flag {self.flag}")
        self.pid = os.getpid()

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d["record"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def _check_pid(self):
        # reference re-opened after fork (DataLoader workers)
        if self.pid != os.getpid():
            self.close()
            self.open()

    def close(self):
        if self.record is not None and not self.record.closed:
            self.record.close()

    def reset(self):
        self.close()
        self.open()

    def write(self, buf: bytes):
        assert self.writable
        self._check_pid()
        length = len(buf)
        # single-part record (cflag 0); large records chunked like dmlc
        pos = 0
        nparts = (length + _K_MAX - 1) // _K_MAX if length else 1
        for i in range(nparts):
            part = buf[pos : pos + _K_MAX]
            pos += len(part)
            if nparts == 1:
                cflag = 0
            elif i == 0:
                cflag = 1
            elif i == nparts - 1:
                cflag = 3
            else:
                cflag = 2
            self.record.write(struct.pack("<II", _MAGIC,
                                          _encode_lrec(cflag, len(part))))
            self.record.write(part)
            pad = (4 - (len(part) % 4)) % 4
            if pad:
                self.record.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        self._check_pid()
        out = b""
        while True:
            head = self.record.read(8)
            if len(head) < 8:
                return out if out else None
            magic, lrec = struct.unpack("<II", head)
            if magic != _MAGIC:
                raise MXNetError("invalid record magic; corrupt .rec file")
            cflag, length = _decode_lrec(lrec)
            data = self.record.read(length)
            pad = (4 - (length % 4)) % 4
            if pad:
                self.record.read(pad)
            out += data
            if cflag in (0, 3):
                return out

    def tell(self):
        return self.record.tell()


class MXIndexedRecordIO(MXRecordIO):
    """.rec + .idx random access (reference: ``MXIndexedRecordIO``)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.exists(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)
        elif self.flag == "w":
            self.fidx = open(self.idx_path, "w")

    def close(self):
        super().close()
        if self.fidx is not None and not self.fidx.closed:
            self.fidx.close()

    def seek(self, idx):
        assert not self.writable
        self._check_pid()
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        if self.flag == "r":
            self._check_pid()  # fork guard before touching self.record
            # native fast path: C++ framing scan + direct copy (reference:
            # dmlc RecordIOReader); falls back to the Python reader
            nr = self._native_reader()
            if nr is not None:
                try:
                    buf, end = nr.read_at(self.idx[idx])
                    # keep read_idx == seek+read semantics: position the
                    # Python handle after the record for subsequent read()
                    self.record.seek(end)
                    return buf
                except (KeyError, RuntimeError):
                    pass
        self.seek(idx)
        return self.read()

    def _native_reader(self):
        if getattr(self, "_nr_pid", None) != os.getpid():
            self._nr = None
            self._nr_pid = os.getpid()
            from . import _native

            if _native.available():
                try:
                    self._nr = _native.NativeRecordReader(self.uri)
                except RuntimeError:
                    self._nr = None
        return self._nr

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)


IndexedRecordIO = MXIndexedRecordIO  # convenience alias

IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a label header + payload (reference: ``recordio.pack``)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        out = struct.pack(_IR_FORMAT, header.flag, header.label, header.id,
                          header.id2)
    else:
        label = _np.asarray(header.label, dtype=_np.float32)
        out = struct.pack(_IR_FORMAT, len(label), 0.0, header.id, header.id2)
        out += label.tobytes()
    return out + s


def unpack(s: bytes):
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = _np.frombuffer(s[: header.flag * 4], dtype=_np.float32)
        s = s[header.flag * 4 :]
        header = header._replace(label=label, flag=0)
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode an image array and pack (requires cv2 or PIL for encode)."""
    encoded = _encode_image(img, quality, img_fmt)
    return pack(header, encoded)


def unpack_img(s, iscolor=-1):
    header, img_bytes = unpack(s)
    return header, _decode_image(img_bytes, iscolor)


def _encode_image(img, quality, img_fmt):
    try:
        import cv2

        ext = img_fmt if img_fmt.startswith(".") else "." + img_fmt
        params = [cv2.IMWRITE_JPEG_QUALITY, quality] if "jp" in ext else []
        ok, buf = cv2.imencode(ext, img, params)
        if not ok:
            raise MXNetError("cv2.imencode failed")
        return buf.tobytes()
    except ImportError:
        pass
    try:
        import io as _io

        from PIL import Image

        b = _io.BytesIO()
        Image.fromarray(_np.asarray(img)[..., ::-1]).save(
            b, format="JPEG", quality=quality
        )
        return b.getvalue()
    except ImportError as e:
        raise MXNetError(
            "image encoding needs cv2 or PIL, neither available"
        ) from e


def _decode_image(img_bytes, iscolor=-1):
    if iscolor == 1:
        # native libjpeg path for force-color decodes (reference: the C++
        # image pipeline over libjpeg-turbo); BGR like cv2, None on
        # non-JPEG. iscolor=-1 ("unchanged") must preserve grayscale as
        # 2-D, which the native path does not — fall through for it.
        from . import _native

        img = _native.jpeg_decode(bytes(img_bytes))
        if img is not None:
            return img
    try:
        import cv2

        return cv2.imdecode(_np.frombuffer(img_bytes, _np.uint8), iscolor)
    except ImportError:
        pass
    try:
        import io as _io

        from PIL import Image

        img = _np.asarray(Image.open(_io.BytesIO(img_bytes)))
        return img[..., ::-1] if img.ndim == 3 else img  # RGB->BGR like cv2
    except ImportError as e:
        raise MXNetError(
            "image decoding needs cv2 or PIL, neither available"
        ) from e
