"""Parallelism: device mesh, shardings, collectives (no reference analogue —
this replaces ``src/kvstore/comm*.h``, NCCL and ps-lite with mesh + GSPMD,
SURVEY.md §2.3).

Axes convention (the "How to Scale Your Model" recipe):
  data  — data parallel (batch sharded; grad psum over ICI)
  model — tensor parallel (weight matrices sharded)
  seq   — sequence/context parallel (ring attention neighbors)
  pipe  — pipeline stages

Use ``make_mesh`` to build a mesh over all visible devices, ``with_sharding``
to annotate arrays, and ``data_parallel_step``/``train_step`` builders in
``mxnet_tpu.parallel.step`` for whole-model jitted training steps.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as _np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = [
    "Mesh",
    "PartitionSpec",
    "NamedSharding",
    "make_mesh",
    "current_mesh",
    "set_mesh",
    "mesh_scope",
    "shard",
    "replicate",
    "with_sharding_constraint",
    "all_reduce_eager",
    "init_process_group",
    "local_mesh_axes",
]

_STATE = threading.local()


def make_mesh(axes: Optional[Dict[str, int]] = None, devices=None) -> Mesh:
    """Build a named mesh. ``axes`` maps axis name -> size; total must cover
    the device count (one axis 'data' over all devices by default)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axes is None:
        axes = {"data": n}
    sizes = list(axes.values())
    total = int(_np.prod(sizes))
    if total != n:
        raise MXNetError(
            f"mesh axes {axes} cover {total} devices but {n} are visible"
        )
    dev_array = _np.array(devices).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))


def set_mesh(mesh: Optional[Mesh]):
    _STATE.mesh = mesh


def current_mesh() -> Optional[Mesh]:
    return getattr(_STATE, "mesh", None)


@contextlib.contextmanager
def mesh_scope(mesh: Mesh):
    prev = current_mesh()
    set_mesh(mesh)
    try:
        yield mesh
    finally:
        set_mesh(prev)


def local_mesh_axes() -> Sequence[str]:
    mesh = current_mesh()
    return mesh.axis_names if mesh is not None else ()


def _unwrap(x):
    return x.data if isinstance(x, NDArray) else x


def shard(array, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """Place an array on the mesh with the given PartitionSpec."""
    mesh = mesh or current_mesh()
    if mesh is None:
        raise MXNetError("no active mesh: call set_mesh/make_mesh first")
    data = jax.device_put(_unwrap(array), NamedSharding(mesh, spec))
    return NDArray(data) if isinstance(array, NDArray) else data


def replicate(array, mesh: Optional[Mesh] = None):
    return shard(array, PartitionSpec(), mesh)


def with_sharding_constraint(x, spec: PartitionSpec):
    """In-jit sharding annotation (GSPMD hint); passthrough outside jit or
    without a mesh."""
    mesh = current_mesh()
    if mesh is None:
        return x
    data = _unwrap(x)
    out = jax.lax.with_sharding_constraint(data, NamedSharding(mesh, spec))
    return NDArray(out) if isinstance(x, NDArray) else out


def all_reduce_eager(arr):
    """Cross-process sum of a replicated array (eager path used by the
    dist KVStore facade; the jitted train step uses in-program psum)."""
    arr = _unwrap(arr)
    if jax.process_count() == 1:
        return arr
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(arr)
    return jnp.sum(gathered, axis=0)


def init_process_group(coordinator_address: str, num_processes: int,
                       process_id: int, local_device_ids=None):
    """Join the cluster coordinator (reference analogue: ps-lite scheduler
    rendezvous in ``ps::Postoffice::Start`` [unverified]).

    The XLA CPU client only forms a multi-node cluster when a cross-process
    collectives implementation is selected (localhost multi-process testing,
    the reference's nightly dist tests), so pick gloo before the backend is
    instantiated — harmless for TPU runs, where the TPU client syncs through
    the coordination service itself."""
    if jax.distributed.is_initialized():
        return  # idempotent: a second KVStore/TrainStep must not re-join
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # older jaxlib without gloo: single-node CPU fallback
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


from . import sharding  # noqa: E402  (SPMD sharding spine)
from .sharding import (  # noqa: E402
    ShardingRules, global_mesh, set_global_mesh, make_global_mesh,
)
from .step import (  # noqa: E402  (public API; needs defs above)
    TrainStep, DeviceBatch, plan_batch, hbm_budget_bytes,
)
from .infer import InferStep  # noqa: E402  (inference twin of TrainStep)

__all__ += ["TrainStep", "DeviceBatch", "plan_batch", "hbm_budget_bytes",
            "InferStep", "sharding", "ShardingRules", "global_mesh",
            "set_global_mesh", "make_global_mesh"]
