"""Ulysses attention: sequence parallelism via head↔sequence all-to-all.

Beyond-reference capability (SURVEY.md §2.3 lists it as the optional
complement to ring attention): instead of rotating K/V chunks around a
ring, each device trades its sequence shard for a head shard with ONE
``all_to_all`` before attention and the inverse after (DeepSpeed-Ulysses
recipe, public; reimplemented on this repo's flash kernel). Where ring
attention's communication scales with n-1 neighbor hops of K/V, Ulysses
moves each activation exactly twice — cheaper when the head count divides
well over the axis, while ring wins when heads are scarce or sequence
lengths dwarf HBM. Both compose with data parallelism inside TrainStep.

Constraint: num_heads % axis_size == 0.
"""

from __future__ import annotations

import math

import jax
from jax.sharding import Mesh

from ..base import MXNetError
from ..ops.pallas.flash_attention import flash_attention

__all__ = ["ulysses_attention", "ulysses_attention_shard"]


def ulysses_attention_shard(q, k, v, axis_name=None, causal=False,
                            sm_scale=None, valid_length=None):
    """Inside shard_map: q/k/v local chunks (B, H, S_local, D) sharded on
    the sequence dim; returns the same layout. ``valid_length`` (B,) is
    the GLOBAL key budget — after the all_to_all each device holds the
    full sequence (for a head subset), so it applies unchanged (placed
    last so positional (q, k, v, axis_name, ...) callers keep working)."""

    def swap_in(x):
        # (B, H, S/n, D) -> (B, H/n, S, D): scatter heads, gather sequence
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def swap_out(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = swap_in(q), swap_in(k), swap_in(v)
    scale = float(sm_scale) if sm_scale is not None else 1.0 / math.sqrt(
        q.shape[-1]
    )
    # full-sequence attention over the local head subset: exact, so causal
    # masking needs no cross-device bookkeeping (unlike the ring)
    out = flash_attention(qh, kh, vh, valid_length, causal=causal,
                          sm_scale=scale)
    return swap_out(out)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "seq", causal=False,
                      sm_scale=None, batch_axis="data", valid_length=None):
    """Sequence-parallel attention over ``mesh`` axis ``axis`` with one
    all-to-all pair. q/k/v (B, H, S, D), S divisible by the axis size,
    H divisible by the axis size. ``valid_length`` (B,) int: GLOBAL count
    of non-padding key positions per row."""
    from .ring_attention import _seq_parallel_call

    def check(qd):
        n = mesh.shape[axis]
        if qd.shape[1] % n:
            raise MXNetError(
                f"ulysses_attention needs num_heads ({qd.shape[1]}) "
                f"divisible by the '{axis}' axis size ({n}); use ring "
                "attention otherwise"
            )

    return _seq_parallel_call(
        ulysses_attention_shard, q, k, v, mesh, axis, causal, sm_scale,
        batch_axis, precheck=check, valid_length=valid_length,
    )
