"""Whole-model jitted training step with GSPMD sharding.

This is the TPU-native replacement for the reference's training hot path
(SURVEY.md §3.3: per-op engine pushes + KVStore push/pull + per-param fused
optimizer kernels). Here ONE XLA executable contains forward, backward,
gradient all-reduce (psum inserted by GSPMD over the mesh's ``data`` axis)
and the optimizer update, with parameter/optimizer buffers donated — the
compiled analogue of CachedOp + kvstore + multi-tensor update in a single
program, with comm/compute overlap handled by XLA's latency-hiding
scheduler.

Tensor parallelism comes free by rule: ``param_rules`` maps parameter-name
regexes to PartitionSpecs; annotated weights shard over the ``model`` axis
and GSPMD inserts the matching collectives.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import compile_cache as _cc
from .. import random as _random
from .. import telemetry as _tel
from .. import optimizer as _opt
from ..ops import optimizer_op as _fused
from . import sharding as _sharding

__all__ = ["TrainStep", "DeviceBatch", "plan_batch", "hbm_budget_bytes"]


def hbm_budget_bytes(limit_bytes=None) -> Optional[int]:
    """The HBM planning budget: the device limit shaved by
    ``MXTPU_HBM_HEADROOM`` — a value <= 1 is the usable FRACTION of HBM
    (default 0.9), a value > 1 is an absolute byte count reserved.
    ``limit_bytes`` overrides the detected limit
    (``telemetry.hbm_limit_bytes``: device ``bytes_limit``, else
    ``MXTPU_HBM_BYTES``). None when no limit is known."""
    import os

    if limit_bytes is None:
        limit_bytes = _tel.hbm_limit_bytes()
    if limit_bytes is None:
        return None
    head = float(os.environ.get("MXTPU_HBM_HEADROOM", "0.9"))
    if head <= 1.0:
        return int(limit_bytes * head)
    return int(limit_bytes - head)


def plan_batch(step, signature_fn, budget_bytes, start=1, max_batch=65536,
               per_shard=None):
    """Largest global batch whose compiled step fits ``budget_bytes``.

    ``signature_fn(batch_size)`` returns the warmup-style signature
    (per-array ``(shape, dtype)`` specs for ``(input0, ..., label)``)
    describing one global batch of that size. Cost model is
    ``step.memory_analysis(sig)['peak_bytes_estimate']`` — abstract
    lowering only, nothing is materialized. Geometric probe up from
    ``start`` then bisection, so ~2*log2(answer) compiles (persistent
    compilation cache hits on re-runs). Returns ``(batch, peak_bytes)``;
    ``(0, None)`` when even ``start`` does not fit.

    ``per_shard`` — bisect against the PER-DEVICE peak
    (``peak_bytes_per_shard``): the budget is one device's HBM, and a
    mesh splits the working set across ``mesh.size`` devices. Default
    auto: per-shard whenever the step runs on a multi-device mesh
    (``hbm_budget_bytes`` is per-device by construction — it reads the
    min device ``bytes_limit``)."""
    if per_shard is None:
        m = getattr(step, "_mesh", None)
        per_shard = m is not None and int(m.size) > 1
    key = "peak_bytes_per_shard" if per_shard else "peak_bytes_estimate"
    memo = {}

    def peak(bs):
        if bs not in memo:
            ma = step.memory_analysis(signature_fn(bs))
            memo[bs] = ma.get(key, ma["peak_bytes_estimate"])
        return memo[bs]

    if peak(start) > budget_bytes:
        return 0, None
    lo, hi, b = start, None, start
    while hi is None and b < max_batch:
        b = min(b * 2, max_batch)
        if peak(b) <= budget_bytes:
            lo = b
        else:
            hi = b
    if hi is not None:
        while hi - lo > 1:
            mid = (lo + hi) // 2
            if peak(mid) <= budget_bytes:
                lo = mid
            else:
                hi = mid
    return lo, peak(lo)


class DeviceBatch:
    """A batch already staged — leading step/accum axes split, per-input
    shardings applied, buffers device-resident — for ONE specific
    ``TrainStep``. Produced by ``TrainStep.device_put_batch`` (the
    ``prefetch_to_device`` worker's placement hook); ``TrainStep.__call__``
    detects it and skips the host-side staging entirely."""

    __slots__ = ("batch", "label", "owner")

    def __init__(self, batch, label, owner):
        self.batch = tuple(batch)
        self.label = label
        self.owner = owner


def _pure_update_factory(optimizer):
    """Map an Optimizer instance to (state_init, pure_update).

    pure_update(w, g, states, lr, wd, t) -> (new_w, new_states); hypers are
    closed over statically, lr/wd/t are dynamic scalars (no retrace when the
    schedule moves).
    """
    clip = optimizer.clip_gradient if optimizer.clip_gradient is not None else -1.0

    if isinstance(optimizer, _opt.SGD):
        mom = optimizer.momentum

        def init(w):
            return (jnp.zeros_like(w),) if mom else ()

        def update(w, g, states, lr, wd, t, rescale):
            if mom:
                new_w, new_m = _fused.sgd_mom_update(
                    w, g, states[0], lr=lr, momentum=mom, wd=wd,
                    rescale_grad=rescale, clip_gradient=clip,
                )
                return new_w, (new_m,)
            return (
                _fused.sgd_update(w, g, lr=lr, wd=wd, rescale_grad=rescale,
                                  clip_gradient=clip),
                (),
            )

        return init, update

    if isinstance(optimizer, _opt.LAMB):
        b1, b2, eps = optimizer.beta1, optimizer.beta2, optimizer.epsilon
        lower = optimizer.lower_bound if optimizer.lower_bound is not None else -1.0
        upper = optimizer.upper_bound if optimizer.upper_bound is not None else -1.0
        bias_corr = optimizer.bias_correction

        def init(w):
            return (jnp.zeros_like(w), jnp.zeros_like(w))

        def update(w, g, states, lr, wd, t, rescale):
            gup, m, v = _fused.lamb_update_phase1(
                w, g, states[0], states[1], beta1=b1, beta2=b2, epsilon=eps,
                t=t.astype(jnp.float32), bias_correction=bias_corr, wd=wd,
                rescale_grad=rescale, clip_gradient=clip,
            )
            r1 = jnp.linalg.norm(w)
            r2 = jnp.linalg.norm(gup)
            new_w = _fused.lamb_update_phase2(
                w, gup, r1, r2, lr=lr, lower_bound=lower, upper_bound=upper
            )
            return new_w, (m, v)

        return init, update

    if isinstance(optimizer, _opt.AdamW):
        b1, b2, eps = optimizer.beta1, optimizer.beta2, optimizer.epsilon
        correct = optimizer.correct_bias

        def init(w):
            return (jnp.zeros_like(w), jnp.zeros_like(w))

        def update(w, g, states, lr, wd, t, rescale):
            if correct:
                tf = t.astype(jnp.float32)
                lr = lr * jnp.sqrt(1.0 - b2 ** tf) / (1.0 - b1 ** tf)
            new_w, m, v = _fused.adamw_update(
                w, g, states[0], states[1], lr=lr, beta1=b1, beta2=b2,
                epsilon=eps, wd=wd, rescale_grad=rescale, clip_gradient=clip,
            )
            return new_w, (m, v)

        return init, update

    if isinstance(optimizer, _opt.Adam):
        b1, b2, eps = optimizer.beta1, optimizer.beta2, optimizer.epsilon

        def init(w):
            return (jnp.zeros_like(w), jnp.zeros_like(w))

        def update(w, g, states, lr, wd, t, rescale):
            tf = t.astype(jnp.float32)
            lr = lr * jnp.sqrt(1.0 - b2 ** tf) / (1.0 - b1 ** tf)
            new_w, m, v = _fused.adam_update(
                w, g, states[0], states[1], lr=lr, beta1=b1, beta2=b2,
                epsilon=eps, wd=wd, rescale_grad=rescale, clip_gradient=clip,
            )
            return new_w, (m, v)

        return init, update

    raise MXNetError(
        f"TrainStep has no fused pure update for {type(optimizer).__name__}; "
        "use Trainer.step (per-param path) or add a mapping"
    )


class TrainStep:
    """Compile net+loss+optimizer into one sharded XLA training step.

    Parameters
    ----------
    net : initialized Gluon Block
    loss_fn : gluon Loss block (applied as ``loss_fn(net(*data), label)``)
    optimizer : Optimizer instance (SGD/Adam/AdamW/LAMB fused)
    mesh : jax Mesh, or None — adopts the process-global mesh
        (``sharding.global_mesh()`` / ``MXTPU_MESH``); single device when
        neither is configured
    sharding : ``sharding.ShardingRules``, preset string ('replicated',
        'fsdp', 'fsdp:<axis>') or None (the ``MXTPU_SHARDING`` process
        default). Maps params + optimizer state + batch inputs to
        ``NamedSharding`` declaratively; 'fsdp' shards parameters AND
        moments over the data axis so a model larger than one chip's
        HBM trains (GSPMD inserts the gather/reduce-scatter collectives)
    data_spec : PartitionSpec for every batch input (default: the rules'
        batch spec, else shard axis 0 over 'data' when the mesh has one)
    param_rules : [(regex, PartitionSpec)] tensor-parallel placement
        rules; checked BEFORE the ``sharding`` rules, so explicit TP
        placements compose with an FSDP default
    grad_accum : microbatch accumulation steps (lax.scan over microbatches)

    Sequence/context parallelism: give the mesh a ``seq`` axis, shard batch
    inputs over it via ``data_spec`` (e.g. ``P('data', 'seq')`` for (B, S)
    token ids), and build the model's attention with ``ring_axis='seq'``
    (``MultiHeadAttention``) — the step's trace runs under this mesh's
    scope, so ring attention resolves the axis automatically and GSPMD
    composes the ring ppermutes with the data-parallel psum.
    """

    def __init__(self, net, loss_fn, optimizer, mesh: Optional[Mesh] = None,
                 data_spec: Optional[PartitionSpec] = None,
                 param_rules: Sequence[Tuple[str, PartitionSpec]] = (),
                 donate: bool = True, grad_accum: int = 1,
                 compute_dtype=None, state_dtype=None, steps_per_call: int = 1,
                 remat: Optional[str] = None, amp: Optional[str] = None,
                 loss_scaler=None, sharding=None):
        from .. import amp as _amp_mod
        from .. import remat as _remat_mod

        self._net = net
        self._loss = loss_fn
        self._optimizer = optimizer
        # sharding spine: explicit mesh/rules win; otherwise the
        # process-global mesh (MXTPU_MESH) and rules (MXTPU_SHARDING)
        rules = _sharding.ShardingRules.resolve(sharding)
        if mesh is None:
            mesh = _sharding.global_mesh()
        self._sharding_rules = rules
        self._mesh = mesh
        self._accum = int(grad_accum)
        # steps_per_call > 1: run that many full optimizer steps per
        # dispatch via a device-side lax.scan; batch inputs then carry a
        # leading (steps_per_call,) axis of distinct microbatches. Trades
        # per-step host control (lr schedule moves only between calls) for
        # dispatch latency — the standard JAX input-dispatch amortization.
        self._steps_per_call = int(steps_per_call)
        # AMP: cast float params/inputs to the compute dtype INSIDE the
        # jitted step. The step differentiates W.R.T. THE CAST COPIES, so
        # gradients carry the compute dtype — the reference's
        # multi-precision scheme exactly (low-precision weights+grads, f32
        # masters inside the optimizer, ``mp_sgd_update`` family in
        # ``src/operator/optimizer_op.cc`` [unverified]) — and the
        # optimizer casts back up. On bandwidth-bound chips halving
        # gradient bytes is a first-order win. Two spellings:
        #   compute_dtype=...  (legacy) casts EVERY float param;
        #   amp='bfloat16'|'float16' consults amp.lists — norm-family
        #   params stay fp32 (the cast-insertion pass at parameter
        #   granularity), losses/reductions stay fp32, and float16 runs
        #   the dynamic LossScaler inside the graph (scaled loss,
        #   all-finite grad check, lax.cond-skipped update, in-graph
        #   scale schedule — overflow steps cost no host sync).
        if amp is None and compute_dtype is None:
            amp = _amp_mod.default_amp()  # amp.init() global / MXTPU_AMP
        if amp is not None:
            if compute_dtype is not None:
                raise MXNetError(
                    "pass either amp= or compute_dtype=, not both")
            amp = str(amp)
            if amp not in ("bfloat16", "float16"):
                raise MXNetError("amp must be 'bfloat16' or 'float16'")
            self._amp = amp
            self._compute_dtype = jnp.dtype(amp)
            self._amp_fp32 = _amp_mod.fp32_param_names(net)
            if loss_scaler is None and amp == "float16":
                loss_scaler = _amp_mod.LossScaler()
        else:
            self._amp = None
            self._compute_dtype = (
                jnp.dtype(compute_dtype) if compute_dtype is not None
                else None
            )
            self._amp_fp32 = frozenset()
            loss_scaler = None  # scaling is the amp='float16' contract
        self._scaler = loss_scaler
        self._scaler_dev = None  # (scale f32, clean-streak i32, skips i32)
        # optionally store optimizer moments (m, v) in a narrow dtype; the
        # update computes in f32 and casts state back down (bf16 shares
        # f32's exponent range, so EMA magnitudes survive; mantissa noise
        # is the accepted trade — like the 8-bit-optimizer line of work)
        self._state_dtype = (
            jnp.dtype(state_dtype) if state_dtype is not None else None
        )
        # rematerialization (jax.checkpoint over the traced forward):
        # trades recompute FLOPs for residual HBM traffic — the standard
        # lever when the step is memory-bound. Policy menu + per-layer
        # grain (hybridize(remat=...)): mxnet_tpu.remat.
        if remat is None:
            remat = _remat_mod.default_policy()  # MXTPU_REMAT
        _remat_mod.resolve_policy(remat)  # validate eagerly
        self._remat = remat
        self._params = list(net.collect_params().items())
        for name, p in self._params:
            if p._data is None:
                raise MXNetError(
                    f"parameter {name} not initialized; run one forward (or "
                    "initialize with known shapes) before building TrainStep"
                )
        self._train_names = [n for n, p in self._params
                             if p.grad_req != "null"]
        self._train_set = frozenset(self._train_names)
        self._init_state, self._pure_update = _pure_update_factory(optimizer)
        self._t = 0

        # placement -------------------------------------------------------
        if mesh is not None:
            axis_names = mesh.axis_names
            if data_spec is None:
                data_spec = rules.batch_partition_spec(mesh) \
                    if rules is not None else (
                        PartitionSpec("data") if "data" in axis_names
                        else PartitionSpec())
            # data_spec may be ONE spec for every input, or a sequence of
            # per-input specs covering (*batch, label) — ragged inputs like
            # a (B,) valid_length can't share the (B, S) spec
            if isinstance(data_spec, (tuple, list)) and not isinstance(
                data_spec, PartitionSpec
            ):
                self._data_sharding = [
                    NamedSharding(mesh, s) for s in data_spec
                ]
            else:
                self._data_sharding = NamedSharding(mesh, data_spec)
            # explicit param_rules first (TP placements), then the
            # declarative rules' policy (FSDP/replicated), so both compose
            legacy = [(re.compile(pat), spec) for pat, spec in param_rules]
            shapes = {n: tuple(p._data.data.shape) for n, p in self._params}

            def param_spec(name):
                for pat, spec in legacy:
                    if pat.search(name):
                        return spec
                if rules is not None:
                    return rules.param_spec(
                        name, shapes.get(name, ()), mesh)
                return PartitionSpec()

            def param_sharding(name):
                return NamedSharding(mesh, param_spec(name))

            self._param_spec = param_spec
            self._param_sharding = param_sharding
        else:
            self._data_sharding = None
            self._param_spec = None
            self._param_sharding = None

        # device state ----------------------------------------------------
        # non-aliasing placement: this state is DONATED every step, so it
        # must never share buffers with the net's live Parameters
        vals: Dict[str, jax.Array] = {}
        for name, p in self._params:
            v = p._data.data
            if self._param_sharding is not None:
                v = _sharding.device_put_donatable(
                    v, self._param_sharding(name))
            vals[name] = v
        self._values = vals  # setter partitions into train/frozen dicts
        def _mk_state(v):
            st = self._init_state(v)
            if self._state_dtype is not None:
                st = tuple(s.astype(self._state_dtype) for s in st)
            return st

        self._opt_state = {
            n: _mk_state(vals[n]) for n in self._train_names
        }
        if self._param_sharding is not None:
            # moments follow their param's placement (the ZeRO contract:
            # FSDP shards optimizer state alongside the weights)
            self._opt_state = {
                n: tuple(
                    _sharding.device_put_donatable(
                        s, self._param_sharding(n)) for s in st
                )
                for n, st in self._opt_state.items()
            }

        # host-dispatch slimming: everything __call__ used to recompute
        # per call is hoisted here — the leading device-loop split axes,
        # the lead-adjusted per-input shardings, and the scalar memos
        lead = (self._steps_per_call,) if self._steps_per_call > 1 else ()
        if self._accum > 1:
            lead = lead + (self._accum,)
        self._lead = lead
        n_split = 1
        for d in lead:
            n_split *= d
        self._split_n = n_split
        if self._data_sharding is None:
            self._feed_shardings = None
        else:
            nlead = len(lead)

            def _with_lead(s):
                if not nlead:
                    return s
                # leading step/accum axes are device-side loop axes, not
                # data axes — shard the per-microbatch axis after them
                return NamedSharding(
                    mesh, PartitionSpec(*([None] * nlead), *s.spec))

            if isinstance(self._data_sharding, list):
                self._feed_shardings = [
                    _with_lead(s) for s in self._data_sharding]
            else:
                self._feed_shardings = _with_lead(self._data_sharding)
        self._split_memo: Dict[int, tuple] = {}
        self._key_dev = None
        self._t_dev = None
        self._lr_host = None
        self._rescale_host = None
        self._last_avals = None
        # every distinct (batch, label) aval signature is one compiled
        # step program; the guard is the exact compile counter and the
        # post-warmup shape-churn alarm (compile_cache.RecompileGuard)
        self.compile_guard = _cc.RecompileGuard(
            f"TrainStep({type(net).__name__})")

        # surface the memory/precision config in telemetry reports and
        # bench rows (amp_dtype / remat_policy columns)
        _tel.set_info(
            amp_dtype=(self._amp or (self._compute_dtype.name
                                     if self._compute_dtype else None)),
            remat_policy=self._remat)
        # shard/ metric family: mesh shape, global vs per-shard param
        # bytes, collective-traffic estimate (report()/bench rows)
        if mesh is not None:
            _sharding.publish_shard_metrics(
                self._values, mesh, rules, trainable=self._train_names)

        self._step_fn = self._build(donate)

    # device values stay pre-partitioned (train vs frozen) so the hot
    # dispatch never rebuilds dicts; cold paths (checkpoint/sync/interop)
    # read this merged view and assign through the setter
    @property
    def _values(self):
        merged = dict(self._frozen_vals)
        merged.update(self._train_vals)
        return merged

    @_values.setter
    def _values(self, vals):
        ts = self._train_set
        self._train_vals = {n: v for n, v in vals.items() if n in ts}
        self._frozen_vals = {n: v for n, v in vals.items() if n not in ts}

    # ---------------------------------------------------------------- build
    def _build(self, donate):
        from ..gluon.block import _aux_scope, _trace_scope
        from ..gluon.parameter import param_override
        from .. import autograd

        net, loss_block = self._net, self._loss
        params = self._params
        train_names = set(self._train_names)
        name2param = {n: p for n, p in params}
        pure_update = self._pure_update
        accum = self._accum
        # static per-param hyper multipliers
        lr_mult = {n: name2param[n].lr_mult for n in train_names}
        wd_mult = {n: name2param[n].wd_mult for n in train_names}
        base_wd = float(self._optimizer.wd)

        name2param_inv = {id(p): n for n, p in params}
        cdt = self._compute_dtype
        fp32_pinned = self._amp_fp32

        def _cast(v):
            if cdt is not None and jnp.issubdtype(v.dtype, jnp.floating):
                return v.astype(cdt)
            return v

        def _cast_param(n, v):
            # amp.lists pass at parameter granularity: norm-family params
            # keep their fp32 masters as the compute value
            if n in fp32_pinned:
                return v
            return _cast(v)

        mesh = self._mesh
        from . import mesh_scope as _mesh_scope
        import contextlib as _ctx

        def forward_loss(cast_vals, frozen_vals, batch, label, key):
            # cast_vals are already in compute dtype — they are the
            # differentiated leaves, so gradients carry that dtype too
            mapping = {}
            for n, p in params:
                v = cast_vals[n] if n in cast_vals \
                    else _cast_param(n, frozen_vals[n])
                mapping[p] = NDArray(v)
            sink = {}
            # activate the mesh during tracing so mesh-aware layers (ring
            # attention) can resolve their axis from current_mesh()
            mscope = _mesh_scope(mesh) if mesh is not None else _ctx.nullcontext()
            with mscope, param_override(mapping), _random.key_supply(key), \
                    _aux_scope(sink), _trace_scope(), \
                    autograd._scope(False, True):
                out = net(*[NDArray(_cast(b)) for b in batch])
                outs = out if isinstance(out, tuple) else (out,)
                L = loss_block(*outs, NDArray(label))
                Lm = L.data.astype(jnp.float32).mean()
            aux = {name2param_inv[id(p)]: v for p, v in sink.items()}
            return Lm, aux

        if self._remat is not None:
            from .. import remat as _remat_mod

            forward_loss = jax.checkpoint(
                forward_loss,
                policy=_remat_mod.resolve_policy(self._remat),
                static_argnums=())

        scaler = self._scaler
        scaled = scaler is not None
        if scaled:
            window = jnp.int32(scaler.scale_window)
            factor = jnp.float32(scaler.scale_factor)

        def apply_updates(train_vals, opt_state, grads, lr, t, rescale):
            new_vals = {}
            new_opt = {}
            for n in sorted(train_vals):
                w, g = train_vals[n], grads[n]
                st = opt_state[n]
                # narrow-state option: lift moments to f32 for the update
                # math; XLA fuses the converts into the update kernel so
                # only the narrow bytes move through HBM
                st_f = tuple(s.astype(w.dtype) for s in st)
                nw, ns = pure_update(
                    w, g.astype(w.dtype), st_f, lr * lr_mult[n],
                    base_wd * wd_mult[n], t, rescale,
                )
                new_vals[n] = nw.astype(w.dtype)
                new_opt[n] = tuple(
                    s_new.astype(s_old.dtype)
                    for s_new, s_old in zip(ns, st)
                )
            return new_vals, new_opt

        # rescale_grad is a dynamic operand: AMP dynamic loss scaling and
        # batch-size changes fold into it per step and must not retrace.
        # key and t are DEVICE-carried state (returned updated, donated):
        # advancing them on host would cost a host->device transfer plus an
        # eager dispatch per step — measurable over the tunneled backend.
        # scaler_state (float16 AMP only) rides the same way: (loss scale,
        # clean-step streak, skipped-step count), adjusted in-graph.
        def step_core(train_vals, frozen_vals, opt_state, batch, label, key,
                      lr, t, rescale, scaler_state):
            key, sub = jax.random.split(key)
            # batch: tuple of arrays; with accum > 1 each has a leading
            # microbatch dim of size `accum` scanned by lax.scan
            cast_vals = {n: _cast_param(n, v) for n, v in train_vals.items()}
            scale = scaler_state[0] if scaled else None

            def fwd(cv, fv, b, l, k):
                L, aux = forward_loss(cv, fv, b, l, k)
                # scaled loss => scaled (finite-checkable) gradients; the
                # unscale folds into rescale_grad below, never a host trip
                return (L * scale, aux) if scaled else (L, aux)

            if accum == 1:
                (L, aux), grads = jax.value_and_grad(
                    fwd, has_aux=True
                )(cast_vals, frozen_vals, batch, label, sub)
            else:
                def micro(carry, inp):
                    g_acc, k = carry
                    k, sk = jax.random.split(k)
                    mb, ml = inp
                    (Lm, aux_m), g = jax.value_and_grad(
                        fwd, has_aux=True
                    )(cast_vals, frozen_vals, mb, ml, sk)
                    # accumulate in f32 regardless of grad dtype
                    g_acc = jax.tree.map(
                        lambda a, b: a + b.astype(a.dtype), g_acc, g
                    )
                    return (g_acc, k), (Lm, aux_m)

                g0 = jax.tree.map(
                    lambda v: jnp.zeros(v.shape, jnp.float32), train_vals
                )
                (grads, _), (Ls, auxs) = jax.lax.scan(
                    micro, (g0, sub), (batch, label)
                )
                grads = jax.tree.map(lambda g: g / accum, grads)
                L = Ls.mean()
                aux = jax.tree.map(lambda a: a[-1], auxs)

            if not scaled:
                t1 = t + 1
                new_vals, new_opt = apply_updates(
                    train_vals, opt_state, grads, lr, t1, rescale)
                return L, new_vals, new_opt, key, t1, aux, None

            # in-graph overflow handling: the all-finite check gates a
            # lax.cond'd update — a skipped step leaves params, moments,
            # aux states and the bias-correction clock t untouched — and
            # the grow/halve schedule advances on device. No host sync
            # anywhere on this path (tools/check_amp_purity.py lints it).
            L = L / scale
            finite = jnp.bool_(True)
            for g in jax.tree.leaves(grads):
                finite = jnp.logical_and(finite, jnp.isfinite(g).all())
            t1 = t + finite.astype(t.dtype)

            def _apply(_):
                return apply_updates(train_vals, opt_state, grads, lr, t1,
                                     rescale / scale)

            def _skip(_):
                return (dict(train_vals),
                        {n: tuple(st) for n, st in opt_state.items()})

            new_vals, new_opt = jax.lax.cond(finite, _apply, _skip, None)
            aux = {
                n: jnp.where(finite, v,
                             train_vals[n] if n in train_vals
                             else frozen_vals[n])
                for n, v in aux.items()
            }
            # the LossScaler schedule, in-graph: halve (floor 1.0) on
            # overflow, double after scale_window consecutive clean steps
            good = jnp.where(finite, scaler_state[1] + 1, jnp.int32(0))
            new_scale = jnp.where(
                finite, scale, jnp.maximum(scale / factor, jnp.float32(1.0)))
            grow = good >= window
            new_scale = jnp.where(grow, new_scale * factor, new_scale)
            good = jnp.where(grow, jnp.int32(0), good)
            skips = scaler_state[2] + \
                jnp.logical_not(finite).astype(jnp.int32)
            return L, new_vals, new_opt, key, t1, aux, \
                (new_scale, good, skips)

        nsteps = self._steps_per_call
        if nsteps > 1:
            # device-side training loop: scan `nsteps` FULL optimizer steps
            # (distinct microbatches stacked on a leading axis) inside one
            # executable — one dispatch amortizes host/tunnel latency over
            # nsteps steps; the scan body is the single-step program, so
            # compile time and numerics are unchanged
            if scaled:
                def multi(train_vals, frozen_vals, opt_state, batch, label,
                          key, lr, t, rescale, scaler_state):
                    def one(carry, inp):
                        tv, os_, k, tt, ss = carry
                        mb, ml = inp
                        L, nv, no, nk, nt, aux, nss = step_core(
                            tv, frozen_vals, os_, mb, ml, k, lr, tt,
                            rescale, ss
                        )
                        return (nv, no, nk, nt, nss), (L, aux)

                    (tv, os_, k, tt, ss), (Ls, auxs) = jax.lax.scan(
                        one, (train_vals, opt_state, key, t, scaler_state),
                        (batch, label)
                    )
                    aux = jax.tree.map(lambda a: a[-1], auxs)
                    return Ls.mean(), tv, os_, k, tt, aux, ss

                donate_args = (0, 2, 5, 7, 9) if donate else ()
                return jax.jit(multi, donate_argnums=donate_args)

            def multi(train_vals, frozen_vals, opt_state, batch, label, key,
                      lr, t, rescale):
                def one(carry, inp):
                    tv, os_, k, tt = carry
                    mb, ml = inp
                    L, nv, no, nk, nt, aux, _ = step_core(
                        tv, frozen_vals, os_, mb, ml, k, lr, tt, rescale,
                        None
                    )
                    return (nv, no, nk, nt), (L, aux)

                (tv, os_, k, tt), (Ls, auxs) = jax.lax.scan(
                    one, (train_vals, opt_state, key, t), (batch, label)
                )
                aux = jax.tree.map(lambda a: a[-1], auxs)
                return Ls.mean(), tv, os_, k, tt, aux

            donate_args = (0, 2, 5, 7) if donate else ()
            return jax.jit(multi, donate_argnums=donate_args)

        if scaled:
            def step(train_vals, frozen_vals, opt_state, batch, label, key,
                     lr, t, rescale, scaler_state):
                return step_core(train_vals, frozen_vals, opt_state, batch,
                                 label, key, lr, t, rescale, scaler_state)

            donate_args = (0, 2, 5, 7, 9) if donate else ()
            return jax.jit(step, donate_argnums=donate_args)

        def step(train_vals, frozen_vals, opt_state, batch, label, key,
                 lr, t, rescale):
            L, nv, no, k, t1, aux, _ = step_core(
                train_vals, frozen_vals, opt_state, batch, label, key, lr,
                t, rescale, None)
            return L, nv, no, k, t1, aux

        donate_args = (0, 2, 5, 7) if donate else ()
        return jax.jit(step, donate_argnums=donate_args)

    # ----------------------------------------------------------------- call
    def __call__(self, *batch_and_label):
        """Run one step. Last argument is the label; returns loss NDArray.

        Accepts either raw host arrays (staged synchronously: convert,
        split, device_put) or ONE pre-placed ``DeviceBatch`` from
        ``device_put_batch`` / ``prefetch_to_device`` — the fast path that
        skips the host-side staging entirely."""
        from ..imperative import flush_bulk

        flush_bulk()  # donated operands may be captured in the eager queue
        if len(batch_and_label) == 1 and \
                isinstance(batch_and_label[0], DeviceBatch):
            db = batch_and_label[0]
            if db.owner is not self:
                raise MXNetError(
                    "DeviceBatch was staged by a different TrainStep; its "
                    "split axes/shardings may not match — feed it to the "
                    "step whose device_put_batch produced it")
            return self._dispatch(db.batch, db.label)
        batch, label = self._stage(batch_and_label)
        return self._dispatch(batch, label)

    # -------------------------------------------------------------- feeding
    def feed_spec(self) -> dict:
        """The host->device feed contract a feeder must apply to enter the
        pre-placed fast path: leading device-loop split axes (shapes), the
        total leading split factor, and the per-input placement.
        ``prefetch_to_device(loader, feed=step)`` applies it through
        ``device_put_batch`` on its worker thread."""
        return {
            "steps_per_call": self._steps_per_call,
            "grad_accum": self._accum,
            "lead": self._lead,
            "split": self._split_n,
            "mesh": self._mesh,
            "data_sharding": self._data_sharding,
            # declarative rules in force (None = legacy/replicated) — the
            # feeder stages batches onto their SHARDED placements, so the
            # device transfer lands each row on its owning shard directly
            "sharding": (self._sharding_rules.describe()
                         if self._sharding_rules is not None else None),
        }

    def device_put_batch(self, batch_and_label) -> DeviceBatch:
        """Stage one flat ``(input0, ..., label)`` batch exactly as
        ``__call__`` would — convert, split the leading step/accum axes,
        device_put with per-input shardings — and wrap it for the fast
        path. Safe to call from a feeder thread concurrently with the
        training loop (the prefetcher does)."""
        batch, label = self._stage(tuple(batch_and_label))
        return DeviceBatch(batch, label, self)

    # -------------------------------------------------------------- warmup
    def warmup(self, signatures):
        """AOT-compile one step program per batch signature, moving every
        compile out of the steady-state loop.

        ``signatures`` is an iterable; each entry describes ONE global
        (unsplit, exactly as ``__call__`` receives it) batch as a
        sequence of per-array specs for ``(input0, ..., label)`` — an
        array, a ``jax.ShapeDtypeStruct``, or a ``(shape, dtype)`` pair::

            step.warmup([(( (bs, key), "int32"), ((bs, key), "int32"))
                         for bs, key in sampler.signatures()])

        Each signature is driven through the REAL jitted step once —
        ``jit(...).lower(...).compile()`` would compile the same program
        but never populates the jit dispatch cache, so the first real
        call would compile again. Donated operands get throwaway
        zero-state copies (transient extra memory of one parameter+
        optimizer state set); the training state, RNG schedule of the
        real steps, and step counter are untouched.

        Afterwards the guard is marked steady: any NEW shape in the
        training loop counts as ``compile/steady_state_recompiles`` and
        warns or raises per ``MXTPU_RECOMPILE_LIMIT``. Returns the
        number of freshly compiled programs."""
        import numpy as _host_np

        reg = _tel.registry()
        compiled = 0
        for entry in signatures:
            specs = [_cc.normalize_spec(s) for s in entry]
            host = [_host_np.zeros(shape, dtype) for shape, dtype in specs]
            batch, label = self._stage(tuple(host))
            sig = tuple((a.shape, a.dtype.name) for a in batch) + (
                (label.shape, label.dtype.name),)
            if not self.compile_guard.observe(
                    sig, lambda: _cc.aval_summary(tuple(batch) + (label,))):
                continue  # already compiled (duplicate signature)
            compiled += 1
            reg.counter("compile/warmup_compiles").inc()
            with (_tel.span("trainstep.warmup", {"signature": str(sig)})
                  if _tel._ENABLED else _tel.NULL_SPAN):
                out = self._step_fn(*self._dummy_args(batch, label))
            jax.block_until_ready(out[0])  # compile + run fully retired
        self.compile_guard.mark_steady()
        return compiled

    def _dummy_args(self, batch, label):
        """Operands for a warmup dispatch: donated slots (train values,
        optimizer state, key, t) get throwaway zero copies with the real
        placement; non-donated slots reuse the live buffers."""
        def _zeros_like(v):
            z = jnp.zeros(v.shape, v.dtype)
            sh = getattr(v, "sharding", None)
            if self._mesh is not None and sh is not None:
                z = jax.device_put(z, sh)
            return z

        dummy_train = {n: _zeros_like(v)
                       for n, v in self._train_vals.items()}
        dummy_opt = {n: tuple(_zeros_like(s) for s in st)
                     for n, st in self._opt_state.items()}
        args = (dummy_train, self._frozen_vals, dummy_opt, batch, label,
                _random.next_key(), jnp.float32(self._current_lr()),
                jnp.int32(0),
                jnp.float32(self._optimizer.rescale_grad))
        if self._scaler is not None:
            # throwaway scaler state: warmup must not advance the real one
            args = args + (self._scaler_fresh(),)
        return args

    def _scaler_fresh(self):
        """Fresh device-resident (scale, clean-streak, skip-count) state
        seeded from the host LossScaler config."""
        s = (jnp.float32(self._scaler.loss_scale), jnp.int32(0),
             jnp.int32(0))
        if self._mesh is not None:
            repl = NamedSharding(self._mesh, PartitionSpec())
            s = tuple(jax.device_put(x, repl) for x in s)
        return s

    def cache_info(self) -> dict:
        """Signature cache summary: programs held, per-signature aval
        rendering, use counts, recency (``compile_cache.RecompileGuard``
        accounting)."""
        return self.compile_guard.info()

    def _stage(self, batch_and_label):
        """Host-side staging (the slow preamble the fast path skips)."""
        *batch, label = batch_and_label
        batch = [b.data if isinstance(b, NDArray) else jnp.asarray(b)
                 for b in batch]
        label = label.data if isinstance(label, NDArray) else jnp.asarray(label)
        n = self._split_n
        if n > 1:
            # split the flat global batch into the leading axes consumed by
            # the device-side loops: (nsteps, accum, microbatch, ...).
            # jax arrays are immutable, so memoize by input identity — a
            # training loop feeding the same buffers (benchmarks, epochs
            # over a device-resident set) pays the eager reshape dispatch
            # once instead of one tunnel round trip per call
            lead = self._lead
            memo = self._split_memo

            def _split(a, pos):
                hit = memo.get(pos)
                if hit is not None and hit[0] is a:
                    return hit[1]
                out = a.reshape(lead + (a.shape[0] // n,) + a.shape[1:])
                memo[pos] = (a, out)
                return out

            batch = [_split(b, i) for i, b in enumerate(batch)]
            label = _split(label, -1)
        sh = self._feed_shardings
        if sh is not None:
            if isinstance(sh, list):
                if len(sh) != len(batch) + 1:
                    raise MXNetError(
                        f"data_spec sequence has {len(sh)} specs but "
                        f"the step takes {len(batch)} inputs + 1 label"
                    )
                per_input = sh
            else:
                per_input = [sh] * (len(batch) + 1)
            batch = [jax.device_put(b, s)
                     for b, s in zip(batch, per_input[:-1])]
            label = jax.device_put(label, per_input[-1])
        return tuple(batch), label

    def _dispatch(self, batch, label):
        """Dispatch one pre-staged step. The pre-placed feed enters here
        directly, so this body must stay free of host conversion, dict
        rebuilds, and anything that blocks on the device —
        ``tools/check_no_sync_in_step.py`` lints it (and ``__call__``)."""
        nsteps = self._steps_per_call
        sig = tuple((a.shape, a.dtype.name) for a in batch) + (
            (label.shape, label.dtype.name),)
        self.compile_guard.observe(
            sig, lambda: _cc.aval_summary(tuple(batch) + (label,)))
        self._t += nsteps
        lr = self._current_lr()
        # key and t live on device, advanced inside the jitted step — the
        # seed is drawn from mx.random state once, on the first step
        if self._key_dev is None:
            self._key_dev = _random.next_key()
            self._t_dev = jnp.int32(self._t - nsteps)
        # scalar operands cost a host->device transfer each; lr/rescale are
        # usually step-invariant, so reuse their device buffers
        rescale = self._optimizer.rescale_grad
        if self._lr_host != lr:
            self._lr_host, self._lr_dev = lr, jnp.float32(lr)
        if self._rescale_host != rescale:
            self._rescale_host = rescale
            self._rescale_dev = jnp.float32(rescale)
        args = (self._train_vals, self._frozen_vals, self._opt_state, batch,
                label, self._key_dev, self._lr_dev, self._t_dev,
                self._rescale_dev)
        if self._scaler is not None:
            if self._scaler_dev is None:
                self._scaler_dev = self._scaler_fresh()
            args = args + (self._scaler_dev,)
        if self._last_avals is None:
            # stash operand avals ONCE so cost_analysis() can re-lower the
            # exact program later (donated buffers are consumed, so keep
            # shapes only; shapes cannot change without recompiling
            # _step_fn anyway)
            self._last_avals = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args)
        if self._scaler is not None:
            (L, new_vals, self._opt_state, self._key_dev, self._t_dev, aux,
             self._scaler_dev) = self._step_fn(*args)
        else:
            L, new_vals, self._opt_state, self._key_dev, self._t_dev, aux = \
                self._step_fn(*args)
        self._train_vals = new_vals
        for n, v in aux.items():
            if n in self._train_set:
                self._train_vals[n] = v
            else:
                self._frozen_vals[n] = v
        return NDArray(L)

    def cost_analysis(self):
        """XLA ``cost_analysis`` of the exact compiled step program
        (flops, bytes accessed) — the honest-MFU/roofline denominator.
        Requires at least one prior call; re-lowers from the stashed
        operand avals (compilation-cache hit when nothing changed)."""
        avals = getattr(self, "_last_avals", None)
        if avals is None:
            raise MXNetError("call the step once before cost_analysis()")
        c = self._step_fn.lower(*avals).compile().cost_analysis()
        if isinstance(c, (list, tuple)):
            c = c[0]
        return c

    def _current_lr(self):
        opt = self._optimizer
        if opt.lr_scheduler is not None:
            return opt.lr_scheduler(self._t)
        return opt.lr

    # ------------------------------------------------------------- sync out
    def sync_params(self):
        """Write device values back into the net's Parameters (for eval /
        checkpointing through the normal Gluon APIs)."""
        vals = self._values  # one merged snapshot, not one per param
        for n, p in self._params:
            p._data._rebind(vals[n])

    @property
    def loss_scale(self):
        """Current dynamic loss scale (1.0 without float16 AMP). Reads
        device state — cold path only, never call per step."""
        if self._scaler is None:
            return 1.0
        if self._scaler_dev is None:
            return float(self._scaler.loss_scale)
        return float(self._scaler_dev[0])

    def scaler_stats(self) -> dict:
        """Device-carried scaler accounting (host sync; cold path):
        current scale, consecutive clean steps, total skipped steps."""
        if self._scaler is None:
            return {"loss_scale": 1.0, "clean_streak": 0,
                    "skipped_steps": 0}
        if self._scaler_dev is None:
            return {"loss_scale": float(self._scaler.loss_scale),
                    "clean_streak": 0, "skipped_steps": 0}
        s, good, skips = self._scaler_dev
        return {"loss_scale": float(s), "clean_streak": int(good),
                "skipped_steps": int(skips)}

    # ------------------------------------------------------- memory planning
    def memory_analysis(self, signature=None) -> dict:
        """XLA ``memory_analysis`` of the exact compiled step executable —
        the HBM planning numbers: argument/output/temp/alias bytes plus a
        peak estimate (``argument + output + temp - alias``; donated
        buffers appear in ``alias_bytes`` and are not double-counted).

        With no argument, analyzes the signature of the last dispatch.
        Pass one warmup-style signature (per-array specs for ``(input0,
        ..., label)``, global unsplit shapes — see ``warmup``) to cost a
        HYPOTHETICAL batch without running or materializing it;
        ``plan_batch``/``tools/hbm_plan.py`` walk bucket menus this way.
        Re-lowering an already-built program is a compilation-cache hit.
        """
        if signature is None:
            avals = getattr(self, "_last_avals", None)
            if avals is None:
                raise MXNetError(
                    "call the step once (or pass a signature) before "
                    "memory_analysis()")
        else:
            avals = self._signature_avals(signature)
        compiled = self._step_fn.lower(*avals).compile()
        ma = compiled.memory_analysis()
        if ma is None:
            raise MXNetError(
                "this backend exposes no compiled memory analysis")
        out = {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        }
        out["peak_bytes_estimate"] = (
            out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]
            - out["alias_bytes"])
        if self._mesh is not None:
            # XLA's analysis reports LOGICAL (global) sizes on this path;
            # the mesh splits arguments/temps across its devices, so one
            # device's working set is ~peak/mesh.size — the figure
            # plan_batch bisects against the per-device HBM budget
            n = int(self._mesh.size)
            out["mesh_devices"] = n
            out["peak_bytes_per_shard"] = out["peak_bytes_estimate"] // n
        limit = _tel.hbm_limit_bytes()
        out["hbm_limit_bytes"] = limit
        peak = out.get("peak_bytes_per_shard",
                       out["peak_bytes_estimate"])
        out["hbm_headroom_bytes"] = (
            limit - peak if limit is not None else None)
        return out

    def _signature_avals(self, signature):
        """Abstract operand avals for ONE global batch signature: the
        batch/label specs get the leading step/accum split axes exactly
        as ``_stage`` would apply them; every other operand's aval comes
        from the live state."""
        specs = [_cc.normalize_spec(s) for s in signature]
        n, lead = self._split_n, self._lead

        def _split_aval(shape, dtype):
            if n > 1:
                if shape[0] % n:
                    raise MXNetError(
                        f"signature batch dim {shape[0]} must divide the "
                        f"leading split factor {n} "
                        "(steps_per_call * grad_accum)")
                shape = lead + (shape[0] // n,) + tuple(shape[1:])
            return jax.ShapeDtypeStruct(tuple(shape), dtype)

        arrs = [_split_aval(sh, dt) for sh, dt in specs]
        batch, label = tuple(arrs[:-1]), arrs[-1]

        def aval(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        if getattr(self, "_key_dev", None) is not None:
            key_aval = aval(self._key_dev)
        else:
            # shape/dtype of the key the first dispatch will draw, without
            # advancing any RNG state (impl set by MXNET_TPU_PRNG)
            key_aval = jax.eval_shape(lambda: jax.random.PRNGKey(0))
        scalar_f = jax.ShapeDtypeStruct((), jnp.float32)
        scalar_i = jax.ShapeDtypeStruct((), jnp.int32)
        args = (
            jax.tree.map(aval, self._train_vals),
            jax.tree.map(aval, self._frozen_vals),
            jax.tree.map(aval, self._opt_state),
            batch, label, key_aval, scalar_f, scalar_i, scalar_f,
        )
        if self._scaler is not None:
            args = args + ((scalar_f, scalar_i, scalar_i),)
        return args

    # ------------------------------------------------------------ state dict
    def _struct_names(self):
        """global param name -> structural name ("0.weight"): stable
        across processes, unlike the auto-incrementing global prefix
        (hybridsequential0_...), mirroring ``Block.save_parameters``."""
        cached = getattr(self, "_struct_cache", None)
        if cached is not None:
            return cached
        byid = {id(p): n for n, p in self._params}
        out = {}
        for sname, p in self._net._collect_params_with_prefix().items():
            g = byid.get(id(p))
            if g is not None and g not in out:
                out[g] = sname
        for n, p in self._params:  # safety: anything structurally hidden
            out.setdefault(n, n)
        self._struct_cache = out
        return out

    def state_dict(self) -> dict:
        """Full resumable state: parameter values, optimizer moments, the
        device-carried PRNG key and step counter — keyed by STRUCTURAL
        parameter names so a fresh process (different global prefixes)
        restores cleanly. The reference's equivalent contract is
        Trainer.save_states + net params (``python/mxnet/gluon/trainer.py``
        [unverified]); here ONE dict covers the whole fused step so a
        killed run loses nothing."""
        s = self._struct_names()
        # snapshot with fresh buffers (sharding preserved): the live ones
        # are donated to XLA by the next __call__, which would leave the
        # returned dict holding deleted arrays
        cp = jnp.copy
        sd = {
            "values": {s[n]: cp(v) for n, v in self._values.items()},
            "opt_state": {s[n]: tuple(cp(x) for x in st)
                          for n, st in self._opt_state.items()},
            "t_host": self._t,
        }
        if getattr(self, "_key_dev", None) is not None:
            sd["key"] = cp(self._key_dev)
            sd["t_dev"] = cp(self._t_dev)
        if getattr(self, "_scaler_dev", None) is not None:
            sd["scaler"] = tuple(cp(x) for x in self._scaler_dev)
        return sd

    def load_state_dict(self, sd: dict):
        """Restore ``state_dict()`` output, re-placing every array onto
        THIS step's mesh/shardings (resharding from a different layout is
        fine — device_put moves arbitrary source placements)."""
        def _place(name, v):
            if self._param_sharding is not None:
                return _sharding.device_put_donatable(
                    v, self._param_sharding(name))
            return jnp.asarray(v)

        s = self._struct_names()
        gname = {v: k for k, v in s.items()}
        vals = sd["values"]
        missing = [n for n, _ in self._params if s[n] not in vals]
        if missing:
            raise MXNetError(
                f"state_dict missing parameters: {missing[:5]}")
        self._values = {gname[sn]: _place(gname[sn], v)
                        for sn, v in vals.items() if sn in gname}
        self._opt_state = {
            gname[sn]: tuple(_place(gname[sn], x) for x in st)
            for sn, st in sd["opt_state"].items() if sn in gname
        }
        self._t = int(sd["t_host"])
        if "key" in sd:
            repl = (NamedSharding(self._mesh, PartitionSpec())
                    if self._mesh is not None else None)

            def _repl(v):
                v = jnp.asarray(v)
                return _sharding.device_put_donatable(v, repl) \
                    if repl is not None else v

            self._key_dev = _repl(sd["key"])
            self._t_dev = _repl(sd["t_dev"])
        else:
            self._key_dev = None
            self._t_dev = None
        if self._scaler is not None and "scaler" in sd:
            repl2 = (NamedSharding(self._mesh, PartitionSpec())
                     if self._mesh is not None else None)
            self._scaler_dev = tuple(
                jax.device_put(jnp.asarray(x), repl2) if repl2 is not None
                else jnp.asarray(x) for x in sd["scaler"])
        # derived scalar memos are stale now
        self._lr_host = None
        self._rescale_host = None

    # ------------------------------------------------------- sharded on-disk
    def _flat_state(self):
        s = self._struct_names()
        flat = {"meta/t_dev": getattr(self, "_t_dev", None),
                "meta/key": getattr(self, "_key_dev", None)}
        flat = {k: v for k, v in flat.items() if v is not None}
        if getattr(self, "_scaler_dev", None) is not None:
            for i, x in enumerate(self._scaler_dev):
                flat[f"meta/scaler{i}"] = x
        for n, v in self._values.items():
            flat[f"values/{s[n]}"] = v
        for n, st in self._opt_state.items():
            for i, x in enumerate(st):
                flat[f"opt/{i}/{s[n]}"] = x
        return flat

    def save_checkpoint(self, directory, step=None):
        """Write a sharded, committed checkpoint of the full step state.

        Every process writes only its addressable shards (no gather — a
        TP-sharded weight is never materialized whole anywhere); call
        from ALL processes. Layout/protocol: ``checkpoint_sharded``."""
        from .. import checkpoint_sharded as cs

        sub = directory if step is None else \
            f"{directory}/step_{int(step)}"
        s = self._struct_names()
        return cs.save_sharded(
            sub, self._flat_state(),
            extra={"t_host": self._t,
                   "train_names": [s[n] for n in self._train_names]})

    def load_checkpoint(self, directory, step=None):
        """Restore ``save_checkpoint`` output onto THIS step's mesh.

        The saved mesh/process layout may differ: each process assembles
        exactly the shards the current placement makes addressable."""
        from .. import checkpoint_sharded as cs
        import json as _json
        import os as _os

        sub = directory if step is None else \
            f"{directory}/step_{int(step)}"
        with open(_os.path.join(sub, "ckpt_meta.json")) as f:
            meta = _json.load(f)

        gname = {v: k for k, v in self._struct_names().items()}

        def sharding_for(flat_name):
            if flat_name.startswith(("values/", "opt/")):
                pname = flat_name.split("/", 1)[1]
                if flat_name.startswith("opt/"):
                    pname = pname.split("/", 1)[1]
                if self._param_sharding is not None:
                    return self._param_sharding(gname.get(pname, pname))
                return None
            if self._mesh is not None:
                return NamedSharding(self._mesh, PartitionSpec())
            return None

        flat = cs.load_sharded(sub, sharding_for)
        sd = {"values": {}, "opt_state": {},
              "t_host": meta["extra"]["t_host"]}
        nstates = {}
        scaler_parts = {}
        for k, v in flat.items():
            if k.startswith("values/"):
                sd["values"][k[7:]] = v
            elif k.startswith("opt/"):
                i, pname = k[4:].split("/", 1)
                nstates.setdefault(pname, {})[int(i)] = v
            elif k == "meta/key":
                sd["key"] = v
            elif k == "meta/t_dev":
                sd["t_dev"] = v
            elif k.startswith("meta/scaler"):
                scaler_parts[int(k[len("meta/scaler"):])] = v
        if scaler_parts:
            sd["scaler"] = tuple(scaler_parts[i]
                                 for i in sorted(scaler_parts))
        sd["opt_state"] = {
            n: tuple(st[i] for i in sorted(st))
            for n, st in nstates.items()
        }
        for n in meta["extra"]["train_names"]:
            sd["opt_state"].setdefault(n, ())
        if "key" not in sd and "t_dev" in sd:
            del sd["t_dev"]
        self.load_state_dict(sd)
        return meta.get("extra", {})

    # --------------------------------------------------------- Trainer interop
    def export_trainer_states(self, trainer):
        """Hand this step's optimizer moments to a Gluon ``Trainer`` over
        the SAME parameters, so training can continue on the eager
        per-param path (reference Trainer.save_states contract). Call
        ``sync_params()`` separately for the weights."""
        name_of = {id(p): n for n, p in self._params}
        if not trainer._kv_initialized:
            trainer._init_kvstore()
        updater = trainer._updaters[0]
        opt = updater.optimizer
        vals = self._values  # one merged snapshot, not one per param
        for i, p in enumerate(trainer._params):
            n = name_of.get(id(p))
            if n is None or n not in self._opt_state:
                continue
            if getattr(opt, "multi_precision", False) and \
                    vals[n].dtype == jnp.float16:
                # Trainer's multi-precision state is (inner_state,
                # fp32_master) — a flat moment tuple here would be
                # unpacked as (state, master) and DESTROY the weight.
                # TrainStep's AMP scheme (compute_dtype) keeps f32
                # masters itself, so this handoff has no meaning.
                raise MXNetError(
                    "export_trainer_states: multi_precision Trainer over "
                    "fp16 params is not interoperable with TrainStep "
                    "state; use a non-multi_precision optimizer or "
                    "TrainStep(compute_dtype=...) AMP")
            st = tuple(NDArray(s.astype(vals[n].dtype))
                       for s in self._opt_state[n])
            if len(st) == 0:
                updater.states[i] = None
            elif len(st) == 1:
                updater.states[i] = st[0]
            else:
                updater.states[i] = st
            updater.states_synced[i] = True
            opt._index_update_count[i] = self._t
        opt.num_update = max(opt.num_update, self._t)

    def import_trainer_states(self, trainer):
        """Adopt moments from a ``Trainer`` that trained the SAME
        parameters (the reverse direction: eager warmup, then switch to
        the fused sharded step)."""
        name_of = {id(p): n for n, p in self._params}
        updater = trainer._updaters[0]
        for i, p in enumerate(trainer._params):
            n = name_of.get(id(p))
            if n is None or n not in self._opt_state:
                continue
            st = updater.states.get(i)
            if st is None:
                continue
            st = st if isinstance(st, tuple) else (st,)
            if any(isinstance(x, (tuple, list)) for x in st):
                # (inner_state, fp32_master) — multi_precision layout
                raise MXNetError(
                    "import_trainer_states: multi_precision Trainer "
                    "states ((state, master) pairs) are not supported; "
                    "TrainStep keeps its own f32 masters via "
                    "compute_dtype AMP")
            want = len(self._opt_state[n])
            if len(st) != want:
                raise MXNetError(
                    f"optimizer state arity mismatch for {n}: trainer has "
                    f"{len(st)}, step expects {want} (same optimizer?)")
            placed = []
            for s_new, s_old in zip(st, self._opt_state[n]):
                v = s_new.data if isinstance(s_new, NDArray) else \
                    jnp.asarray(s_new)
                v = v.astype(s_old.dtype)
                if self._param_sharding is not None:
                    v = _sharding.device_put_donatable(
                        v, self._param_sharding(n))
                placed.append(v)
            self._opt_state[n] = tuple(placed)
        t = int(trainer._optimizer.num_update)
        if t:
            self._t = t
            if getattr(self, "_t_dev", None) is not None:
                self._t_dev = jnp.asarray(self._t_dev * 0 + t)
