"""SPMD sharding spine: process-global device Mesh + declarative rules.

This module owns the answers to "which devices?" and "how is every array
placed?" for the whole execution layer — the GSPMD-native replacement for
the reference's host-side data parallelism (KVStore push/pull per step,
``src/kvstore/comm*.h``):

- **Process-global Mesh.** ``global_mesh()`` is the mesh every
  ``TrainStep``/``InferStep`` built without an explicit ``mesh=`` picks
  up. Configure it programmatically (``set_global_mesh``) or from the
  environment: ``MXTPU_MESH=data=4`` / ``2x2`` / ``auto``. CPU rigs
  simulate any mesh via
  ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (the test
  suite's 8-device virtual mesh).

- **Declarative ShardingRules.** One object maps every pytree the jitted
  steps carry — parameters, optimizer state, batch inputs — to
  ``NamedSharding``/``PartitionSpec``: replicated params (classic data
  parallel), FSDP/ZeRO-style parameter+optimizer sharding (each param's
  largest divisible axis sharded over ``fsdp_axis``, so a model larger
  than one chip's HBM trains and serves), and explicit name-pattern
  rules for tensor-parallel placements. Presets resolve from strings
  (``'fsdp'``, ``'replicated'``, ``'fsdp:model'``) or from the
  ``MXTPU_SHARDING`` env var.

- **Placement + accounting helpers.** ``place_params`` puts a value tree
  on the mesh under the rules; ``shard_summary`` reports total vs
  per-shard parameter bytes and an allreduce/allgather traffic estimate,
  publishing the ``shard/`` telemetry family
  (``mx.telemetry.report()`` / ``tools/telemetry_report.py``).

Silent-fallback honesty: ``param_explain`` returns WHY a param got its
spec (matched rule, fsdp, or a replication fallback with the reason);
``tools/check_sharding.py`` lints that every param entering the jitted
step carries its declared sharding and that no rule silently degraded to
full replication.

Env knobs: ``MXTPU_MESH`` (mesh axes), ``MXTPU_SHARDING`` (rules
preset), ``MXTPU_FSDP_MIN_SIZE`` (elements below which a param stays
replicated, default 1024).
"""

from __future__ import annotations

import os
import re
import threading
from typing import Dict, Optional, Sequence, Tuple, Union

import jax
import numpy as _np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError
from .. import telemetry as _tel

__all__ = [
    "ShardingRules",
    "device_put_donatable",
    "parse_mesh_spec",
    "make_global_mesh",
    "global_mesh",
    "set_global_mesh",
    "reset_global_mesh",
    "mesh_shape_str",
    "mesh_spans_processes",
    "default_rules",
    "place_params",
    "shard_summary",
    "publish_shard_metrics",
]

DEFAULT_FSDP_MIN_SIZE = 1024


def _fsdp_min_size_default() -> int:
    v = os.environ.get("MXTPU_FSDP_MIN_SIZE", "").strip()
    try:
        return int(v) if v else DEFAULT_FSDP_MIN_SIZE
    except ValueError:
        return DEFAULT_FSDP_MIN_SIZE


# ------------------------------------------------------------- global mesh
def parse_mesh_spec(spec: Optional[str]) -> Optional[Dict[str, int]]:
    """Parse a ``MXTPU_MESH``-style mesh spec into ``{axis: size}``.

    Accepted forms: ``"data=4"`` / ``"data=2,model=2"`` (explicit axes),
    ``"4"`` (one ``data`` axis), ``"2x2"`` (``data`` x ``model``),
    ``"auto"``/``"data"`` (one ``data`` axis over ALL visible devices,
    size resolved at mesh build). ``None``/``""``/``"0"``/``"off"`` ->
    None (no mesh)."""
    if spec is None:
        return None
    s = str(spec).strip().lower()
    if s in ("", "0", "off", "none", "false"):
        return None
    if s in ("auto", "data"):
        return {"data": -1}
    if "=" in s:
        axes: Dict[str, int] = {}
        for part in s.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise MXNetError(f"bad mesh spec segment {part!r} in {spec!r}")
            name, _, size = part.partition("=")
            axes[name.strip()] = int(size)
        if not axes:
            raise MXNetError(f"empty mesh spec {spec!r}")
        return axes
    if "x" in s:
        d, _, m = s.partition("x")
        return {"data": int(d), "model": int(m)}
    return {"data": int(s)}


def make_global_mesh(axes: Union[None, str, Dict[str, int]] = None,
                     devices=None) -> Mesh:
    """Build a mesh from a spec, using the FIRST ``prod(sizes)`` visible
    devices — so a 4-device mesh is constructible on the 8-device test
    rig (the "forced 4-device CPU mesh" of the sharding tests). An axis
    size of ``-1`` absorbs all remaining devices."""
    if isinstance(axes, str) or axes is None:
        axes = parse_mesh_spec(axes if axes is not None
                               else os.environ.get("MXTPU_MESH"))
    if axes is None:
        axes = {"data": -1}
    if devices is None:
        devices = jax.devices()
    sizes = dict(axes)
    fill = [k for k, v in sizes.items() if v == -1]
    if len(fill) > 1:
        raise MXNetError(f"at most one mesh axis may be -1, got {axes}")
    fixed = 1
    for k, v in sizes.items():
        if v != -1:
            if v < 1:
                raise MXNetError(f"mesh axis {k} must be >= 1, got {v}")
            fixed *= v
    if fill:
        if len(devices) % fixed:
            raise MXNetError(
                f"mesh axes {axes}: {len(devices)} devices not divisible "
                f"by the fixed axes product {fixed}")
        sizes[fill[0]] = len(devices) // fixed
    total = 1
    for v in sizes.values():
        total *= v
    if total > len(devices):
        raise MXNetError(
            f"mesh axes {sizes} need {total} devices but only "
            f"{len(devices)} are visible (CPU rigs: XLA_FLAGS="
            f"--xla_force_host_platform_device_count={total})")
    dev_array = _np.array(devices[:total]).reshape(list(sizes.values()))
    return Mesh(dev_array, tuple(sizes.keys()))


_GLOBAL_LOCK = threading.Lock()
_GLOBAL = {"mesh": None, "explicit": False, "env_checked": False}


def set_global_mesh(mesh: Optional[Mesh]):
    """Pin the process-global mesh every step built without ``mesh=``
    adopts. ``None`` pins "no mesh" (overriding ``MXTPU_MESH``)."""
    with _GLOBAL_LOCK:
        _GLOBAL["mesh"] = mesh
        _GLOBAL["explicit"] = True
    if mesh is not None:
        _tel.set_info(mesh_shape=mesh_shape_str(mesh))


def reset_global_mesh():
    """Forget any pinned/env-derived global mesh (tests; re-reads
    ``MXTPU_MESH`` on the next ``global_mesh()`` call)."""
    with _GLOBAL_LOCK:
        _GLOBAL["mesh"] = None
        _GLOBAL["explicit"] = False
        _GLOBAL["env_checked"] = False


def global_mesh() -> Optional[Mesh]:
    """The process-global mesh: the one ``set_global_mesh`` pinned, else
    one built from ``MXTPU_MESH`` on first call, else None."""
    with _GLOBAL_LOCK:
        if _GLOBAL["explicit"]:
            return _GLOBAL["mesh"]
        if not _GLOBAL["env_checked"]:
            _GLOBAL["env_checked"] = True
            axes = parse_mesh_spec(os.environ.get("MXTPU_MESH"))
            if axes is not None:
                _GLOBAL["mesh"] = make_global_mesh(axes)
        return _GLOBAL["mesh"]


def mesh_shape_str(mesh: Optional[Mesh]) -> Optional[str]:
    """``"data=4,model=2"`` rendering for telemetry/bench rows."""
    if mesh is None:
        return None
    return ",".join(f"{k}={v}" for k, v in mesh.shape.items())


def mesh_spans_processes(mesh: Optional[Mesh] = None) -> bool:
    """True when the (given or global) mesh covers every process in a
    multi-process run — in-graph collectives then OWN cross-process
    gradient sync, and the host-side KVStore allreduce loop is redundant
    (``Trainer._allreduce_grads`` skips it)."""
    if mesh is None:
        mesh = global_mesh()
    if mesh is None:
        return False
    nproc = jax.process_count()
    if nproc <= 1:
        return False
    try:
        procs = {d.process_index for d in mesh.devices.flat}
    except Exception:  # noqa: BLE001 - exotic device objects
        return False
    return len(procs) >= nproc


# ----------------------------------------------------------- sharding rules
def _size(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def fsdp_partition_spec(shape, axis: str, axis_size: int) -> PartitionSpec:
    """FSDP placement for one param: shard the LARGEST dim divisible by
    ``axis_size`` over ``axis`` (ties -> first). ``P()`` when no dim
    divides — the caller decides whether that fallback is acceptable."""
    best, best_dim = -1, None
    for i, d in enumerate(shape):
        d = int(d)
        if d >= axis_size and d % axis_size == 0 and d > best:
            best, best_dim = d, i
    if best_dim is None:
        return PartitionSpec()
    parts = [None] * len(shape)
    parts[best_dim] = axis
    # drop trailing Nones: jax canonicalizes them away in out_shardings,
    # so the declared spec must match the canonical form bit-for-bit
    return PartitionSpec(*parts[:best_dim + 1])


class ShardingRules:
    """Declarative placement registry for the jitted steps' pytrees.

    Parameters
    ----------
    batch_spec : PartitionSpec or None — placement for every batch
        input/label (None: ``P('data')`` when the mesh has a data axis,
        else replicated). Per-input sequences stay on the step's
        ``data_spec=`` argument.
    rules : [(regex, PartitionSpec)] — explicit name-pattern placements
        (tensor parallel etc.); first match wins, checked before the
        default policy.
    params : 'replicate' | 'fsdp' — default policy for params that match
        no rule. ``'fsdp'`` shards each param's largest divisible axis
        over ``fsdp_axis`` (optimizer moments follow their param — the
        ZeRO contract).
    fsdp_axis : mesh axis FSDP shards over (default ``'data'``).
    fsdp_min_size : params with fewer elements stay replicated (env
        default ``MXTPU_FSDP_MIN_SIZE``, 1024) — sharding tiny biases
        buys nothing and costs collectives.
    """

    def __init__(self, batch_spec: Optional[PartitionSpec] = None,
                 rules: Sequence[Tuple[str, PartitionSpec]] = (),
                 params: str = "replicate", fsdp_axis: str = "data",
                 fsdp_min_size: Optional[int] = None):
        if params not in ("replicate", "fsdp"):
            raise MXNetError(
                f"params policy must be 'replicate' or 'fsdp', got "
                f"{params!r}")
        self.batch_spec = batch_spec
        self.rules = [(pat, spec) for pat, spec in rules]
        self._compiled = [(re.compile(pat), spec) for pat, spec in rules]
        self.params = params
        self.fsdp_axis = fsdp_axis
        self.fsdp_min_size = (int(fsdp_min_size) if fsdp_min_size is not None
                              else _fsdp_min_size_default())

    # ------------------------------------------------------------ presets
    @classmethod
    def replicated(cls, **kw) -> "ShardingRules":
        """Params/optimizer state replicated, batch over ``data`` —
        classic in-graph data parallelism (grad psum by GSPMD)."""
        return cls(params="replicate", **kw)

    # batch-sharded + replicated params IS data parallelism; alias
    data_parallel = replicated

    @classmethod
    def fsdp(cls, axis: str = "data", min_size: Optional[int] = None,
             **kw) -> "ShardingRules":
        """ZeRO/FSDP: params + optimizer moments sharded over ``axis``,
        batch over ``data`` — a model larger than one chip's HBM trains
        and serves; GSPMD inserts the gather/reduce-scatter collectives."""
        return cls(params="fsdp", fsdp_axis=axis, fsdp_min_size=min_size,
                   **kw)

    @classmethod
    def from_string(cls, preset: str) -> "ShardingRules":
        s = str(preset).strip().lower()
        if s in ("replicated", "replicate", "dp", "data_parallel"):
            return cls.replicated()
        if s == "fsdp":
            return cls.fsdp()
        if s.startswith("fsdp:"):
            return cls.fsdp(axis=s.split(":", 1)[1])
        raise MXNetError(
            f"unknown sharding preset {preset!r}; use 'replicated', "
            "'fsdp', or 'fsdp:<axis>' (or pass a ShardingRules)")

    @classmethod
    def resolve(cls, obj) -> Optional["ShardingRules"]:
        """``sharding=`` argument coercion: None -> the ``MXTPU_SHARDING``
        env default (None when unset), str -> preset, rules -> itself."""
        if obj is None:
            return default_rules()
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, str):
            return cls.from_string(obj)
        raise MXNetError(
            f"sharding must be a ShardingRules, preset string or None, "
            f"got {type(obj).__name__}")

    # ---------------------------------------------------------- resolution
    def param_explain(self, name: str, shape, mesh: Optional[Mesh]
                      ) -> Tuple[PartitionSpec, str]:
        """(spec, reason) for one param — the reason string is the lint's
        evidence trail: ``rule:<pattern>``, ``fsdp``, or a
        ``replicated:*`` fallback explaining why."""
        for pat, spec in self._compiled:
            if pat.search(name):
                return spec, f"rule:{pat.pattern}"
        if self.params == "fsdp":
            if mesh is None or self.fsdp_axis not in mesh.shape:
                return PartitionSpec(), "replicated:no_fsdp_axis"
            n = int(mesh.shape[self.fsdp_axis])
            if n <= 1:
                return PartitionSpec(), "replicated:axis_size_1"
            if _size(shape) < self.fsdp_min_size:
                return PartitionSpec(), "replicated:small"
            spec = fsdp_partition_spec(shape, self.fsdp_axis, n)
            if spec == PartitionSpec():
                return spec, "replicated:indivisible"
            return spec, "fsdp"
        return PartitionSpec(), "replicated:default"

    def param_spec(self, name: str, shape,
                   mesh: Optional[Mesh]) -> PartitionSpec:
        return self.param_explain(name, shape, mesh)[0]

    def param_sharding(self, mesh: Mesh, name: str, shape) -> NamedSharding:
        return NamedSharding(mesh, self.param_spec(name, shape, mesh))

    def batch_partition_spec(self, mesh: Mesh) -> PartitionSpec:
        if self.batch_spec is not None:
            return self.batch_spec
        return PartitionSpec("data") if "data" in mesh.axis_names \
            else PartitionSpec()

    # ----------------------------------------------------------- reporting
    def label(self) -> str:
        base = f"fsdp({self.fsdp_axis})" if self.params == "fsdp" \
            else "replicated"
        return f"{base}+{len(self.rules)}rules" if self.rules else base

    def describe(self) -> dict:
        return {
            "params": self.params,
            "fsdp_axis": self.fsdp_axis,
            "fsdp_min_size": self.fsdp_min_size,
            "rules": [pat for pat, _ in self.rules],
            "batch_spec": (None if self.batch_spec is None
                           else str(self.batch_spec)),
        }


_ENV_RULES = {"checked": False, "rules": None}


def default_rules() -> Optional[ShardingRules]:
    """The ``MXTPU_SHARDING`` process default (None when unset/off)."""
    if not _ENV_RULES["checked"]:
        _ENV_RULES["checked"] = True
        s = os.environ.get("MXTPU_SHARDING", "").strip().lower()
        if s and s not in ("0", "off", "none", "false"):
            _ENV_RULES["rules"] = ShardingRules.from_string(s)
    return _ENV_RULES["rules"]


def reset_default_rules():
    """Forget the cached env-derived rules (tests)."""
    _ENV_RULES["checked"] = False
    _ENV_RULES["rules"] = None


# ------------------------------------------------------ placement helpers
def device_put_donatable(x, sharding):
    """``device_put`` that never aliases the source's buffers.

    Plain ``device_put`` may reuse an already-in-place per-device buffer
    of the SOURCE array inside the result (e.g. the device-0 replica
    when replicating a single-device param over a mesh). Donating such a
    result to a jitted step then invalidates the source too — the net's
    live Parameter dies on the first training step (measured on the CPU
    backend; ``may_alias=False`` is NOT honored on this path in the
    pinned jax). Placement of any state that will be DONATED goes
    through here: jax-array sources get an explicit post-placement copy
    (fresh buffers, sharding preserved; build-time cost only)."""
    placed = jax.device_put(x, sharding)
    if isinstance(x, jax.Array):
        import jax.numpy as jnp

        placed = jnp.copy(placed)
    return placed


def place_params(values: Dict[str, jax.Array], mesh: Mesh,
                 rules: ShardingRules) -> Dict[str, jax.Array]:
    """device_put a name->array tree under the rules' param placements."""
    return {
        n: jax.device_put(
            v, rules.param_sharding(mesh, n, _np.shape(v)))
        for n, v in values.items()
    }


def _shard_bytes(v) -> int:
    """Bytes ONE device holds for this array (its shard, or the full
    array when replicated/single-device)."""
    itemsize = _np.dtype(v.dtype).itemsize
    sh = getattr(v, "sharding", None)
    if sh is None:
        return _size(v.shape) * itemsize
    try:
        return _size(sh.shard_shape(v.shape)) * itemsize
    except Exception:  # noqa: BLE001 - sharding types without shard_shape
        return _size(v.shape) * itemsize


def shard_summary(values: Dict[str, jax.Array], mesh: Optional[Mesh],
                  trainable: Optional[Sequence[str]] = None) -> dict:
    """Parameter placement accounting: global vs per-shard bytes, how
    many params are actually partitioned, and a per-step collective
    traffic estimate (ring-allreduce ``2(n-1)/n * grad bytes`` for
    replicated params; ``3(n-1)/n * param bytes`` — allgather fwd+bwd +
    reduce-scatter — for sharded params)."""
    total = 0
    per_shard = 0
    sharded = 0
    replicated = 0
    train = set(trainable) if trainable is not None else None
    coll = 0.0
    n = int(mesh.size) if mesh is not None else 1
    for name, v in values.items():
        b = _size(v.shape) * _np.dtype(v.dtype).itemsize
        sb = _shard_bytes(v)
        total += b
        per_shard += sb
        partitioned = sb < b
        if partitioned:
            sharded += 1
        else:
            replicated += 1
        if train is None or name in train:
            if n > 1:
                coll += (3.0 if partitioned else 2.0) * b * (n - 1) / n
    return {
        "mesh_shape": mesh_shape_str(mesh),
        "mesh_devices": n,
        "param_bytes_total": int(total),
        "param_bytes_per_shard": int(per_shard),
        "params_sharded": sharded,
        "params_replicated": replicated,
        "collective_bytes_per_step_est": int(coll),
    }


def publish_shard_metrics(values: Dict[str, jax.Array],
                          mesh: Optional[Mesh],
                          rules: Optional[ShardingRules] = None,
                          trainable: Optional[Sequence[str]] = None) -> dict:
    """Compute ``shard_summary`` and publish it as the ``shard/`` metric
    family + ``mesh_shape``/``sharding`` run info (surfaced by
    ``mx.telemetry.report()`` and ``tools/telemetry_report.py``)."""
    s = shard_summary(values, mesh, trainable)
    reg = _tel.registry()
    reg.gauge("shard/mesh_devices").set(s["mesh_devices"])
    reg.gauge("shard/param_bytes_total").set(s["param_bytes_total"])
    reg.gauge("shard/param_bytes_per_shard").set(s["param_bytes_per_shard"])
    reg.gauge("shard/params_sharded").set(s["params_sharded"])
    reg.gauge("shard/params_replicated").set(s["params_replicated"])
    reg.gauge("shard/collective_bytes_per_step_est").set(
        s["collective_bytes_per_step_est"])
    _tel.set_info(mesh_shape=s["mesh_shape"],
                  sharding=rules.label() if rules is not None else None)
    return s
