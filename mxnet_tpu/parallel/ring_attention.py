"""Ring attention: sequence/context parallelism over an ICI mesh axis.

Beyond-reference capability (SURVEY.md §5: the reference's long-context
ceiling was the O(L²) interleaved attention of
``src/operator/contrib/transformer.cc`` [unverified] plus bucketing) — here
the sequence dimension is sharded over a mesh axis and K/V blocks rotate
around the ring via ``ppermute`` while each device's flash kernel consumes
them blockwise. Per-device memory is O(S/n); the full sequence never
materializes on any chip.

Design (Liu et al. 2023 "Ring Attention with Blockwise Transformers"; the
public-domain recipe, reimplemented here on this repo's own flash kernel):

forward   n-1 neighbor ppermutes; each step runs the local Pallas flash
          kernel on (q_local, k_visiting, v_visiting) and merges the chunk
          partial into a running (out, lse) with the standard online-softmax
          combine. Causal masking degenerates to a static per-step choice:
          step 0 processes the diagonal chunk (local causal kernel); step
          s>0 processes chunk (i-s) mod n, which is fully visible iff
          i >= s — an all-or-nothing inclusion folded into the lse merge.
backward  one custom_vjp around the whole ring: recompute per visiting
          chunk with the saved GLOBAL lse (the same blockwise-recompute
          scheme as the single-chip flash backward), accumulating dk/dv on
          carriers that travel the ring with their chunks and arrive home
          after n rotations; dq stays local.

Use ``ring_flash_attention(q, k, v, mesh, axis)`` from regular code (wraps
``shard_map``; composes inside jit/TrainStep), or
``ring_flash_attention_shard`` directly inside an existing ``shard_map``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..ops.pallas.flash_attention import _flash_fwd

__all__ = ["ring_flash_attention", "ring_flash_attention_shard"]

_NEG_INF = -1e30


def _merge(acc_out, acc_lse, out_s, lse_s):
    """Online-softmax combine of two normalized partials."""
    m = jnp.maximum(acc_lse, lse_s)
    # guard fully-excluded rows (both -inf): keep weights finite
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    w_acc = jnp.exp(acc_lse - m_safe)[..., None]
    w_s = jnp.exp(lse_s - m_safe)[..., None]
    # floor must be a NORMAL f32: 1e-38 is subnormal and flushes to zero
    # on FTZ backends, turning fully-masked rows into 0/0 = NaN
    new_out = (acc_out * w_acc + out_s * w_s) / jnp.maximum(
        w_acc + w_s, 1e-30
    )
    new_lse = m_safe + jnp.log(jnp.maximum(w_acc + w_s, 1e-30))[..., 0]
    return new_out, new_lse


def _ring_perm(axis_name, n):
    return [(j, (j + 1) % n) for j in range(n)]


def _local_vl(vl, j, s_local):
    """Per-visiting-chunk key budget: chunk j holds GLOBAL key positions
    [j*s_local, (j+1)*s_local), so a row with global valid_length ``vl``
    keeps ``clip(vl - j*s_local, 0, s_local)`` keys of it (the flash
    kernel's local valid_length semantics)."""
    if vl is None:
        return None
    return jnp.clip(vl.astype(jnp.int32) - j * s_local, 0, s_local)


def _ring_fwd(q, k, v, vl, axis_name, causal, sm_scale):
    """Inside shard_map: q/k/v are LOCAL chunks (B, H, S_local, D);
    ``vl`` (B,) is the GLOBAL per-row valid key length (or None)."""
    n = jax.lax.psum(1, axis_name)  # static axis size
    i = jax.lax.axis_index(axis_name)
    perm = _ring_perm(axis_name, n)
    s_local = k.shape[2]

    out0, lse0 = _flash_fwd(q, k, v, _local_vl(vl, i, s_local), causal,
                            sm_scale, 128, 128)
    acc_out = out0.astype(jnp.float32)
    acc_lse = lse0
    k_cur, v_cur = k, v
    for s in range(1, n):
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        j = (i - s) % n  # home index of the chunk visiting at step s
        out_s, lse_s = _flash_fwd(q, k_cur, v_cur, _local_vl(vl, j, s_local),
                                  False, sm_scale, 128, 128)
        if causal:
            include = i >= s  # visiting chunk j=(i-s)%n is fully past iff so
            lse_s = jnp.where(include, lse_s, _NEG_INF)
        # a fully-masked visiting chunk (vl <= j*s_local) contributes
        # nothing: its kernel rows come back with lse == -inf already, so
        # the merge drops them without extra handling
        acc_out, acc_lse = _merge(acc_out, acc_lse, out_s.astype(jnp.float32),
                                  lse_s)
    return acc_out.astype(q.dtype), acc_lse


def _ring_bwd_math(q, k_cur, v_cur, g, out, lse, sm_scale, local_causal,
                   include, vl_local=None):
    """Gradient contributions of one visiting chunk: the single-chip
    blockwise-recompute backward with the GLOBAL lse — O(S_local·block)
    memory, never the full S_local² score matrix."""
    from ..ops.pallas.flash_attention import _flash_bwd_impl

    B = q.shape[0]
    if vl_local is None:
        vl_local = jnp.full((B,), k_cur.shape[2], jnp.int32)
    dq_b, dk_b, dv_b = _flash_bwd_impl(
        q, k_cur, v_cur, vl_local, out, lse, g, local_causal, sm_scale, 128
    )
    if include is not None:  # all-or-nothing chunk inclusion (causal ring)
        dq_b = dq_b * include
        dk_b = dk_b * include
        dv_b = dv_b * include
    return dq_b, dk_b, dv_b


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_flash_attention_shard(q, k, v, axis_name, causal=False,
                               sm_scale=None, valid_length=None):
    """Ring attention over ``axis_name``; call INSIDE shard_map with the
    sequence dimension sharded over that axis. Shapes (B, H, S_local, D);
    ``valid_length`` (B,) GLOBAL key budget per row, or None (placed last
    so positional (q, k, v, axis_name, ...) callers keep working)."""
    out, _ = _ring_fwd(q, k, v, valid_length, axis_name, causal,
                       _scale(sm_scale, q))
    return out


def _scale(sm_scale, q):
    return float(sm_scale) if sm_scale is not None else 1.0 / math.sqrt(
        q.shape[-1]
    )


def _ring_fwd_rule(q, k, v, axis_name, causal, sm_scale, valid_length):
    out, lse = _ring_fwd(q, k, v, valid_length, axis_name, causal,
                         _scale(sm_scale, q))
    return out, (q, k, v, valid_length, out, lse)


def _ring_bwd_rule(axis_name, causal, sm_scale, res, g):
    q, k, v, vl, out, lse = res
    scale = _scale(sm_scale, q)
    n = jax.lax.psum(1, axis_name)
    i = jax.lax.axis_index(axis_name)
    perm = _ring_perm(axis_name, n)
    s_local = k.shape[2]

    # step 0: diagonal chunk (local causal when causal)
    dq0, dk0, dv0 = _ring_bwd_math(
        q, k, v, g, out, lse, scale, local_causal=causal, include=None,
        vl_local=_local_vl(vl, i, s_local),
    )
    dq = dq0.astype(jnp.float32)
    dk_cur = dk0.astype(jnp.float32)
    dv_cur = dv0.astype(jnp.float32)
    k_cur, v_cur = k, v
    for s in range(1, n):
        # rotate chunks AND their grad accumulators together; after the
        # loop's n-1 rotations plus one final rotation they arrive home
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_cur = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = jax.lax.ppermute(dv_cur, axis_name, perm)
        j = (i - s) % n
        include = (i >= s).astype(jnp.float32) if causal else None
        dq_b, dk_b, dv_b = _ring_bwd_math(
            q, k_cur, v_cur, g, out, lse, scale, local_causal=False,
            include=include, vl_local=_local_vl(vl, j, s_local),
        )
        dq = dq + dq_b.astype(jnp.float32)
        dk_cur = dk_cur + dk_b.astype(jnp.float32)
        dv_cur = dv_cur + dv_b.astype(jnp.float32)
    # one more rotation brings accumulators back to their home device
    dk = jax.lax.ppermute(dk_cur, axis_name, perm)
    dv = jax.lax.ppermute(dv_cur, axis_name, perm)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None)


ring_flash_attention_shard.defvjp(_ring_fwd_rule, _ring_bwd_rule)


def _seq_parallel_call(shard_fn, q, k, v, mesh, axis, causal, sm_scale,
                       batch_axis, precheck=None, valid_length=None):
    """Shared wrapper for sequence-parallel attention variants: NDArray
    unwrap/rewrap, batch-axis resolution (shard B over ``batch_axis`` when
    the mesh has it — replicating B over 'data' would silently double
    attention FLOPs per device), and the shard_map plumbing. Composes
    under jit — GSPMD sees an opaque manually-sharded region.

    ``valid_length`` (B,) is the GLOBAL per-row key budget; each variant
    translates it to its own local masking (ring: per-visiting-chunk
    offsets; ulysses: pass-through after the all_to_all)."""
    from ..ndarray.ndarray import NDArray

    unwrap = lambda x: x.data if isinstance(x, NDArray) else x  # noqa: E731
    wrapped = isinstance(q, NDArray)
    q, k, v = unwrap(q), unwrap(k), unwrap(v)
    vl = unwrap(valid_length) if valid_length is not None else None
    if precheck is not None:
        precheck(q)
    b_ax = batch_axis if (batch_axis in mesh.axis_names
                          and batch_axis != axis) else None
    spec = PartitionSpec(b_ax, None, axis, None)
    in_specs = (spec, spec, spec)
    args = (q, k, v)
    if vl is not None:
        in_specs = in_specs + (PartitionSpec(b_ax),)
        args = args + (vl,)

        def inner(q, k, v, vl_):
            return shard_fn(q, k, v, axis_name=axis, causal=causal,
                            sm_scale=sm_scale, valid_length=vl_)
    else:
        inner = functools.partial(shard_fn, axis_name=axis, causal=causal,
                                  sm_scale=sm_scale, valid_length=None)
    fn = shard_map(
        inner,
        mesh=mesh, in_specs=in_specs, out_specs=spec,
        check_vma=False,  # pallas_call out_shapes carry no vma info
    )
    out = fn(*args)
    return NDArray(out) if wrapped else out


def ring_flash_attention(q, k, v, mesh: Mesh, axis: str = "seq",
                         causal=False, sm_scale=None, batch_axis="data",
                         valid_length=None):
    """Sequence-parallel attention over ``mesh`` axis ``axis``.

    q/k/v (B, H, S, D) with S divisible by the axis size; K/V chunks
    rotate around the ring via ppermute (see module docstring).
    ``valid_length`` (B,) int: GLOBAL count of non-padding key positions
    per row (ragged batches). See also ``parallel.ulysses`` for the
    all-to-all variant."""
    return _seq_parallel_call(ring_flash_attention_shard, q, k, v, mesh,
                              axis, causal, sm_scale, batch_axis,
                              valid_length=valid_length)
