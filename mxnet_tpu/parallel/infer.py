"""Whole-model jitted inference step: the serving twin of ``TrainStep``.

Where ``TrainStep`` compiles forward+backward+optimizer into one donated
XLA program, ``InferStep`` compiles the *serving* hot paths:

- ``__call__`` — one jitted predict-mode forward (dropout off, aux state
  frozen) for scoring / encoder workloads (e.g. BERT prefill);
- ``prefill`` + ``decode_n`` — KV-cached autoregressive generation for
  nets speaking the incremental protocol (``net.prefill`` /
  ``net.decode_step``, see ``gluon.model_zoo.transformer``): prefill
  encodes the (bucket-padded) prompt and seeds per-layer
  ``(max_len, B, H, D)`` caches; ``decode_n`` runs a ``lax.while_loop``
  of O(1) incremental steps with the cache DONATED into the loop and an
  early exit once every row has emitted EOS. One jitted dispatch emits up
  to ``max_new_tokens`` tokens — no per-token host round trips
  (``tools/check_no_sync_in_step.py`` lints ``__call__``/``_dispatch``/
  ``decode_n``).

Shape stability reuses the PR-3 machinery: prompts pad to a
``FixedBucketSampler.signatures()``-style bucket menu, ``warmup()``
drives the REAL jitted prefill+decode programs per bucket signature, the
``RecompileGuard`` counts every signature as exactly one compile and
alarms on post-warmup churn, and the persistent compilation cache makes
the programs outlive the process. ``amp='bfloat16'`` casts float params
(minus the ``amp.lists`` norm families) ONCE at build — inference has no
master-weight round trip, so the cast is free after construction.

Env knobs: ``MXTPU_DECODE_MAX_LEN`` (default decode cache capacity, 256).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import compile_cache as _cc
from .. import telemetry as _tel
from . import sharding as _sharding

__all__ = ["InferStep", "decode_max_len"]


def decode_max_len(default: int = 256) -> int:
    """``MXTPU_DECODE_MAX_LEN``: default KV-cache capacity (= prompt-side
    decode slots) for engines built without an explicit ``max_len``."""
    v = os.environ.get("MXTPU_DECODE_MAX_LEN", "").strip()
    try:
        return int(v) if v else default
    except ValueError:
        return default


def _sample_tokens(logits, key, method, top_k, temperature):
    """Next-token draw from (B, V) logits. ``method``/``top_k`` are
    trace-time constants; ``temperature`` is a traced scalar so serving
    can change it without recompiling."""
    if method == "greedy":
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if method == "top_k":
        vals, idx = jax.lax.top_k(logits, top_k)
        draw = jax.random.categorical(key, vals / temperature, axis=-1)
        return jnp.take_along_axis(idx, draw[:, None], axis=1)[:, 0].astype(
            jnp.int32)
    if method == "sample":
        return jax.random.categorical(
            key, logits / temperature, axis=-1).astype(jnp.int32)
    raise MXNetError(f"unknown sampling method {method!r}; "
                     "use greedy/top_k/sample")


class InferStep:
    """Compile a net's inference paths into jitted, shape-stable programs.

    Parameters
    ----------
    net : initialized Gluon Block. Any net gets the jitted ``__call__``
        forward; nets implementing the incremental protocol
        (``prefill(src, tgt_prefix, src_valid_length, max_len)`` +
        ``decode_step(tokens, pos, state)``) additionally get
        ``prefill``/``decode_n``/``generate``.
    mesh / data_spec : optional GSPMD placement for batch inputs; with
        no explicit mesh the process-global one
        (``sharding.global_mesh()`` / ``MXTPU_MESH``) is adopted.
    sharding : ``sharding.ShardingRules``, preset string or None (the
        ``MXTPU_SHARDING`` default). Parameters are placed under the
        rules — ``'fsdp'`` serves a model whose full params exceed one
        chip's HBM (GSPMD gathers shards per layer); default/None keeps
        the replicated-params + sharded-batch serving layout.
    amp : 'bfloat16'/'float16' — cast float params (minus ``amp.lists``
        norm families) once at build; activations follow the param dtype.
    max_len : decode cache capacity (``MXTPU_DECODE_MAX_LEN`` default).
    bos_id / eos_id / pad_id : special token ids for generation.
    """

    def __init__(self, net, mesh: Optional[Mesh] = None,
                 data_spec=None, amp: Optional[str] = None,
                 max_len: Optional[int] = None,
                 bos_id: int = 1, eos_id: int = 2, pad_id: int = 0,
                 sharding=None):
        from .. import amp as _amp_mod

        self._net = net
        rules = _sharding.ShardingRules.resolve(sharding)
        if mesh is None:
            mesh = _sharding.global_mesh()
        self._sharding_rules = rules
        self._mesh = mesh
        self._max_len = int(max_len) if max_len is not None \
            else decode_max_len()
        self._bos, self._eos, self._pad = int(bos_id), int(eos_id), int(pad_id)
        if amp is not None:
            amp = str(amp)
            if amp not in ("bfloat16", "float16"):
                raise MXNetError("amp must be 'bfloat16' or 'float16'")
        self._amp = amp
        self._params = list(net.collect_params().items())
        for name, p in self._params:
            if p._data is None:
                raise MXNetError(
                    f"parameter {name} not initialized; run one forward (or "
                    "initialize with known shapes) before building InferStep")
        fp32_pinned = _amp_mod.fp32_param_names(net) if amp else frozenset()
        cdt = jnp.dtype(amp) if amp else None

        def _cast(name, v):
            # inference AMP: no fp32 masters needed — cast ONCE at build,
            # norm-family params pinned fp32 per amp.lists
            if cdt is not None and name not in fp32_pinned and \
                    jnp.issubdtype(v.dtype, jnp.floating):
                return v.astype(cdt)
            return v

        # param placement: the rules' spec per param (FSDP-sharded
        # serving), else replicated — serving's classic layout
        if mesh is not None:
            if rules is not None:
                def _param_sharding(name, shape):
                    return rules.param_sharding(mesh, name, shape)
            else:
                def _param_sharding(name, shape):
                    return NamedSharding(mesh, PartitionSpec())
        else:
            _param_sharding = None
        self._param_sharding = _param_sharding
        vals = {}
        for name, p in self._params:
            v = _cast(name, p._data.data)
            if _param_sharding is not None:
                v = jax.device_put(v, _param_sharding(name, v.shape))
            vals[name] = v
        # the LIVE param buffer: hot-swap (swap_params) stages a full
        # replacement dict and flips this reference atomically between
        # dispatches — dispatch paths snapshot it once per dispatch so a
        # request's prefill and decode always see one coherent version
        self._values = vals
        self._version_counter = 0
        self._weights_version = "v0"
        self._cache_dtype = cdt
        if mesh is not None:
            _sharding.publish_shard_metrics(vals, mesh, rules)

        # batch placement (mirrors TrainStep's data_spec contract)
        if mesh is not None:
            if data_spec is None:
                data_spec = PartitionSpec("data") \
                    if "data" in mesh.axis_names else PartitionSpec()
            if isinstance(data_spec, (tuple, list)) and not isinstance(
                    data_spec, PartitionSpec):
                self._data_sharding = [NamedSharding(mesh, s)
                                       for s in data_spec]
            else:
                self._data_sharding = NamedSharding(mesh, data_spec)
        else:
            self._data_sharding = None

        # speculative decoding: attach_draft() fills these — the draft
        # engine plus the (target, draft, version) coherent-pair snapshot
        self.draft: Optional["InferStep"] = None
        self._live_pair = None
        self._fwd_tree = [None]  # output treedef captured at trace time
        self._fwd_fn = self._build_forward()
        # predict mode draws no randomness: one fixed key serves every
        # forward dispatch (built here so _dispatch stays pure dispatch)
        self._fixed_key = jax.random.PRNGKey(0)
        self._prefill_fns = {}  # max_len is closed over; keyed by it
        self._decode_fns = {}   # (max_new, method, top_k) -> jitted fn
        self._paged_fns = {}    # paged prefill/decode-iter programs
        self.compile_guard = _cc.RecompileGuard(
            f"InferStep({type(net).__name__})")
        _tel.set_info(amp_dtype=self._amp, infer_engine=type(net).__name__)

    @property
    def supports_decode(self) -> bool:
        return hasattr(self._net, "prefill") and \
            hasattr(self._net, "decode_step")

    @property
    def supports_paged(self) -> bool:
        """Whether the net speaks the PAGED protocol (``prefill_paged`` /
        ``decode_step_paged`` / ``init_paged_state``) — the continuous-
        batching engine path (``serving.ContinuousBatcher``)."""
        return hasattr(self._net, "prefill_paged") and \
            hasattr(self._net, "decode_step_paged") and \
            hasattr(self._net, "init_paged_state")

    @property
    def weights_version(self) -> str:
        """Tag of the param set serving new dispatches. Responses carry
        the version their dispatch ran on (``serving.DynamicBatcher``
        stamps it onto each ``GenerationResult``)."""
        return self._weights_version

    # ---------------------------------------------------------------- build
    def _net_scope(self, values, key):
        """Context stack for tracing the net functionally: params resolve
        to the (cast, device) values, predict mode, supplied PRNG key."""
        import contextlib

        from ..gluon.block import _aux_scope, _trace_scope
        from ..gluon.parameter import param_override
        from .. import autograd
        from .. import random as _random
        from . import mesh_scope as _mesh_scope

        name2p = {n: p for n, p in self._params}
        mapping = {name2p[n]: NDArray(v) for n, v in values.items()}
        stack = contextlib.ExitStack()
        if self._mesh is not None:
            stack.enter_context(_mesh_scope(self._mesh))
        stack.enter_context(param_override(mapping))
        stack.enter_context(_random.key_supply(key))
        stack.enter_context(_aux_scope({}))  # aux writes dropped: predict
        stack.enter_context(_trace_scope())
        stack.enter_context(autograd._scope(False, False))
        return stack

    def _build_forward(self):
        net, tree_holder = self._net, self._fwd_tree

        def fwd(values, batch, key):
            with self._net_scope(values, key):
                out = net(*[NDArray(b) for b in batch])
            leaves, tree = jax.tree.flatten(
                out, is_leaf=lambda x: isinstance(x, NDArray))
            tree_holder[0] = tree
            return tuple(o.data if isinstance(o, NDArray) else jnp.asarray(o)
                         for o in leaves)

        return jax.jit(fwd)

    def _get_prefill_fn(self, max_len):
        fn = self._prefill_fns.get(max_len)
        if fn is not None:
            return fn
        net, cache_dtype = self._net, self._cache_dtype

        def prefill(values, src, vl, prime, key, temperature):
            with self._net_scope(values, key):
                logits, state = net.prefill(
                    NDArray(src), NDArray(prime),
                    src_valid_length=NDArray(vl), max_len=max_len,
                    cache_dtype=cache_dtype)
            return logits.data.astype(jnp.float32), state

        fn = jax.jit(prefill)
        self._prefill_fns[max_len] = fn
        return fn

    def _get_decode_fn(self, max_new, method, top_k):
        cfg = (max_new, method, top_k)
        fn = self._decode_fns.get(cfg)
        if fn is not None:
            return fn
        net, eos, pad = self._net, self._eos, self._pad

        def decode(values, state, first_logits, prefix_len, key,
                   temperature):
            B = first_logits.shape[0]
            key, sub = jax.random.split(key)
            tok0 = _sample_tokens(first_logits, sub, method, top_k,
                                  temperature)
            buf = jnp.full((B, max_new), pad, jnp.int32)
            buf = jax.lax.dynamic_update_slice(buf, tok0[:, None], (0, 0))
            fin0 = tok0 == eos

            def cond(c):
                i = c[0]
                return jnp.logical_and(i < max_new,
                                       jnp.logical_not(jnp.all(c[2])))

            def body(c):
                i, tok, fin, st, k, bf = c
                # tok is the PREVIOUS emitted token buf[i-1]: it sits at
                # absolute target position prefix_len + i - 1
                with self._net_scope(values, jax.random.PRNGKey(0)):
                    logits, st = net.decode_step(
                        tok, prefix_len + i - 1, st)
                logits = logits.data if isinstance(logits, NDArray) \
                    else logits
                k, sk = jax.random.split(k)
                nxt = _sample_tokens(logits.astype(jnp.float32), sk, method,
                                     top_k, temperature)
                nxt = jnp.where(fin, jnp.int32(pad), nxt)
                bf = jax.lax.dynamic_update_slice(bf, nxt[:, None], (0, i))
                fin = jnp.logical_or(fin, nxt == eos)
                return i + 1, nxt, fin, st, k, bf

            _, _, fin, _, _, buf = jax.lax.while_loop(
                cond, body, (jnp.int32(1), tok0, fin0, state, key, buf))
            has_eos = (buf == eos).any(axis=1)
            first_eos = jnp.argmax(buf == eos, axis=1)
            lengths = jnp.where(has_eos, first_eos + 1,
                                jnp.int32(max_new)).astype(jnp.int32)
            return buf, lengths

        # the cache pytree (argument 1) is DONATED into the loop: decode
        # reuses the prefill-seeded buffers instead of copying them. The
        # CPU test backend can't alias pass-through leaves (the static
        # cross_kv projections) and warns per dispatch — skip there.
        donate = () if jax.default_backend() == "cpu" else (1,)
        fn = jax.jit(decode, donate_argnums=donate)
        self._decode_fns[cfg] = fn
        return fn

    # ----------------------------------------------------------------- call
    def __call__(self, *batch):
        """One jitted predict-mode forward. Accepts NDArrays / arrays;
        returns the net's outputs as NDArrays. Pure dispatch after
        ``_stage`` — the lint keeps it sync-free."""
        from ..imperative import flush_bulk

        flush_bulk()
        staged = self._stage(batch)
        return self._dispatch(staged)

    def _stage(self, batch):
        """Host-side staging (slow path): convert + optional device_put."""
        arrs = [b.data if isinstance(b, NDArray) else jnp.asarray(b)
                for b in batch]
        sh = self._data_sharding
        if sh is not None:
            per = sh if isinstance(sh, list) else [sh] * len(arrs)
            if len(per) != len(arrs):
                raise MXNetError(
                    f"data_spec sequence has {len(per)} specs but the "
                    f"forward takes {len(arrs)} inputs")
            arrs = [jax.device_put(a, s) for a, s in zip(arrs, per)]
        return tuple(arrs)

    def _dispatch(self, staged):
        """Hot dispatch: signature accounting + the jitted call. Must stay
        free of host syncs (``tools/check_no_sync_in_step.py``)."""
        sig = ("fwd",) + tuple((a.shape, a.dtype.name) for a in staged)
        self.compile_guard.observe(
            sig, lambda: "fwd " + _cc.aval_summary(staged))
        vals = self._values  # one coherent read per dispatch (hot swap)
        outs = self._fwd_fn(vals, staged, self._fixed_key)
        nds = [NDArray(o) for o in outs]
        out = jax.tree.unflatten(self._fwd_tree[0], nds)
        return out

    # --------------------------------------------------------------- decode
    @staticmethod
    def _decode_cfg(max_new_tokens, method, top_k, seed):
        """Host-side config normalization (kept out of the linted decode
        dispatch — these are Python-value coercions, never device reads)."""
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        return max_new, str(method), int(top_k), \
            0 if seed is None else int(seed)

    def _stage_src(self, src, src_valid_length):
        src = src.data if isinstance(src, NDArray) else jnp.asarray(src)
        src = src.astype(jnp.int32)
        if src_valid_length is None:
            vl = jnp.full((src.shape[0],), src.shape[1], jnp.int32)
        else:
            vl = src_valid_length.data \
                if isinstance(src_valid_length, NDArray) \
                else jnp.asarray(src_valid_length)
            vl = vl.astype(jnp.int32)
        if self._data_sharding is not None and not isinstance(
                self._data_sharding, list):
            src = jax.device_put(src, self._data_sharding)
        return src, vl

    def decode_n(self, src, src_valid_length=None, max_new_tokens=32,
                 method="greedy", top_k=0, temperature=1.0, seed=None,
                 prefix=None):
        """KV-cached generation: ONE prefill dispatch + ONE decode-loop
        dispatch; returns ``(tokens (B, max_new), lengths (B,))`` as
        NDArrays, asynchronously (no host sync — the decode hot path is
        linted). ``prefix`` overrides the BOS priming column with an
        explicit (B, Lp) target prefix."""
        if not self.supports_decode:
            raise MXNetError(
                f"{type(self._net).__name__} does not implement the "
                "incremental protocol (prefill/decode_step)")
        max_new, method, top_k, seed = self._decode_cfg(
            max_new_tokens, method, top_k, seed)
        src, vl = self._stage_src(src, src_valid_length)
        B = src.shape[0]
        if prefix is None:
            prime = jnp.full((B, 1), self._bos, jnp.int32)
        else:
            prime = (prefix.data if isinstance(prefix, NDArray)
                     else jnp.asarray(prefix)).astype(jnp.int32)
        if prime.shape[1] + max_new > self._max_len:
            raise MXNetError(
                f"prefix {prime.shape[1]} + max_new_tokens {max_new} "
                f"exceeds the decode cache capacity max_len={self._max_len} "
                "(MXTPU_DECODE_MAX_LEN / InferStep(max_len=...))")
        key = jax.random.PRNGKey(seed)
        temp = jnp.float32(temperature)
        cfg = (max_new, method, top_k)
        sig = ("decode", cfg, (src.shape, src.dtype.name),
               (prime.shape, prime.dtype.name))
        self.compile_guard.observe(
            sig, lambda: f"decode{cfg} " + _cc.aval_summary((src, prime)))
        prefill_fn = self._get_prefill_fn(self._max_len)
        decode_fn = self._get_decode_fn(*cfg)
        key, pk = jax.random.split(key)
        # snapshot the live buffer ONCE: a concurrent hot swap flips
        # self._values between dispatches, and this request's prefill and
        # decode must run on the same weights
        vals = self._values
        logits, state = prefill_fn(vals, src, vl, prime, pk, temp)
        toks, lengths = decode_fn(vals, state, logits,
                                  jnp.int32(prime.shape[1]), key, temp)
        return NDArray(toks), NDArray(lengths)

    # ---------------------------------------------------------- paged decode
    # Continuous batching (ISSUE 8): decode runs as ONE dispatch per
    # ITERATION over a shared paged KV pool instead of one while_loop per
    # request batch. Between iterations the scheduler (serving.
    # ContinuousBatcher) retires EOS rows, frees their pages and admits
    # queued requests into the vacated slots — the dispatch shapes (slot
    # count, page-table width, pool size) never change, so the whole
    # serving loop compiles exactly twice per bucket menu entry (one
    # admission prefill + one decode-iteration program) and never again.

    def init_paged_state(self, slots, num_pages, page_size, mem_len):
        """Allocate the device-side paged decode state (per-layer pools +
        per-slot cross-attention buffers) in the engine's cache dtype.
        ``num_pages`` counts ALLOCATABLE pages; one extra trash page (id
        0) is added, matching ``serving.pages.PagePool`` ids."""
        if not self.supports_paged:
            raise MXNetError(
                f"{type(self._net).__name__} does not implement the paged "
                "protocol (prefill_paged/decode_step_paged)")
        return self._net.init_paged_state(
            int(slots), int(num_pages) + 1, int(page_size), int(mem_len),
            dtype=self._cache_dtype)

    def _get_paged_prefill_fn(self, method, top_k):
        cfg = ("paged_prefill", method, top_k)
        fn = self._paged_fns.get(cfg)
        if fn is not None:
            return fn
        net, bos = self._net, self._bos

        def prefill(values, state, src, vl, slot_ids, first_pages, active,
                    key, temperature):
            B = src.shape[0]
            prime = jnp.full((B, 1), bos, jnp.int32)
            with self._net_scope(values, key):
                logits, new_state = net.prefill_paged(
                    NDArray(src), NDArray(prime), NDArray(vl), state,
                    slot_ids, first_pages, active)
            logits = logits.data if isinstance(logits, NDArray) else logits
            key, sub = jax.random.split(key)
            tok0 = _sample_tokens(logits.astype(jnp.float32), sub, method,
                                  top_k, temperature)
            return tok0, new_state

        donate = () if jax.default_backend() == "cpu" else (1,)
        fn = jax.jit(prefill, donate_argnums=donate)
        self._paged_fns[cfg] = fn
        return fn

    def _get_suffix_fn(self, method, top_k, wide=False):
        cfg = ("paged_suffix", method, top_k, bool(wide))
        fn = self._paged_fns.get(cfg)
        if fn is not None:
            return fn
        net, wide = self._net, bool(wide)

        def prefill(values, state, tokens, token_vl, q_offset,
                    page_tables, slot_ids, active, key, temperature):
            with self._net_scope(values, key):
                logits, new_state = net.prefill_suffix_paged(
                    NDArray(tokens), token_vl, q_offset, state,
                    page_tables, slot_ids, active, wide=wide)
            logits = logits.data if isinstance(logits, NDArray) else logits
            key, sub = jax.random.split(key)
            tok0 = _sample_tokens(logits.astype(jnp.float32), sub, method,
                                  top_k, temperature)
            return tok0, new_state

        donate = () if jax.default_backend() == "cpu" else (1,)
        fn = jax.jit(prefill, donate_argnums=donate)
        self._paged_fns[cfg] = fn
        return fn

    def _get_decode_iter_fn(self, steps, method, top_k):
        cfg = ("decode_iter", steps, method, top_k)
        fn = self._paged_fns.get(cfg)
        if fn is not None:
            return fn
        net, eos, pad = self._net, self._eos, self._pad

        def decode(values, state, page_tables, tokens, lengths, active,
                   key, temperature):
            B = tokens.shape[0]
            buf = jnp.full((B, steps), pad, jnp.int32)
            fin0 = jnp.logical_not(active)

            def body(j, c):
                tok, fin, st, k, bf = c
                live = jnp.logical_not(fin)
                with self._net_scope(values, jax.random.PRNGKey(0)):
                    logits, st = net.decode_step_paged(
                        NDArray(tok), lengths + j, st, page_tables, live)
                logits = logits.data if isinstance(logits, NDArray) \
                    else logits
                k, sk = jax.random.split(k)
                nxt = _sample_tokens(logits.astype(jnp.float32), sk,
                                     method, top_k, temperature)
                nxt = jnp.where(fin, jnp.int32(pad), nxt)
                bf = jax.lax.dynamic_update_slice(
                    bf, nxt[:, None], (0, j))
                fin = jnp.logical_or(fin, nxt == eos)
                return nxt, fin, st, k, bf

            _, _, state, _, buf = jax.lax.fori_loop(
                0, steps, body, (tokens, fin0, state, key, buf))
            return buf, state

        donate = () if jax.default_backend() == "cpu" else (1,)
        fn = jax.jit(decode, donate_argnums=donate)
        self._paged_fns[cfg] = fn
        return fn

    @staticmethod
    def _paged_cfg(method, top_k, seed, steps=1):
        """Host-side config normalization (kept out of the linted paged
        dispatches — Python-value coercions, never device reads)."""
        return str(method), int(top_k), 0 if seed is None else int(seed), \
            max(int(steps), 1)

    def prefill_paged(self, state, src, src_valid_length, slot_ids,
                      first_pages, active, method="greedy", top_k=0,
                      temperature=1.0, seed=0):
        """One admission dispatch: prefill the (padded) admission batch
        INTO pool pages/slot buffers and sample each admitted row's first
        token. Pure staging + dispatch, sync-free by lint
        (``tools/check_no_sync_in_step.py``) — the scheduler reads the
        returned tokens at its designated sync point. Returns
        ``(tok0 (slots,) NDArray, new_state)``."""
        src = jnp.asarray(src, jnp.int32)
        vl = jnp.asarray(src_valid_length, jnp.int32)
        slot_ids = jnp.asarray(slot_ids, jnp.int32)
        first_pages = jnp.asarray(first_pages, jnp.int32)
        active = jnp.asarray(active, jnp.bool_)
        method, top_k, seed, _ = self._paged_cfg(method, top_k, seed)
        cfg = (method, top_k)
        sig = ("paged_prefill", cfg, (src.shape, src.dtype.name),
               state["k_pools"][0].shape, state["cross_k"][0].shape)
        self.compile_guard.observe(
            sig, lambda: f"paged_prefill{cfg} " + _cc.aval_summary((src,)))
        fn = self._get_paged_prefill_fn(*cfg)
        vals = self._values  # one coherent weight snapshot per dispatch
        tok0, new_state = fn(vals, state, src, vl, slot_ids, first_pages,
                             active, jax.random.PRNGKey(seed),
                             jnp.float32(temperature))
        return NDArray(tok0), new_state

    def prefill_suffix_paged(self, state, tokens, token_vl, q_offset,
                             page_tables, slot_ids, active,
                             method="greedy", top_k=0, temperature=1.0,
                             seed=0, wide=False):
        """Prefix-cache admission dispatch: run the decode-side forward
        over ONLY each row's uncached suffix (absolute positions
        ``q_offset[r] + j``) and sample its first new token. The encoder
        never runs — cross memory comes from the adopted cache root (or
        a prior prefill). ``wide`` routes the replay through the ONE-pass
        q_offset-aware window program (paged flash kernel when enabled)
        instead of the bit-exact sequential stream. Same staging/guard/
        donation contract as ``prefill_paged``; sync-free by lint.
        Returns ``(tok0 (B,) NDArray, new_state)``."""
        tokens = jnp.asarray(tokens, jnp.int32)
        token_vl = jnp.asarray(token_vl, jnp.int32)
        q_offset = jnp.asarray(q_offset, jnp.int32)
        page_tables = jnp.asarray(page_tables, jnp.int32)
        slot_ids = jnp.asarray(slot_ids, jnp.int32)
        active = jnp.asarray(active, jnp.bool_)
        method, top_k, seed, _ = self._paged_cfg(method, top_k, seed)
        wide = True if wide else False
        cfg = (method, top_k, wide)
        sig = ("paged_suffix", cfg, (tokens.shape, tokens.dtype.name),
               page_tables.shape, state["k_pools"][0].shape,
               state["cross_k"][0].shape)
        self.compile_guard.observe(
            sig, lambda: f"paged_suffix{cfg} "
            + _cc.aval_summary((tokens,)))
        fn = self._get_suffix_fn(*cfg)
        vals = self._values  # one coherent weight snapshot per dispatch
        tok0, new_state = fn(vals, state, tokens, token_vl, q_offset,
                             page_tables, slot_ids, active,
                             jax.random.PRNGKey(seed),
                             jnp.float32(temperature))
        return NDArray(tok0), new_state

    def decode_iter(self, state, page_tables, tokens, lengths, active,
                    steps=1, method="greedy", top_k=0, temperature=1.0,
                    seed=0):
        """One decode ITERATION over the slot batch: ``steps`` incremental
        tokens per live row in a single jitted dispatch, K/V read and
        written through ``page_tables``. The big pool state is the
        donated carry; tokens/lengths/active are small per-dispatch host
        operands. Sync-free by lint — the scheduler's collect phase is
        the sync point. Returns ``(tok_block (slots, steps) NDArray,
        new_state)``."""
        page_tables = jnp.asarray(page_tables, jnp.int32)
        tokens = jnp.asarray(tokens, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        active = jnp.asarray(active, jnp.bool_)
        method, top_k, seed, steps = self._paged_cfg(method, top_k, seed,
                                                     steps)
        cfg = (steps, method, top_k)
        sig = ("decode_iter", cfg, (page_tables.shape, tokens.shape),
               state["k_pools"][0].shape, state["cross_k"][0].shape)
        self.compile_guard.observe(
            sig, lambda: f"decode_iter{cfg} "
            + _cc.aval_summary((page_tables, tokens)))
        fn = self._get_decode_iter_fn(steps, method, top_k)
        vals = self._values
        buf, new_state = fn(vals, state, page_tables, tokens, lengths,
                            active, jax.random.PRNGKey(seed),
                            jnp.float32(temperature))
        return NDArray(buf), new_state

    # ---------------------------------------------------- speculative decode
    # Speculative decoding (ISSUE 14): a small DRAFT engine proposes k
    # greedy tokens per slot (one decode_iter dispatch of its own), then
    # ONE target dispatch scores all k+1 positions and accepts the longest
    # agreeing prefix in-graph. The acceptance rule — draft token j
    # accepted iff it equals the target argmax at position j-1 — makes the
    # emitted stream EXACTLY the target's greedy output for ANY draft
    # proposals: the draft buys speed, never changes tokens. Draft and
    # target share the one PagePool table; the draft keeps its own pools.

    @property
    def has_draft(self) -> bool:
        """Whether a draft engine is attached (``attach_draft``)."""
        return self.draft is not None

    def attach_draft(self, draft_net) -> "InferStep":
        """Attach a draft engine over ``draft_net`` (same vocab and
        special ids; typically a shallower stack). The draft shares this
        engine's ``RecompileGuard`` (one steady-state accounting domain)
        and inherits its AMP/max_len config. ``spec_pair()`` snapshots
        (target params, draft params, version) as ONE tuple, reassigned
        atomically by ``swap_params`` — a spec round can therefore never
        observe mixed draft/target versions."""
        draft = InferStep(draft_net, mesh=self._mesh, amp=self._amp,
                          max_len=self._max_len, bos_id=self._bos,
                          eos_id=self._eos, pad_id=self._pad)
        draft.compile_guard = self.compile_guard
        self.draft = draft
        self._live_pair = (self._values, draft._values,
                           self._weights_version)
        return draft

    def spec_pair(self):
        """One coherent ``(target_values, draft_values, version)``
        snapshot. Spec rounds read this ONCE and thread it through both
        dispatches; the swap plane flips the whole tuple in a single
        reference assignment."""
        if self._live_pair is None:
            raise MXNetError("spec_pair() needs attach_draft() first")
        return self._live_pair

    def init_draft_state(self, slots, num_pages, page_size, mem_len):
        """Paged decode state for the DRAFT engine with the same pool
        geometry as the target's — both sides are indexed by the one
        shared ``PagePool`` page table."""
        if self.draft is None:
            raise MXNetError("init_draft_state() needs attach_draft() "
                             "first")
        return self.draft.init_paged_state(slots, num_pages, page_size,
                                           mem_len)

    def _get_spec_draft_fn(self, steps, method, top_k):
        """The draft proposal program IS the draft's ``decode_iter`` with
        ``steps = k+1``: step j scatters token x_j at ``len+j`` and
        samples x_{j+1}, so proposals are ``buf[:, :k]`` and the extra
        step writes d_k's KV at ``len+k`` — a full-acceptance round
        leaves no draft-cache hole. No new program shape: the batcher's
        warmed draft decode_iter menu covers it."""
        return self.draft._get_decode_iter_fn(steps, method, top_k)

    def spec_draft(self, dstate, page_tables, tokens, lengths, active,
                   k=4, pair=None, seed=0):
        """Draft proposal dispatch: k+1 greedy draft steps per live slot
        in ONE jitted call (the draft's donated-carry decode_iter).
        ``tokens`` are the slots' carry tokens; returns ``(buf (slots,
        k+1) NDArray, new_dstate)`` — proposals are ``buf[:, :k]``, the
        last column is the hole-closing extra step. Sync-free by lint;
        pass the whole buf to ``spec_verify``."""
        page_tables = jnp.asarray(page_tables, jnp.int32)
        tokens = jnp.asarray(tokens, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        active = jnp.asarray(active, jnp.bool_)
        method, top_k, seed, steps = self._paged_cfg("greedy", 0, seed,
                                                     k + 1)
        cfg = (steps, method, top_k)
        sig = ("spec_draft", cfg, (page_tables.shape, tokens.shape),
               dstate["k_pools"][0].shape)
        self.compile_guard.observe(
            sig, lambda: f"spec_draft{cfg} "
            + _cc.aval_summary((page_tables, tokens)))
        fn = self._get_spec_draft_fn(steps, method, top_k)
        vals = pair[1] if pair is not None else self.draft._values
        buf, new_dstate = fn(vals, dstate, page_tables, tokens, lengths,
                             active, jax.random.PRNGKey(seed),
                             jnp.float32(1.0))
        return NDArray(buf), new_dstate

    @staticmethod
    def _spec_cfg(drafts_width, wide):
        """Host-side spec-verify config normalization (kept out of the
        linted dispatch — Python-value coercions, never device reads)."""
        k = int(drafts_width) - 1
        if k < 1:
            raise MXNetError("spec_verify needs a (slots, k+1) draft "
                             "buffer with k >= 1")
        return k, bool(wide)

    def _get_spec_verify_fn(self, k, wide):
        """Target verification program: score all k+1 positions (carry +
        k proposals), accept in-graph. ``wide=False`` (exact mode,
        default) runs a fori_loop of the SAME ``decode_step_paged``
        program shape as plain decoding — bit-identical logits by
        construction; ``wide=True`` scores the window in one
        ``decode_window_paged`` pass (the flash-kernel fast path, equal
        argmax up to attention-order rounding). Output packs ``(slots,
        k+2)`` int32: target argmaxes t_0..t_k then the per-row emit
        count ``n_accepted + 1``."""
        cfg = ("spec_verify", k, bool(wide))
        fn = self._paged_fns.get(cfg)
        if fn is not None:
            return fn
        net = self._net

        def verify(values, state, page_tables, drafts, tokens, lengths,
                   active):
            B = drafts.shape[0]
            # x_0 = carry, x_j = draft proposal j; the draft buffer's
            # last column (the hole-closing extra step) is unused here
            x = jnp.concatenate([tokens[:, None], drafts[:, :k]], axis=1)
            if wide:
                with self._net_scope(values, jax.random.PRNGKey(0)):
                    logits, state = net.decode_window_paged(
                        NDArray(x), lengths, state, page_tables, active)
                logits = logits.data if isinstance(logits, NDArray) \
                    else logits
                t = jnp.argmax(logits.astype(jnp.float32),
                               axis=-1).astype(jnp.int32)
            else:
                tbuf = jnp.zeros((B, k + 1), jnp.int32)

                def body(j, c):
                    st, tb = c
                    tok_j = jax.lax.dynamic_index_in_dim(
                        x, j, axis=1, keepdims=False)
                    with self._net_scope(values, jax.random.PRNGKey(0)):
                        lg, st = net.decode_step_paged(
                            NDArray(tok_j), lengths + j, st, page_tables,
                            active)
                    lg = lg.data if isinstance(lg, NDArray) else lg
                    tj = jnp.argmax(lg.astype(jnp.float32),
                                    axis=-1).astype(jnp.int32)
                    return st, jax.lax.dynamic_update_slice(
                        tb, tj[:, None], (0, j))

                state, t = jax.lax.fori_loop(0, k + 1, body, (state, tbuf))
            # longest agreeing prefix: d_j accepted iff d_j == t_{j-1}
            agree = (drafts[:, :k] == t[:, :k]).astype(jnp.int32)
            n_acc = jnp.sum(jnp.cumprod(agree, axis=1), axis=1)
            count = jnp.where(active, n_acc + 1, 0).astype(jnp.int32)
            return jnp.concatenate([t, count[:, None]], axis=1), state

        donate = () if jax.default_backend() == "cpu" else (1,)
        fn = jax.jit(verify, donate_argnums=donate)
        self._paged_fns[cfg] = fn
        return fn

    def spec_verify(self, state, page_tables, drafts, tokens, lengths,
                    active, pair=None, wide=False):
        """Target verification dispatch: ONE jitted call scores the carry
        token plus k proposals and accepts the longest agreeing prefix
        in-graph. ``drafts`` is ``spec_draft``'s whole (slots, k+1)
        buffer (k inferred from its width). Returns ``(out (slots, k+2)
        NDArray, new_state)``: columns 0..k are the target greedy tokens
        t_0..t_k, column k+1 the per-row emit count — the scheduler
        emits ``t_0..t_{count-1}`` and advances length by count.
        Sync-free by lint; greedy only (spec never engages for sampled
        requests)."""
        page_tables = jnp.asarray(page_tables, jnp.int32)
        drafts = drafts.data if isinstance(drafts, NDArray) \
            else jnp.asarray(drafts)
        drafts = drafts.astype(jnp.int32)
        tokens = jnp.asarray(tokens, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        active = jnp.asarray(active, jnp.bool_)
        k, wide = self._spec_cfg(drafts.shape[1], wide)
        cfg = (k, wide)
        sig = ("spec_verify", cfg, (page_tables.shape, drafts.shape),
               state["k_pools"][0].shape)
        self.compile_guard.observe(
            sig, lambda: f"spec_verify{cfg} "
            + _cc.aval_summary((page_tables, drafts)))
        fn = self._get_spec_verify_fn(k, wide)
        vals = pair[0] if pair is not None else self._values
        out, new_state = fn(vals, state, page_tables, drafts, tokens,
                            lengths, active)
        return NDArray(out), new_state

    def decode_spec_n(self, src, src_valid_length=None, max_new_tokens=32,
                      k=4, wide=False, seed=0, page_size=16):
        """Speculative twin of ``decode_n``: one paged prefill, then host
        rounds of draft-propose + target-verify until every row finishes.
        ``k=0`` degenerates to sequential paged decoding (one
        ``decode_iter`` step per round) — the bench ablation baseline on
        the same program set. Greedy only; the acceptance rule emits
        exactly the target's greedy stream, so output matches the
        non-speculative engine token for token. Returns ``(tokens (B,
        max_new), lengths (B,))`` NDArrays (pad-filled past EOS)."""
        import numpy as _np

        max_new, _, _, seed = self._decode_cfg(max_new_tokens, "greedy",
                                               0, seed)
        k = max(int(k), 0)
        if k and self.draft is None:
            raise MXNetError("decode_spec_n(k>0) needs attach_draft()")
        src, vl = self._stage_src(src, src_valid_length)
        B, L = int(src.shape[0]), int(src.shape[1])
        page_size = int(page_size)
        cap = 1 + max_new + k + 1  # BOS + emitted + one drafted window
        pps = -(-cap // page_size)
        table = _np.zeros((B, pps), _np.int32)
        for r in range(B):
            table[r] = 1 + r * pps + _np.arange(pps)
        pair = self.spec_pair() if self.draft is not None else None
        state = self.init_paged_state(B, B * pps, page_size, L)
        slot_ids = _np.arange(B, dtype=_np.int32)
        ones = _np.ones((B,), bool)
        tok0, state = self.prefill_paged(state, src, vl, slot_ids,
                                         table[:, 0], ones, seed=seed)
        dstate = None
        if k:
            dstate = self.init_draft_state(B, B * pps, page_size, L)
            _, dstate = self.draft.prefill_paged(
                dstate, src, vl, slot_ids, table[:, 0], ones, seed=seed)
        carry = tok0.asnumpy().astype(_np.int32)
        lengths = _np.ones((B,), _np.int32)
        emitted = [[int(carry[r])] for r in range(B)]
        done = _np.array([t[0] == self._eos for t in emitted])
        while True:
            live = _np.array([not done[r] and len(emitted[r]) < max_new
                              for r in range(B)])
            if not live.any():
                break
            if k:
                dbuf, dstate = self.spec_draft(
                    dstate, table, carry, lengths, live, k=k, pair=pair,
                    seed=seed)
                out, state = self.spec_verify(
                    state, table, dbuf, carry, lengths, live, pair=pair,
                    wide=wide)
                toks = out.asnumpy()
                for r in range(B):
                    if not live[r]:
                        continue
                    adv = 0
                    for j in range(int(toks[r, k + 1])):
                        t = int(toks[r, j])
                        emitted[r].append(t)
                        carry[r] = t
                        adv += 1
                        if t == self._eos:
                            done[r] = True
                            break
                        if len(emitted[r]) >= max_new:
                            break
                    lengths[r] += adv
            else:
                buf, state = self.decode_iter(state, table, carry,
                                              lengths, live, steps=1,
                                              seed=seed)
                toks = buf.asnumpy()
                for r in range(B):
                    if not live[r]:
                        continue
                    t = int(toks[r, 0])
                    emitted[r].append(t)
                    carry[r] = t
                    lengths[r] += 1
                    if t == self._eos:
                        done[r] = True
        out_t = _np.full((B, max_new), self._pad, _np.int32)
        out_l = _np.zeros((B,), _np.int32)
        for r in range(B):
            n = min(len(emitted[r]), max_new)
            out_t[r, :n] = emitted[r][:n]
            out_l[r] = n
        return NDArray(jnp.asarray(out_t)), NDArray(jnp.asarray(out_l))

    def generate(self, src, src_valid_length=None, max_new_tokens=32,
                 **kwargs):
        """User-facing generation. Same contract as ``decode_n``; when
        telemetry is enabled the prefill and decode dispatches are timed
        (blocking — the instrumented path trades the async dispatch for
        honest ``infer/prefill_ms`` and ``infer/decode_ms_per_token``)."""
        if not _tel._ENABLED:
            return self.decode_n(src, src_valid_length,
                                 max_new_tokens=max_new_tokens, **kwargs)
        return self._generate_timed(src, src_valid_length, max_new_tokens,
                                    **kwargs)

    def _generate_timed(self, src, src_valid_length, max_new_tokens,
                        **kwargs):
        """Telemetry-instrumented generation (cold-ish path: syncs twice
        per call to attribute prefill vs decode time)."""
        import time

        reg = _tel.registry()
        t0 = time.perf_counter()
        with _tel.span("infer.decode_n"):
            toks, lengths = self.decode_n(
                src, src_valid_length, max_new_tokens=max_new_tokens,
                **kwargs)
            jax.block_until_ready(toks.data)
        total_ms = (time.perf_counter() - t0) * 1e3
        n_tokens = int(jnp.sum(lengths.data))
        reg.histogram("infer/prefill_ms").observe(total_ms)  # upper bound
        if n_tokens:
            reg.histogram("infer/decode_ms_per_token").observe(
                total_ms / n_tokens)
            reg.gauge("infer/tokens_per_sec").set(
                n_tokens / (total_ms / 1e3))
        reg.counter("infer/tokens").inc(n_tokens)
        return toks, lengths

    # -------------------------------------------------------------- warmup
    def warmup(self, signatures, max_new_tokens=None, **decode_kwargs):
        """AOT-compile the real jitted inference programs for every prompt
        signature, so the serving loop never compiles.

        ``signatures`` entries are either ``(batch, bucket)`` pairs (the
        ``FixedBucketSampler.signatures()`` menu — int32 token prompts
        assumed) or full warmup-style per-array spec sequences for the
        generic forward. With ``max_new_tokens`` set (and a decode-capable
        net) each prompt signature drives the REAL prefill+decode
        programs on zero prompts; otherwise the plain forward. Marks the
        guard steady afterwards; returns the number of fresh programs."""
        import numpy as _host_np

        reg = _tel.registry()
        before = self.compile_guard.signatures
        for entry in signatures:
            if len(entry) == 2 and all(
                    isinstance(x, (int, _host_np.integer)) for x in entry):
                bs, bucket = int(entry[0]), int(entry[1])
                src = _host_np.zeros((bs, bucket), _host_np.int32)
                vl = _host_np.full((bs,), bucket, _host_np.int32)
                if max_new_tokens is not None and self.supports_decode:
                    out = self.decode_n(src, vl,
                                        max_new_tokens=max_new_tokens,
                                        **decode_kwargs)
                    jax.block_until_ready(out[0].data)
                else:
                    out = self(src)
                    leaf = jax.tree.leaves(
                        out, is_leaf=lambda x: isinstance(x, NDArray))[0]
                    jax.block_until_ready(leaf.data)
            else:
                specs = [_cc.normalize_spec(s) for s in entry]
                host = [_host_np.zeros(shape, dtype)
                        for shape, dtype in specs]
                out = self(*host)
                leaf = jax.tree.leaves(
                    out, is_leaf=lambda x: isinstance(x, NDArray))[0]
                jax.block_until_ready(leaf.data)
        compiled = self.compile_guard.signatures - before
        reg.counter("compile/warmup_compiles").inc(compiled)
        self.compile_guard.mark_steady()
        return compiled

    def cache_info(self) -> dict:
        """Signature cache summary (``compile_cache.RecompileGuard``)."""
        return self.compile_guard.info()

    # -------------------------------------------------- weight lifecycle
    def _bump_version(self, version: Optional[str]) -> str:
        self._version_counter += 1
        self._weights_version = version if version is not None \
            else f"v{self._version_counter}"
        _tel.set_info(weights_version=self._weights_version)
        return self._weights_version

    def sync_params(self, version: Optional[str] = None):
        """Re-read the net's current parameter values (after external
        updates, e.g. ``TrainStep.sync_params`` handed fresh weights),
        re-placing each under its declared sharding and bumping
        ``weights_version``."""
        self.swap_params(
            staged=self.stage_params(
                {name: p._data.data for name, p in self._params}),
            version=version)

    def stage_params(self, arrays) -> dict:
        """Stage a full replacement param set into a standby device
        buffer; the LIVE set is untouched (double buffering — staging can
        run on a background thread while serving continues).

        ``arrays`` maps param name -> array; ``TrainStep`` checkpoint
        naming (``values/<name>``) is accepted, extra entries (optimizer
        moments, scaler state) are ignored. Every engine param must be
        present with its exact shape; values are cast to the LIVE entry's
        dtype and placed under its sharding, so flipping to the staged
        set can never change a dispatch signature (zero recompiles by
        construction)."""
        live = self._values
        vals = {}
        for name, _ in self._params:
            v = arrays.get(name)
            if v is None:
                v = arrays.get("values/" + name)
            if v is None:
                raise MXNetError(
                    f"swap source is missing parameter {name!r}")
            v = jnp.asarray(v)
            cur = live[name]
            if tuple(v.shape) != tuple(cur.shape):
                raise MXNetError(
                    f"swap shape mismatch for {name!r}: "
                    f"{tuple(v.shape)} != {tuple(cur.shape)}")
            v = v.astype(cur.dtype)
            if self._param_sharding is not None:
                v = jax.device_put(v, self._param_sharding(name, v.shape))
            vals[name] = v
        if self.draft is not None:
            # draft params ride the same checkpoint under a "draft/"
            # prefix; staging both here lets swap_params flip the pair
            # in one barrier step
            sub = {}
            for key, val in arrays.items():
                if key.startswith("draft/"):
                    sub[key[len("draft/"):]] = val
                elif key.startswith("values/draft/"):
                    sub["values/" + key[len("values/draft/"):]] = val
            if sub:
                vals["__draft_staged__"] = self.draft.stage_params(sub)
        return vals

    def swap_params(self, arrays=None, *, staged: Optional[dict] = None,
                    version: Optional[str] = None) -> str:
        """Hot weight swap: flip the live param buffer to ``staged`` (or
        to ``stage_params(arrays)``), atomically between dispatches.

        In-flight dispatches hold their own snapshot and finish on the
        OLD version; every dispatch entered after this call serves the
        new one. The flip itself is one reference assignment — it stalls
        serving by zero dispatches. Returns the new ``weights_version``
        (``version`` or an auto-bumped ``v<N>`` tag)."""
        if staged is None:
            if arrays is None:
                raise MXNetError("swap_params needs arrays= or staged=")
            staged = self.stage_params(arrays)
        dstaged = staged.pop("__draft_staged__", None)
        if set(staged) != {n for n, _ in self._params}:
            raise MXNetError(
                "staged param set does not cover the engine's params "
                "(use stage_params())")
        self._values = staged
        ver = self._bump_version(version)
        if self.draft is not None:
            if dstaged is not None:
                self.draft._values = dstaged
                self.draft._weights_version = ver
            # flip the PAIR last and as one tuple: spec rounds snapshot
            # it once (spec_pair), so a concurrent round sees either the
            # old (target, draft) pair or the new one — never a mix
            self._live_pair = (self._values, self.draft._values, ver)
        return ver
