"""``mx.contrib``: quantization and other contrib subsystems
(reference: ``python/mxnet/contrib/`` [unverified])."""

from . import quantization

__all__ = ["quantization"]
