"""INT8 quantization (reference: ``python/mxnet/contrib/quantization.py`` +
``src/operator/quantization/`` [unverified]).

The reference flow: calibrate activation ranges on sample data (min/max or
entropy), rewrite the graph with quantize/dequantize + INT8 kernels. The
TPU-native rewrite: per-tensor symmetric INT8 with the matmul issued as an
int8xint8->int32 ``lax.dot_general`` (the MXU's native low-precision path;
``preferred_element_type=int32`` keeps the accumulator wide), dequantized by
the product of the two scales. Calibration is layer-wise min/max over
forwarded batches, like the reference's 'naive' calib mode.

APIs:
- ops ``_contrib_quantize_v2`` / ``_contrib_dequantize`` in the registry
- ``QuantizedDense``: drop-in gluon block holding int8 weights
- ``quantize_net(net, calib_data)``: rewrite Dense layers after calibration
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..ops.registry import register, maybe_get

__all__ = ["quantize_v2", "dequantize", "quantize_net", "QuantizedDense",
           "calib_ranges"]


def _scale_from_range(min_val, max_val):
    # symmetric per-tensor: scale maps [-amax, amax] -> [-127, 127]
    amax = jnp.maximum(jnp.abs(min_val), jnp.abs(max_val))
    return jnp.maximum(amax, 1e-8) / 127.0


if maybe_get("_contrib_quantize_v2") is None:
    @register("_contrib_quantize_v2", aliases=["quantize_v2"],
              num_outputs=3, differentiable=False)
    def quantize_v2(data, min_calib_range=None, max_calib_range=None, **kw):
        """float -> (int8, min, max). Symmetric; calib range optional
        (defaults to the tensor's own range, reference 'auto' mode)."""
        mn = jnp.asarray(
            min_calib_range if min_calib_range is not None else data.min(),
            jnp.float32,
        )
        mx_ = jnp.asarray(
            max_calib_range if max_calib_range is not None else data.max(),
            jnp.float32,
        )
        scale = _scale_from_range(mn, mx_)
        q = jnp.clip(jnp.round(data / scale), -127, 127).astype(jnp.int8)
        return q, mn, mx_

    @register("_contrib_dequantize", aliases=["dequantize"],
              differentiable=False)
    def dequantize(data, min_range, max_range, **kw):
        scale = _scale_from_range(jnp.asarray(min_range),
                                  jnp.asarray(max_range))
        return data.astype(jnp.float32) * scale
else:  # pragma: no cover - double import guard
    quantize_v2 = maybe_get("_contrib_quantize_v2").fn
    dequantize = maybe_get("_contrib_dequantize").fn


def _int8_matmul(x_q, w_q_t, x_scale, w_scale):
    """(M,K)i8 @ (K,N)i8 -> f32, accumulating in int32 on the MXU."""
    acc = jax.lax.dot_general(
        x_q, w_q_t, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * (x_scale * w_scale)


class QuantizedDense:
    """INT8 replacement for a trained ``gluon.nn.Dense``.

    Weights are quantized once at conversion; activations are quantized
    per-call with the calibrated range (static scale -> no data-dependent
    recompilation under jit)."""

    def __init__(self, dense, act_min, act_max):
        from ..gluon.nn import Dense

        if not isinstance(dense, Dense):
            raise MXNetError("QuantizedDense wraps a gluon Dense layer")
        w = dense.weight.data().data  # (units, in)
        self._w_scale = float(_np.asarray(
            jnp.maximum(jnp.abs(w).max(), 1e-8) / 127.0
        ))
        self._w_q_t = jnp.clip(
            jnp.round(w / self._w_scale), -127, 127
        ).astype(jnp.int8).T  # (in, units)
        self._bias = dense.bias.data().data if dense.bias is not None else None
        self._act_scale = float(_np.asarray(
            _scale_from_range(jnp.asarray(act_min), jnp.asarray(act_max))
        ))
        self._act = dense.act
        self._flatten = getattr(dense, "_flatten", True)

    def __call__(self, x):
        from ..imperative import invoke_fn

        def fwd(xd):
            shape = xd.shape
            if self._flatten and xd.ndim > 2:
                xd = xd.reshape(shape[0], -1)
            elif xd.ndim > 2:
                xd = xd.reshape(-1, shape[-1])
            x_q = jnp.clip(
                jnp.round(xd / self._act_scale), -127, 127
            ).astype(jnp.int8)
            out = _int8_matmul(x_q, self._w_q_t, self._act_scale,
                               self._w_scale)
            if self._bias is not None:
                out = out + self._bias
            if not self._flatten and len(shape) > 2:
                out = out.reshape(shape[:-1] + (out.shape[-1],))
            return out

        out = invoke_fn(fwd, x)
        if self._act is not None:
            out = self._act(out)
        return out


def calib_ranges(net, calib_data, layers) -> Dict[int, tuple]:
    """Min/max of each target layer's INPUT over the calibration batches
    (reference 'naive' calibration). ``layers``: list of Dense blocks."""
    ranges: Dict[int, List[float]] = {}
    hooks = []

    def make_hook(key):
        def hook(block, inputs):
            x = inputs[0]
            arr = _np.asarray(x.asnumpy() if hasattr(x, "asnumpy") else x)
            lo, hi = float(arr.min()), float(arr.max())
            if key in ranges:
                ranges[key][0] = min(ranges[key][0], lo)
                ranges[key][1] = max(ranges[key][1], hi)
            else:
                ranges[key] = [lo, hi]

        return hook

    for layer in layers:
        hooks.append(layer.register_forward_pre_hook(make_hook(id(layer))))
    try:
        for batch in calib_data:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            net(x)
    finally:
        for h in hooks:
            h.detach()
    return {k: tuple(v) for k, v in ranges.items()}


def quantize_net(net, calib_data=None, exclude=()):
    """Replace every calibrated ``Dense`` child with ``QuantizedDense``
    in-place; returns the rewritten net (reference: ``quantize_model``'s
    graph rewrite, gluon-style). Runs ``calib_data`` through the net for
    activation ranges (required)."""
    from ..gluon.nn import Dense

    dense_layers = []

    def collect(block):
        for child in block._children.values():
            if isinstance(child, Dense) and child not in exclude:
                dense_layers.append(child)
            collect(child)

    collect(net)
    if not dense_layers:
        raise MXNetError("quantize_net: no Dense layers found to quantize")
    if calib_data is None:
        raise MXNetError("quantize_net needs calibration data")
    ranges = calib_ranges(net, calib_data, dense_layers)

    def rewrite(block):
        for name, child in list(block._children.items()):
            if isinstance(child, Dense) and id(child) in ranges:
                lo, hi = ranges[id(child)]
                newb = _QuantizedDenseBlock(QuantizedDense(child, lo, hi))
                block._children[name] = newb
                # attribute-style blocks (self.fc = Dense(...)) call the
                # child through the instance attribute, not _children —
                # swap every attribute referencing the old layer too
                for attr, val in list(vars(block).items()):
                    if val is child:
                        object.__setattr__(block, attr, newb)
            else:
                rewrite(child)

    rewrite(net)
    if hasattr(net, "_clear_cached_op"):
        net._clear_cached_op()
    return net


# the ops above registered after mx.nd was generated at package import:
# refresh the generated namespaces so nd._contrib_quantize_v2 etc. appear
def _refresh_namespaces():
    import sys

    nd_mod = sys.modules.get("mxnet_tpu.ndarray")
    if nd_mod is not None:
        from ..ndarray import register as _nd_register

        _nd_register.populate_module(nd_mod, "nd")
    ndc = sys.modules.get("mxnet_tpu.ndarray.contrib")
    if ndc is not None:
        ndc._populate()


_refresh_namespaces()


def _quantized_dense_block_cls():
    from ..gluon.block import Block

    class _QDB(Block):
        """Block adapter holding a QuantizedDense (a real Block subclass,
        so save_parameters/apply/initialize traversals keep working —
        it simply owns no Parameters; weights are baked-in int8)."""

        def __init__(self, q):
            super().__init__(prefix="quantized_", params=None)
            self._q = q

        def forward(self, x, *args):
            return self._q(x)

    return _QDB


def _QuantizedDenseBlock(q):
    return _quantized_dense_block_cls()(q)
