"""INT8 quantization (reference: ``python/mxnet/contrib/quantization.py`` +
``src/operator/quantization/`` [unverified]).

The reference flow: calibrate activation ranges on sample data (min/max or
entropy), rewrite the graph with quantize/dequantize + INT8 kernels. The
TPU-native rewrite: per-tensor symmetric INT8 with the matmul issued as an
int8xint8->int32 ``lax.dot_general`` (the MXU's native low-precision path;
``preferred_element_type=int32`` keeps the accumulator wide), dequantized by
the product of the two scales. Calibration is layer-wise min/max over
forwarded batches, like the reference's 'naive' calib mode.

APIs:
- ops ``_contrib_quantize_v2`` / ``_contrib_dequantize`` in the registry
- ``QuantizedDense``: drop-in gluon block holding int8 weights
- ``quantize_net(net, calib_data)``: rewrite Dense layers after calibration
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..ops.registry import register, maybe_get

__all__ = ["quantize_v2", "dequantize", "quantize_net", "QuantizedDense",
           "QuantizedConv2D", "calib_ranges", "entropy_threshold"]


def _scale_from_range(min_val, max_val):
    # symmetric per-tensor: scale maps [-amax, amax] -> [-127, 127]
    amax = jnp.maximum(jnp.abs(min_val), jnp.abs(max_val))
    return jnp.maximum(amax, 1e-8) / 127.0


def _quantize_symmetric(arr):
    """Per-tensor symmetric int8: (q, scale). The ONE place the epsilon
    and clip bounds live — Dense and Conv paths share it."""
    scale = float(_np.asarray(
        jnp.maximum(jnp.abs(arr).max(), 1e-8) / 127.0
    ))
    q = jnp.clip(jnp.round(arr / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _quantize_per_channel(w):
    """Per-OUTPUT-channel symmetric int8 for conv weights (O, I, kh, kw)
    — the reference's channel-wise weight path: one scale per filter,
    recovering the dynamic range a single outlier filter would otherwise
    destroy. Returns (q int8, scales (O,) f32)."""
    amax = jnp.max(jnp.abs(w.reshape(w.shape[0], -1)), axis=1)
    scales = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scales.reshape(-1, 1, 1, 1)),
                 -127, 127).astype(jnp.int8)
    return q, scales.astype(jnp.float32)


def _quantize_act(x, scale):
    return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)


if maybe_get("_contrib_quantize_v2") is None:
    @register("_contrib_quantize_v2", aliases=["quantize_v2"],
              num_outputs=3, differentiable=False)
    def quantize_v2(data, min_calib_range=None, max_calib_range=None, **kw):
        """float -> (int8, min, max). Symmetric; calib range optional
        (defaults to the tensor's own range, reference 'auto' mode)."""
        mn = jnp.asarray(
            min_calib_range if min_calib_range is not None else data.min(),
            jnp.float32,
        )
        mx_ = jnp.asarray(
            max_calib_range if max_calib_range is not None else data.max(),
            jnp.float32,
        )
        scale = _scale_from_range(mn, mx_)
        q = jnp.clip(jnp.round(data / scale), -127, 127).astype(jnp.int8)
        return q, mn, mx_

    @register("_contrib_dequantize", aliases=["dequantize"],
              differentiable=False)
    def dequantize(data, min_range, max_range, **kw):
        scale = _scale_from_range(jnp.asarray(min_range),
                                  jnp.asarray(max_range))
        return data.astype(jnp.float32) * scale
else:  # pragma: no cover - double import guard
    quantize_v2 = maybe_get("_contrib_quantize_v2").fn
    dequantize = maybe_get("_contrib_dequantize").fn


def _int8_conv(x_q, w_q, stride, pad, dilate, groups):
    """int8 x int8 -> int32 convolution in NC[DHW] layout (shared by
    QuantizedConv2D, QuantizedConvUnit, and the registry op); caller
    applies the dequant scales."""
    nd_sp = x_q.ndim - 2
    spatial = "DHW"[-nd_sp:]
    return jax.lax.conv_general_dilated(
        x_q, w_q, window_strides=tuple(stride),
        padding=[(p, p) for p in pad], rhs_dilation=tuple(dilate),
        dimension_numbers=("NC" + spatial, "OI" + spatial, "NC" + spatial),
        feature_group_count=groups,
        preferred_element_type=jnp.int32,
    ).astype(jnp.float32)


def _int8_matmul(x_q, w_q_t, x_scale, w_scale):
    """(M,K)i8 @ (K,N)i8 -> f32, accumulating in int32 on the MXU."""
    acc = jax.lax.dot_general(
        x_q, w_q_t, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * (x_scale * w_scale)


class QuantizedDense:
    """INT8 replacement for a trained ``gluon.nn.Dense``.

    Weights are quantized once at conversion; activations are quantized
    per-call with the calibrated range (static scale -> no data-dependent
    recompilation under jit)."""

    def __init__(self, dense, act_min, act_max):
        from ..gluon.nn import Dense

        if not isinstance(dense, Dense):
            raise MXNetError("QuantizedDense wraps a gluon Dense layer")
        w = dense.weight.data().data  # (units, in)
        w_q, self._w_scale = _quantize_symmetric(w)
        self._w_q_t = w_q.T  # (in, units)
        self._bias = dense.bias.data().data if dense.bias is not None else None
        self._act_scale = float(_np.asarray(
            _scale_from_range(jnp.asarray(act_min), jnp.asarray(act_max))
        ))
        self._act = dense.act
        self._flatten = getattr(dense, "_flatten", True)

    def __call__(self, x):
        from ..imperative import invoke_fn

        def fwd(xd):
            shape = xd.shape
            if self._flatten and xd.ndim > 2:
                xd = xd.reshape(shape[0], -1)
            elif xd.ndim > 2:
                xd = xd.reshape(-1, shape[-1])
            x_q = _quantize_act(xd, self._act_scale)
            out = _int8_matmul(x_q, self._w_q_t, self._act_scale,
                               self._w_scale)
            if self._bias is not None:
                out = out + self._bias
            if not self._flatten and len(shape) > 2:
                out = out.reshape(shape[:-1] + (out.shape[-1],))
            return out

        out = invoke_fn(fwd, x)
        if self._act is not None:
            out = self._act(out)
        return out


def entropy_threshold(abs_hist, bin_width, num_quantized_bins=255):
    """KL-divergence-optimal clipping threshold over an |x| histogram
    (the reference's 'entropy' calibration, ``calibrate.py``'s
    _get_optimal_threshold [unverified]): for every candidate threshold,
    compare the clipped reference distribution P with its
    num_quantized_bins-level quantization Q and keep the argmin."""
    nbins = len(abs_hist)
    best_kl, best_t = _np.inf, nbins * bin_width
    hist = abs_hist.astype(_np.float64)
    start = max(num_quantized_bins // 2, 32)
    for i in range(start, nbins + 1, max(1, nbins // 128)):
        p = hist[:i].copy()
        p[i - 1] += hist[i:].sum()  # clip outliers into the last bin
        if p.sum() <= 0:
            continue
        # quantize the i bins down to num_quantized_bins levels
        factor = i / num_quantized_bins
        q = _np.zeros(i)
        for j in range(num_quantized_bins):
            lo = int(_np.floor(j * factor))
            hi = int(_np.ceil((j + 1) * factor))
            chunk = hist[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = _np.where(chunk > 0, chunk.sum() / nz, 0)
        pn = p / p.sum()
        qs = q.sum()
        if qs <= 0:
            continue
        qn = q / qs
        mask = pn > 0
        kl = float(_np.sum(
            pn[mask] * _np.log(pn[mask] / _np.maximum(qn[mask], 1e-12))
        ))
        if kl < best_kl:
            best_kl, best_t = kl, i * bin_width
    return best_t


class QuantizedConv2D:
    """INT8 replacement for a trained ``gluon.nn.Conv2D`` (closes the
    round-2 gap: quantization now reaches the CV models).

    Per-tensor symmetric weights quantized once; activations per-call at
    the calibrated static scale; the convolution itself runs
    int8 x int8 -> int32 through ``lax.conv_general_dilated`` with a wide
    accumulator (the MXU's native low-precision path), dequantized by the
    product of scales."""

    def __init__(self, conv, act_min, act_max):
        from ..gluon.nn.conv_layers import _Conv

        if not isinstance(conv, _Conv):
            raise MXNetError("QuantizedConv2D wraps a gluon Conv layer")
        kw = conv._kwargs
        if kw.get("layout", "NCHW")[-1] == "C":
            raise MXNetError(
                "QuantizedConv2D supports channel-first layouts only"
            )
        w = conv.weight.data().data  # (O, I/g, kh, kw)
        self._w_q, self._w_scale = _quantize_symmetric(w)
        self._bias = conv.bias.data().data if conv.bias is not None else None
        self._act_scale = float(_np.asarray(
            _scale_from_range(jnp.asarray(act_min), jnp.asarray(act_max))
        ))
        self._kw = dict(kw)
        self._act = conv.act

    def __call__(self, x):
        from ..imperative import invoke_fn

        kw = self._kw

        def fwd(xd):
            x_q = _quantize_act(xd, self._act_scale)
            nd_sp = x_q.ndim - 2
            stride = kw.get("stride") or (1,) * nd_sp
            dilate = kw.get("dilate") or (1,) * nd_sp
            pad = kw.get("pad") or (0,) * nd_sp
            out = _int8_conv(x_q, self._w_q, stride, pad, dilate,
                             kw.get("num_group", 1)) \
                * (self._act_scale * self._w_scale)
            if self._bias is not None:
                out = out + self._bias.reshape((1, -1) + (1,) * nd_sp)
            return out

        out = invoke_fn(fwd, x)
        if self._act is not None:
            out = self._act(out)
        return out


class QTensor:
    """An int8 activation + its scale flowing BETWEEN quantized units
    (the reference's requantized INT8 graph edges). Only produced when
    the next unit is known to consume it."""

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = float(scale)


class QuantizedConvUnit:
    """One INT8 execution unit: Conv2D [+ folded BatchNorm] [+ relu]
    [+ MaxPool2D], with per-output-channel weight scales and requantized
    int8 output when the next unit consumes int8.

    Math: int8 conv -> int32 acc; the BN eval affine folds into the
    per-channel dequant multiplier M_c = s_in * s_w[c] * gamma_c /
    sqrt(var_c + eps) and bias B_c (reference INT8 conv+bn+relu
    subgraph); relu in f32; when emitting int8, requantize at the
    calibrated OUTPUT scale and run max-pooling ON THE CODES (max and
    requantize commute — bit-identical to pooling in f32 first)."""

    def __init__(self, conv, bn, act_kind, pool, act_min, act_max,
                 out_min, out_max, emit_q=False):
        kw = conv._kwargs
        if kw.get("layout", "NCHW")[-1] == "C":
            raise MXNetError("QuantizedConvUnit: channel-first only")
        w = conv.weight.data().data
        self._w_q, w_scales = _quantize_per_channel(w)
        bias = conv.bias.data().data if conv.bias is not None else None
        if bn is not None:
            gamma = bn.gamma.data().data
            beta = bn.beta.data().data
            mean = bn.running_mean.data().data
            var = bn.running_var.data().data
            eps = bn._kwargs.get("eps", 1e-5)
            bscale = gamma * jax.lax.rsqrt(var + eps)
            self._mult = (w_scales * bscale).astype(jnp.float32)
            shift = beta - mean * bscale
            self._bias = shift if bias is None else bias * bscale + shift
        else:
            self._mult = w_scales
            self._bias = bias
        self._act_scale = float(_np.asarray(
            _scale_from_range(jnp.asarray(act_min), jnp.asarray(act_max))))
        self._out_scale = float(_np.asarray(
            _scale_from_range(jnp.asarray(out_min), jnp.asarray(out_max))))
        self._relu = act_kind == "relu"
        self._pool_kw = dict(pool._kwargs) if pool is not None else None
        self._kw = dict(kw)
        self.emit_q = emit_q
        # non-relu conv activation (tanh/sigmoid/...): applied in f32
        # after dequant, exactly as the pre-fusion QuantizedConv2D did
        self.post_act = None

    def __call__(self, x):
        from ..imperative import invoke_fn

        if isinstance(x, QTensor):
            s_in, x_in, preq = x.scale, x.q, True
        else:
            s_in, x_in, preq = self._act_scale, x, False
        kw = self._kw

        def fwd(xd):
            x_q = xd if preq else _quantize_act(xd, s_in)
            nd_sp = x_q.ndim - 2
            stride = kw.get("stride") or (1,) * nd_sp
            dilate = kw.get("dilate") or (1,) * nd_sp
            pad = kw.get("pad") or (0,) * nd_sp
            acc = _int8_conv(x_q, self._w_q, stride, pad, dilate,
                             kw.get("num_group", 1))
            mult = (s_in * self._mult).reshape((1, -1) + (1,) * nd_sp)
            out = acc * mult
            if self._bias is not None:
                out = out + self._bias.reshape((1, -1) + (1,) * nd_sp)
            if self._relu:
                out = jnp.maximum(out, 0.0)
            if self.emit_q:
                oq = _quantize_act(out, self._out_scale)
                if self._pool_kw is not None:
                    oq = self._pool_int8(oq)
                return oq
            if self._pool_kw is not None:
                out = self._pool_f32(out)
            return out

        out = invoke_fn(fwd, x_in)
        if self.emit_q:
            return QTensor(out, self._out_scale)
        if self.post_act is not None:
            out = self.post_act(out)
        return out

    def _pool_int8(self, q):
        pk = self._pool_kw
        k = pk["kernel"]
        s = pk["stride"]
        p = pk["pad"]
        return jax.lax.reduce_window(
            q, jnp.int8(-128), jax.lax.max,
            (1, 1) + tuple(k), (1, 1) + tuple(s),
            ((0, 0), (0, 0)) + tuple((x, x) for x in p),
        )

    def _pool_f32(self, out):
        pk = self._pool_kw
        return jax.lax.reduce_window(
            out, -jnp.inf, jax.lax.max,
            (1, 1) + tuple(pk["kernel"]), (1, 1) + tuple(pk["stride"]),
            ((0, 0), (0, 0)) + tuple((x, x) for x in pk["pad"]),
        )


def calib_ranges(net, calib_data, layers, mode="naive", out_layers=None):
    """Activation ranges of each target layer's INPUT over the
    calibration batches. ``mode``: 'naive' (min/max, the reference
    default) or 'entropy' (KL-optimal symmetric threshold).
    ``layers``: list of Dense/Conv2D blocks. ``out_layers``: blocks whose
    OUTPUT min/max is also wanted (chained-unit requantize scales) —
    observed in the SAME forward pass; when given, returns
    (input_ranges, output_ranges)."""
    if mode not in ("naive", "entropy"):
        raise MXNetError(
            f"unknown calibration mode {mode!r}; use 'naive' or 'entropy'"
        )
    ranges: Dict[int, List[float]] = {}
    hists: Dict[int, _np.ndarray] = {}
    NBINS, hooks = 2048, []

    def make_hook(key):
        def hook(block, inputs):
            x = inputs[0]
            arr = _np.asarray(x.asnumpy() if hasattr(x, "asnumpy") else x)
            lo, hi = float(arr.min()), float(arr.max())
            if key in ranges:
                ranges[key][0] = min(ranges[key][0], lo)
                ranges[key][1] = max(ranges[key][1], hi)
            else:
                ranges[key] = [lo, hi]
            if mode == "entropy":
                amax = max(abs(lo), abs(hi), 1e-8)
                h, _ = _np.histogram(_np.abs(arr), bins=NBINS,
                                     range=(0, amax))
                # keep per-batch (hist, amax) pairs; they are re-binned
                # to the layer's GLOBAL range at the end — batches with
                # different dynamic ranges must not be summed bin-wise
                hists.setdefault(key, []).append(
                    (h.astype(_np.float64), amax))

        return hook

    out_ranges: Dict[int, List[float]] = {}

    def make_out_hook(key):
        def hook(block, inputs, output):
            x = output[0] if isinstance(output, (list, tuple)) else output
            arr = _np.asarray(x.asnumpy() if hasattr(x, "asnumpy") else x)
            lo, hi = float(arr.min()), float(arr.max())
            if key in out_ranges:
                out_ranges[key][0] = min(out_ranges[key][0], lo)
                out_ranges[key][1] = max(out_ranges[key][1], hi)
            else:
                out_ranges[key] = [lo, hi]

        return hook

    for layer in layers:
        hooks.append(layer.register_forward_pre_hook(make_hook(id(layer))))
    for layer in (out_layers or ()):
        hooks.append(layer.register_forward_hook(make_out_hook(id(layer))))
    try:
        for batch in calib_data:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            net(x)
    finally:
        for h in hooks:
            h.detach()

    def _ret(inp):
        if out_layers is None:
            return inp
        return inp, {k: (v[0], v[1]) for k, v in out_ranges.items()}

    if mode == "entropy":
        out = {}
        for k, v in ranges.items():
            parts = hists[k]
            gmax = max(a for _, a in parts)
            merged = _np.zeros(NBINS)
            for h, a in parts:
                # map each batch bin center onto the global-width grid
                idx = _np.minimum(
                    (( _np.arange(NBINS) + 0.5) * (a / gmax)).astype(int),
                    NBINS - 1,
                )
                _np.add.at(merged, idx, h)
            t = entropy_threshold(merged, gmax / NBINS)
            out[k] = (-t, t)
        return _ret(out)
    return _ret({k: (v[0], v[1]) for k, v in ranges.items()})


def _collect_units(net, exclude, report):
    """Walk containers, grouping each Conv2D with its immediately
    following BatchNorm / Activation(relu) / MaxPool2D siblings into one
    INT8 unit (the reference's fused quantized subgraph); Dense layers
    are single-layer units. Returns [(container, [child names], head,
    parts dict)] in forward order per container."""
    from ..gluon.nn import Dense
    from ..gluon.nn.activations import Activation
    from ..gluon.nn.basic_layers import BatchNorm, HybridSequential, \
        Sequential
    from ..gluon.nn.conv_layers import Conv2D, MaxPool2D

    units = []

    def walk(block, path):
        children = list(block._children.items())
        i = 0
        while i < len(children):
            name, child = children[i]
            cpath = f"{path}.{name}" if path else name
            if child in exclude:
                report.append((cpath, type(child).__name__, "float",
                               "excluded by caller"))
                i += 1
                continue
            if isinstance(child, Conv2D):
                if child._kwargs.get("layout", "NCHW")[-1] == "C":
                    report.append((cpath, "Conv2D", "float",
                                   "channel-last layout unsupported"))
                    i += 1
                    continue
                parts = {"conv": child, "bn": None, "act": None,
                         "post_act": None, "pool": None, "tail": child,
                         "names": [name]}
                fusable = True
                if child.act is not None:
                    act_name = getattr(child.act, "_act_type", None) or \
                        getattr(child.act, "act_type", None)
                    if act_name == "relu":
                        parts["act"] = "relu"
                    else:
                        # non-relu act: quantize the conv, apply the act
                        # in f32 after dequant (pre-round-4 behavior);
                        # no sibling folding / no int8 handoff
                        parts["post_act"] = child.act
                        fusable = False
                j = i + 1
                # sibling folding is only meaningful where execution
                # order == child order: Sequential containers
                seq = isinstance(block, (Sequential, HybridSequential))
                while fusable and seq and j < len(children):
                    nxt = children[j][1]
                    if nxt in exclude:
                        break  # honor the caller's opt-out: stop folding
                    if isinstance(nxt, BatchNorm) and parts["bn"] is None \
                            and parts["act"] is None and parts["pool"] is None:
                        parts["bn"] = nxt
                    elif isinstance(nxt, Activation) and parts["act"] is None \
                            and parts["pool"] is None and \
                            getattr(nxt, "_act_type", None) == "relu":
                        parts["act"] = "relu"
                    elif isinstance(nxt, MaxPool2D) and parts["pool"] is None \
                            and nxt._kwargs.get("pooling_convention",
                                                "valid") == "valid" \
                            and nxt._kwargs.get("layout", "NCHW") == "NCHW":
                        # ceil_mode ('full') pooling has different output
                        # sizes than reduce_window: left unfolded
                        parts["pool"] = nxt
                    else:
                        break
                    parts["tail"] = nxt
                    parts["names"].append(children[j][0])
                    j += 1
                units.append((block, cpath, parts))
                i = j
                continue
            if isinstance(child, Dense):
                units.append((block, cpath,
                              {"dense": child, "names": [name]}))
                i += 1
                continue
            walk(child, cpath)
            i += 1

    walk(net, "")
    return units


def quantize_net(net, calib_data=None, exclude=(), calib_mode="naive",
                 verbose=False):
    """Rewrite the net with INT8 execution units in-place and return it
    (reference: ``quantize_model``'s graph rewrite, gluon-style).

    Round-4 depth: Conv2D units absorb an immediately following
    BatchNorm (eval-affine folded into the per-output-channel requantize
    multiplier), relu, and MaxPool2D; consecutive quantized units pass
    requantized int8 activations directly (max-pooling runs on the int8
    codes), so a conv stack stays int8 end-to-end. Weight scales are
    per output channel for convs, per tensor for Dense.

    Every considered layer lands in ``net._quantization_report`` as
    (path, kind, 'int8'|'int8-chained'|'float', detail); ``verbose=True``
    prints the table (what stayed float and WHY)."""
    from ..gluon.nn.basic_layers import HybridSequential, Sequential

    report = []
    units = _collect_units(net, exclude, report)
    if not units:
        raise MXNetError("quantize_net: no Dense/Conv2D layers to quantize")
    if calib_data is None:
        raise MXNetError("quantize_net needs calibration data")
    heads = [u[2].get("conv") or u[2]["dense"] for u in units]

    # chain detection FIRST (decides which output hooks are needed):
    # unit k hands int8 to unit k+1 only when both are consecutive
    # children of the SAME Sequential container (execution order ==
    # child order there, and nowhere else — parallel-branch containers
    # like squeezenet's concat blocks must not chain) and neither side
    # carries a non-relu activation
    feeds_next = []
    for k, (block, _, parts) in enumerate(units):
        nxt = units[k + 1] if k + 1 < len(units) else None
        direct = False
        if nxt is not None and nxt[0] is block \
                and isinstance(block, (Sequential, HybridSequential)) \
                and "conv" in parts and "conv" in nxt[2] \
                and parts.get("post_act") is None \
                and nxt[2].get("post_act") is None:
            names = list(block._children.keys())
            direct = names.index(nxt[2]["names"][0]) == \
                names.index(parts["names"][-1]) + 1
        feeds_next.append(direct)

    # ONE calibration pass: input ranges for every head + output ranges
    # for the tails of units that will actually chain. The tail is the
    # last FOLDED sibling (activation included), so the observed range
    # is post-relu — exactly what the emitted int8 codes carry.
    chain_tails = [u[2]["tail"] for k, u in enumerate(units)
                   if feeds_next[k]]
    ranges, out_ranges = calib_ranges(net, calib_data, heads,
                                      mode=calib_mode,
                                      out_layers=chain_tails)

    for k, (block, cpath, parts) in enumerate(units):
        head = parts.get("conv") or parts["dense"]
        if id(head) not in ranges:
            report.append((cpath, type(head).__name__, "float",
                           "never reached by calibration data"))
            continue
        lo, hi = ranges[id(head)]
        if "dense" in parts:
            newb = _QuantizedDenseBlock(QuantizedDense(parts["dense"],
                                                       lo, hi))
            _swap(block, parts["names"][0], newb)
            report.append((cpath, "Dense", "int8",
                           "per-tensor weights"))
            continue
        olo, ohi = out_ranges.get(id(parts["tail"]), (lo, hi))
        unit = QuantizedConvUnit(
            parts["conv"], parts["bn"], parts["act"], parts["pool"],
            lo, hi, olo, ohi, emit_q=feeds_next[k])
        if parts.get("post_act") is not None:
            unit.post_act = parts["post_act"]
        newb = _QuantizedDenseBlock(unit)
        _swap(block, parts["names"][0], newb)
        for extra in parts["names"][1:]:
            _swap(block, extra, _identity_block())
        fused = [p for p in ("bn", "act", "pool") if parts.get(p)]
        status = "int8-chained" if feeds_next[k] else "int8"
        detail = "per-channel weights" \
            + (f", fused {'+'.join(fused)}" if fused else "") \
            + (", int8 handoff to next unit" if feeds_next[k] else "") \
            + (", f32 activation after dequant"
               if parts.get("post_act") is not None else "")
        report.append((cpath, "Conv2D", status, detail))

    if hasattr(net, "_clear_cached_op"):
        net._clear_cached_op()
    net._quantization_report = report
    if verbose:
        print(f"{'layer':40s} {'kind':8s} {'status':13s} detail")
        for path, kind, status, detail in report:
            print(f"{path:40s} {kind:8s} {status:13s} {detail}")
        n_q = sum(1 for r in report if r[2].startswith("int8"))
        print(f"quantized {n_q}/{len(report)} considered layers")
    return net


def _swap(block, name, newb):
    child = block._children[name]
    block._children[name] = newb
    # attribute-style blocks (self.fc = Dense(...)) call the child
    # through the instance attribute, not _children — swap those too
    for attr, val in list(vars(block).items()):
        if val is child:
            object.__setattr__(block, attr, newb)


def _identity_block():
    from ..gluon.block import Block

    class _Identity(Block):
        """Placeholder for siblings folded into a QuantizedConvUnit."""

        def __init__(self):
            super().__init__(prefix="qfolded_", params=None)

        def forward(self, x, *args):
            return x

    return _Identity()


# the ops above registered after mx.nd was generated at package import:
# refresh the generated namespaces so nd._contrib_quantize_v2 etc. appear
def _refresh_namespaces():
    import sys

    nd_mod = sys.modules.get("mxnet_tpu.ndarray")
    if nd_mod is not None:
        from ..ndarray import register as _nd_register

        _nd_register.populate_module(nd_mod, "nd")
    ndc = sys.modules.get("mxnet_tpu.ndarray.contrib")
    if ndc is not None:
        ndc._populate()


_refresh_namespaces()


def _quantized_dense_block_cls():
    from ..gluon.block import Block

    class _QDB(Block):
        """Block adapter holding a QuantizedDense (a real Block subclass,
        so save_parameters/apply/initialize traversals keep working —
        it simply owns no Parameters; weights are baked-in int8)."""

        def __init__(self, q):
            super().__init__(prefix="quantized_", params=None)
            self._q = q

        def forward(self, x, *args):
            return self._q(x)

    return _QDB


def _QuantizedDenseBlock(q):
    return _quantized_dense_block_cls()(q)


# ----------------------------------------------- registry op forms (INT8)
# Reference op names (``src/operator/quantization/quantized_fully_
# connected.cc``, ``quantized_conv.cc``, ``requantize.cc`` [unverified]):
# graph-level INT8 execution as registry ops over the same int8 helpers
# the gluon rewrite uses. Min/max range operands follow the reference's
# (data, min, max) convention; outputs carry their own range.
def _install_quantized_ops():
    from ..ops.registry import maybe_get

    if maybe_get("_contrib_quantized_dense") is not None:
        return

    def _split_q_args(args, no_bias):
        """Reference arity: (bias?, data_min, data_max, w_min, w_max) —
        the bias operand is OMITTED under no_bias (6-input form)."""
        if no_bias or len(args) == 4:
            return (None,) + tuple(args[-4:])
        if len(args) != 5:
            raise MXNetError(
                "quantized op expects (bias, data_min, data_max, "
                "weight_min, weight_max) or the 4-range no_bias form, "
                f"got {len(args)} trailing operands")
        return tuple(args)

    @register("_contrib_quantized_dense",
              aliases=["_contrib_quantized_fully_connected"],
              num_outputs=3, differentiable=False)
    def quantized_dense(data, weight, *args, num_hidden=None,
                        no_bias=False, **kw):
        """int8 x int8 -> int32 dense; returns (out collapsed to f32,
        out_min, out_max) like the reference's dequantize-fused path.
        Trailing operands: (bias?, data_min, data_max, weight_min,
        weight_max) — bias omitted under no_bias (reference arity)."""
        bias, data_min, data_max, weight_min, weight_max = \
            _split_q_args(args, no_bias)
        ds = _scale_from_range(jnp.asarray(data_min), jnp.asarray(data_max))
        ws = _scale_from_range(jnp.asarray(weight_min),
                               jnp.asarray(weight_max))
        acc = _int8_matmul(data, weight.T, ds, ws)
        if bias is not None:
            acc = acc + bias
        mx_ = jnp.max(jnp.abs(acc))
        return acc, -mx_, mx_

    @register("_contrib_quantized_conv", num_outputs=3,
              differentiable=False)
    def quantized_conv(data, weight, *args, kernel=None, stride=None,
                       pad=None, dilate=None, num_filter=None,
                       num_group=1, no_bias=False, **kw):
        """int8 conv with int32 accumulation (NC[DHW]), dequantized by
        the product of scales; returns (out, out_min, out_max).
        Trailing operands as in quantized_dense; stride/pad/dilate
        default per the input's spatial rank."""
        bias, data_min, data_max, weight_min, weight_max = \
            _split_q_args(args, no_bias)
        ds = _scale_from_range(jnp.asarray(data_min), jnp.asarray(data_max))
        ws = _scale_from_range(jnp.asarray(weight_min),
                               jnp.asarray(weight_max))
        nd_sp = data.ndim - 2
        stride = tuple(stride) if stride is not None else (1,) * nd_sp
        pad = tuple(pad) if pad is not None else (0,) * nd_sp
        dilate = tuple(dilate) if dilate is not None else (1,) * nd_sp
        acc = _int8_conv(data, weight, stride, pad, dilate,
                         num_group) * (ds * ws)
        if bias is not None:
            acc = acc + bias.reshape((1, -1) + (1,) * nd_sp)
        mx_ = jnp.max(jnp.abs(acc))
        return acc, -mx_, mx_

    @register("_contrib_requantize", num_outputs=3, differentiable=False)
    def requantize(data, min_range, max_range, min_calib_range=None,
                   max_calib_range=None, **kw):
        """f32 (or wide) -> int8 at the calibrated range (reference
        requantize.cc collapsing int32+ranges to int8)."""
        lo = min_calib_range if min_calib_range is not None else min_range
        hi = max_calib_range if max_calib_range is not None else max_range
        scale = _scale_from_range(jnp.asarray(lo), jnp.asarray(hi))
        q = _quantize_act(data.astype(jnp.float32), scale)
        return q, jnp.asarray(lo, jnp.float32), jnp.asarray(hi, jnp.float32)


_install_quantized_ops()
_refresh_namespaces()
