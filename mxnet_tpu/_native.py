"""Native (C++) acceleration library: build-on-demand + ctypes bindings.

The reference ships its IO hot path in C++ (dmlc RecordIOReader +
``src/io`` image pipeline [unverified]); here ``src/librecordio.cc`` is
compiled once per machine into a cached ``.so`` and bound via ctypes. Every
entry point has a pure-Python fallback — the native path is an
acceleration, never a requirement (machines without g++/libjpeg still
work)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_LOCK = threading.Lock()
_LIB = None
_TRIED = False

_SRC = os.path.join(os.path.dirname(__file__), "..", "src", "librecordio.cc")


def _cache_dir() -> str:
    base = os.environ.get("MXNET_TPU_CACHE",
                          os.path.join(os.path.expanduser("~"), ".cache",
                                       "mxnet_tpu"))
    os.makedirs(base, exist_ok=True)
    return base


def _build() -> Optional[str]:
    so = os.path.join(_cache_dir(), "libmxtpu_io.so")
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return None
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    try:
        # compile to a per-process temp name and rename into place: rename
        # is atomic on the same filesystem, so a concurrent process (the
        # multi-worker launcher) can never dlopen a half-written .so
        tmp = f"{so}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, src, "-ljpeg"],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, so)
        return so
    except Exception:  # noqa: BLE001 - no compiler / no libjpeg: fallback
        return None


def lib() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("MXNET_TPU_NO_NATIVE"):
            return None
        so = _build()
        if so is None:
            return None
        try:
            L = ctypes.CDLL(so)
        except OSError:
            return None
        if L.mxtpu_io_abi_version() != 1:
            return None
        L.mxtpu_rio_open.restype = ctypes.c_void_p
        L.mxtpu_rio_open.argtypes = [ctypes.c_char_p]
        L.mxtpu_rio_count.restype = ctypes.c_longlong
        L.mxtpu_rio_count.argtypes = [ctypes.c_void_p]
        L.mxtpu_rio_size.restype = ctypes.c_longlong
        L.mxtpu_rio_size.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        L.mxtpu_rio_offset.restype = ctypes.c_longlong
        L.mxtpu_rio_offset.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        L.mxtpu_rio_end.restype = ctypes.c_longlong
        L.mxtpu_rio_end.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        L.mxtpu_rio_read.restype = ctypes.c_longlong
        L.mxtpu_rio_read.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                     ctypes.c_char_p, ctypes.c_longlong]
        L.mxtpu_rio_read_at.restype = ctypes.c_longlong
        L.mxtpu_rio_read_at.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                        ctypes.c_char_p, ctypes.c_longlong]
        L.mxtpu_rio_close.argtypes = [ctypes.c_void_p]
        L.mxtpu_jpeg_probe.restype = ctypes.c_int
        L.mxtpu_jpeg_probe.argtypes = [
            ctypes.c_char_p, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
        ]
        L.mxtpu_jpeg_decode.restype = ctypes.c_int
        L.mxtpu_jpeg_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_longlong, ctypes.c_char_p,
            ctypes.c_longlong,
        ]
        _LIB = L
        return _LIB


def available() -> bool:
    return lib() is not None


class NativeRecordReader:
    """Random/sequential reader over one .rec file via the C++ scanner.

    The constructor scans the full framing into an offset index in native
    code (no Python per-record overhead); reads copy straight into bytes.
    """

    def __init__(self, path: str):
        L = lib()
        if L is None:
            raise RuntimeError("native IO library unavailable")
        self._L = L
        self._h = L.mxtpu_rio_open(path.encode())
        if not self._h:
            raise RuntimeError(f"cannot open/scan {path}")
        self._by_offset = None

    def __len__(self):
        return int(self._L.mxtpu_rio_count(self._h))

    def read(self, i: int) -> bytes:
        size = self._L.mxtpu_rio_size(self._h, i)
        if size < 0:
            raise IndexError(i)
        buf = ctypes.create_string_buffer(int(size))
        got = self._L.mxtpu_rio_read(self._h, i, buf, size)
        if got != size:
            raise RuntimeError(f"short read on record {i}")
        return buf.raw

    def read_at(self, offset: int):
        """-> (payload, end_offset) for the record starting at ``offset``;
        end_offset is where a sequential reader would stand afterwards."""
        if self._by_offset is None:
            self._by_offset = {
                int(self._L.mxtpu_rio_offset(self._h, i)): i
                for i in range(len(self))
            }
        i = self._by_offset.get(int(offset))
        if i is None:
            raise KeyError(f"no record at offset {offset}")
        return self.read(i), int(self._L.mxtpu_rio_end(self._h, i))

    def close(self):
        if self._h:
            self._L.mxtpu_rio_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def jpeg_decode(img_bytes: bytes):
    """Decode a JPEG to an HWC uint8 BGR numpy array; None if the native
    path is unavailable or the payload is not a decodable JPEG."""
    import numpy as np

    L = lib()
    if L is None:
        return None
    w = ctypes.c_int()
    h = ctypes.c_int()
    c = ctypes.c_int()
    if L.mxtpu_jpeg_probe(img_bytes, len(img_bytes), ctypes.byref(w),
                          ctypes.byref(h), ctypes.byref(c)) != 0:
        return None
    out = np.empty((h.value, w.value, 3), np.uint8)
    rc = L.mxtpu_jpeg_decode(
        img_bytes, len(img_bytes),
        out.ctypes.data_as(ctypes.c_char_p), out.nbytes,
    )
    return out if rc == 0 else None
