"""Training callbacks (reference: ``python/mxnet/callback.py`` [unverified]).

The reference fed these to ``Module.fit``'s ``batch_end_callback`` /
``epoch_end_callback``; the TPU build keeps the same callable contracts so
training scripts port unchanged. ``Speedometer`` measures wall-clock
between callback invocations, which under async TPU dispatch reports the
dispatch-limited rate unless the training loop syncs per batch.
"""

from __future__ import annotations

import logging
import time

__all__ = [
    "Speedometer", "ProgressBar", "do_checkpoint", "log_train_metric",
    "LogValidationMetricsCallback", "module_checkpoint",
]


class Speedometer:
    """Log training speed and metrics every ``frequent`` batches.

    Reference semantics: with ``auto_reset`` the metric is reset after each
    log line so values are per-window, not running means.
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0.0
        self.last_count = 0
        self._logger = logging.getLogger(__name__)

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False  # new epoch
        self.last_count = count
        if not self.init:
            self.init = True
            self.tic = time.time()
            return
        if count % self.frequent != 0:
            return
        elapsed = time.time() - self.tic
        speed = self.frequent * self.batch_size / elapsed if elapsed > 0 \
            else float("inf")
        if param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            if self.auto_reset:
                param.eval_metric.reset()
            msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s" % (
                param.epoch, count, speed,
                "\t".join(f"{n}={v:f}" for n, v in name_value),
            )
        else:
            msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec" % (
                param.epoch, count, speed,
            )
        self._logger.info(msg)
        self.tic = time.time()


class ProgressBar:
    """Text progress bar over total batch count (reference API)."""

    def __init__(self, total, length=80):
        self.total = total
        self.length = length

    def __call__(self, param):
        count = param.nbatch
        filled = int(round(self.length * count / float(self.total)))
        pct = round(100.0 * count / float(self.total), 1)
        bar = "=" * filled + "-" * (self.length - filled)
        print(f"[{bar}] {pct}%", end="\r")


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving module params every ``period`` epochs."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            from .module.module import save_checkpoint as _save

            _save(prefix, iter_no + 1, sym, arg, aux)

    return _callback


module_checkpoint = do_checkpoint


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the metric every ``period`` batches."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            logging.info(
                "Iter[%d] Batch[%d] Train-%s",
                param.epoch, param.nbatch,
                ["%s=%f" % nv for nv in name_value],
            )
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class LogValidationMetricsCallback:
    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                         value)
