"""Runtime feature introspection (reference: ``python/mxnet/runtime.py`` over
``src/libinfo.cc`` [unverified]: ``mx.runtime.feature_list()``)."""

from __future__ import annotations

from collections import namedtuple

import jax

__all__ = ["Feature", "feature_list", "Features"]

Feature = namedtuple("Feature", ["name", "enabled"])


def _detect():
    feats = {
        # compute backends
        "TPU": any(d.platform == "tpu" for d in jax.devices())
        if _safe_devices()
        else False,
        "CUDA": False,
        "CUDNN": False,
        "NCCL": False,
        "TENSORRT": False,
        "MKLDNN": False,
        # our backends
        "XLA": True,
        "PALLAS": True,
        "BF16": True,
        "F16C": True,
        "INT64_TENSOR_SIZE": True,
        # capabilities
        "OPENCV": _has("cv2"),
        "BLAS_OPEN": True,
        "SSE": False,
        "DIST_KVSTORE": True,
        "PROFILER": True,
        "SIGNAL_HANDLER": True,
        "DEBUG": False,
    }
    return feats


def _safe_devices():
    try:
        jax.devices()
        return True
    except Exception:
        return False


def _has(mod):
    try:
        __import__(mod)
        return True
    except ImportError:
        return False


class Features(dict):
    """dict of name -> Feature with ``is_enabled`` (reference API)."""

    def __init__(self):
        super().__init__(
            (k, Feature(k, v)) for k, v in _detect().items()
        )

    def __repr__(self):
        return f"[{', '.join(f.name + (' ✔' if f.enabled else ' ✖') for f in self.values())}]"

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError(f"feature '{feature_name}' is unknown")
        return self[feature_name].enabled


def feature_list():
    return list(Features().values())
